#!/usr/bin/env python
"""Static check: the checked-in bench history parses as the ledger expects.

``telemetry.perf_ledger.ingest_bench_file`` turns ``BENCH_r*.json`` /
``MULTICHIP_r*.json`` driver snapshots into perf-ledger series — but it
is deliberately lenient (a malformed file yields NO records rather than
an error), so a drifted record shape would silently drop history from
the regression detector instead of failing loudly. This checker is the
loud half: every checked-in snapshot must carry the record keys the
ledger keys its series by.

Schema enforced per ``BENCH_r*.json``:

- top level: ``n`` (int), ``cmd`` (str), ``rc`` (int), ``tail`` (str),
  ``parsed`` (dict — the headline record);
- ``parsed``: ``metric`` (non-empty str), ``value`` (finite number),
  ``unit`` (str), ``extra`` (dict); ``vs_baseline``, when present, a
  finite number.

Per ``MULTICHIP_r*.json``: two generations share the prefix. The legacy
dry-run receipts (r01–r05, no ``parsed`` block) keep their original
3-key contract: ``n_devices`` (int), ``ok`` (bool), ``rc`` (int). A
MEASURED record (r06+, ``parsed`` present) must additionally carry
``device_kind`` (non-empty str — the platform×count series key that
separates forced-host CPU runs from real slices) and a
``fleet_scan_rounds_per_sec`` headline: ``better='higher'``,
``unit='rounds/s'``, a finite value, ``extra.n_devices`` matching the
envelope, and the nested per-device ``device_step_reading``
(``better='lower'``, ``unit='ms'``) — a throughput record without its
device rollup is half a story, exactly like serving's rate/p99 pair.

Usage:
    python scripts/check_bench_schema.py [FILE.json ...]

With no arguments it checks every ``BENCH_r*.json`` and
``MULTICHIP_r*.json`` in the repo root — the self-check its test twin
(tests/test_bench_schema.py) runs, alongside pinned corruption classes.
"""

from __future__ import annotations

import json
import math
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent


def _is_finite_number(x) -> bool:
    return (
        isinstance(x, (int, float))
        and not isinstance(x, bool)
        and math.isfinite(x)
    )


def check_parsed(parsed, where: str) -> list[str]:
    """Violations in one headline record (the ``parsed`` block — also
    the shape ``bench.py`` prints and ``_ledger_append`` consumes)."""
    out: list[str] = []
    if not isinstance(parsed, dict):
        return [f"{where}: parsed block is {type(parsed).__name__}, not a dict"]
    metric = parsed.get("metric")
    if not (isinstance(metric, str) and metric):
        out.append(f"{where}: parsed.metric must be a non-empty string")
    if not _is_finite_number(parsed.get("value")):
        out.append(f"{where}: parsed.value must be a finite number")
    if not isinstance(parsed.get("unit"), str):
        out.append(f"{where}: parsed.unit must be a string")
    if not isinstance(parsed.get("extra"), dict):
        out.append(f"{where}: parsed.extra must be a dict")
    if "vs_baseline" in parsed and not _is_finite_number(
        parsed["vs_baseline"]
    ):
        out.append(f"{where}: parsed.vs_baseline must be a finite number")
    # the serving plane's paired series: the throughput headline must
    # trend up and CARRY its latency sibling (a placements/sec reading
    # without its p99 is half a story — the ledger would trend the rate
    # while the tail silently regressed), and the p99 series must trend
    # down in ms
    if metric == "serving_placements_per_sec":
        if parsed.get("better") != "higher":
            out.append(
                f"{where}: serving_placements_per_sec must declare "
                "better='higher' (a throughput series)"
            )
        if not isinstance(parsed.get("p99_reading"), dict):
            out.append(
                f"{where}: serving_placements_per_sec must nest its "
                "p99_reading sibling (the serving ledger is a PAIR of "
                "series: placements/sec AND p99 ms)"
            )
        if not isinstance(parsed.get("slo_reading"), dict):
            out.append(
                f"{where}: serving_placements_per_sec must nest its "
                "slo_reading sibling (the serve cell's error-budget "
                "burn — rate and tail without budget accounting is "
                "still half a story)"
            )
    if metric == "serving_p99_ms":
        if parsed.get("better") != "lower":
            out.append(
                f"{where}: serving_p99_ms must declare better='lower' "
                "(a latency series)"
            )
        if parsed.get("unit") != "ms":
            out.append(f"{where}: serving_p99_ms must carry unit='ms'")
    if metric == "slo_budget_burn_frac":
        if parsed.get("better") != "lower":
            out.append(
                f"{where}: slo_budget_burn_frac must declare "
                "better='lower' (budget burned, not budget left)"
            )
        if parsed.get("unit") != "frac":
            out.append(
                f"{where}: slo_budget_burn_frac must carry unit='frac'"
            )
    # the multichip pair: the measured MULTICHIP record's throughput
    # headline must trend up in rounds/s and carry its per-device
    # rollup sibling; the device series must trend down in ms
    if metric == "fleet_scan_rounds_per_sec":
        if parsed.get("better") != "higher":
            out.append(
                f"{where}: fleet_scan_rounds_per_sec must declare "
                "better='higher' (a throughput series)"
            )
        if parsed.get("unit") != "rounds/s":
            out.append(
                f"{where}: fleet_scan_rounds_per_sec must carry "
                "unit='rounds/s'"
            )
        if not isinstance(parsed.get("device_step_reading"), dict):
            out.append(
                f"{where}: fleet_scan_rounds_per_sec must nest its "
                "device_step_reading sibling (mesh throughput without "
                "the per-device rollup is half a story)"
            )
        extra = parsed.get("extra")
        if isinstance(extra, dict) and not isinstance(
            extra.get("n_devices"), int
        ):
            out.append(
                f"{where}: fleet_scan_rounds_per_sec extra.n_devices "
                "must be an int (the mesh identity the ledger keys by)"
            )
    if metric == "multichip_device_step_ms_p99":
        if parsed.get("better") != "lower":
            out.append(
                f"{where}: multichip_device_step_ms_p99 must declare "
                "better='lower' (a latency series)"
            )
        if parsed.get("unit") != "ms":
            out.append(
                f"{where}: multichip_device_step_ms_p99 must carry "
                "unit='ms'"
            )
    # nested ledger readings (``*_reading`` — the fleet cell's rollup and
    # global-amortization series, and any future sibling): each is
    # appended to the perf ledger as its OWN series, so each must carry
    # the same headline-record keys or the ledger silently drops it
    for key, sub in parsed.items():
        if key.endswith("_reading"):
            out.extend(check_parsed(sub, f"{where}: parsed.{key}"))
    return out


def check_file(path: str | Path) -> list[str]:
    """Violations in one driver snapshot file (empty = clean)."""
    p = Path(path)
    try:
        doc = json.loads(p.read_text())
    except OSError as e:
        return [f"{p.name}: unreadable ({e})"]
    except json.JSONDecodeError as e:
        return [f"{p.name}: invalid JSON ({e})"]
    if not isinstance(doc, dict):
        return [f"{p.name}: top level is {type(doc).__name__}, not a dict"]
    out: list[str] = []
    if p.name.startswith("MULTICHIP"):
        if not isinstance(doc.get("n_devices"), int):
            out.append(f"{p.name}: n_devices must be an int")
        if not isinstance(doc.get("ok"), bool):
            out.append(f"{p.name}: ok must be a bool")
        if not isinstance(doc.get("rc"), int):
            out.append(f"{p.name}: rc must be an int")
        if "parsed" not in doc:
            # legacy dry-run receipt (r01–r05): the 3-key contract above
            # is the whole schema
            return out
        # measured record (r06+): the envelope must carry the mesh
        # identity and the parsed block the ledger ingests
        kind = doc.get("device_kind")
        if not (isinstance(kind, str) and kind):
            out.append(
                f"{p.name}: measured MULTICHIP records must carry a "
                "non-empty device_kind (the platform×count series key "
                "that keeps forced-host CPU runs off real-slice trends)"
            )
        out.extend(check_parsed(doc["parsed"], p.name))
        parsed = doc["parsed"]
        if (
            isinstance(parsed, dict)
            and parsed.get("metric") != "fleet_scan_rounds_per_sec"
        ):
            out.append(
                f"{p.name}: measured MULTICHIP headline must be "
                "fleet_scan_rounds_per_sec, got "
                f"{parsed.get('metric')!r}"
            )
        return out
    for key, typ in (("n", int), ("cmd", str), ("rc", int), ("tail", str)):
        if not isinstance(doc.get(key), typ):
            out.append(f"{p.name}: {key} must be {typ.__name__}")
    if "parsed" not in doc:
        out.append(
            f"{p.name}: no parsed headline block — the ledger would "
            "silently drop this snapshot"
        )
    else:
        out.extend(check_parsed(doc["parsed"], p.name))
    return out


def violations(paths=None) -> list[str]:
    if paths is None:
        paths = sorted(ROOT.glob("BENCH_r*.json")) + sorted(
            ROOT.glob("MULTICHIP_r*.json")
        )
        if not paths:
            return ["no BENCH_r*.json / MULTICHIP_r*.json found in repo root"]
    out: list[str] = []
    for p in paths:
        out.extend(check_file(p))
    return out


def main(argv: list[str]) -> int:
    bad = violations(argv or None)
    if bad:
        sys.stderr.write(
            "bench history schema drift — ledger ingestion would silently "
            "lose these records:\n" + "".join(f"  {v}\n" for v in bad)
        )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
