#!/usr/bin/env python
"""Static check: the controller never calls the backend boundary raw.

Every ``monitor()`` / ``apply_move()`` the control loop issues must route
through the retry-and-circuit-breaker boundary (``bench/boundary.py``) —
one raw ``backend.monitor()`` re-introduces the reference's
crash-on-flaky-cluster behavior the resilience layer exists to remove.

AST-based, like its sibling ``check_no_print.py``: inside
``bench/controller.py`` and the multiplexed fleet loop
``bench/fleet.py``, a ``.monitor(...)`` or ``.apply_move(...)`` call is
only legal on a receiver NAMED ``boundary`` — the bare name the solo
loop builds, or a ``<tenant>.boundary`` attribute (the fleet loop's
per-tenant BoundaryClient). The boundary module itself is the one place
allowed to touch ``self.backend.<call>``.

Run directly (exit 1 on violation) or through its test twin
(tests/test_boundary_retry.py).
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

PACKAGE = Path(__file__).resolve().parent.parent / "kubernetes_rescheduling_tpu"
# the control loops: the consumers of the Backend protocol that must be
# resilient — the solo loop and the multiplexed fleet loop. (harness/CLI
# measurement phases deliberately stay raw — a broken ruler should fail
# loudly, not retry.)
CHECKED = (
    PACKAGE / "bench" / "controller.py",
    PACKAGE / "bench" / "fleet.py",
)
BOUNDARY_CALLS = {"monitor", "apply_move"}
ALLOWED_RECEIVERS = {"boundary"}


def _is_boundary_receiver(recv: ast.expr) -> bool:
    """``boundary.<call>`` (the solo loop's local) or ``<x>.boundary.<call>``
    (the fleet loop's per-tenant BoundaryClient attribute)."""
    if isinstance(recv, ast.Name) and recv.id in ALLOWED_RECEIVERS:
        return True
    return isinstance(recv, ast.Attribute) and recv.attr in ALLOWED_RECEIVERS


def find_raw_boundary_calls(path: Path) -> list[tuple[int, str]]:
    """(line, source-ish) pairs for boundary calls on a raw receiver."""
    tree = ast.parse(path.read_text(), filename=str(path))
    out = []
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in BOUNDARY_CALLS
        ):
            continue
        if _is_boundary_receiver(node.func.value):
            continue
        recv = node.func.value
        recv_txt = ast.unparse(recv) if hasattr(ast, "unparse") else "<recv>"
        out.append((node.lineno, f"{recv_txt}.{node.func.attr}(...)"))
    return out


def violations() -> list[str]:
    return [
        f"{path.relative_to(PACKAGE.parent)}:{line}: {what}"
        for path in CHECKED
        for line, what in find_raw_boundary_calls(path)
    ]


def main() -> int:
    bad = violations()
    if bad:
        sys.stderr.write(
            "raw boundary call in the controller — route monitor()/"
            "apply_move() through the BoundaryClient (bench/boundary.py):\n"
            + "".join(f"  {v}\n" for v in bad)
        )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
