"""Regenerate the round-5 optimality-gap chart.

Data: the MEASURED 2026-07-31 gap table (scripts/gap_table.py + the
best-of-4 probe; provenance in RESULTS.md "Optimality gap, round 5").
Negative = the solver beat the MILP's 180 s incumbent.
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from kubernetes_rescheduling_tpu.bench.plots import plot_optimality_gap

ROWS = [
    {"instance": "40×5", "configs": {
        "9 sweeps": 21.4, "27 sweeps": 10.7,
        "27 sweeps + swaps": 7.1, "9 sweeps, best-of-4": 10.7}},
    {"instance": "60×6", "configs": {
        "9 sweeps": 19.1, "27 sweeps": 8.5,
        "27 sweeps + swaps": 8.5, "9 sweeps, best-of-4": 2.1}},
    {"instance": "100×6", "configs": {
        "9 sweeps": 10.5, "27 sweeps": 5.3,
        "27 sweeps + swaps": 2.6, "9 sweeps, best-of-4": 6.6}},
]

if __name__ == "__main__":
    out = Path(__file__).resolve().parent.parent / "result" / "charts"
    print(plot_optimality_gap(ROWS, out))
