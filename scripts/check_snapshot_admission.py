#!/usr/bin/env python
"""Static check: every monitor snapshot passes the admission guard.

PR 2 made the boundary resilient (``check_boundary_retry.py``); this is
the sibling check for the DATA: a ``boundary.monitor()`` result that
reaches device state without passing ``AdmissionGuard.admit``
(``bench/admission.py``) re-opens the poisoned-metrics hole — one
NaN/Inf/negative load silently corrupts the solver score, the forecast
RLS state, the attribution sums, and the perf ledger.

AST-based, like its siblings: inside ``bench/controller.py``,
``bench/fleet.py``, and ``serving/engine.py``, a ``.monitor(...)`` call
is only legal inside the designated admitted-monitor wrappers —
``_Runtime.monitor_admitted`` (the solo loop), ``_admitted_monitor``
(the fleet loop), and ``ServingEngine._admitted_snapshot`` (the serving
plane) — and each wrapper must itself contain an ``.admit(...)`` call,
so the wrapper cannot quietly stop guarding. Every other control-loop
code path gets its snapshots from a wrapper and therefore admitted.

Run directly (exit 1 on violation) or through its test twin
(tests/test_snapshot_admission.py).
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

PACKAGE = Path(__file__).resolve().parent.parent / "kubernetes_rescheduling_tpu"
# the control loops: the consumers whose snapshots touch device state.
# (bench/boundary.py is the transport layer below the guard; harness/CLI
# measurement phases read the raw backend on purpose — a broken ruler
# should fail loudly, not be repaired.)
CHECKED = (
    PACKAGE / "bench" / "controller.py",
    PACKAGE / "bench" / "fleet.py",
    PACKAGE / "serving" / "engine.py",
)
# the designated wrappers: the ONLY functions allowed to call .monitor()
WRAPPERS = {"monitor_admitted", "_admitted_monitor", "_admitted_snapshot"}


def _functions(tree: ast.AST):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _calls(tree: ast.AST, attr: str):
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == attr
        ):
            yield node


def find_violations(path: Path) -> list[tuple[int, str]]:
    """(line, message) pairs: monitor calls outside the wrappers, plus
    wrappers that lost their admit call."""
    tree = ast.parse(path.read_text(), filename=str(path))
    out: list[tuple[int, str]] = []

    # map every node to its innermost enclosing function. ast.walk
    # yields outer functions before the defs nested inside them, so
    # plain assignment lets the inner function win — a monitor() call
    # inside a closure nested in a wrapper is attributed to the
    # closure (a violation), not laundered through the wrapper's name.
    enclosing: dict[ast.AST, ast.AST] = {}
    for fn in _functions(tree):
        for node in ast.walk(fn):
            if node is not fn:
                enclosing[node] = fn

    wrappers_seen: set[str] = set()
    for call in _calls(tree, "monitor"):
        fn = enclosing.get(call)
        name = getattr(fn, "name", None)
        if name in WRAPPERS:
            wrappers_seen.add(name)
            continue
        recv = (
            ast.unparse(call.func.value)
            if hasattr(ast, "unparse")
            else "<recv>"
        )
        out.append(
            (
                call.lineno,
                f"{recv}.monitor(...) outside the admitted-monitor "
                f"wrappers {sorted(WRAPPERS)}",
            )
        )

    for fn in _functions(tree):
        if fn.name not in wrappers_seen:
            continue
        if not any(True for _ in _calls(fn, "admit")):
            out.append(
                (
                    fn.lineno,
                    f"wrapper {fn.name} never calls .admit(...) — the "
                    "admission guard has been bypassed",
                )
            )
    return out


def violations() -> list[str]:
    return [
        f"{path.relative_to(PACKAGE.parent)}:{line}: {what}"
        for path in CHECKED
        for line, what in find_violations(path)
    ]


def main() -> int:
    bad = violations()
    if bad:
        sys.stderr.write(
            "unadmitted monitor snapshot in the control loop — route "
            "monitor() results through the admission guard "
            "(bench/admission.py):\n" + "".join(f"  {v}\n" for v in bad)
        )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
