"""Attribute sparse-solver device time to components at 10k x 1k.

Each component runs K times inside one jitted scan with a true data
dependency (carry folded into the inputs), fenced once — per-iteration
cost = total / K. Chain length is sized so total device work is well
over the tunnel RTT (memory discipline: micro-probes under the RTT
window read as zero).
"""

import sys
import time
from functools import partial
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_rescheduling_tpu.core import sparsegraph
from kubernetes_rescheduling_tpu.core.sparsegraph import BLOCK_R
from kubernetes_rescheduling_tpu.core.topology import synthetic_scenario
from kubernetes_rescheduling_tpu.ops.fused_admission import fused_score_admission
from kubernetes_rescheduling_tpu.ops.sparse_mass import (
    chunk_local_slabs,
    hub_neighbor_mass,
    hub_tile_arrays,
    sparse_neighbor_mass,
)
from kubernetes_rescheduling_tpu.solver.sparse_solver import sparse_pod_comm_cost
from kubernetes_rescheduling_tpu.core.sparsegraph import sparse_pair_comm_cost

scn = synthetic_scenario(
    n_pods=10_000, n_nodes=1_000, powerlaw=True, mean_degree=4.0, seed=0,
    node_cpu_cap_m=2_000.0,
)
sg = sparsegraph.from_comm_graph(scn.graph)
SP = sg.sp
N = 1000
NHB = len(sg.hub_blocks)
print(f"blocks={sg.num_blocks} hub={NHB} TU={sg.w_local.shape[1]}")

rng = np.random.default_rng(0)
assign0 = jnp.asarray(rng.integers(0, N, size=SP), jnp.int32)
rv = jnp.asarray((rng.random(SP) > 0.02).astype(np.float32))
rvu = jnp.where(sg.u_ids < SP, rv[jnp.clip(sg.u_ids, 0, SP - 1)], 0.0)
w_mm = sg.w_local.astype(jnp.bfloat16)
toff = jnp.asarray(sg.block_toff, jnp.int32)
blocks = jnp.asarray(sg.regular_blocks[:4], jnp.int32)
ids = (np.asarray(blocks)[:, None] * BLOCK_R + np.arange(BLOCK_R)).reshape(-1)
ids_j = jnp.asarray(ids)
h_col, h_lcol, h_out, h_first = hub_tile_arrays(sg)
u_g = jnp.concatenate(
    [
        sg.u_ids[
            sg.block_toff[b] * sg.bu :
            (sg.block_toff[b] + sg.block_ntiles[b]) * sg.bu
        ]
        for b in sg.hub_blocks
    ]
)
rvu_g = jnp.where(u_g < SP, rv[jnp.clip(u_g, 0, SP - 1)], 0.0)

cpu_load = jnp.asarray(rng.random(N) * 1000, jnp.float32)
mem_load = jnp.zeros(N)
cap = jnp.full(N, 2000.0)
mem_cap = jnp.full(N, jnp.inf)
node_valid = jnp.ones(N, bool)
c_cpu = jnp.asarray(rng.random(1024) * 100, jnp.float32)
c_mem = jnp.zeros(1024)
valid_c = jnp.ones(1024, bool)


def timeit(name, step, k=400):
    @partial(jax.jit, static_argnames=("kk",))
    def run(a0, kk):
        def body(a, i):
            return step(a, i), 0
        a, _ = jax.lax.scan(body, a0, jnp.arange(kk))
        return a

    out = run(assign0, k)
    jnp.sum(out).item()  # warm + fence
    best = float("inf")
    for _ in range(3):
        t = time.perf_counter()
        out = run(assign0, k)
        jnp.sum(out).item()
        best = min(best, time.perf_counter() - t)
    print(f"{name:28s} {best / k * 1e3:8.4f} ms/iter")


# 1. the per-chunk tgt gather
timeit(
    "tgt gather (52k)",
    lambda a, i: a.at[0].set(jnp.sum(a[jnp.clip(sg.u_ids, 0, SP - 1)]) % N),
)

# 2. regular-chunk mass kernel (4 blocks x 2 tiles, chunk-local slabs)
def mass_step(a, i):
    starts = toff[blocks] * sg.bu
    u_c, rvu_c = chunk_local_slabs(sg.u_ids, rvu, starts, sg.u_reg)
    tgt_c = a[jnp.clip(u_c, 0, SP - 1)]
    M = sparse_neighbor_mass(
        w_mm, tgt_c, rvu_c, blocks, toff,
        num_nodes=N, bu=sg.bu, reg_tiles=sg.reg_tiles,
    )
    return a.at[0].set(jnp.sum(M).astype(jnp.int32) % N)

timeit("chunk mass (slab+kernel)", mass_step)

# 3. hub mass (all hub tiles, group-local slab)
def hub_step(a, i):
    tgt_l = a[jnp.clip(u_g, 0, SP - 1)]
    M = hub_neighbor_mass(
        w_mm, tgt_l, rvu_g, h_col, h_lcol, h_out, h_first,
        num_nodes=N, num_hub_blocks=NHB, bu=sg.bu,
    )
    return a.at[0].set(jnp.sum(M).astype(jnp.int32) % N)

timeit("hub mass (slab+kernel)", hub_step)

# 4. score+admission epilogue (C=1024)
def place_step(a, i):
    M = (a[ids_j][:, None] * jnp.ones((1, N))).astype(jnp.float32)
    new_node, admitted, d_cpu, d_mem = fused_score_admission(
        M, a[ids_j], c_cpu, c_mem, valid_c,
        cpu_load, mem_load, cap, mem_cap, node_valid,
        0.0, 0.5, i.astype(jnp.int32),
        enforce_capacity=True, use_noise=True, emit_x_rows=False,
    )
    return a.at[ids_j].set(new_node)

timeit("score+admission (C=1024)", place_step)

# 5. per-sweep exact objective (COO)
def obj_step(a, i):
    c = sparse_pair_comm_cost(sg, a[:SP], rv[:SP])
    return a.at[0].set(c.astype(jnp.int32) % N)

timeit("objective COO", obj_step)

# 6. loads refresh (scatter-add)
svc_cpu = jnp.asarray(rng.random(SP) * 100, jnp.float32)
def loads_step(a, i):
    l = jnp.zeros((N + 1,), jnp.float32).at[jnp.where(rv > 0, a, N)].add(svc_cpu)[:N]
    return a.at[0].set(jnp.sum(l).astype(jnp.int32) % N)

timeit("loads scatter-add", loads_step)
print("OK")
