#!/usr/bin/env python
"""Static check: perf-ledger JSONL files keep their schema invariants.

Every record must be a JSON object carrying the required keys
(``telemetry.perf_ledger.REQUIRED_KEYS``: schema, seq, metric, value,
unit, scenario, device_kind, config_digest, better), its value must be a
finite number (NaN/inf would silently poison every median downstream),
``better`` must be a known direction, and ``seq`` must be STRICTLY
MONOTONE within the file — an interleaved or rewritten ledger is
corrupt, not merely stale, and the detector's "newest reading" pick
would judge the wrong sample.

Usage:
    python scripts/check_perf_ledger.py LEDGER.jsonl [...]

With no arguments it self-checks: a synthetic ledger written through
``PerfLedger`` plus one built by ingesting the repo's checked-in
``BENCH_r*.json`` / ``MULTICHIP_r*.json`` history must both validate —
so the writer, the ingester, and this checker cannot drift apart. Run
directly (exit 1 on violation) or through the test twin
(tests/test_perf_ledger_check.py).
"""

from __future__ import annotations

import json
import sys
import tempfile
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

from kubernetes_rescheduling_tpu.telemetry.perf_ledger import (  # noqa: E402
    PerfLedger,
    ingest_history,
    validate_entry,
)


def check_ledger_file(path: str | Path) -> list[str]:
    """Schema violations in one ledger file (empty = clean)."""
    p = Path(path)
    if not p.is_file():
        return [f"{p}: not a file"]
    out: list[str] = []
    last_seq: int | None = None
    for i, line in enumerate(p.read_text().splitlines(), start=1):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError as e:
            out.append(f"{p}:{i}: not JSON ({e})")
            continue
        if not isinstance(rec, dict):
            out.append(f"{p}:{i}: record is not an object")
            continue
        for bad in validate_entry(rec):
            out.append(f"{p}:{i}: {bad}")
        seq = rec.get("seq")
        if isinstance(seq, int):
            if last_seq is not None and seq <= last_seq:
                out.append(
                    f"{p}:{i}: seq {seq} not monotone (follows {last_seq})"
                )
            last_seq = seq
    if last_seq is None:
        out.append(f"{p}: no ledger records")
    return out


def self_check() -> list[str]:
    """No-args mode: the writer and the history ingester must both
    produce ledgers this checker accepts."""
    out: list[str] = []
    with tempfile.TemporaryDirectory() as td:
        synth = Path(td) / "synthetic.jsonl"
        led = PerfLedger(synth)
        for i, v in enumerate((10.0, 9.5, 9.8, 12.0)):
            led.append(
                metric="decisions_per_sec", value=v, unit="1/s",
                scenario="selfcheck", device_kind="cpu",
                digest="selfcheck", better="higher", run=i,
            )
        out.extend(check_ledger_file(synth))

        history = sorted(ROOT.glob("BENCH_r*.json")) + sorted(
            ROOT.glob("MULTICHIP_r*.json")
        )
        if history:
            ingested = Path(td) / "history.jsonl"
            ingest_history(history, PerfLedger(ingested))
            out.extend(check_ledger_file(ingested))
    return out


def main(argv: list[str]) -> int:
    bad = (
        [v for p in argv for v in check_ledger_file(p)]
        if argv
        else self_check()
    )
    if bad:
        sys.stderr.write(
            "perf-ledger schema violations:\n"
            + "".join(f"  {v}\n" for v in bad)
        )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
