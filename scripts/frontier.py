"""Disruption/quality frontier: move-cost pricing vs wave capping.

Runs the µBench experiment matrix (global algorithm, load sustained
through the loop — reference release2.sh semantics) across a sweep of
``move_cost`` values and a sweep of ``global_moves_cap`` values, and
prints the measured frontier: pods restarted, request error rate during
rescheduling, and final communication cost. This is the evidence behind
RESULTS.md's operator guidance on pricing restarts inside the solve
versus capping the wave after it.

CPU-friendly (sim backend at µBench scale): JAX_PLATFORMS=cpu recommended.
"""

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

# Force the CPU platform even when a site hook pre-imported jax and pinned
# the tunneled TPU (env var alone is not enough — every eager op would pay
# a ~0.1 s tunnel round trip and this matrix would take hours)
import jax

jax.config.update("jax_platforms", "cpu")

from kubernetes_rescheduling_tpu.bench.harness import (
    ExperimentConfig,
    run_experiment,
)


def run(tag, **kw):
    cfg = ExperimentConfig(
        algorithms=("global",),
        repeats=3,
        rounds=20,
        scenario="mubench",
        out_dir=f"/tmp/frontier/{tag}",
        session_name=tag,
        seed=2,
        **kw,
    )
    agg = run_experiment(cfg)["aggregate"]["global"]
    return {
        "config": tag,
        "restarts": round(agg["restarts"], 1),
        "error_rate_during": round(agg["error_rate_during"], 4),
        "communication_cost": round(agg["communication_cost"], 2),
        # the point of rescheduling: a config that avoids all disruption by
        # never moving leaves the pile-up's queueing latency in place
        "response_time_ms": round(agg["response_time_ms"], 2),
        "load_std": round(agg["load_std"], 2),
    }


rows = []
rows.append(run("uncapped"))
for k in (1, 2, 4):
    rows.append(run(f"cap{k}", global_moves_cap=k))
for mc in (0.5, 2.0, 4.0, 8.0):
    rows.append(run(f"mc{mc}", move_cost=mc))
for r in rows:
    print(json.dumps(r))
