#!/usr/bin/env python
"""Static check: the metric inventory in OBSERVABILITY.md matches the code.

Every metric name registered anywhere in ``kubernetes_rescheduling_tpu/``
(via ``registry.counter/gauge/histogram("name", ...)``) must appear in
OBSERVABILITY.md's inventory table, and every name the table lists must
still exist in the code — so the operator-facing metric docs can no
longer drift from what the ``/metrics`` endpoint actually serves.

Source side: a regex over the package for ``.counter("...")`` /
``.gauge("...")`` / ``.histogram("...")`` call sites with a literal
first argument (the registry's get-or-create surface; ``\\s*`` spans the
newline in multi-line calls), plus ``.counter_inc("...")`` /
``.gauge_set("...")`` — the budget-gated ``TenantSeries`` gateway
(``telemetry/fleet_rollup.py``) through which every tenant-labeled
family registers. A registration whose name is built dynamically would
be invisible to this check — keep names literal.

Doc side: backticked tokens in the FIRST column of the inventory table's
rows (lines starting with ``| `` in OBSERVABILITY.md).

Run directly (exit 1 on drift) or through its test twin
(tests/test_metrics_documented.py).
"""

from __future__ import annotations

import re
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
PACKAGE = ROOT / "kubernetes_rescheduling_tpu"
DOC = ROOT / "OBSERVABILITY.md"

_REGISTER = re.compile(
    r"\.(?:counter|gauge|histogram|counter_inc|gauge_set)"
    r"\(\s*\"([a-zA-Z_][a-zA-Z0-9_]*)\"",
    re.S,
)
_TICKED = re.compile(r"`([a-z_][a-z0-9_]*)`")


def code_metrics() -> dict[str, list[str]]:
    """metric name -> source files registering it."""
    out: dict[str, list[str]] = {}
    for path in sorted(PACKAGE.rglob("*.py")):
        for name in _REGISTER.findall(path.read_text()):
            out.setdefault(name, []).append(
                str(path.relative_to(ROOT))
            )
    return out


def documented_metrics(doc: Path = DOC) -> set[str]:
    """Backticked metric names from the first column of the inventory
    table — the table under the '**Metrics**' heading (other tables in
    the doc describe files/flags, not metrics)."""
    names: set[str] = set()
    in_section = False
    for line in doc.read_text().splitlines():
        if line.startswith("**Metrics**"):
            in_section = True
            continue
        if in_section and line.startswith("**"):
            break
        if in_section and line.startswith("|") and line.count("|") >= 2:
            first_cell = line.split("|")[1]
            names.update(_TICKED.findall(first_cell))
    return names


def violations() -> list[str]:
    code = code_metrics()
    docs = documented_metrics()
    out = []
    for name in sorted(set(code) - docs):
        out.append(
            f"registered but not in OBSERVABILITY.md inventory: {name} "
            f"({', '.join(sorted(set(code[name])))})"
        )
    for name in sorted(docs - set(code)):
        out.append(f"documented but never registered in code: {name}")
    return out


def main() -> int:
    bad = violations()
    if bad:
        sys.stderr.write(
            "metric inventory drift between code and OBSERVABILITY.md:\n"
            + "".join(f"  {v}\n" for v in bad)
        )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
