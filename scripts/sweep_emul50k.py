"""Emulate ONE full sparse sweep at 50k from measured components, vs the
real solver's 15.2 ms/sweep slope — to locate overhead beyond the parts."""
import runpy, sys, time
from functools import partial
from pathlib import Path
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
import jax, jax.numpy as jnp, numpy as np
from jax import lax

bench = runpy.run_path(str(Path(__file__).resolve().parent.parent / "bench.py"))
state, sg = bench["_sparse50k_problem"]()
from kubernetes_rescheduling_tpu.core.sparsegraph import BLOCK_R, sparse_pair_comm_cost
from kubernetes_rescheduling_tpu.solver.sparse_solver import hub_slab
from kubernetes_rescheduling_tpu.ops.fused_admission import fused_score_admission
from kubernetes_rescheduling_tpu.ops.sparse_mass import (
    chunk_local_slabs, hub_neighbor_mass, hub_tile_arrays, sparse_neighbor_mass,
)
SP, N = sg.sp, int(state.num_nodes)
NBR = len(sg.regular_blocks); NHB = len(sg.hub_blocks)
KB = 4
n_chunks = -(-NBR // KB)
ndummy = n_chunks * KB - NBR
SPX = SP + ndummy * BLOCK_R
rng = np.random.default_rng(0)
rv = jnp.asarray((rng.random(SPX) > 0.02).astype(np.float32))
rvu = jnp.where(sg.u_ids < SP, rv[jnp.clip(sg.u_ids, 0, SPX - 1)], 0.0)
toff_ext = jnp.asarray(np.asarray(list(sg.block_toff) + [sg.zero_toff] * ndummy, np.int32))
reg_ext = jnp.asarray(np.asarray(list(sg.regular_blocks) + [sg.num_blocks + d for d in range(ndummy)], np.int32))
cpu_load0 = jnp.asarray(rng.random(N) * 1000, jnp.float32)
mem_load0 = jnp.zeros(N)
cap = jnp.full(N, 2000.0); mem_cap = jnp.full(N, jnp.inf)
node_valid = jnp.ones(N, bool)
svc_cpu = jnp.asarray(rng.random(SPX) * 2, jnp.float32)
svc_mem = jnp.zeros(SPX)
svc_valid = jnp.ones(SPX, bool)
assign0 = jnp.asarray(rng.integers(0, N, size=SPX), jnp.int32)

hub_groups = []
for g in range(0, NHB, KB):
    hb = sg.hub_blocks[g:g+KB]
    ids_g = jnp.asarray(np.concatenate([np.arange(BLOCK_R, dtype=np.int32) + b*BLOCK_R for b in hb]))
    u_g, rvu_g = hub_slab(sg, hb, rv, SPX)
    hub_groups.append((hb, ids_g, u_g, rvu_g, hub_tile_arrays(sg, hb)))

def one_sweep(carry, sweep_key, w_mm):
    assign, cpu_load, mem_load, best_assign, best_obj = carry
    perm_key, noise_key = jax.random.split(sweep_key)
    keys = jax.random.split(noise_key, n_chunks + len(hub_groups))
    chunk_keys = keys[:n_chunks]
    def place(inner, ids, M, chunk_key):
        assign, cpu_load, mem_load = inner
        seed = jax.random.randint(chunk_key, (), 0, 2**31 - 1)
        new_node, admitted, d_cpu, d_mem = fused_score_admission(
            M, assign[ids], svc_cpu[ids], svc_mem[ids], svc_valid[ids],
            cpu_load, mem_load, cap, mem_cap, node_valid,
            0.0, 0.5, seed, enforce_capacity=True, use_noise=True,
            emit_x_rows=False)
        return (assign.at[ids].set(new_node), cpu_load + d_cpu, mem_load + d_mem), admitted
    inner = (assign, cpu_load, mem_load)
    for g, (hb, ids_g, u_g, rvu_g, (hc, hl, ho, hf)) in enumerate(hub_groups):
        assign = inner[0]
        tgt_l = assign[jnp.clip(u_g, 0, SPX-1)]
        M = hub_neighbor_mass(w_mm, tgt_l, rvu_g, hc, hl, ho, hf,
                              num_nodes=N, num_hub_blocks=len(hb), bu=sg.bu)
        M = M * rv[ids_g][:, None]
        inner, _ = place(inner, ids_g, M, keys[n_chunks + g])
    assign, cpu_load, mem_load = inner
    bp = jax.random.permutation(perm_key, n_chunks * KB)
    chunk_blocks = reg_ext[bp].reshape(n_chunks, KB)
    chunk_ids = (chunk_blocks[:, :, None] * BLOCK_R + jnp.arange(BLOCK_R, dtype=jnp.int32)[None, None, :]).reshape(n_chunks, KB * BLOCK_R)
    def chunk_step(inner, xs):
        blocks, ids, ck = xs
        assign = inner[0]
        starts = toff_ext[blocks] * sg.bu
        u_c, rvu_c = chunk_local_slabs(sg.u_ids, rvu, starts, sg.u_reg)
        tgt_c = assign[jnp.clip(u_c, 0, SPX-1)]
        M = sparse_neighbor_mass(w_mm, tgt_c, rvu_c, blocks, toff_ext,
                                 num_nodes=N, bu=sg.bu, reg_tiles=sg.reg_tiles)
        M = M * rv[ids][:, None]
        inner, admitted = place(inner, ids, M, ck)
        return inner, jnp.sum(admitted)
    (assign, _, _), moves = lax.scan(chunk_step, (assign, cpu_load, mem_load),
                                     (chunk_blocks, chunk_ids, chunk_keys), unroll=2)
    a = jnp.where(svc_valid, assign, N)
    cpu_fresh = jnp.zeros((N+1,), jnp.float32).at[a].add(svc_cpu)[:N]
    mem_fresh = jnp.zeros((N+1,), jnp.float32).at[a].add(svc_mem)[:N]
    obj = sparse_pair_comm_cost(sg, assign[:SP], rv[:SP])
    better = obj < best_obj
    best_assign = jnp.where(better, assign, best_assign)
    best_obj = jnp.where(better, obj, best_obj)
    return (assign, cpu_fresh, mem_fresh, best_assign, best_obj), jnp.sum(moves)

def timeit(name, k1=20, k2=80):
    @partial(jax.jit, static_argnames=("kk",))
    def run(a0, g, kk):
        w_mm = g.w_local.astype(jnp.bfloat16)
        carry = (a0, cpu_load0, mem_load0, a0, jnp.float32(1e30))
        def body(c, i):
            return one_sweep(c, jax.random.fold_in(jax.random.PRNGKey(0), i), w_mm)
        c, _ = lax.scan(body, carry, jnp.arange(kk))
        return c[0]
    def best_of(kk, reps=3):
        out = run(assign0, sg, kk); jnp.sum(out).item()
        best = float("inf")
        for _ in range(reps):
            t = time.perf_counter()
            out = run(assign0, sg, kk); jnp.sum(out).item()
            best = min(best, time.perf_counter() - t)
        return best
    ms = (best_of(k2) - best_of(k1)) / (k2 - k1) * 1e3
    print(f"{name:30s} {ms:8.3f} ms/sweep", flush=True)

timeit("EMULATED full sweep")
