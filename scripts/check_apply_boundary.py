#!/usr/bin/env python
"""Static check: the controller fences and pulls only at designated sites.

The wall-clock round arc (ISSUE 9 / ROADMAP item 3) holds only while
every device→host synchronization in the control loops happens at the
two designated boundaries:

- the APPLY boundary — ``bench.round_end.fence`` (one batched
  ``device_get`` of the decision outputs) / ``bench.round_end.block``
  (a completion fence without a transfer, for fenced timings);
- the ROUND-END boundary — ``bench.round_end.RoundCloser.flush`` (ONE
  counted ``round_end`` pull per executed round) and the fleet loop's
  ``_pull_round_bundle`` (its packed decision and metrics bundles).

One stray ``jax.block_until_ready`` / ``jax.device_get`` /
``telemetry.pull`` inside a round helper silently re-introduces the
per-round RTTs the single-bundle protocol removed — the exact failure
mode BENCH_r04/r05 measured as a 4-5× wall-over-device gap. AST-based,
like its sibling ``check_boundary_retry.py``: inside
``bench/controller.py``, ``bench/fleet.py``, and ``bench/scan.py``, a
call named ``block_until_ready``, ``device_get``, or ``pull`` is only
legal inside that file's designated block-boundary fence functions (the
per-file allowlist in ``CHECKED`` — the fleet loop's bundle-pull helper
and the scan module's block pull, which together make "one transfer per
K scanned rounds" statically enforceable). ``bench/round_end.py`` is
the designated home of the real sync primitives and is deliberately not
checked.

Run directly (exit 1 on violation) or through its test twin
(tests/test_apply_boundary.py).
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

PACKAGE = Path(__file__).resolve().parent.parent / "kubernetes_rescheduling_tpu"
BANNED_CALLS = {"block_until_ready", "device_get", "pull"}
# the control loops whose round helpers must stay sync-free outside the
# designated boundaries (round_end.py itself is the designated module):
# file -> functions allowed to contain a banned call in that file
CHECKED: dict[Path, frozenset[str]] = {
    PACKAGE / "bench" / "controller.py": frozenset(),
    # the fleet loop's designated round-end transfer site (ALL fleet
    # planes — greedy, proactive, global — route their single pull here)
    PACKAGE / "bench" / "fleet.py": frozenset({"_pull_round_bundle"}),
    # the scan module's designated block-boundary transfer: ONE counted
    # round_end pull per K-round scan block
    PACKAGE / "bench" / "scan.py": frozenset({"pull_block"}),
    # the multichip harness rides scan.pull_block for its one transfer
    # per sharded block; the module itself must stay sync-free (the
    # device plane's attribution inputs are host-resident by contract)
    PACKAGE / "bench" / "multichip.py": frozenset(),
    # the batched fleet planes must stay sync-free end to end: the
    # forecast diag and the global solver's move bundle ride the fleet
    # loop's one counted pull, never their own
    PACKAGE / "forecast" / "fleet.py": frozenset(),
    PACKAGE / "solver" / "fleet_global.py": frozenset(),
}
# the union, kept as the default for direct find_raw_syncs() callers
ALLOWED_FUNCS = frozenset().union(*CHECKED.values())


def _call_name(node: ast.Call) -> str | None:
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def find_raw_syncs(
    path: Path, allowed: frozenset[str] | None = None
) -> list[tuple[int, str]]:
    """(line, description) pairs for banned sync calls outside the
    designated functions (``allowed`` defaults to the union allowlist)."""
    allowed = ALLOWED_FUNCS if allowed is None else allowed
    tree = ast.parse(path.read_text(), filename=str(path))
    out: list[tuple[int, str]] = []

    def walk(node: ast.AST, func: str | None) -> None:
        for child in ast.iter_child_nodes(node):
            child_func = func
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                child_func = child.name
            if isinstance(child, ast.Call):
                name = _call_name(child)
                if name in BANNED_CALLS and func not in allowed:
                    out.append(
                        (child.lineno, f"{name}(...) in {func or '<module>'}")
                    )
            walk(child, child_func)

    walk(tree, None)
    return out


def violations() -> list[str]:
    return [
        f"{path.relative_to(PACKAGE.parent)}:{line}: {what}"
        for path, allowed in CHECKED.items()
        for line, what in find_raw_syncs(path, allowed)
    ]


def main() -> int:
    bad = violations()
    if bad:
        sys.stderr.write(
            "raw device sync in a controller round helper — route host\n"
            "reads through the apply boundary (bench.round_end.fence/"
            "block)\nor the round-end bundle (RoundCloser.flush / "
            "_pull_round_bundle):\n"
            + "".join(f"  {v}\n" for v in bad)
        )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
