#!/usr/bin/env python
"""Static check: every solver/attribution kernel threads validity masks.

Elastic topologies work because padded array slots are INERT: every
device kernel consuming a padded ``ClusterState``/``CommGraph`` must
read the validity masks (``pod_valid`` / ``node_valid`` /
``service_valid``, or a batched ``tenant_mask``) — directly or through a
helper that does — so masked slots never emit moves and never contribute
cost. A kernel that forgets the masks is bit-exact on unpadded inputs
and silently wrong the first time a shape bucket pads one, which is
exactly the failure mode the mask-twin tests (tests/test_elastic.py)
catch dynamically and this checker catches statically, at the entry
point, before any test runs.

Mechanics (AST, like its siblings ``check_no_print.py`` /
``check_boundary_retry.py``): for every function in the package, collect
(a) mask usage — an attribute read of a mask name, or a ``*mask``
parameter that the body actually reads — and (b) the bare names it
calls. Mask usage then propagates transitively over the call graph,
resolving each call to a SAME-MODULE definition first and falling back
to the package-wide bare name. Every ENTRY_POINT must be defined in the
module it is listed under, ACCEPT mask-carrying arguments (a state/
graph/mask parameter), and REACH mask usage.

Adding a new device kernel? List it in ``ENTRY_POINTS`` — the test twin
(tests/test_mask_threading.py) will hold it to the same rule.

Run directly (exit 1 on violation); with no arguments it self-checks the
repo's own package.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
PACKAGE = ROOT / "kubernetes_rescheduling_tpu"

# module path (relative to the package) -> kernel entry points that MUST
# thread the masks. These are the functions the controller/fleet/metric
# planes hand padded states and graphs to.
ENTRY_POINTS: dict[str, tuple[str, ...]] = {
    "solver/round_loop.py": (
        "decide",
        "decide_explain",
        "round_step",
        "decide_with_forecast",
        "decide_explain_with_forecast",
    ),
    "forecast/model.py": ("forecast_step", "node_loads"),
    "forecast/fleet.py": ("_fleet_forecast_step",),
    "solver/fleet.py": (
        "_fleet_decide",
        "_fleet_decide_proactive",
        "_fleet_metrics",
    ),
    "solver/fleet_global.py": ("_fleet_global_solve",),
    "parallel/fleet.py": (
        "fleet_solve_dp",
        "fleet_solve_proactive_dp",
        "fleet_global_solve_dp",
    ),
    "objectives/metrics.py": (
        "communication_cost",
        "communication_cost_deployment",
        "load_std",
        "node_cpu_pct_rounded",
        "capacity_violation",
        "node_pair_cost_matrix",
        "communication_cost_attribution",
        "communication_cost_edges",
    ),
    "bench/round_end.py": ("round_end_metrics",),
    "backends/sim_device.py": (
        "scheduler_choice",
        "apply_decision",
        "sim_step",
    ),
    "bench/scan.py": ("_scan_rounds", "_fleet_scan_rounds"),
    "telemetry/tripwire.py": ("tripwire_step", "fleet_tripwire_step"),
    "policies/hazard.py": ("detect_hazard",),
    "policies/scoring.py": ("node_features", "policy_scores", "choose_node"),
    "policies/victim.py": ("pick_victim", "deployment_group"),
    "solver/global_solver.py": ("global_assign",),
}

MASK_ATTRS = {"pod_valid", "node_valid", "service_valid"}
MASK_PARAMS = {"tenant_mask", "hazard_mask"}
# parameters that carry masks inside a pytree — an entry point must take
# at least one of these (or a bare mask) to be maskable at all
CARRIER_PARAMS = {
    "state", "states", "st", "removed", "graph", "graphs",
} | MASK_PARAMS


class _FnInfo(ast.NodeVisitor):
    """Per-function facts: mask usage + called bare names."""

    def __init__(self) -> None:
        self.uses_mask = False
        self.calls: set[str] = set()

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if node.attr in MASK_ATTRS:
            self.uses_mask = True
        self.generic_visit(node)

    def visit_Name(self, node: ast.Name) -> None:
        if isinstance(node.ctx, ast.Load) and node.id in MASK_PARAMS:
            self.uses_mask = True
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if isinstance(f, ast.Name):
            self.calls.add(f.id)
        elif isinstance(f, ast.Attribute):
            self.calls.add(f.attr)
        self.generic_visit(node)


def _functions(tree: ast.AST):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _param_names(fn: ast.FunctionDef) -> set[str]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return set(names)


def analyze(package: Path = PACKAGE):
    """(facts, params, defs): facts/params keyed by (module, name) —
    the module being the file's package-relative posix path — plus a
    per-module set of defined function names. Calls resolve to a
    same-module definition FIRST and fall back to any package-wide
    definition by bare name, so a same-named helper in another module
    cannot vouch for a kernel that stopped reading masks itself."""
    facts: dict[tuple[str, str], _FnInfo] = {}
    params: dict[tuple[str, str], set[str]] = {}
    defs: dict[str, set[str]] = {}
    by_name: dict[str, list[tuple[str, str]]] = {}
    for path in sorted(package.rglob("*.py")):
        mod = path.relative_to(package).as_posix()
        defs.setdefault(mod, set())
        tree = ast.parse(path.read_text(), filename=str(path))
        for fn in _functions(tree):
            info = _FnInfo()
            for stmt in fn.body:
                info.visit(stmt)
            key = (mod, fn.name)
            if key in facts:  # re-definition in one module: merge
                facts[key].uses_mask |= info.uses_mask
                facts[key].calls |= info.calls
                params[key] |= _param_names(fn)
            else:
                facts[key] = info
                params[key] = _param_names(fn)
                by_name.setdefault(fn.name, []).append(key)
            defs[mod].add(fn.name)
    # transitive closure: a function that calls a mask-using function
    # uses masks (fixpoint; same-module resolution wins, then any
    # package-wide definition of that bare name)
    changed = True
    while changed:
        changed = False
        for (mod, _name), info in facts.items():
            if info.uses_mask:
                continue
            for c in info.calls:
                if (mod, c) in facts:
                    targets = [(mod, c)]
                else:
                    targets = by_name.get(c, [])
                if any(facts[t].uses_mask for t in targets):
                    info.uses_mask = True
                    changed = True
                    break
    return facts, params, defs


def violations(
    package: Path = PACKAGE,
    entries: dict[str, tuple[str, ...]] | None = None,
) -> list[str]:
    entries = ENTRY_POINTS if entries is None else entries
    facts, params, defs = analyze(package)
    out: list[str] = []
    for mod, fns in sorted(entries.items()):
        mod_path = package / mod
        if not mod_path.is_file():
            out.append(f"{mod}: listed in ENTRY_POINTS but missing")
            continue
        for name in fns:
            # the kernel must be defined IN the module it is listed
            # under — a same-named function elsewhere cannot stand in
            if name not in defs.get(mod, ()):
                out.append(f"{mod}: entry point {name}() not found")
                continue
            key = (mod, name)
            if not (params[key] & CARRIER_PARAMS):
                out.append(
                    f"{mod}: {name}() accepts no mask-carrying argument "
                    f"(expected one of {sorted(CARRIER_PARAMS)})"
                )
            if not facts[key].uses_mask:
                out.append(
                    f"{mod}: {name}() never reaches a validity mask "
                    f"({sorted(MASK_ATTRS | MASK_PARAMS)}) — padded slots "
                    "would not be inert"
                )
    return out


def main() -> int:
    bad = violations()
    if bad:
        sys.stderr.write(
            "kernel entry points that do not thread validity masks:\n"
            + "".join(f"  {v}\n" for v in bad)
        )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
