"""Ablate the REAL sparse solver at 50k via monkeypatches, slope method:
(a) baseline, (b) hub pass removed (timing-only: hub rows simply never
move). Run ON the TPU."""
import runpy, sys, time
from functools import partial
from pathlib import Path
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
import jax, jax.numpy as jnp

bench = runpy.run_path(str(Path(__file__).resolve().parent.parent / "bench.py"))
state, sg = bench["_sparse50k_problem"]()
import kubernetes_rescheduling_tpu.solver.sparse_solver as ss
from kubernetes_rescheduling_tpu.solver import GlobalSolverConfig

def solve_ms(sgraph, sweeps, k1=2, k2=8):
    cfg = GlobalSolverConfig(sweeps=sweeps, swap_every=0)

    @partial(jax.jit, static_argnames=("k",))
    def chained(st0, g, key0, k):
        def body(st, i):
            st_n, inf = ss.global_assign_sparse(
                st, g, jax.random.fold_in(key0, i), cfg
            )
            return st_n, inf["objective_after"]
        return jax.lax.scan(body, st0, jnp.arange(k))

    def timed(k):
        _, objs = chained(state, sgraph, jax.random.PRNGKey(7), k)
        float(objs[-1])
        best = float("inf")
        for rep in range(3):
            t = time.perf_counter()
            _, objs = chained(state, sgraph, jax.random.PRNGKey(8 + rep), k)
            float(objs[-1])
            best = min(best, time.perf_counter() - t)
        return best

    t1 = timed(k1); t2 = timed(k2)
    return (t2 - t1) / (k2 - k1) * 1e3

def run(tag, sgraph):
    s3 = solve_ms(sgraph, 3); s9 = solve_ms(sgraph, 9)
    per = (s9 - s3) / 6
    print(f"{tag:24s} s3={s3:7.1f} s9={s9:7.1f}  per-sweep={per:6.2f} fixed={s3-3*per:6.1f}", flush=True)

run("baseline", sg)
# The "objective zeroed" variants were removed twice over: (a) their
# monkeypatch of ss.sparse_pair_comm_cost was silently defeated by the
# inner jit's trace cache (the first recorded run re-measured the
# baseline — found by review; any future ablation of a jitted solver
# needs jax.clear_caches() between variants), and (b) the per-sweep
# objective no longer calls that module global at all — it is the
# precomputed rv-weighted cut-sum (core.sparsegraph.edge_cut_sum),
# measured at ~0.2 ms/sweep, so the question the variant asked is
# answered in RESULTS.md ("The 50k fixed-cost hunt").
sg_nohub = sg.replace(hub_blocks=())
jax.clear_caches()
run("no hub pass", sg_nohub)
