#!/usr/bin/env python3
"""Perf probe: device-side per-round latency (slope method) for solver
variants, to attribute time between the chunk loop, the per-sweep
objective, and the epilogue kernels. Not part of the public API.

Usage: python scripts/perf_probe.py [chunk_size ...]
Env:   PROBE_SWEEPS (default 8), PROBE_SCENARIO (default large)
"""

from __future__ import annotations

import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp


def main() -> int:
    chunks = [int(a) for a in sys.argv[1:]] or [1024, 1020]
    sweeps = int(os.environ.get("PROBE_SWEEPS", "8"))
    scenario = os.environ.get("PROBE_SCENARIO", "large")

    from kubernetes_rescheduling_tpu.bench.harness import make_backend
    from kubernetes_rescheduling_tpu.solver import GlobalSolverConfig, global_assign

    backend = make_backend(scenario, seed=0)
    state = backend.monitor()
    graph = backend.comm_graph()

    @partial(jax.jit, static_argnames=("k", "cfg"))
    def chained(st0, g, key0, k, cfg):
        def body(st_c, i):
            st_n, inf_n = global_assign(st_c, g, jax.random.fold_in(key0, i), cfg)
            return st_n, inf_n["objective_after"]

        return jax.lax.scan(body, st0, jnp.arange(k))

    def slope_ms(cfg):
        def timed(k):
            _, objs = chained(state, graph, jax.random.PRNGKey(7), k, cfg)
            float(objs[-1])  # warm
            t = time.perf_counter()
            _, objs = chained(state, graph, jax.random.PRNGKey(8), k, cfg)
            float(objs[-1])
            return time.perf_counter() - t

        k1, k2 = 2, 12
        return (timed(k2) - timed(k1)) / (k2 - k1) * 1e3

    for c in chunks:
        cfg = GlobalSolverConfig(sweeps=sweeps, chunk_size=c)
        ms = slope_ms(cfg)
        _, inf = global_assign(state, graph, jax.random.PRNGKey(0), cfg)
        print(
            f"chunk={c:5d} sweeps={sweeps} device_ms={ms:8.2f} "
            f"obj_after={float(inf['objective_after']):10.1f}"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
