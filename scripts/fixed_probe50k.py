"""Probe the 50k solve's FIXED-cost suspects: the pod-level comm cost
scan, the sorted-space prologue, and per-sweep threefry chatter."""
import runpy, sys, time
from functools import partial
from pathlib import Path
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
import jax, jax.numpy as jnp, numpy as np

bench = runpy.run_path(str(Path(__file__).resolve().parent.parent / "bench.py"))
state, sg = bench["_sparse50k_problem"]()
from kubernetes_rescheduling_tpu.solver.sparse_solver import (
    sparse_pod_comm_cost, sorted_problem_arrays,
)
SP = sg.sp
N = int(state.num_nodes)
E2 = sg.edges_src.shape[0]
print(f"E2={E2} P={state.num_pods}", flush=True)
rng = np.random.default_rng(0)
assign0 = jnp.asarray(rng.integers(0, N, size=SP), jnp.int32)

def timeit(name, step, k1=20, k2=120):
    @partial(jax.jit, static_argnames=("kk",))
    def run(a0, st, g, kk):
        def body(a, i):
            return step(a, i, st, g), 0
        a, _ = jax.lax.scan(body, a0, jnp.arange(kk))
        return a
    def best_of(kk, reps=3):
        out = run(assign0, state, sg, kk); jnp.sum(out).item()
        best = float("inf")
        for _ in range(reps):
            t = time.perf_counter()
            out = run(assign0, state, sg, kk); jnp.sum(out).item()
            best = min(best, time.perf_counter() - t)
        return best
    ms = (best_of(k2) - best_of(k1)) / (k2 - k1) * 1e3
    print(f"{name:34s} {ms:8.4f} ms/iter", flush=True)

# 1. pod-level comm cost (the obj_true0 / info twin)
def pod_cost_step(a, i, st, g):
    st2 = st.replace(pod_node=jnp.where(st.pod_valid, a[:st.num_pods] % N, st.pod_node))
    return a.at[0].set(sparse_pod_comm_cost(st2, g).astype(jnp.int32) % N)
timeit("pod-level comm cost", pod_cost_step)

# 2. sorted-space prologue (aggregates + gathers + rvu)
def prologue_step(a, i, st, g):
    st2 = st.replace(pod_node=jnp.where(st.pod_valid, a[:st.num_pods] % N, st.pod_node))
    sv, sc, sm, cu, rv_s, rvu = sorted_problem_arrays(st2, g, SP)
    return a.at[0].set((jnp.sum(rv_s) + jnp.sum(rvu)).astype(jnp.int32) % N)
timeit("sorted prologue (aggr+rvu)", prologue_step)

# 3. W cast to bf16
def cast_step(a, i, st, g):
    w = (g.w_local * (1.0 + 0.0 * a[0])).astype(jnp.bfloat16)
    return a.at[0].set(jnp.sum(w[:, :8]).astype(jnp.int32) % N)
timeit("W cast f32->bf16", cast_step)

# 4. per-sweep threefry chatter: split(50) + 50 randints + permutation
def rng_step(a, i, st, g):
    key = jax.random.fold_in(jax.random.PRNGKey(0), a[0])
    pk, nk = jax.random.split(key)
    keys = jax.random.split(nk, 50)
    tot = jnp.int32(0)
    for c in range(50):
        tot = tot + jax.random.randint(keys[c], (), 0, 2**31 - 1)
    bp = jax.random.permutation(pk, 160)
    return a.at[0].set((tot + jnp.sum(bp)) % N)
timeit("sweep PRNG (split+50 randint)", rng_step)

# 5. ONE randint
def rng1_step(a, i, st, g):
    key = jax.random.fold_in(jax.random.PRNGKey(0), a[0])
    return a.at[0].set(jax.random.randint(key, (), 0, 2**31 - 1) % N)
timeit("one fold_in+randint", rng1_step)
print("OK", flush=True)
