"""Verify the sparse solver end-to-end on the real TPU at flagship scale.

Drives: SparseCommGraph build (10k services), global_assign_sparse on the
chip (real Mosaic lowering of sparse_neighbor_mass / hub_neighbor_mass /
fused_score_admission), never-worse + improvement checks, and a rough
fenced timing + objective comparison against the dense solver.
"""

import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax
import numpy as np

from kubernetes_rescheduling_tpu.core import sparsegraph
from kubernetes_rescheduling_tpu.core.topology import large_10000x1000
from kubernetes_rescheduling_tpu.objectives import communication_cost
from kubernetes_rescheduling_tpu.solver import (
    GlobalSolverConfig,
    global_assign,
    global_assign_sparse,
)

print("devices:", jax.devices())
scn = large_10000x1000()
t0 = time.perf_counter()
sg = sparsegraph.from_comm_graph(scn.graph)
print(
    f"sparse build: {time.perf_counter()-t0:.2f}s  blocks={sg.num_blocks} "
    f"hub={len(sg.hub_blocks)} reg={len(sg.regular_blocks)} "
    f"TU={sg.w_local.shape[1]} weight_MB={sg.weight_bytes()/2**20:.1f} "
    f"(dense would be {sg.sp*sg.sp*6/2**20:.0f} MB)"
)

cfg = GlobalSolverConfig()
key = jax.random.PRNGKey(0)
before = float(communication_cost(scn.state, scn.graph))

t0 = time.perf_counter()
new_sp, info_sp = global_assign_sparse(scn.state, sg, key, cfg)
jax.block_until_ready(new_sp.pod_node)
print(f"sparse first call (compile+run): {time.perf_counter()-t0:.1f}s")
for _ in range(3):
    t0 = time.perf_counter()
    new_sp, info_sp = global_assign_sparse(scn.state, sg, key, cfg)
    jax.block_until_ready(new_sp.pod_node)
    print(f"sparse warm fenced: {(time.perf_counter()-t0)*1e3:.1f} ms")
after_sp = float(communication_cost(new_sp, scn.graph))

t0 = time.perf_counter()
new_d, info_d = global_assign(scn.state, scn.graph, key, cfg)
jax.block_until_ready(new_d.pod_node)
print(f"dense first call (compile+run): {time.perf_counter()-t0:.1f}s")
for _ in range(3):
    t0 = time.perf_counter()
    new_d, info_d = global_assign(scn.state, scn.graph, key, cfg)
    jax.block_until_ready(new_d.pod_node)
    print(f"dense warm fenced: {(time.perf_counter()-t0)*1e3:.1f} ms")
after_d = float(communication_cost(new_d, scn.graph))

print(f"comm cost before={before:.0f} sparse_after={after_sp:.0f} dense_after={after_d:.0f}")
print(
    "sparse obj:", float(info_sp["objective_before"]),
    "->", float(info_sp["objective_after"]),
    "improved:", bool(info_sp["improved"]),
    "hub_pass:", bool(info_sp["hub_pass"]),
)
assert after_sp <= before, "never-worse violated"
assert after_sp < before * 0.9, "expected a substantial improvement"
print("OK")
