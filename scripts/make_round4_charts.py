"""Regenerate the round-4 charts.

Frontier rows: pass a path to scripts/frontier.py's JSON-lines output as
argv[1] to plot a fresh matrix run; with no argument the MEASURED
2026-07-31 rows below are used (provenance in RESULTS.md — the full
9-config run, including the redundant mc4.0/mc8.0 points that coincide
with mc2.0). Scale points are the slope-method device readings.
"""

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from kubernetes_rescheduling_tpu.bench.plots import (
    plot_disruption_frontier,
    plot_scale_curve,
)

FRONTIER = [
    {"config": "uncapped", "restarts": 23.3, "error_rate_during": 0.1857,
     "communication_cost": 3.67, "response_time_ms": 56.59},
    {"config": "cap1", "restarts": 14.3, "error_rate_during": 0.1277,
     "communication_cost": 5.67, "response_time_ms": 62.24},
    {"config": "cap2", "restarts": 16.3, "error_rate_during": 0.1422,
     "communication_cost": 5.33, "response_time_ms": 61.3},
    {"config": "cap4", "restarts": 14.0, "error_rate_during": 0.1238,
     "communication_cost": 5.67, "response_time_ms": 62.24},
    {"config": "mc0.5", "restarts": 14.0, "error_rate_during": 0.1252,
     "communication_cost": 4.0, "response_time_ms": 57.53},
    {"config": "mc2.0", "restarts": 0.0, "error_rate_during": 0.0,
     "communication_cost": 0.0, "response_time_ms": 205.78},
    {"config": "mc4.0", "restarts": 0.0, "error_rate_during": 0.0,
     "communication_cost": 0.0, "response_time_ms": 205.78},
    {"config": "mc8.0", "restarts": 0.0, "error_rate_during": 0.0,
     "communication_cost": 0.0, "response_time_ms": 205.78},
]

SCALE = [
    {"scale": "2k×200", "services": 2_000, "solver": "dense", "ms": 4.2},
    {"scale": "10k×1k", "services": 10_000, "solver": "dense", "ms": 31.3},
    {"scale": "20k×2k", "services": 20_000, "solver": "dense", "ms": 159.0},
    {"scale": "10k×1k", "services": 10_000, "solver": "sparse", "ms": 29.7},
    {"scale": "20k×2k", "services": 20_000, "solver": "sparse", "ms": 58.3},
    {"scale": "50k×2k", "services": 50_000, "solver": "sparse", "ms": 148.8},
    {"scale": "50k×2k", "services": 50_000, "solver": "dense", "ms": None},
    {"scale": "100k×4k", "services": 100_000, "solver": "sparse", "ms": 358.6},
    {"scale": "100k×4k", "services": 100_000, "solver": "dense", "ms": None},
]

rows = FRONTIER
if len(sys.argv) > 1:
    rows = [
        json.loads(line)
        for line in Path(sys.argv[1]).read_text().splitlines()
        if line.strip()
    ]

out = Path(__file__).resolve().parent.parent / "result" / "charts"
print(plot_disruption_frontier(rows, out))
print(plot_scale_curve(SCALE, out))
