#!/usr/bin/env python
"""Static check: no bare ``print()`` inside the library.

Everything under ``kubernetes_rescheduling_tpu/`` reports through the
structured logger or the telemetry registry; stdout belongs to the CLI
(``cli.py``), whose JSON output a pipeline consumes — one stray debug
print inside the package corrupts it. AST-based (not grep) so comments,
strings, and methods NAMED print don't false-positive.

Run directly (exit 1 on violation) or through its test twin
(tests/test_no_print.py).
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

PACKAGE = Path(__file__).resolve().parent.parent / "kubernetes_rescheduling_tpu"
# stdout is the CLI's output channel — the one module allowed to print
ALLOWED = {PACKAGE / "cli.py"}


def find_bare_prints(path: Path) -> list[int]:
    """Line numbers of ``print(...)`` calls on the builtin name."""
    tree = ast.parse(path.read_text(), filename=str(path))
    return [
        node.lineno
        for node in ast.walk(tree)
        if isinstance(node, ast.Call)
        and isinstance(node.func, ast.Name)
        and node.func.id == "print"
    ]


def violations() -> list[str]:
    out = []
    for path in sorted(PACKAGE.rglob("*.py")):
        if path in ALLOWED:
            continue
        for lineno in find_bare_prints(path):
            out.append(f"{path.relative_to(PACKAGE.parent)}:{lineno}")
    return out


def main() -> int:
    bad = violations()
    if bad:
        sys.stderr.write(
            "bare print() outside the CLI — route through the structured "
            "logger or the telemetry registry:\n"
            + "".join(f"  {v}\n" for v in bad)
        )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
