"""Objective vs latency budget at 10k x 1k (TPU).

Measures (a) the autotuner's per-sweep/fixed cost model, (b) the solve
objective as a function of sweep count — composing them gives the
objective-vs-budget curve that justifies the --latency-budget default and
re-justifies the 9-sweep default against the measured quality curve.
"""

import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax

from kubernetes_rescheduling_tpu.bench.harness import make_backend
from kubernetes_rescheduling_tpu.solver import GlobalSolverConfig, global_assign
from kubernetes_rescheduling_tpu.solver.autotune import (
    _device_ms_per_round,
    tune_sweeps,
)

backend = make_backend("large", seed=0)
state = backend.monitor()
graph = backend.comm_graph()
cfg = GlobalSolverConfig()

tuned, info = tune_sweeps(state, graph, cfg, 100.0)
print("autotune@100ms:", json.dumps(info))

for s in (3, 6, 9, 18, 36):
    c = cfg.replace(sweeps=s)
    # objective after a 3-round chain (the controller regime), exact value
    st = state
    inf = None
    for i in range(3):
        st, inf = global_assign(st, graph, jax.random.PRNGKey(40 + i), c)
    obj = float(inf["objective_after"])
    ms = info["fixed_ms"] + s * info["per_sweep_ms"]
    print(json.dumps({"sweeps": s, "pred_ms": round(ms, 1), "objective_3rounds": obj}))
