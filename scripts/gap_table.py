"""Mid-scale optimality-gap table: solver comm cost vs the MILP optimum.

Reproduces RESULTS.md's round-4 methodology: power-law instances with
capacity 1.4x the mean node load (binding), solver at the default config
vs the HiGHS MILP optimum/incumbent (180 s cap). Adds the round-5 axis:
the pairwise-swap phase (GlobalSolverConfig.swap_every) on/off at EQUAL
sweep budget, plus a chunk-size sensitivity column (small instances
auto-chunk to ~S/10, which limits how many pairs each swap phase can
see).

CPU-friendly. Run: JAX_PLATFORMS=cpu python scripts/gap_table.py
"""

import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

from kubernetes_rescheduling_tpu.core.topology import synthetic_scenario
from kubernetes_rescheduling_tpu.objectives.metrics import communication_cost
from kubernetes_rescheduling_tpu.oracle.optimum import milp_optimum
from kubernetes_rescheduling_tpu.solver.global_solver import (
    GlobalSolverConfig,
    global_assign,
)

INSTANCES = [(40, 5), (60, 6), (100, 6)]
MILP_CAP_S = 180.0


def solve_comm(state, graph, sweeps, swap_every, chunk_size=0, seed=0):
    cfg = GlobalSolverConfig(
        sweeps=sweeps, swap_every=swap_every, chunk_size=chunk_size
    )
    new_state, _ = global_assign(state, graph, jax.random.PRNGKey(seed), cfg)
    return float(communication_cost(new_state, graph))


def main():
    rows = []
    for S, N in INSTANCES:
        cap_m = 1.4 * S * 100.0 / N
        sc = synthetic_scenario(
            n_pods=S, n_nodes=N, powerlaw=True, mean_degree=4.0, seed=0,
            node_cpu_cap_m=cap_m,
        )
        t0 = time.time()
        milp, proven = milp_optimum(sc.state, sc.graph, time_limit_s=MILP_CAP_S)
        milp_s = time.time() - t0
        row = {
            "instance": f"{S}x{N}",
            "milp": milp,
            "proven": bool(proven),
            "milp_s": round(milp_s, 1),
        }
        for sweeps in (9, 27):
            for tag, swap_every, chunk in [
                ("nosw", 0, 0),
                ("sw3", 3, 0),
                ("sw1", 1, 0),
                ("sw1_bigC", 1, S),
            ]:
                comm = solve_comm(sc.state, sc.graph, sweeps, swap_every, chunk)
                row[f"s{sweeps}_{tag}"] = comm
                row[f"s{sweeps}_{tag}_gap%"] = round(
                    100.0 * (comm - milp) / max(milp, 1e-9), 1
                )
        rows.append(row)
        print(json.dumps(row), flush=True)
    return rows


if __name__ == "__main__":
    main()
    sys.exit(0)
