"""Loadgen constant sensitivity: are the claimed policy ORDERINGS stable?

RESULTS.md quotes absolute milliseconds from the simulated client fleet
(bench/loadgen.py), whose proc/hop/jitter/drop constants are plausible
but uncalibrated (no live cluster exists in this environment — reference
release1.sh measures a real one). What the charts actually CLAIM is the
ordering: comm-optimized placements beat the cordon pile-up and beat a
random spread on response time. This sweep perturbs every constant
across wide ranges (hop-remote/local ratio 5-50x, per-service cost
0.5-5 ms, jitter sigma up to 0.5, drop onset 0.7-1.0) and records
whether the ordering holds at each corner.

CPU-friendly: JAX_PLATFORMS=cpu python scripts/loadgen_sensitivity.py
"""

import itertools
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

from kubernetes_rescheduling_tpu.bench.harness import make_backend
from kubernetes_rescheduling_tpu.bench.loadgen import LoadGenConfig, LoadGenerator
from kubernetes_rescheduling_tpu.core.workmodel import mubench_workmodel_c
from kubernetes_rescheduling_tpu.solver import GlobalSolverConfig, global_assign


def placements():
    """Three placements of the µBench scenario, fixed across the sweep —
    MONITORED THROUGH THE SIM BACKEND, exactly like the harness: the
    backend's load model couples placement to node utilization (the
    pile-up drives its node to ~85% CPU), which is where the queueing and
    overload terms the latency claims rest on come from. Raw
    request-based states would read a few % utilization everywhere and
    make total colocation trivially 'win'."""
    import jax.numpy as jnp

    def monitored(pod_node_by_name=None, solve=False):
        backend = make_backend("mubench", seed=0)
        backend.inject_imbalance(backend.node_names[0])
        st = backend.monitor()
        if solve:
            after, _ = global_assign(
                st, backend.comm_graph(), jax.random.PRNGKey(0),
                GlobalSolverConfig(
                    sweeps=9, balance_weight=0.5, enforce_capacity=True,
                    capacity_frac=0.5,
                ),
            )
            backend.restore_placement(after)
            st = backend.monitor()
        elif pod_node_by_name is not None:
            st = backend.monitor()
            rng = np.random.default_rng(1)
            rand = st.replace(
                pod_node=jnp.asarray(
                    np.where(
                        np.asarray(st.pod_valid),
                        rng.integers(0, st.num_nodes, st.num_pods),
                        np.asarray(st.pod_node),
                    ),
                    jnp.int32,
                )
            )
            backend.restore_placement(rand)
            st = backend.monitor()
        return st

    return {
        "pileup": monitored(),
        "global": monitored(solve=True),
        "random": monitored(pod_node_by_name="random"),
    }


def main():
    wm = mubench_workmodel_c()
    states = placements()
    grid = {
        "proc_ms": [0.5, 1.5, 5.0],
        "hop_remote_ms": [1.0, 3.0, 10.0],
        "jitter_sigma": [0.05, 0.15, 0.5],
        "drop_rho": [0.7, 1.0],
    }
    rows, violations = [], 0
    for pm, hr, js, dr in itertools.product(*grid.values()):
        cfg = LoadGenConfig(
            proc_ms=pm, hop_remote_ms=hr, jitter_sigma=js, drop_rho=dr,
            requests_per_phase=4000,
        )
        gen = LoadGenerator(wm, cfg)
        lat = {
            name: gen.measure(st, jax.random.PRNGKey(2)).latency_avg_ms
            for name, st in states.items()
        }
        ordered = lat["global"] < lat["pileup"] and lat["global"] < lat["random"]
        violations += 0 if ordered else 1
        rows.append(
            {
                "proc_ms": pm, "hop_remote_ms": hr, "jitter_sigma": js,
                "drop_rho": dr,
                **{k: round(v, 2) for k, v in lat.items()},
                "ordering_holds": ordered,
            }
        )
        print(json.dumps(rows[-1]), flush=True)
    print(
        json.dumps(
            {"corners": len(rows), "ordering_violations": violations}
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
