"""Loadgen constant sensitivity: are the claimed policy ORDERINGS stable?

RESULTS.md quotes absolute milliseconds from the simulated client fleet
(bench/loadgen.py), whose proc/hop/jitter/drop constants are plausible
but uncalibrated (no live cluster exists in this environment — reference
release1.sh measures a real one). What the charts actually CLAIM is the
ordering: comm-optimized placements beat the cordon pile-up and beat a
random spread on response time. This sweep perturbs every constant
across wide ranges (hop-remote/local ratio 5-50x, per-service cost
0.5-5 ms, jitter sigma up to 0.5, drop onset 0.7-1.0) and records
whether the ordering holds at each corner.

CPU-friendly: JAX_PLATFORMS=cpu python scripts/loadgen_sensitivity.py
"""

import itertools
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

from kubernetes_rescheduling_tpu.bench.harness import (
    mubench_reference_placements,
)
from kubernetes_rescheduling_tpu.bench.loadgen import LoadGenConfig, LoadGenerator
from kubernetes_rescheduling_tpu.core.workmodel import mubench_workmodel_c


def main():
    wm = mubench_workmodel_c()
    states = mubench_reference_placements()
    grid = {
        "proc_ms": [0.5, 1.5, 5.0],
        "hop_remote_ms": [1.0, 3.0, 10.0],
        "jitter_sigma": [0.05, 0.15, 0.5],
        "drop_rho": [0.7, 1.0],
    }
    rows, violations = [], 0
    for pm, hr, js, dr in itertools.product(*grid.values()):
        cfg = LoadGenConfig(
            proc_ms=pm, hop_remote_ms=hr, jitter_sigma=js, drop_rho=dr,
            requests_per_phase=4000,
        )
        gen = LoadGenerator(wm, cfg)
        lat = {
            name: gen.measure(st, jax.random.PRNGKey(2)).latency_avg_ms
            for name, st in states.items()
        }
        ordered = lat["global"] < lat["pileup"] and lat["global"] < lat["random"]
        violations += 0 if ordered else 1
        rows.append(
            {
                "proc_ms": pm, "hop_remote_ms": hr, "jitter_sigma": js,
                "drop_rho": dr,
                **{k: round(v, 2) for k, v in lat.items()},
                "ordering_holds": ordered,
            }
        )
        print(json.dumps(rows[-1]), flush=True)
    print(
        json.dumps(
            {"corners": len(rows), "ordering_violations": violations}
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
