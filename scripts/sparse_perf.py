"""Slope-method device timing: sparse vs dense solver at scale.

Measures pure device ms/round (chained solves inside one jitted scan,
fenced once; slope between K=2 and K=12 removes dispatch+RTT) for:
  - 10k x 1k (flagship `large`): sparse vs dense head-to-head
  - 20k x 2k (`xlarge`): sparse vs the round-3 dense 159 ms
  - 50k x 2k: sparse only (dense raises its sizing error here)
"""

import sys
import time
from functools import partial
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_rescheduling_tpu.core import sparsegraph
from kubernetes_rescheduling_tpu.core.topology import (
    _random_workmodel,
    state_from_workmodel,
    synthetic_scenario,
)
from kubernetes_rescheduling_tpu.solver import (
    GlobalSolverConfig,
    global_assign,
    global_assign_sparse,
)

cfg = GlobalSolverConfig()


def slope(fn, state, gr, k1=2, k2=12):
    @partial(jax.jit, static_argnames=("k",))
    def chained(st0, g, key0, k):
        def body(st_c, i):
            st_n, inf = fn(st_c, g, jax.random.fold_in(key0, i), cfg)
            return st_n, inf["objective_after"]

        return jax.lax.scan(body, st0, jnp.arange(k))

    obj = [None]

    def timed(k):
        _, objs = chained(state, gr, jax.random.PRNGKey(7), k)
        o = float(objs[-1])  # warm-up/compile + completion fence
        if obj[0] is None:
            obj[0] = o  # first call = k2: the longest-chain objective
        best = float("inf")
        for rep in range(3):
            t = time.perf_counter()
            _, objs = chained(state, gr, jax.random.PRNGKey(8 + rep), k)
            float(objs[-1])
            best = min(best, time.perf_counter() - t)
        return best

    return (timed(k2) - timed(k1)) / (k2 - k1) * 1e3, obj[0]


def build_sparse_scenario(n_services, n_nodes, seed=0):
    rng = np.random.default_rng(seed)
    t0 = time.perf_counter()
    wm = _random_workmodel(n_services, rng, powerlaw=True, mean_degree=4.0)
    sg = sparsegraph.from_workmodel(wm)
    build_s = time.perf_counter() - t0
    state = state_from_workmodel(
        wm,
        node_names=[f"w{i:05d}" for i in range(n_nodes)],
        node_cpu_cap_m=2_000.0 * (n_services / n_nodes) / 10.0,
        seed=seed,
    )
    return state, sg, build_s


# ---- 10k x 1k head-to-head ----
scn = synthetic_scenario(
    n_pods=10_000, n_nodes=1_000, powerlaw=True, mean_degree=4.0, seed=0,
    node_cpu_cap_m=2_000.0,
)
sg = sparsegraph.from_comm_graph(scn.graph)
print(
    f"10k graph: hub={len(sg.hub_blocks)} TU={sg.w_local.shape[1]} "
    f"MB={sg.weight_bytes()/2**20:.0f}"
)
d_ms, d_obj = slope(global_assign, scn.state, scn.graph)
print(f"10k x 1k dense : {d_ms:7.2f} ms/round  obj10={d_obj:.0f}")
s_ms, s_obj = slope(global_assign_sparse, scn.state, sg)
print(f"10k x 1k sparse: {s_ms:7.2f} ms/round  obj10={s_obj:.0f}")

# ---- 20k x 2k ----
state20, sg20, bs = build_sparse_scenario(20_000, 2_000, seed=1)
print(f"20k build {bs:.1f}s hub={len(sg20.hub_blocks)} MB={sg20.weight_bytes()/2**20:.0f}")
s_ms, s_obj = slope(global_assign_sparse, state20, sg20)
print(f"20k x 2k sparse: {s_ms:7.2f} ms/round  obj10={s_obj:.0f}  (dense r3: 159 ms)")

# ---- 50k x 2k ----
state50, sg50, bs = build_sparse_scenario(50_000, 2_000, seed=2)
print(f"50k build {bs:.1f}s hub={len(sg50.hub_blocks)} MB={sg50.weight_bytes()/2**20:.0f}")
s_ms, s_obj = slope(global_assign_sparse, state50, sg50, k1=2, k2=8)
print(f"50k x 2k sparse: {s_ms:7.2f} ms/round  obj10={s_obj:.0f}  (dense: sizing error)")
print("OK")
