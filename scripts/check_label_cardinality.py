#!/usr/bin/env python
"""Static check: unbounded-identity label keys stay out of metric space.

The cardinality budget (OBSERVABILITY.md "Fleet observability") is only
enforceable if per-tenant metric series cannot come into existence
anywhere BUT the budget-gated gateway: one stray
``registry.counter(..., labelnames=("tenant",))`` call site re-creates
the O(T) series explosion the budget exists to prevent, silently and
permanently (registry children are memoized forever). Same story for
``service``/``pod`` label keys — service and pod names are unbounded
identity spaces (PR 5's convention: names ride event payloads and
rank-labeled values, never label KEYS).

This checker walks every ``.counter(...)`` / ``.gauge(...)`` /
``.histogram(...)`` call in ``kubernetes_rescheduling_tpu/`` (AST, not
regex — multi-line calls and keyword/positional ``labelnames`` both
resolve) and fails if any registers a label key from
``UNBOUNDED_LABELS`` outside the allowlisted budget-gated helpers in
``telemetry/fleet_rollup.py``. A ``labelnames`` argument that is not a
literal tuple/list is also flagged outside the allowlist — a
dynamically built label set cannot be audited statically.

Run directly (exit 1 on violations) or through its test twin
(tests/test_label_cardinality.py); the no-args self-check over the
checked-in tree must stay green.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
PACKAGE = ROOT / "kubernetes_rescheduling_tpu"

# identity spaces that grow with the workload: tenants, services, pods —
# and devices, which are physically bounded per host but unbounded
# across a fleet of meshes (a pod-scale dp mesh is exactly the blast
# radius ObsConfig.device_label_budget exists for)
UNBOUNDED_LABELS = ("tenant", "service", "pod", "device")

# the budget-gated helpers — THE legal homes for tenant-/device-labeled
# registrations (telemetry.fleet_rollup.TenantSeries and
# telemetry.mesh.DeviceSeries; costmodel's memory_stats sampler
# predates the device budget and is bounded by jax.local_devices())
ALLOWED_FILES = (
    "kubernetes_rescheduling_tpu/telemetry/fleet_rollup.py",
    "kubernetes_rescheduling_tpu/telemetry/mesh.py",
    "kubernetes_rescheduling_tpu/telemetry/costmodel.py",
)

_REGISTER_METHODS = ("counter", "gauge", "histogram")


def _labelnames_node(call: ast.Call) -> ast.AST | None:
    """The labelnames argument of one registration call, keyword or
    positional (counter(name, help, labelnames))."""
    for kw in call.keywords:
        if kw.arg == "labelnames":
            return kw.value
    if len(call.args) >= 3:
        return call.args[2]
    return None


def _literal_strings(node: ast.AST) -> list[str] | None:
    """The label keys when the node is a literal tuple/list of string
    constants; None when it cannot be statically read."""
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                out.append(elt.value)
            else:
                return None
        return out
    if isinstance(node, ast.Constant) and node.value == ():
        return []
    return None


def scan_source(text: str, rel_path: str) -> list[str]:
    """Violations in one module's source (``rel_path`` is repo-relative,
    used for the allowlist and the messages)."""
    if rel_path.replace("\\", "/") in ALLOWED_FILES:
        return []
    try:
        tree = ast.parse(text)
    except SyntaxError as e:  # pragma: no cover - the suite parses
        return [f"{rel_path}: unparseable ({e})"]
    out: list[str] = []
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _REGISTER_METHODS
        ):
            continue
        ln = _labelnames_node(node)
        if ln is None:
            continue
        keys = _literal_strings(ln)
        if keys is None:
            out.append(
                f"{rel_path}:{node.lineno}: .{node.func.attr}() labelnames "
                f"is not a literal tuple/list of strings — unauditable "
                f"label keys are only allowed in the budget-gated helpers "
                f"({', '.join(ALLOWED_FILES)})"
            )
            continue
        bad = [k for k in keys if k in UNBOUNDED_LABELS]
        if bad:
            out.append(
                f"{rel_path}:{node.lineno}: .{node.func.attr}() registers "
                f"unbounded-identity label key(s) {bad} — per-tenant/"
                f"service/pod series may only be created through the "
                f"budget-gated helpers in {ALLOWED_FILES[0]} "
                f"(telemetry.fleet_rollup.TenantSeries)"
            )
    return out


def violations() -> list[str]:
    out: list[str] = []
    for path in sorted(PACKAGE.rglob("*.py")):
        out.extend(
            scan_source(path.read_text(), str(path.relative_to(ROOT)))
        )
    return out


def main() -> int:
    bad = violations()
    if bad:
        sys.stderr.write(
            "unbounded-identity label keys outside the budget-gated "
            "helpers:\n" + "".join(f"  {v}\n" for v in bad)
        )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
