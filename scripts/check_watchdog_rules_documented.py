#!/usr/bin/env python
"""Static check: every watchdog/SLO rule name has a doc-table row.

The rule inventory (`RULE_*` constants in ``telemetry/watchdog.py`` and
``telemetry/slo.py``) is the vocabulary of every /healthz verdict,
``slo_violations_total{rule}`` label, and flight-recorder trigger — an
operator reading an alert looks the rule up in OBSERVABILITY.md's "SLO
watchdog" table. Both directions drift silently: a new rule shipped
without a row is an undocumented page, and a renamed rule leaves a
ghost row describing nothing. This checker pins both, in the style of
``check_metrics_documented.py``.

Usage:
    python scripts/check_watchdog_rules_documented.py

Exits 1 listing undocumented rules and ghost rows. The test twin
(tests/test_watchdog_rules_documented.py) runs the same ``violations()``
no-args self-check plus synthetic drift cases through the text-taking
helpers.
"""

from __future__ import annotations

import re
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
RULE_SOURCES = (
    ROOT / "kubernetes_rescheduling_tpu" / "telemetry" / "watchdog.py",
    ROOT / "kubernetes_rescheduling_tpu" / "telemetry" / "slo.py",
)
DOC = ROOT / "OBSERVABILITY.md"

# module-level RULE_* constants bound to a string literal — the one
# registration idiom both modules use
_RULE_DEF = re.compile(r'^RULE_[A-Z0-9_]+\s*=\s*"([a-z0-9_]+)"', re.M)
_BACKTICKED = re.compile(r"`([a-z0-9_]+)`")


def registered_rules(sources: list[str]) -> set[str]:
    """Rule names bound to ``RULE_*`` constants in the given sources."""
    out: set[str] = set()
    for text in sources:
        out.update(_RULE_DEF.findall(text))
    return out


def documented_rules(doc_text: str) -> set[str]:
    """Backticked names in the FIRST column of the "SLO watchdog"
    section's table rows (header/divider rows carry no backticks)."""
    out: set[str] = set()
    in_section = False
    for line in doc_text.splitlines():
        if line.startswith("## "):
            in_section = line.strip() == "## SLO watchdog"
            continue
        if not in_section or not line.startswith("|"):
            continue
        cells = line.split("|")
        if len(cells) < 2:
            continue
        m = _BACKTICKED.search(cells[1])
        if m:
            out.add(m.group(1))
    return out


def violations(
    sources: list[str] | None = None, doc_text: str | None = None
) -> list[str]:
    if sources is None:
        sources = [p.read_text() for p in RULE_SOURCES]
    if doc_text is None:
        doc_text = DOC.read_text()
    rules = registered_rules(sources)
    documented = documented_rules(doc_text)
    out = [
        f"rule {name!r} is registered but has no row in OBSERVABILITY.md's "
        "SLO watchdog table"
        for name in sorted(rules - documented)
    ]
    out += [
        f"OBSERVABILITY.md documents rule {name!r} but no RULE_* constant "
        "registers it (ghost row — renamed or removed rule?)"
        for name in sorted(documented - rules)
    ]
    if not rules:
        out.append("no RULE_* constants found (checker regex drifted?)")
    return out


def main() -> int:
    bad = violations()
    if bad:
        sys.stderr.write(
            "watchdog rule inventory drift:\n"
            + "".join(f"  {v}\n" for v in bad)
        )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
