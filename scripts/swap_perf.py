"""Swap-phase cost/quality on real hardware at the flagship scale.

Measures, for several (swap_every, sweeps) configs at 10k x 1k (dense and
sparse): the device slope per round (K=2 vs K=8 chained solves, prepared
weights on the dense path) and the final communication cost — the
"objective at equal device budget" evidence for the pairwise-swap phase.

Run ON the TPU: python scripts/swap_perf.py [dense|sparse|50k]
"""

import json
import sys
import time
from functools import partial
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax
import jax.numpy as jnp


def slope(chained, state, graph, wp, k1=2, k2=8):
    def timed(k):
        _, objs = chained(state, graph, wp, jax.random.PRNGKey(7), k)
        float(objs[-1])
        best = float("inf")
        for rep in range(3):
            t = time.perf_counter()
            _, objs = chained(state, graph, wp, jax.random.PRNGKey(8 + rep), k)
            float(objs[-1])
            best = min(best, time.perf_counter() - t)
        return best

    return (timed(k2) - timed(k1)) / (k2 - k1) * 1e3


def main():
    mode = sys.argv[1] if len(sys.argv) > 1 else "dense"
    from kubernetes_rescheduling_tpu.objectives import communication_cost
    from kubernetes_rescheduling_tpu.solver import (
        GlobalSolverConfig,
        global_assign,
        global_assign_sparse,
        sparse_pod_comm_cost,
    )

    if mode == "50k":
        import runpy

        bench = runpy.run_path(
            str(Path(__file__).resolve().parent.parent / "bench.py")
        )
        state, graph = bench["_sparse50k_problem"]()
        solve, cost_of, sparse = global_assign_sparse, sparse_pod_comm_cost, True
    else:
        from kubernetes_rescheduling_tpu.bench.harness import make_backend

        backend = make_backend("large", seed=0)
        state = backend.monitor()
        graph = backend.comm_graph()
        sparse = mode == "sparse"
        if sparse:
            from kubernetes_rescheduling_tpu.core import sparsegraph

            graph = sparsegraph.from_comm_graph(graph)
            solve, cost_of = global_assign_sparse, sparse_pod_comm_cost
        else:
            solve, cost_of = global_assign, communication_cost

    configs = [
        ("sw0_s9", 0, 9),
        ("sw3_s9", 3, 9),
        ("sw0_s10", 0, 10),
        ("sw1_s9", 1, 9),
        ("sw0_s12", 0, 12),
        ("sw3_s12", 3, 12),
    ]
    for tag, se, sweeps in configs:
        cfg = GlobalSolverConfig(sweeps=sweeps, swap_every=se)
        wp = None
        if not sparse:
            from kubernetes_rescheduling_tpu.solver.global_solver import (
                prepare_weights,
            )

            wp = prepare_weights(state, graph, cfg)

        @partial(jax.jit, static_argnames=("k",))
        def chained(st0, g, w, key0, k, cfg=cfg):
            def body(st_c, i):
                kk = jax.random.fold_in(key0, i)
                if sparse:
                    st_n, inf = solve(st_c, g, kk, cfg)
                else:
                    st_n, inf = solve(st_c, g, kk, cfg, w_mm=w)
                return st_n, inf["objective_after"]

            return jax.lax.scan(body, st0, jnp.arange(k))

        ms = slope(chained, state, graph, wp)
        st1, info = (
            solve(state, graph, jax.random.PRNGKey(0), cfg)
            if sparse
            else solve(state, graph, jax.random.PRNGKey(0), cfg, w_mm=wp)
        )
        comm = float(cost_of(st1, graph))
        sw = [int(x) for x in info.get("swaps_per_sweep", [])]
        print(
            json.dumps(
                {
                    "mode": mode, "config": tag, "device_ms": round(ms, 2),
                    "comm_after": round(comm, 1), "swaps_per_sweep": sw,
                }
            ),
            flush=True,
        )


if __name__ == "__main__":
    main()
