"""Attribute sparse-solver device time to components at 50k x 2k.

Same scan-chained slope discipline as scripts/sparse_ablate.py, at the
flagship sparse scale, to locate the per-chunk fixed cost the round-4/5
measurements diagnosed (59 chunk steps/sweep x ~0.35 ms). Run ON the TPU.
"""

import runpy
import sys
import time
from functools import partial
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_rescheduling_tpu.core.sparsegraph import BLOCK_R
from kubernetes_rescheduling_tpu.solver.sparse_solver import hub_slab
from kubernetes_rescheduling_tpu.core.sparsegraph import sparse_pair_comm_cost
from kubernetes_rescheduling_tpu.ops.fused_admission import fused_score_admission
from kubernetes_rescheduling_tpu.ops.sparse_mass import (
    chunk_local_slabs,
    hub_neighbor_mass,
    hub_tile_arrays,
    sparse_neighbor_mass,
)

bench = runpy.run_path(str(Path(__file__).resolve().parent.parent / "bench.py"))
state, sg = bench["_sparse50k_problem"]()
SP = sg.sp
N = int(state.num_nodes)
NHB = len(sg.hub_blocks)
NBR = len(sg.regular_blocks)
print(
    f"S={sg.num_services} SP={SP} N={N} blocks={sg.num_blocks} hub={NHB} "
    f"regular={NBR} TU={sg.w_local.shape[1]} u_reg={sg.u_reg} "
    f"reg_tiles={sg.reg_tiles} chunks/sweep={-(-NBR // 4)}"
)

rng = np.random.default_rng(0)
assign0 = jnp.asarray(rng.integers(0, N, size=SP), jnp.int32)
rv = jnp.asarray((rng.random(SP) > 0.02).astype(np.float32))
rvu = jnp.where(sg.u_ids < SP, rv[jnp.clip(sg.u_ids, 0, SP - 1)], 0.0)
w_mm = sg.w_local.astype(jnp.bfloat16)
toff = jnp.asarray(sg.block_toff, jnp.int32)
blocks = jnp.asarray(sg.regular_blocks[:4], jnp.int32)
ids = (np.asarray(blocks)[:, None] * BLOCK_R + np.arange(BLOCK_R)).reshape(-1)
ids_j = jnp.asarray(ids)

cpu_load = jnp.asarray(rng.random(N) * 1000, jnp.float32)
mem_load = jnp.zeros(N)
cap = jnp.full(N, 2000.0)
mem_cap = jnp.full(N, jnp.inf)
node_valid = jnp.ones(N, bool)
c_cpu = jnp.asarray(rng.random(1024) * 100, jnp.float32)
c_mem = jnp.zeros(1024)
valid_c = jnp.ones(1024, bool)


def timeit(name, step, k1=100, k2=900):
    """Slope between two chain lengths — the tunnel RTT and dispatch are
    the same constant at both, so the slope is pure per-iteration device
    time (the plain total/k form reads RTT/k ~ 0.6 ms of phantom cost)."""

    @partial(jax.jit, static_argnames=("kk",))
    def run(a0, kk):
        def body(a, i):
            return step(a, i), 0
        a, _ = jax.lax.scan(body, a0, jnp.arange(kk))
        return a

    def best_of(kk, reps=3):
        out = run(assign0, kk)
        jnp.sum(out).item()  # warm + fence
        best = float("inf")
        for _ in range(reps):
            t = time.perf_counter()
            out = run(assign0, kk)
            jnp.sum(out).item()
            best = min(best, time.perf_counter() - t)
        return best

    ms = (best_of(k2) - best_of(k1)) / (k2 - k1) * 1e3
    print(f"{name:34s} {ms:8.4f} ms/iter")


# 0. chunk-local slab slice alone
def slab_step(a, i):
    starts = toff[blocks] * sg.bu
    u_c, rvu_c = chunk_local_slabs(sg.u_ids, rvu, starts, sg.u_reg)
    return a.at[0].set((jnp.sum(u_c) + jnp.sum(rvu_c).astype(jnp.int32)) % N)

timeit("chunk slabs (slices only)", slab_step)


# 1. chunk-local tgt gather (KB*u_reg elements from SP table)
def gather_step(a, i):
    starts = toff[blocks] * sg.bu
    u_c, rvu_c = chunk_local_slabs(sg.u_ids, rvu, starts, sg.u_reg)
    tgt = a[jnp.clip(u_c, 0, SP - 1)]
    return a.at[0].set(jnp.sum(tgt) % N)

timeit("slabs + tgt gather", gather_step)


# 2. regular-chunk mass kernel
def mass_step(a, i):
    starts = toff[blocks] * sg.bu
    u_c, rvu_c = chunk_local_slabs(sg.u_ids, rvu, starts, sg.u_reg)
    tgt_c = a[jnp.clip(u_c, 0, SP - 1)]
    M = sparse_neighbor_mass(
        w_mm, tgt_c, rvu_c, blocks, toff,
        num_nodes=N, bu=sg.bu, reg_tiles=sg.reg_tiles,
    )
    return a.at[0].set(jnp.sum(M).astype(jnp.int32) % N)

timeit("slabs + gather + mass kernel", mass_step)


# 3. score+admission epilogue (C=1024, N=2048)
def place_step(a, i):
    M = (a[ids_j][:, None] * jnp.ones((1, N))).astype(jnp.float32)
    new_node, admitted, d_cpu, d_mem = fused_score_admission(
        M, a[ids_j], c_cpu, c_mem, valid_c,
        cpu_load, mem_load, cap, mem_cap, node_valid,
        0.0, 0.5, i.astype(jnp.int32),
        enforce_capacity=True, use_noise=True, emit_x_rows=False,
    )
    return a.at[ids_j].set(new_node)

timeit("score+admission (C=1024)", place_step)


# 3b. score kernel alone (drop the admission call: emit prop via a
# degenerate race) — approximated by enforce_capacity=False which skips
# the priority matmul path
def place_nocap_step(a, i):
    M = (a[ids_j][:, None] * jnp.ones((1, N))).astype(jnp.float32)
    new_node, admitted, d_cpu, d_mem = fused_score_admission(
        M, a[ids_j], c_cpu, c_mem, valid_c,
        cpu_load, mem_load, cap, mem_cap, node_valid,
        0.0, 0.5, i.astype(jnp.int32),
        enforce_capacity=False, use_noise=True, emit_x_rows=False,
    )
    return a.at[ids_j].set(new_node)

timeit("score+admission (no race)", place_nocap_step)


# 4. full chunk step (mass -> place -> commit scatters)
def full_step(a, i):
    starts = toff[blocks] * sg.bu
    u_c, rvu_c = chunk_local_slabs(sg.u_ids, rvu, starts, sg.u_reg)
    tgt_c = a[jnp.clip(u_c, 0, SP - 1)]
    M = sparse_neighbor_mass(
        w_mm, tgt_c, rvu_c, blocks, toff,
        num_nodes=N, bu=sg.bu, reg_tiles=sg.reg_tiles,
    )
    new_node, admitted, d_cpu, d_mem = fused_score_admission(
        M, a[ids_j], c_cpu, c_mem, valid_c,
        cpu_load, mem_load, cap, mem_cap, node_valid,
        0.0, 0.5, i.astype(jnp.int32),
        enforce_capacity=True, use_noise=True, emit_x_rows=False,
    )
    return a.at[ids_j].set(new_node)

timeit("FULL chunk step", full_step)


# 5. per-sweep exact objective (COO, E2 edges)
def obj_step(a, i):
    c = sparse_pair_comm_cost(sg, a[:SP], rv[:SP])
    return a.at[0].set(c.astype(jnp.int32) % N)

timeit("objective COO (per sweep)", obj_step)


# 6. loads refresh (per sweep)
svc_cpu = jnp.asarray(rng.random(SP) * 100, jnp.float32)
def loads_step(a, i):
    l = jnp.zeros((N + 1,), jnp.float32).at[jnp.where(rv > 0, a, N)].add(svc_cpu)[:N]
    return a.at[0].set(jnp.sum(l).astype(jnp.int32) % N)

timeit("loads scatter-add (per sweep)", loads_step)


# 7. hub mass (one group of <=4 hub blocks as the solver batches them)
if NHB:
    hb = sg.hub_blocks[:4]
    h_col, h_lcol, h_out, h_first = hub_tile_arrays(sg, hb)
    u_g, rvu_g = hub_slab(sg, hb, rv, SP)

    def hub_step(a, i):
        tgt_l = a[jnp.clip(u_g, 0, SP - 1)]
        M = hub_neighbor_mass(
            w_mm, tgt_l, rvu_g, h_col, h_lcol, h_out, h_first,
            num_nodes=N, num_hub_blocks=len(hb), bu=sg.bu,
        )
        return a.at[0].set(jnp.sum(M).astype(jnp.int32) % N)

    timeit(f"hub mass group ({len(hb)} blocks)", hub_step)

print("OK")


# 8. ALL hub groups (as the solver batches them: KB=4 per group), mass
# + place, chained — the full per-sweep hub pass
KB = 4
hub_groups = []
for g in range(0, NHB, KB):
    hb = sg.hub_blocks[g : g + KB]
    hc = hub_tile_arrays(sg, hb)
    u_gg, rvu_gg = hub_slab(sg, hb, rv, SP)
    ids_g = jnp.asarray(
        np.concatenate(
            [np.arange(BLOCK_R, dtype=np.int32) + b * BLOCK_R for b in hb]
        )
    )
    hub_groups.append((hb, ids_g, u_gg, rvu_gg, hc))
    print(f"  hub group {g//KB}: blocks={list(hb)} width={u_gg.shape[0]}")


def hub_pass_step(a, i):
    for hb, ids_g, u_gg, rvu_gg, (hcol, hlcol, hout, hfirst) in hub_groups:
        tgt_l = a[jnp.clip(u_gg, 0, SP - 1)]
        M = hub_neighbor_mass(
            w_mm, tgt_l, rvu_gg, hcol, hlcol, hout, hfirst,
            num_nodes=N, num_hub_blocks=len(hb), bu=sg.bu,
        )
        CG = len(hb) * BLOCK_R
        new_node, admitted, d_cpu, d_mem = fused_score_admission(
            M, a[ids_g], c_cpu[:CG], c_mem[:CG], valid_c[:CG],
            cpu_load, mem_load, cap, mem_cap, node_valid,
            0.0, 0.5, i.astype(jnp.int32),
            enforce_capacity=True, use_noise=True, emit_x_rows=False,
        )
        a = a.at[ids_g].set(new_node)
    return a

timeit("FULL hub pass (all groups)", hub_pass_step, k1=50, k2=300)
print("OK2")
