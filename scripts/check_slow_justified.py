#!/usr/bin/env python
"""Static check: every ``@pytest.mark.slow`` carries its justification.

The tier-1 run excludes ``slow`` tests, so each mark is a claim: "every
invariant this test covers keeps at least one fast representative" (the
marker registration in tests/conftest.py). PRs 3–4 applied the
convention by hand — a comment ON the marker line (continued by
immediately-following full-line comments) naming the surviving fast pin.
This checker enforces it:

- any line applying the mark — decorator form, ``marks=pytest.mark.slow``
  inside ``pytest.param``, or a module-level ``pytestmark`` — must carry
  a same-line ``#`` comment;
- the justification (same-line comment + any directly-following
  full-line comments, up to the decorated ``def``/next decorator) must
  say the coverage survives — it must mention ``pin``/``fast``/
  ``tier-1`` — AND name where: a ``test_*``/``Test*`` reference, or a
  positional one (``above``/``below``/``... cases``/the harness matrix).

Usage:
    python scripts/check_slow_justified.py [TESTFILE.py ...]

With no arguments it self-checks the repo's own ``tests/`` directory —
the checked-in suite must satisfy the convention it documents. Run
directly (exit 1 on violation) or through the test twin
(tests/test_slow_justified.py).
"""

from __future__ import annotations

import re
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
TESTS = ROOT / "tests"

# any spelling of the mark: decorator (@pytest.mark.slow), parametrize
# (marks=pytest.mark.slow), or module-level (pytestmark = ...) — every
# form removes coverage from tier-1, so every form owes a justification
_MARK = re.compile(r"^[^#]*\bpytest\.mark\.slow\b(?P<tail>.*)$")
_COMMENT_LINE = re.compile(r"^\s*#(.*)$")
# "the coverage survives": the justification must say the invariant
# stays pinned fast somewhere
_SURVIVES = re.compile(r"\b(pin|pinned|pins|fast|tier-1)\b", re.I)
# "...and names where": a concrete test reference or a positional one
_NAMES_PIN = re.compile(
    r"(test_[a-zA-Z0-9_]+|Test[A-Za-z0-9_]+|\babove\b|\bbelow\b|"
    r"\bcases\b|\bmatrix\b)"
)


def _justification(lines: list[str], idx: int) -> str:
    """The marker's comment text: same-line tail + following full-line
    comments (the continuation convention), stopped by code."""
    m = _MARK.match(lines[idx])
    parts = []
    tail = m.group("tail")
    if "#" in tail:
        parts.append(tail.split("#", 1)[1])
    j = idx + 1
    while j < len(lines):
        cm = _COMMENT_LINE.match(lines[j])
        if cm is None:
            break
        parts.append(cm.group(1))
        j += 1
    return " ".join(p.strip() for p in parts)


def check_file(path: str | Path) -> list[str]:
    """Violations in one test file (empty = clean)."""
    p = Path(path)
    if not p.is_file():
        return [f"{p}: not a file"]
    out: list[str] = []
    lines = p.read_text().splitlines()
    for i, line in enumerate(lines):
        m = _MARK.match(line)
        if m is None:
            continue
        if "#" not in m.group("tail"):
            out.append(
                f"{p}:{i + 1}: pytest.mark.slow without a same-line "
                f"justification comment"
            )
            continue
        just = _justification(lines, i)
        if not _SURVIVES.search(just):
            out.append(
                f"{p}:{i + 1}: slow justification does not say the "
                f"coverage stays pinned fast: {just!r}"
            )
        elif not _NAMES_PIN.search(just):
            out.append(
                f"{p}:{i + 1}: slow justification does not NAME the "
                f"surviving fast pin (a test_*/Test* reference or "
                f"above/below/cases/matrix): {just!r}"
            )
    return out


def violations(paths: list[str] | None = None) -> list[str]:
    if paths:
        files = [Path(p) for p in paths]
    else:
        files = sorted(TESTS.glob("test_*.py"))
    out: list[str] = []
    for f in files:
        out.extend(check_file(f))
    return out


def main(argv: list[str] | None = None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    bad = violations(args)
    if bad:
        sys.stderr.write(
            "unjustified @pytest.mark.slow markers:\n"
            + "".join(f"  {v}\n" for v in bad)
        )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
