"""Split the 50k x 2k sparse solve into per-sweep cost and per-solve
fixed cost: chained-solve slope at two sweep counts. Run ON the TPU.

Per-solve device ms at sweeps=s is  fixed + s * per_sweep;  measuring the
chained-K slope at s1 and s2 gives both terms.
"""

import runpy
import sys
import time
from functools import partial
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax
import jax.numpy as jnp

bench = runpy.run_path(str(Path(__file__).resolve().parent.parent / "bench.py"))
state, sg = bench["_sparse50k_problem"]()

from kubernetes_rescheduling_tpu.solver import (  # noqa: E402
    GlobalSolverConfig,
    global_assign_sparse,
)


def solve_ms(sweeps: int, swap_every: int = 0, k1: int = 2, k2: int = 8):
    cfg = GlobalSolverConfig(sweeps=sweeps, swap_every=swap_every)

    @partial(jax.jit, static_argnames=("k",))
    def chained(st0, g, key0, k):
        def body(st, i):
            st_n, inf = global_assign_sparse(
                st, g, jax.random.fold_in(key0, i), cfg
            )
            return st_n, inf["objective_after"]

        return jax.lax.scan(body, st0, jnp.arange(k))

    def timed(k):
        _, objs = chained(state, sg, jax.random.PRNGKey(7), k)
        float(objs[-1])
        best = float("inf")
        for rep in range(3):
            t = time.perf_counter()
            _, objs = chained(state, sg, jax.random.PRNGKey(8 + rep), k)
            float(objs[-1])
            best = min(best, time.perf_counter() - t)
        return best, float(objs[-1])

    t2, _ = timed(k1)
    t8, obj = timed(k2)
    return (t8 - t2) / (k2 - k1) * 1e3, obj


for sweeps in (3, 9, 15):
    ms, obj = solve_ms(sweeps)
    print(f"sweeps={sweeps:2d}  {ms:7.1f} ms/solve  obj={obj:.0f}", flush=True)
