#!/usr/bin/env python
"""Static check: checked-in fixture traces satisfy the ClusterTrace schema.

The corpus loader (``traces/corpus.py``) is deliberately lenient — a
malformed row is quarantined and counted, never an error — so a drifted
fixture would silently shrink the replay corpus instead of failing
loudly. This checker is the loud half (the ``check_bench_schema.py``
convention): every ``*.trace.jsonl`` fixture must parse with ZERO
quarantined rows and satisfy the schema's structural contracts.

Enforced per file:

- every row parses as a JSON object with a known ``kind``
  (``node`` | ``pod`` | ``edge`` | ``placement``) and its identity
  fields present (the corpus loader's quarantine reasons, promoted to
  errors for checked-in fixtures);
- timestamps are finite and monotone non-decreasing across the file;
- every numeric value field (``cpu_cap_m``/``mem_cap_b``/
  ``cpu_used_m``/``mem_used_b``/``cpu_m``/``mem_b``/``w``) is finite —
  checked-in fixtures model dirty data only in files deliberately named
  OUTSIDE the ``*.trace.jsonl`` glob (e.g. ``corrupt_trace.jsonl``);
- every pod's ``node`` reference (when non-null) names a declared node;
- at least one window exists.

Usage:
    python scripts/check_trace_schema.py [FILE.trace.jsonl ...]

With no arguments it checks every ``*.trace.jsonl`` under
``tests/fixtures/`` — the self-check its test twin
(tests/test_trace_schema.py) runs, alongside pinned corruption classes.
"""

from __future__ import annotations

import json
import math
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
FIXTURES = ROOT / "tests" / "fixtures"

KINDS = ("node", "pod", "edge", "placement")
REQUIRED = {
    "node": ("node",),
    "pod": ("pod", "service"),
    "edge": ("a", "b"),
    "placement": ("pod", "node"),
}
VALUE_FIELDS = (
    "cpu_cap_m", "mem_cap_b", "cpu_used_m", "mem_used_b",
    "cpu_m", "mem_b", "w",
)


def check_file(path: str | Path) -> list[str]:
    """Violations in one fixture trace (empty = clean)."""
    p = Path(path)
    try:
        lines = p.read_text().splitlines()
    except OSError as e:
        return [f"{p.name}: unreadable ({e})"]
    out: list[str] = []
    last_t: float | None = None
    declared_nodes: set[str] = set()
    windows = 0
    for i, line in enumerate(lines, start=1):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            out.append(f"{p.name}:{i}: broken JSON")
            continue
        if not isinstance(rec, dict):
            out.append(f"{p.name}:{i}: not a JSON object")
            continue
        kind = rec.get("kind")
        if kind not in KINDS:
            out.append(f"{p.name}:{i}: unknown kind {kind!r}")
            continue
        # absent/empty, NOT falsy: integer-id corpora use 0 legitimately
        missing = [
            f
            for f in REQUIRED[kind]
            if rec.get(f) is None or rec.get(f) == ""
        ]
        if missing:
            out.append(
                f"{p.name}:{i}: {kind} record missing {', '.join(missing)}"
            )
            continue
        try:
            t = float(rec.get("t", 0.0))
        except (TypeError, ValueError):
            out.append(f"{p.name}:{i}: non-numeric timestamp")
            continue
        if not math.isfinite(t):
            out.append(f"{p.name}:{i}: non-finite timestamp")
            continue
        if last_t is not None and t < last_t:
            out.append(
                f"{p.name}:{i}: timestamp {t} < previous {last_t} "
                f"(must be monotone non-decreasing)"
            )
        if last_t is None or t != last_t:
            windows += 1
        last_t = t
        for f in VALUE_FIELDS:
            if f in rec:
                v = rec[f]
                if not isinstance(v, (int, float)) or isinstance(v, bool) \
                        or not math.isfinite(float(v)):
                    out.append(
                        f"{p.name}:{i}: non-finite value field {f}={v!r}"
                    )
        if kind == "node":
            declared_nodes.add(rec["node"])
        elif kind == "pod" and rec.get("node") is not None:
            if rec["node"] not in declared_nodes:
                out.append(
                    f"{p.name}:{i}: pod references undeclared node "
                    f"{rec['node']!r}"
                )
    if windows == 0:
        out.append(f"{p.name}: no snapshot windows (empty trace)")
    return out


def violations(paths=None) -> list[str]:
    if paths is None:
        paths = sorted(FIXTURES.rglob("*.trace.jsonl"))
        if not paths:
            return ["no *.trace.jsonl fixtures found under tests/fixtures/"]
    out: list[str] = []
    for p in paths:
        out.extend(check_file(p))
    return out


def main(argv: list[str]) -> int:
    bad = violations(argv or None)
    if bad:
        sys.stderr.write(
            "trace fixture schema drift — the corpus loader would "
            "silently quarantine these rows:\n"
            + "".join(f"  {v}\n" for v in bad)
        )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
