"""Placement policies as jit-able kernels.

The reference implements five placement strategies as separate Python
functions over dict snapshots (reference rescheduling.py:77-218); here they
are branches of one unified scoring kernel (`choose_node`) driven by
masked lexicographic argmax, plus hazard detection and victim selection.
"""

from kubernetes_rescheduling_tpu.policies.hazard import detect_hazard
from kubernetes_rescheduling_tpu.policies.victim import pick_victim, deployment_group
from kubernetes_rescheduling_tpu.policies.scoring import (
    POLICY_IDS,
    POLICY_NAMES,
    choose_node,
    lex_argmax,
    node_features,
)

__all__ = [
    "detect_hazard",
    "pick_victim",
    "deployment_group",
    "POLICY_IDS",
    "POLICY_NAMES",
    "choose_node",
    "lex_argmax",
    "node_features",
]
