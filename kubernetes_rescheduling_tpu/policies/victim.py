"""Victim selection: which pod (and hence which Deployment) gets moved.

Reference semantics (delete_replaced_pod.py:41-61, 144-185): pick the
max-CPU pod on the hazard node (strict ``>`` → first max in pod order),
then delete its whole Deployment — every replica of that service moves
together when it is re-created.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from kubernetes_rescheduling_tpu.core.state import ClusterState


def pick_victim(state: ClusterState, node_idx: jax.Array) -> jax.Array:
    """i32 scalar — index of the max-CPU valid pod on ``node_idx``; -1 when the
    node has no pods (reference returns None → round skipped, main.py:103-107).
    """
    on_node = state.pod_valid & (state.pod_node == node_idx)
    masked = jnp.where(on_node, state.pod_cpu, -jnp.inf)
    victim = jnp.argmax(masked).astype(jnp.int32)
    return jnp.where(jnp.any(on_node), victim, -1)


def deployment_group(state: ClusterState, pod_idx: jax.Array) -> jax.Array:
    """bool[P] — all valid pods of the same service as ``pod_idx``.

    Deleting a pod's Deployment tears down every replica (foreground cascade,
    reference delete_replaced_pod.py:173-174), and re-creation places them all
    on the chosen node; the group is therefore the unit of movement.
    A pod_idx of -1 yields an empty group.
    """
    svc = state.pod_service[jnp.clip(pod_idx, 0, state.num_pods - 1)]
    group = state.pod_valid & (state.pod_service == svc)
    return jnp.where(pod_idx >= 0, group, jnp.zeros_like(group))
