"""The ``proactive`` algorithm: reactive CAR's kernels, one window ahead.

The reference's five strategies — and our ``global`` solver — all score
the *last observed* snapshot, so under bursty/diurnal load they place
against a cluster that no longer exists by the time the move lands.
``proactive`` keeps the exact greedy machinery (hazard detection →
victim → ``policies.scoring.policy_scores`` → masked lex argmax) but
runs it against the PREDICTED next-window state: the online forecaster
(``forecast/``) supplies a per-node load delta, and the decision kernels
(``solver.round_loop.decide_with_forecast`` /
``decide_explain_with_forecast``) fold it into ``node_base_cpu`` before
scoring — one compiled program, same explain bundle, same audit
invariants.

This module is the host-side glue: the algorithm name, the scoring
policy it delegates to (the forecast only moves the STATE the policy
sees, not the policy itself — by default reactive CAR's
``communication``), and :func:`predicted_state`, the one shared
definition of how a load delta becomes a state (also used by the mask
twins and the oracle tests, so the device and test views can never
disagree on what "predicted state" means).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from kubernetes_rescheduling_tpu.core.state import ClusterState
from kubernetes_rescheduling_tpu.policies.scoring import POLICY_IDS

PROACTIVE = "proactive"


def scoring_policy(algorithm: str, forecast_cfg) -> str:
    """The greedy policy whose key table a round actually scores with:
    ``proactive`` delegates to the forecast config's base policy
    (reactive CAR by default); every other algorithm scores as itself."""
    if algorithm == PROACTIVE:
        return forecast_cfg.base_policy
    return algorithm


def scoring_policy_id(algorithm: str, forecast_cfg) -> int:
    return POLICY_IDS[scoring_policy(algorithm, forecast_cfg)]


def predicted_state(state: ClusterState, delta: jax.Array) -> ClusterState:
    """The next-window state the proactive policy decides against:
    observed state with the forecast per-node load delta folded into
    ``node_base_cpu`` (so ``node_cpu_used``/``node_cpu_pct`` — hazard
    detection AND every load-derived scoring feature — see predicted
    loads). A zero delta (cold start, skill-gated degrade, invalid
    slots) reproduces the reactive state bit-for-bit: adding 0.0 changes
    no value, so the decision kernels emit identical moves.
    """
    delta = jnp.asarray(delta, jnp.float32)
    return state.replace(node_base_cpu=state.node_base_cpu + delta)
