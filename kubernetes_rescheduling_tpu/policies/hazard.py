"""Hazard (overload) detection.

Reference semantics (harzard_detect.py:3-27): a node is hazardous when the
monitor's **rounded** CPU percent (reference get_resource_usage.py:37) is
>= threshold (default 30); the "most hazardous" node is the first max in
node order (Python ``max`` over a dict preserves insertion order on ties).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from kubernetes_rescheduling_tpu.core.state import ClusterState
from kubernetes_rescheduling_tpu.objectives.metrics import node_cpu_pct_rounded


def detect_hazard(
    state: ClusterState, threshold: float = 30.0
) -> tuple[jax.Array, jax.Array]:
    """Returns ``(most_hazard, hazard_mask)``.

    most_hazard: i32 scalar node index, -1 when no node is hazardous.
    hazard_mask: bool[N], True for every node at/over the threshold.

    ``jnp.argmax`` picks the first max — same tie-break as the reference's
    ``max`` over the hazard dict (harzard_detect.py:24).
    """
    pct = node_cpu_pct_rounded(state)  # i32[N], -1 for invalid/zero-cap
    # compare in float so a fractional threshold (30.9) is not truncated to 30
    hazard_mask = state.node_valid & (
        pct.astype(jnp.float32) >= jnp.asarray(threshold, jnp.float32)
    )
    any_hazard = jnp.any(hazard_mask)
    masked = jnp.where(hazard_mask, pct, jnp.iinfo(jnp.int32).min)
    most = jnp.where(any_hazard, jnp.argmax(masked).astype(jnp.int32), -1)
    return most, hazard_mask
