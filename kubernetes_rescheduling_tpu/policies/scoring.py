"""The unified placement-scoring kernel.

All five reference strategies (reference rescheduling.py:77-218) are branches
of one jit-able function: compute per-node features once, then pick a node by
masked **lexicographic argmax** over policy-specific keys, reproducing each
strategy's exact tie-break:

| policy          | keys (maximize, in order)            | reference         |
|-----------------|--------------------------------------|-------------------|
| spread          | -pod_count, -lex_rank                | rescheduling.py:101 (min by (count, name)) |
| binpack         | rounded cpu_pct, +lex_rank           | rescheduling.py:133 (max by (pct, name))   |
| random          | Gumbel noise (uniform over cands)    | rescheduling.py:153 (rd.choice; parity is distribution-level, SURVEY.md §7) |
| kubescheduling  | free-CPU fraction (least-allocated)  | rescheduling.py:159-171 delegates to kube-scheduler; this is OUR model of its default NodeResourcesFit scoring |
| communication   | related-pod count, remaining CPU     | rescheduling.py:188-214 (tie → max remaining CPU, first max wins) |

Every policy first excludes hazard nodes — the reference patches a NodeAffinity
``NotIn <hazard nodes>`` rule into the re-created Deployment
(rescheduling.py:42-55, 86-87) or skips them in its scoring loop
(rescheduling.py:92-93, 189-190).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from kubernetes_rescheduling_tpu.core.state import ClusterState, CommGraph
from kubernetes_rescheduling_tpu.objectives.metrics import node_cpu_pct_rounded

POLICY_NAMES: tuple[str, ...] = (
    "spread",
    "binpack",
    "random",
    "kubescheduling",
    "communication",
)
POLICY_IDS: dict[str, int] = {name: i for i, name in enumerate(POLICY_NAMES)}


def lex_argmax(keys: Sequence[jax.Array], mask: jax.Array) -> jax.Array:
    """Index of the masked lexicographic maximum of ``keys``.

    Ties after the last key resolve to the lowest index — matching Python's
    first-max-wins iteration order in the reference's scoring loops.
    Returns -1 when the mask is empty.
    """
    winners = mask
    for k in keys:
        kf = k.astype(jnp.float32)
        best = jnp.max(jnp.where(winners, kf, -jnp.inf))
        winners = winners & (kf == best)
    idx = jnp.argmax(winners).astype(jnp.int32)
    return jnp.where(jnp.any(mask), idx, -1)


def node_features(
    state: ClusterState, graph: CommGraph, service_idx: jax.Array
) -> dict[str, jax.Array]:
    """All per-node features any policy needs, computed in one pass.

    ``affinity`` is CAR's score: the number of pods on each node whose service
    communicates with ``service_idx`` (reference rescheduling.py:188-195) —
    here a single row-gather + matvec against the occupancy matrix.
    """
    occ = state.service_node_counts(graph.num_services)          # f32[S, N]
    rel_row = (graph.adj[service_idx] > 0).astype(jnp.float32)   # f32[S]
    return {
        "pod_count": state.node_pod_count(),
        "cpu_pct_rounded": node_cpu_pct_rounded(state).astype(jnp.float32),
        "cpu_free": state.node_cpu_free(),
        "free_frac": jnp.where(
            state.node_cpu_cap > 0,
            state.node_cpu_free() / jnp.where(state.node_cpu_cap > 0, state.node_cpu_cap, 1.0),
            0.0,
        ),
        "affinity": rel_row @ occ,
        "lex_rank": state.node_lex_rank.astype(jnp.float32),
    }


def policy_key_table(
    f: dict[str, jax.Array], state: ClusterState, key: jax.Array
) -> tuple[jax.Array, jax.Array]:
    """The per-policy lexicographic key rows — SINGLE source of truth.

    Returns ``(k1, k2)``, each ``f32[len(POLICY_NAMES), N]``: policy ``p``
    picks the masked lexicographic argmax of ``(k1[p], k2[p])`` (see the
    module docstring's table; policies with one key get a constant-zero
    second key, which never changes the winner). Both the single-device
    :func:`choose_node` and the node-sharded
    ``parallel.sharded.sharded_choose_node`` consume this table, so a policy
    edit can never de-synchronize the two paths.
    """
    g = jax.random.gumbel(key, (state.num_nodes,))
    zero = jnp.zeros_like(g)
    k1 = jnp.stack(
        [-f["pod_count"], f["cpu_pct_rounded"], g, f["free_frac"], f["affinity"]]
    )
    k2 = jnp.stack([-f["lex_rank"], f["lex_rank"], zero, zero, f["cpu_free"]])
    return k1, k2


def policy_scores(
    policy_id: jax.Array,
    state: ClusterState,
    graph: CommGraph,
    service_idx: jax.Array,
    hazard_mask: jax.Array,
    key: jax.Array,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """The active policy's per-node scoring rows for one placement decision:
    ``(k1, k2, cand)`` — primary key, tie-break key (both f32[N]) and the
    candidate mask (valid ∧ ¬hazard). :func:`choose_node` is exactly the
    masked lexicographic argmax of these rows; the decision-explainability
    path records the same rows (top-k) so a recorded explanation can
    re-derive the chosen node as their argmax — one definition, two readers.
    """
    f = node_features(state, graph, service_idx)
    cand = state.node_valid & ~hazard_mask
    k1, k2 = policy_key_table(f, state, key)
    pid = jnp.clip(policy_id, 0, len(POLICY_NAMES) - 1)
    return k1[pid], k2[pid], cand


def choose_node(
    policy_id: jax.Array,
    state: ClusterState,
    graph: CommGraph,
    service_idx: jax.Array,
    hazard_mask: jax.Array,
    key: jax.Array,
) -> jax.Array:
    """i32 scalar — the chosen target node for ``service_idx``'s Deployment.

    ``policy_id`` may be traced (it indexes the key table), so a whole batch
    of policies can be evaluated under one compilation. Returns -1 when
    every valid node is hazardous (the reference raises RuntimeError there,
    rescheduling.py:98-99; the caller decides whether to skip or fail).
    """
    k1, k2, cand = policy_scores(
        policy_id, state, graph, service_idx, hazard_mask, key
    )
    return lex_argmax([k1, k2], cand)
