"""Unified telemetry: metrics registry, span tracing, JAX-aware accounting.

The control loop's value is operational — decisions/sec, per-round latency,
cost before/after — yet the repo historically observed itself through
ad-hoc JSONL and hand-rolled timers. This package is the one place all of
that lives now:

- :mod:`registry` — labeled ``Counter``/``Gauge``/``Histogram`` series
  with Prometheus text exposition and a JSONL sink. Histograms are
  fixed-bucket streaming (bounded memory), replacing the unbounded
  sample-list ``LatencyHistogram``.
- :mod:`spans` — nested host-side spans (``with span("solve/compile")``)
  exported as Chrome trace-event JSON (load it in Perfetto), with the
  ``jax.profiler`` integration folded in (``span(..., profile_dir=...)``).
- :mod:`accounting` — ``instrument_jit`` counts traces/compiles and
  lowering time per compiled function; ``pull`` counts device→host
  transfers. A silent retrace in a hot loop becomes a visible metric.
- :mod:`manifest` — per-run provenance (config, devices, jax version,
  git rev).
- :mod:`report` — summarize a run's JSONL into a human-readable report
  (the ``telemetry`` CLI subcommand).
- :mod:`server` — the LIVE ops plane: in-process ``/metrics`` /
  ``/healthz`` / ``/events`` HTTP endpoint plus the :class:`OpsPlane`
  aggregate the controller consumes (``--serve PORT``).
- :mod:`explain` — decision explainability: per-decision
  ``DecisionExplanation`` records whose chosen move re-derives as the
  argmax of the recorded candidate scores (consistency-checked).
- :mod:`attribution` — communication-cost attribution & topology plane:
  per-edge/per-node-pair decomposition of the cost scalar (one bundled
  device transfer per round), cardinality-bounded topology gauges, and
  the placement-timeline / move-provenance tracker whose per-move edge
  deltas telescope to the round's objective delta (consistency-checked).
- :mod:`fleet_rollup` — fleet-scale observability: device-side tenant
  rollups (quantiles + worst-k over the per-tenant metric matrix,
  riding the fleet round-end bundle at zero extra transfers), the
  tenant-label cardinality budget (:class:`TenantSeries` — the one
  legal gateway for tenant-labeled families, statically enforced), and
  the bounded live-plane views behind ``/tenants`` and the over-budget
  ``/healthz`` fleet summary.
- :mod:`mesh` — the device-axis sibling of :mod:`fleet_rollup`:
  per-device step-time/transfer/HBM rollups for the dp fleet planes
  (quantiles + worst-k, attributed from host-side dispatch wall — zero
  extra transfers), the :class:`DeviceSeries` label budget, the
  ``mesh_imbalance`` feed, and the :class:`ProfilerGate` behind
  ``POST /profile`` / ``--profile-rounds`` (bounded on-demand
  ``jax.profiler`` captures into the flight-recorder bundle dir).
- :mod:`flight_recorder` — bounded ring of recent rounds, dumped as a
  self-contained diagnostics bundle on breaker-open / crash / SIGUSR1.
- :mod:`watchdog` — rolling-window SLO rules (latency p95, comm-cost
  regression, retraces, perf-ledger regressions) feeding ``/healthz``
  and ``slo_violations_total{rule}``.
- :mod:`costmodel` — compiled-cost introspection: XLA
  ``cost_analysis``/``memory_analysis`` captured at each instrumented
  kernel's first compile (``jax_cost_*``/``jax_hbm_*`` gauges), live
  ``device.memory_stats()`` sampling, and per-round roofline numbers.
- :mod:`perf_ledger` — append-only JSONL perf history keyed by
  (metric, scenario, device kind, config digest) with a rolling-window
  regression detector (the ``telemetry perf`` trend table and the
  watchdog's ``perf_regression`` rule).

Everything routes through one default :class:`MetricsRegistry`
(:func:`get_registry`) unless a caller injects its own; the registry is
pure Python (no jax import), so the never-traced k8s adapter can use it
too.
"""

from kubernetes_rescheduling_tpu.telemetry.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_registry,
    set_registry,
)
from kubernetes_rescheduling_tpu.telemetry.spans import (
    Tracer,
    get_tracer,
    set_tracer,
    span,
    trace_to,
)
from kubernetes_rescheduling_tpu.telemetry.accounting import (
    count_reconcile,
    instrument_jit,
    pull,
    publish_round_telemetry,
    timed_call,
)
from kubernetes_rescheduling_tpu.telemetry.manifest import (
    run_manifest,
    write_manifest,
)
from kubernetes_rescheduling_tpu.telemetry.costmodel import (
    CostBook,
    get_costbook,
    sample_device_memory,
)
from kubernetes_rescheduling_tpu.telemetry.explain import (
    explanation_consistent,
)
from kubernetes_rescheduling_tpu.telemetry.attribution import (
    AttributionBook,
    PlacementTimeline,
    attribution_consistent,
    get_attribution_book,
)
from kubernetes_rescheduling_tpu.telemetry.fleet_rollup import (
    TenantSeries,
    TenantSummaryRing,
)
from kubernetes_rescheduling_tpu.telemetry.mesh import (
    DeviceSeries,
    MeshPlane,
    ProfilerGate,
)
from kubernetes_rescheduling_tpu.telemetry.perf_ledger import PerfLedger
from kubernetes_rescheduling_tpu.telemetry.flight_recorder import FlightRecorder
from kubernetes_rescheduling_tpu.telemetry.server import (
    HealthState,
    OpsPlane,
    OpsServer,
)
from kubernetes_rescheduling_tpu.telemetry.watchdog import SLORules, Watchdog

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "get_registry",
    "set_registry",
    "Tracer",
    "get_tracer",
    "set_tracer",
    "span",
    "trace_to",
    "count_reconcile",
    "instrument_jit",
    "pull",
    "publish_round_telemetry",
    "timed_call",
    "run_manifest",
    "write_manifest",
    "CostBook",
    "get_costbook",
    "sample_device_memory",
    "PerfLedger",
    "DeviceSeries",
    "MeshPlane",
    "ProfilerGate",
    "TenantSeries",
    "TenantSummaryRing",
    "explanation_consistent",
    "AttributionBook",
    "PlacementTimeline",
    "attribution_consistent",
    "get_attribution_book",
    "FlightRecorder",
    "HealthState",
    "OpsPlane",
    "OpsServer",
    "SLORules",
    "Watchdog",
]
