"""Unified telemetry: metrics registry, span tracing, JAX-aware accounting.

The control loop's value is operational — decisions/sec, per-round latency,
cost before/after — yet the repo historically observed itself through
ad-hoc JSONL and hand-rolled timers. This package is the one place all of
that lives now:

- :mod:`registry` — labeled ``Counter``/``Gauge``/``Histogram`` series
  with Prometheus text exposition and a JSONL sink. Histograms are
  fixed-bucket streaming (bounded memory), replacing the unbounded
  sample-list ``LatencyHistogram``.
- :mod:`spans` — nested host-side spans (``with span("solve/compile")``)
  exported as Chrome trace-event JSON (load it in Perfetto), with the
  ``jax.profiler`` integration folded in (``span(..., profile_dir=...)``).
- :mod:`accounting` — ``instrument_jit`` counts traces/compiles and
  lowering time per compiled function; ``pull`` counts device→host
  transfers. A silent retrace in a hot loop becomes a visible metric.
- :mod:`manifest` — per-run provenance (config, devices, jax version,
  git rev).
- :mod:`report` — summarize a run's JSONL into a human-readable report
  (the ``telemetry`` CLI subcommand).

Everything routes through one default :class:`MetricsRegistry`
(:func:`get_registry`) unless a caller injects its own; the registry is
pure Python (no jax import), so the never-traced k8s adapter can use it
too.
"""

from kubernetes_rescheduling_tpu.telemetry.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_registry,
    set_registry,
)
from kubernetes_rescheduling_tpu.telemetry.spans import (
    Tracer,
    get_tracer,
    set_tracer,
    span,
)
from kubernetes_rescheduling_tpu.telemetry.accounting import (
    count_reconcile,
    instrument_jit,
    pull,
    publish_round_telemetry,
    timed_call,
)
from kubernetes_rescheduling_tpu.telemetry.manifest import (
    run_manifest,
    write_manifest,
)

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "get_registry",
    "set_registry",
    "Tracer",
    "get_tracer",
    "set_tracer",
    "span",
    "count_reconcile",
    "instrument_jit",
    "pull",
    "publish_round_telemetry",
    "timed_call",
    "run_manifest",
    "write_manifest",
]
