"""Flight recorder: a bounded ring of the last N rounds, dumped as a
self-contained diagnostics bundle when something goes wrong.

Per executed round the controller records a compact entry — snapshot
digest, the full ``RoundRecord`` dict (including its decision
explanations), the round's structured events, and the tail of recent
spans. On a trigger (circuit-breaker open, a crash escaping the loop, or
SIGUSR1) the ring plus a registry snapshot and a provenance manifest is
written as ONE JSON file an operator can ship — no access to the dead
process required. ``telemetry bundle <file>`` summarizes it, including
the explain-consistency verdict over every recorded decision.

Dumping is deliberately best-effort: a recorder failure must never take
down the loop it is there to diagnose (failures are logged and counted,
never raised). jax-free.
"""

from __future__ import annotations

import collections
import hashlib
import json
import time
from pathlib import Path
from typing import Any

from kubernetes_rescheduling_tpu.telemetry.registry import (
    MetricsRegistry,
    get_registry,
)

BUNDLE_KIND = "flight_recorder_bundle"


def state_digest(state) -> str:
    """Short content hash of a snapshot's placement (pod→node + validity):
    two bundles with the same digest saw the same placement."""
    import numpy as np

    h = hashlib.sha1()
    h.update(np.asarray(state.pod_node).tobytes())
    h.update(np.asarray(state.pod_valid).tobytes())
    return h.hexdigest()[:16]


class FlightRecorder:
    def __init__(
        self,
        capacity: int = 16,
        *,
        bundle_dir: str | Path | None = None,
        registry: MetricsRegistry | None = None,
        logger=None,
        span_tail: int = 20,
    ) -> None:
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = capacity
        self.bundle_dir = Path(bundle_dir) if bundle_dir is not None else None
        self.registry = registry
        self.logger = logger
        self.span_tail = span_tail
        self._ring: collections.deque[dict[str, Any]] = collections.deque(
            maxlen=capacity
        )
        self._dump_seq = 0
        self.dumps: list[Path] = []

    def _reg(self) -> MetricsRegistry:
        return self.registry if self.registry is not None else get_registry()

    # ---- recording ----

    def record_round(
        self,
        *,
        round: int,
        digest: str | None = None,
        record: dict[str, Any] | None = None,
        events: list[dict[str, Any]] | None = None,
        spans: list[dict[str, Any]] | None = None,
    ) -> None:
        self._ring.append(
            {
                "round": round,
                "ts": time.time(),
                "digest": digest,
                "record": record,
                "events": list(events or ()),
                "spans": list(spans or ()),
            }
        )

    def record_skip(self, round: int, **fields: Any) -> None:
        self._ring.append(
            {"round": round, "ts": time.time(), "skipped": True, **fields}
        )

    @property
    def rounds(self) -> list[dict[str, Any]]:
        return list(self._ring)

    # ---- dumping ----

    def snapshot(self, reason: str, **fields: Any) -> dict[str, Any]:
        """The bundle object — self-contained: ring + metrics + manifest
        + the compiled-cost book (what the kernels in these rounds cost,
        even if the process dies before anyone scrapes /metrics)."""
        from kubernetes_rescheduling_tpu.telemetry.attribution import (
            get_attribution_book,
        )
        from kubernetes_rescheduling_tpu.telemetry.costmodel import get_costbook
        from kubernetes_rescheduling_tpu.telemetry.manifest import run_manifest

        return {
            "kind": BUNDLE_KIND,
            "reason": reason,
            "ts": time.time(),
            **fields,
            "rounds": self.rounds,
            "metrics": self._reg().snapshot(),
            "device_costs": get_costbook().as_dict(),
            "attribution": get_attribution_book().as_dict(),
            "manifest": run_manifest(),
        }

    def dump(
        self, reason: str, path: str | Path | None = None, **fields: Any
    ) -> Path | None:
        """Write a bundle; returns the path, or None when no destination
        is configured or the write failed (best-effort by contract)."""
        if path is None:
            if self.bundle_dir is None:
                return None
            self._dump_seq += 1
            path = self.bundle_dir / f"flight_{self._dump_seq:03d}_{reason}.json"
        p = Path(path)
        try:
            p.parent.mkdir(parents=True, exist_ok=True)
            p.write_text(
                json.dumps(self.snapshot(reason, **fields), default=str)
            )
        except Exception as e:  # noqa: BLE001 — diagnostics must not crash the loop
            if self.logger is not None:
                self.logger.error(
                    "flight_dump_failed", reason=reason, error=repr(e)
                )
            return None
        self._reg().counter(
            "flight_recorder_dumps_total",
            "flight-recorder bundles written",
            labelnames=("reason",),
        ).labels(reason=reason).inc()
        self.dumps.append(p)
        if self.logger is not None:
            self.logger.info("flight_dump", reason=reason, path=str(p))
        return p
