"""Communication-cost attribution & topology plane: the host half.

The controller's telemetry historically reported the objective only as an
opaque scalar — an operator could see *that* ``communication_cost``
changed but never *which* service edges or node pairs carry it, or
*which* moves paid for an improvement. This module turns the one-transfer
device bundle (``objectives.metrics.communication_cost_attribution``,
pulled with ``site="attribution"``) into:

- an **attribution record** per round — top-k service-edge rows
  (src/dst service, dominant src/dst node, cost), the node-pair cost
  matrix, per-node ingress/egress totals, and the tail (cost outside the
  top-k) — riding on ``RoundRecord.attribution`` → ``rounds.jsonl`` →
  flight-recorder bundles;
- **cardinality-bounded Prometheus gauges** — fixed top-k label sets:
  ``comm_cost_node_pair{src,dst}`` (unordered pairs, ≤ N·(N−1)/2
  children over a run), ``comm_cost_node_ingress|egress{node}`` (≤ N),
  and the rank-labeled ``comm_cost_edge_topk{rank}`` (≤ k);
- a **placement timeline / move-provenance tracker**
  (:class:`PlacementTimeline`): service→node residency over rounds, each
  applied move linked to its per-edge cost delta, deltas telescoping to
  the round's objective delta.

The audit invariant, in the spirit of
``telemetry.explain.explanation_consistent``
(:func:`attribution_consistent` / :func:`check_attribution`): per-edge
contributions (top-k + the explicitly-carried tail) must sum to the
recorded ``communication_cost`` scalar (f32 tolerance), ingress and
egress totals must each sum to it too, and every move's per-edge deltas
must sum to its recorded ``cost_delta`` with the round's move deltas
summing to the recorded ``objective_delta``. An attribution that cannot
re-derive its own totals is a bug, not a rendering problem.

Everything here is jax-free: the device bundle arrives as a plain
ndarray through ``telemetry.pull``; the timeline's initial residency is
collapsed host-side once at bind time.
"""

from __future__ import annotations

import math
import threading
from typing import Any, Iterable

import numpy as np

from kubernetes_rescheduling_tpu.telemetry.registry import MetricsRegistry

ATTRIBUTION_SITE = "attribution"

# f32 tolerance for the sum checks: the device reduces in a different
# association order than the host re-derivation
_RTOL = 1e-4
_ATOL = 1e-2


def _name(names: tuple[str, ...] | list[str], i: int, prefix: str) -> str:
    return names[i] if 0 <= i < len(names) else f"{prefix}{i}"


def decode_attribution(
    bundle: np.ndarray,
    *,
    node_names: tuple[str, ...],
    service_names: tuple[str, ...],
    top_k: int,
    num_nodes: int,
    num_services: int,
) -> dict[str, Any]:
    """Flat device bundle → the JSONL-safe attribution record.

    ``num_nodes``/``num_services`` are the PADDED capacities the kernel
    ran with (array shapes); the name tuples carry only real entries —
    padded indices (which can only appear with zero cost) fall back to
    synthetic names. Ingress/egress are the half-weighted row/column sums
    of the node-pair matrix, so each totals to the cost scalar.
    """
    flat = np.asarray(bundle, dtype=np.float64).reshape(-1)
    k = max(1, min(int(top_k), num_services * num_services))
    expect = 2 + 5 * k + num_nodes * num_nodes
    if flat.size != expect:
        raise ValueError(
            f"attribution bundle has {flat.size} values, expected {expect} "
            f"(top_k={top_k}, num_nodes={num_nodes})"
        )
    total = float(flat[0])
    tail = float(flat[1])
    rows = flat[2 : 2 + 5 * k].reshape(k, 5)
    m = flat[2 + 5 * k :].reshape(num_nodes, num_nodes)

    edges = []
    for r in rows:
        si, di, a, b = (int(v) for v in r[:4])
        if si < 0 or di < 0:
            continue
        edges.append(
            {
                "src_service": _name(service_names, si, "svc"),
                "dst_service": _name(service_names, di, "svc"),
                "src_node": _name(node_names, a, "node") if a >= 0 else None,
                "dst_node": _name(node_names, b, "node") if b >= 0 else None,
                "cost": float(r[4]),
            }
        )

    nodes = [_name(node_names, i, "node") for i in range(num_nodes)]
    node_pairs = [
        [nodes[a], nodes[b], float(m[a, b])]
        for a in range(num_nodes)
        for b in range(num_nodes)
        if m[a, b] > 0
    ]
    ingress = {nodes[i]: float(0.5 * m[:, i].sum()) for i in range(num_nodes)}
    egress = {nodes[i]: float(0.5 * m[i, :].sum()) for i in range(num_nodes)}
    # real nodes only in the per-node maps once padding contributes nothing
    real = set(node_names)
    if real:
        ingress = {n: v for n, v in ingress.items() if n in real or v > 0}
        egress = {n: v for n, v in egress.items() if n in real or v > 0}
    return {
        "total": total,
        "tail": tail,
        "edges": edges,
        "node_pairs": node_pairs,
        "ingress": ingress,
        "egress": egress,
    }


def _close(a: float, b: float, scale: float) -> bool:
    return abs(a - b) <= _ATOL + _RTOL * max(1.0, abs(scale))


def attribution_consistent(
    attr: dict[str, Any],
    *,
    communication_cost: float | None = None,
) -> bool:
    """Re-derive the attribution's own totals — the audit invariant.

    - Σ(top-k edge costs) + tail == total;
    - Σ ingress == total == Σ egress (the node-pair collapse preserves
      the scalar);
    - total == the recorded ``communication_cost`` scalar when given;
    - per move: Σ(edge deltas) == cost_delta; per round:
      Σ(move cost_deltas) == objective_delta (skipped for pod-level
      rounds, which record moves without service-collapsed deltas).
    """
    if not isinstance(attr, dict):
        return False
    total = attr.get("total")
    if total is None or not math.isfinite(total):
        return False
    scale = total
    edge_sum = sum(e.get("cost", 0.0) for e in attr.get("edges") or ())
    if not _close(edge_sum + attr.get("tail", 0.0), total, scale):
        return False
    for key in ("ingress", "egress"):
        side = attr.get(key)
        if side is not None and not _close(sum(side.values()), total, scale):
            return False
    if communication_cost is not None and not _close(
        total, communication_cost, scale
    ):
        return False
    moves = attr.get("moves")
    if moves:
        delta_sum = 0.0
        for mv in moves:
            d = mv.get("cost_delta")
            if d is None:
                continue  # pod-level move: no service-collapsed delta
            per_edge = sum(e.get("delta", 0.0) for e in mv.get("edges") or ())
            if not _close(per_edge, d, scale):
                return False
            delta_sum += d
        obj_delta = attr.get("objective_delta")
        if obj_delta is not None and not _close(delta_sum, obj_delta, scale):
            return False
    return True


def iter_attributions(
    records: Iterable[dict[str, Any]],
) -> list[tuple[dict[str, Any], float | None]]:
    """(attribution, recorded cost scalar) pairs from a mixed record
    stream: ``rounds.jsonl`` round dicts, flight-recorder ring entries
    (``record`` nested), or bare attribution dicts."""
    out = []
    for r in records:
        if not isinstance(r, dict):
            continue
        rec = r.get("record") if isinstance(r.get("record"), dict) else r
        attr = rec.get("attribution")
        if isinstance(attr, dict):
            out.append((attr, rec.get("communication_cost")))
        elif "total" in r and ("edges" in r or "node_pairs" in r):
            out.append((r, None))
    return out


def check_attribution(
    records: Iterable[dict[str, Any]],
) -> tuple[int, list[dict[str, Any]]]:
    """(checked, inconsistent) over a record stream — the bundle
    summarizer's and the acceptance test's shared verdict."""
    checked = 0
    bad = []
    for attr, cost in iter_attributions(records):
        checked += 1
        if not attribution_consistent(attr, communication_cost=cost):
            bad.append(attr)
    return checked, bad


# ---------------- Prometheus gauges (cardinality-bounded) ----------------


def _zero_family(fam) -> None:
    """Stale children keep their last value forever otherwise — a node
    pair that leaves the top-k must read 0, not its old cost."""
    for _labels, leaf in fam._series():
        if leaf is not fam:
            leaf.set(0.0)


def publish_attribution(
    registry: MetricsRegistry, attr: dict[str, Any], *, top_k: int
) -> None:
    """One gauge sample set per round. Label cardinality is bounded by
    construction: node pairs draw from the run's fixed node set (≤
    N·(N−1) children ever), per-node totals from the node set (≤ N), and
    the edge top-k is RANK-labeled (≤ k) — service names never become
    label values, so a large service graph cannot explode the registry.
    """
    pair_fam = registry.gauge(
        "comm_cost_node_pair",
        "communication cost carried between an unordered node pair "
        "(top-k pairs by cost; pairs outside the top-k read 0)",
        labelnames=("src", "dst"),
    )
    _zero_family(pair_fam)
    # UNORDERED pairs (the matrix is symmetric — publishing both
    # directions would double-count and waste half the top-k budget);
    # each pair carries its full cost, so an untruncated family sums to
    # the scalar — with more than top_k active pairs the tail is dropped,
    # which the HELP text says out loud
    seen: set[frozenset] = set()
    pairs = []
    for src, dst, cost in sorted(
        attr.get("node_pairs") or (), key=lambda p: p[2], reverse=True
    ):
        key = frozenset((src, dst))
        if key in seen:
            continue
        seen.add(key)
        pairs.append((src, dst, cost))
    for src, dst, cost in pairs[: max(int(top_k), 1)]:
        pair_fam.labels(src=src, dst=dst).set(cost)

    ing_fam = registry.gauge(
        "comm_cost_node_ingress",
        "per-node ingress share of communication cost (sums to the scalar)",
        labelnames=("node",),
    )
    eg_fam = registry.gauge(
        "comm_cost_node_egress",
        "per-node egress share of communication cost (sums to the scalar)",
        labelnames=("node",),
    )
    for node, v in (attr.get("ingress") or {}).items():
        ing_fam.labels(node=node).set(v)
    for node, v in (attr.get("egress") or {}).items():
        eg_fam.labels(node=node).set(v)

    edge_fam = registry.gauge(
        "comm_cost_edge_topk",
        "cost of the rank-th service edge (rank-labeled: fixed cardinality)",
        labelnames=("rank",),
    )
    # zero first: a later run with a SMALLER top_k must not leave the
    # higher ranks exposing a previous run's costs forever
    _zero_family(edge_fam)
    edges = attr.get("edges") or ()
    for rank in range(max(int(top_k), 1)):
        cost = edges[rank]["cost"] if rank < len(edges) else 0.0
        edge_fam.labels(rank=str(rank)).set(cost)


# ---------------- placement timeline / move provenance ----------------


class PlacementTimeline:
    """Service→node residency over rounds, with per-move cost provenance.

    Maintains a host-side occupancy model (replica counts per
    service×node, collapsed once from the initial snapshot at
    :meth:`bind`) and applies each LANDED move to it: a service-unit move
    re-homes every replica to the landed node — exactly what the backends
    do. Each move's **per-edge cost delta** is the change of
    ``adj[s,j]·cross_pairs(s,j)`` over the move's peers at the move's
    sequential working state, so the deltas telescope: their sum IS the
    round's objective delta under the model (the re-derivable invariant
    :func:`attribution_consistent` checks). Pod-level rounds record
    residency-free moves with ``cost_delta: null`` — a single replica's
    hop has no service-collapsed delta.

    The model is provenance, not ground truth: under chaos a snapshot can
    drift from it (a killed node's pods re-homed outside any move). The
    per-round ``model_total`` is recorded so drift is visible; internal
    consistency holds regardless.
    """

    def __init__(self) -> None:
        self._occ: np.ndarray | None = None
        self._adj: np.ndarray | None = None
        self._svc_names: tuple[str, ...] = ()
        self._node_names: tuple[str, ...] = ()
        self.residency: dict[str, list[tuple[int, str]]] = {}

    def bind(self, state, graph) -> None:
        """Collapse the initial snapshot host-side (once per run)."""
        num_s = graph.num_services
        n = state.num_nodes
        svc = np.asarray(state.pod_service)
        node = np.asarray(state.pod_node)
        valid = np.asarray(state.pod_valid)
        occ = np.zeros((num_s, n))
        sel = valid & (svc >= 0) & (svc < num_s) & (node >= 0) & (node < n)
        np.add.at(occ, (svc[sel], node[sel]), 1.0)
        sv = np.asarray(graph.service_valid)
        adj = np.asarray(graph.adj) * sv[:, None] * sv[None, :]
        self._occ = occ
        self._adj = adj
        self._svc_names = tuple(graph.names)
        self._node_names = tuple(state.node_names)
        for s in range(min(num_s, len(self._svc_names))):
            if occ[s].sum() > 0:
                home = int(np.argmax(occ[s]))
                self.residency[self._svc_names[s]] = [
                    (0, _name(self._node_names, home, "node"))
                ]

    @property
    def bound(self) -> bool:
        return self._occ is not None

    def _model_total(self) -> float:
        occ, adj = self._occ, self._adj
        tot = occ.sum(axis=1)
        cross = tot[:, None] * tot[None, :] - occ @ occ.T
        return float(0.5 * np.sum(adj * cross))

    def _move_delta(self, s: int, t: int) -> tuple[float, list[dict]]:
        """Per-edge deltas of re-homing every replica of service ``s`` to
        node ``t`` at the CURRENT working state (then applied to it)."""
        occ, adj = self._occ, self._adj
        tot = occ.sum(axis=1)
        w = adj[s]
        before = w * (tot[s] * tot - occ @ occ[s])
        after = w * (tot[s] * tot - occ[:, t] * tot[s])
        deltas = after - before
        deltas[s] = 0.0
        occ[s] = 0.0
        occ[s, t] = tot[s]
        edges = [
            {"peer": _name(self._svc_names, int(j), "svc"), "delta": float(deltas[j])}
            for j in np.flatnonzero(np.abs(deltas) > 0)
        ]
        return float(deltas.sum()), edges

    def observe_round(
        self,
        rnd: int,
        applied_moves: Iterable[tuple[str, str]],
        *,
        pod_level: bool = False,
    ) -> dict[str, Any]:
        """Fold one round's landed moves into the model; returns the
        provenance block the controller merges into the round's
        attribution record."""
        moves_out: list[dict[str, Any]] = []
        obj_delta = 0.0  # sum over moves with a computable delta
        for service, landed in applied_moves:
            s = (
                self._svc_names.index(service)
                if service in self._svc_names
                else -1
            )
            t = (
                self._node_names.index(landed)
                if landed in self._node_names
                else -1
            )
            prev = self.residency.get(service)
            entry: dict[str, Any] = {
                "service": service,
                "from": prev[-1][1] if prev else None,
                "to": landed,
                "cost_delta": None,
                "edges": [],
            }
            if not pod_level and s >= 0 and t >= 0 and self.bound:
                delta, edges = self._move_delta(s, t)
                entry["cost_delta"] = delta
                entry["edges"] = edges
                obj_delta += delta
            self.residency.setdefault(service, []).append((rnd, landed))
            moves_out.append(entry)
        return {
            "moves": moves_out,
            "objective_delta": None if pod_level else obj_delta,
            "model_total": self._model_total() if self.bound else None,
            "pod_level": bool(pod_level),
        }

    def render_residency(self) -> list[str]:
        return render_residency(self.residency)


def residency_from_rounds(
    records: Iterable[dict[str, Any]],
) -> dict[str, list[tuple[int | str, str]]]:
    """Rebuild the service→node residency map from a recorded round
    stream (the ``moves`` provenance in each round's attribution) — the
    post-hoc twin of :attr:`PlacementTimeline.residency`, so
    ``telemetry topo`` can render residency from rounds.jsonl alone."""
    residency: dict[str, list[tuple[int | str, str]]] = {}
    for attr, _cost in iter_attributions(records):
        rnd = attr.get("round", "?")
        for mv in attr.get("moves") or ():
            hops = residency.setdefault(mv["service"], [])
            if not hops and mv.get("from") is not None:
                hops.append((0, mv["from"]))
            hops.append((rnd, mv["to"]))
    return residency


def render_residency(
    residency: dict[str, list[tuple[int | str, str]]],
) -> list[str]:
    """Human-readable pod→node residency over rounds."""
    if not residency:
        return ["  no residency recorded"]
    lines = []
    for service in sorted(residency):
        hops = residency[service]
        path = " -> ".join(
            f"{node}@r{rnd}" if rnd else node for rnd, node in hops
        )
        lines.append(f"  {service}: {path}")
    return lines


# ---------------- process-global book (manifests/bundles) ----------------


class AttributionBook:
    """Latest attribution summary per algorithm — the manifest/bundle
    rider, so a diagnostics artifact carries *where the cost sits* even
    if nobody scraped /metrics before the process died."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._latest: dict[str, dict[str, Any]] = {}

    def update(self, algorithm: str, rnd: int, attr: dict[str, Any]) -> None:
        edges = attr.get("edges") or ()
        with self._lock:
            self._latest[algorithm] = {
                "round": rnd,
                "total": attr.get("total"),
                "tail": attr.get("tail"),
                "top_edge": dict(edges[0]) if edges else None,
                "edges_recorded": len(edges),
                "moves_tracked": len(attr.get("moves") or ()),
            }

    def as_dict(self) -> dict[str, dict[str, Any]]:
        with self._lock:
            return {k: dict(v) for k, v in self._latest.items()}

    def clear(self) -> None:
        with self._lock:
            self._latest.clear()


_book = AttributionBook()


def get_attribution_book() -> AttributionBook:
    return _book


# ---------------- rendering (telemetry topo) ----------------


def render_edges(attr: dict[str, Any]) -> list[str]:
    edges = attr.get("edges") or ()
    if not edges:
        return ["  no edge attribution recorded"]
    total = attr.get("total") or 0.0
    lines = [
        "  edge attribution (top-k by cost):",
        "    src_service -> dst_service        src_node -> dst_node      cost    share",
    ]
    for e in edges:
        share = e["cost"] / total if total else 0.0
        lines.append(
            f"    {e['src_service']} -> {e['dst_service']}".ljust(38)
            + f"{e.get('src_node')} -> {e.get('dst_node')}".ljust(26)
            + f"{e['cost']:<8.4g}{share:6.1%}"
        )
    tail = attr.get("tail")
    if tail:
        lines.append(f"    (+ tail outside top-k: {tail:.4g})")
    return lines


def render_heatmap(attr: dict[str, Any]) -> list[str]:
    """The node-pair cost matrix as a text heatmap."""
    pairs = attr.get("node_pairs") or ()
    nodes = sorted({p[0] for p in pairs} | {p[1] for p in pairs})
    if not nodes:
        return ["  no cross-node cost (everything co-located)"]
    idx = {n: i for i, n in enumerate(nodes)}
    m = np.zeros((len(nodes), len(nodes)))
    for src, dst, cost in pairs:
        m[idx[src], idx[dst]] = cost
    peak = m.max() or 1.0
    shades = " .:-=+*#%@"
    width = max(len(n) for n in nodes)
    col = max(6, min(10, width))
    lines = ["  node-pair heatmap (row=src, col=dst):"]
    header = " " * (width + 4) + " ".join(n[:col].rjust(col) for n in nodes)
    lines.append("  " + header.rstrip())
    for i, n in enumerate(nodes):
        cells = []
        for j in range(len(nodes)):
            v = m[i, j]
            shade = shades[min(int(v / peak * (len(shades) - 1)), len(shades) - 1)]
            cells.append(
                f"{shade}{v:{col - 1}.0f}" if v else f"{'·':>{col}}"
            )
        lines.append(f"    {n.rjust(width)}  " + " ".join(cells))
    return lines


def render_provenance(rounds: Iterable[dict[str, Any]]) -> list[str]:
    """Move provenance over a round stream: each applied move with its
    cost delta, plus the per-round objective delta."""
    lines: list[str] = []
    for attr, _cost in iter_attributions(rounds):
        moves = attr.get("moves") or ()
        if not moves:
            continue
        rnd = attr.get("round", "?")
        od = attr.get("objective_delta")
        head = f"  r{rnd}: {len(moves)} move(s)"
        if od is not None:
            head += f", objective delta {od:+.4g}"
        lines.append(head)
        for mv in moves:
            d = mv.get("cost_delta")
            lines.append(
                f"    {mv['service']}: {mv.get('from')} -> {mv.get('to')}"
                + (f"  Δcost {d:+.4g}" if d is not None else "  (pod-level)")
            )
    return lines or ["  no moves recorded"]
