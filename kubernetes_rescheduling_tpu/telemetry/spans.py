"""Nested host-side spans, exported as Chrome trace-event JSON.

``with span("solve/compile"):`` wraps any host-side region; spans nest
naturally (the exporter emits complete events — ``ph: "X"`` — whose
nesting Perfetto reconstructs from timestamps per thread). The resulting
file loads directly in https://ui.perfetto.dev or ``chrome://tracing``.

``span(..., profile_dir=...)`` folds the ``jax.profiler`` integration
(:func:`trace_to`, which lives HERE now — ``utils.profiling`` re-exports
it as a deprecation shim) under the same API: the host span is recorded
AND the region runs under a device trace for TensorBoard — one call
site instead of two nested context managers.

Span durations also feed the metrics registry (histogram
``span_seconds{span=...}``), so the exposition dump carries per-region
latency distributions without a second instrumentation pass.
"""

from __future__ import annotations

import collections
import contextlib
import itertools
import json
import os
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Iterator

from kubernetes_rescheduling_tpu.telemetry.registry import (
    MetricsRegistry,
    get_registry,
)


@contextlib.contextmanager
def trace_to(log_dir: str | None):
    """``jax.profiler.trace`` when a directory is given, no-op otherwise.

    Canonical home of the device-profiler integration (it was
    ``utils.profiling.trace_to``; that module keeps a deprecation
    re-export pinned to this object). ``span(..., profile_dir=...)``
    routes through it."""
    if log_dir is None:
        yield
        return
    import jax

    with jax.profiler.trace(log_dir):
        yield


@dataclass(frozen=True)
class SpanEvent:
    """One completed span (Chrome trace-event ``ph: "X"`` semantics)."""

    name: str
    ts_us: float      # wall-clock start, microseconds since the epoch
    dur_us: float
    tid: int
    depth: int
    args: dict[str, Any] = field(default_factory=dict)


class Tracer:
    """Collects spans; bounded to ``max_events`` (ring semantics — the
    newest spans win, matching the logger's ring buffer contract)."""

    def __init__(
        self,
        registry: MetricsRegistry | None = None,
        max_events: int = 100_000,
    ) -> None:
        self._events: collections.deque[SpanEvent] = collections.deque(
            maxlen=max_events
        )
        self._dropped = 0
        self._max_events = max_events
        self._lock = threading.Lock()
        self._local = threading.local()
        self._registry = registry
        # perf_counter gives monotonic durations; the wall anchor places
        # them on the epoch axis so traces from separate processes align
        self._wall_anchor = time.time()
        self._perf_anchor = time.perf_counter()

    def _now_us(self) -> float:
        return (
            self._wall_anchor + (time.perf_counter() - self._perf_anchor)
        ) * 1e6

    def _depth_stack(self) -> list[str]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    @contextlib.contextmanager
    def span(
        self,
        name: str,
        profile_dir: str | None = None,
        **args: Any,
    ) -> Iterator[None]:
        stack = self._depth_stack()
        depth = len(stack)
        stack.append(name)
        t0_us = self._now_us()
        t0 = time.perf_counter()
        try:
            if profile_dir is not None:
                with trace_to(profile_dir):
                    yield
            else:
                yield
        finally:
            dur_s = time.perf_counter() - t0
            stack.pop()
            ev = SpanEvent(
                name=name,
                ts_us=t0_us,
                dur_us=dur_s * 1e6,
                tid=threading.get_ident(),
                depth=depth,
                args=args,
            )
            with self._lock:
                if len(self._events) == self._max_events:
                    self._dropped += 1  # deque evicts the oldest span
                self._events.append(ev)
            reg = self._registry if self._registry is not None else get_registry()
            reg.histogram(
                "span_seconds",
                "wall time of named host-side spans",
                labelnames=("span",),
            ).labels(span=name).observe(dur_s)

    @property
    def events(self) -> list[SpanEvent]:
        with self._lock:
            return list(self._events)

    def tail(self, n: int) -> list[SpanEvent]:
        """The newest ``n`` spans (oldest-first), without copying the whole
        ring — the flight recorder reads this once per round."""
        with self._lock:
            it = itertools.islice(reversed(self._events), max(n, 0))
            return list(it)[::-1]

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._dropped = 0

    def to_chrome(self) -> dict[str, Any]:
        """Chrome trace-event JSON object (Perfetto-loadable)."""
        pid = os.getpid()
        events = [
            {
                "name": ev.name,
                "ph": "X",
                "ts": ev.ts_us,
                "dur": ev.dur_us,
                "pid": pid,
                "tid": ev.tid,
                "args": {**ev.args, "depth": ev.depth},
            }
            for ev in self.events
        ]
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def export_chrome(self, path: str | Path) -> None:
        p = Path(path)
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(json.dumps(self.to_chrome(), default=float))


_default_tracer = Tracer()


def get_tracer() -> Tracer:
    return _default_tracer


def set_tracer(tracer: Tracer) -> Tracer:
    """Swap the process-default tracer; returns the previous one."""
    global _default_tracer
    prev = _default_tracer
    _default_tracer = tracer
    return prev


@contextlib.contextmanager
def span(name: str, profile_dir: str | None = None, **args: Any):
    """``with span("solve/compile"):`` on the process-default tracer."""
    with _default_tracer.span(name, profile_dir=profile_dir, **args):
        yield
