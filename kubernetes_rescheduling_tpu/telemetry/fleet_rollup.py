"""Fleet-scale observability: device-side tenant rollups + the
cardinality budget.

Fleet mode historically observed itself the way the solo loop does —
one labeled series PER TENANT (``fleet_rounds_total{tenant}``, cost and
load gauges, a ``/healthz`` row each), which makes the telemetry plane
O(T) in series, scrape bytes, and host decode work: the exact
cardinality explosion the attribution plane (PR 5) solved for node
pairs, re-created on the tenant axis. Production TSDBs survive
multi-tenancy by enforcing label-cardinality budgets at ingestion and
letting per-identity detail degrade into bounded rollups; this module
is that discipline for the fleet:

- **Device half** — :func:`rollup_matrix`: a jittable reduction over the
  per-tenant metric matrix ``f32[T, M]`` (comm cost, load std,
  degraded/skipped flags, reconcile drift) producing per-dimension
  quantiles (p50/p90/p99/max via one in-trace sort), sums, and the
  top-k WORST tenants (``lax.top_k`` values + indices). It rides the
  fleet's existing round-end bundle (``bench/fleet.py``'s metrics pull,
  ``bench/scan.py``'s ``fleet_scan_rounds`` block) — **zero new
  transfers**, and O(k + quantile points) decode work however large T
  grows.
- **Host half** — :func:`decode_rollup` / :func:`publish_rollup`: the
  flat vector becomes BOUNDED metric families — ``fleet_cost_quantile{q}``,
  ``fleet_load_std_quantile{q}``, ``fleet_drift_quantile{q}``,
  rank-labeled ``fleet_worst_tenant{rank,dim}`` — plus fleet-total
  gauges. PR 5's attribution convention applies to the tenant axis:
  tenant NAMES ride event payloads (:func:`rollup_event`) and the
  ``/tenants`` drill-down, never unbounded label keys.
- **The budget gate** — :class:`TenantSeries`: THE one legal gateway for
  tenant-labeled metric families (statically enforced by
  ``scripts/check_label_cardinality.py``). Fleets at or under
  ``ObsConfig.tenant_label_budget`` keep the legacy per-tenant series
  bit-identically (golden-pinned); fleets over budget suppress them —
  counted ``tenant_series_suppressed_total{family}`` — and observe
  through the rollup families instead.
- **The live plane's bounded views** — :func:`fleet_health_block` (the
  ``/healthz`` fleet block: per-tenant rows at budget, breaker-state
  counts + worst-k rows over it) and :class:`TenantSummaryRing` (the
  bounded per-tenant summary store behind ``/tenants`` and
  ``/tenants/<name>``: last record, breaker, drift, a capped cost
  window, LRU-evicted under tenant churn).

The numpy twin :func:`rollup_numpy` re-derives the device rollup
host-side (same nearest-rank quantile definition, same stable tie
order as ``lax.top_k``) — the acceptance soak checks them against each
other within f32 tolerance every round.

Module import stays jax-free (the server and report consumers are);
the device functions import jax lazily at trace time.
"""

from __future__ import annotations

import collections
import math
import threading
from typing import Any

import numpy as np

# the rollup's dimensions, in matrix-column order: per tenant, this
# round's communication cost, node-load std, degraded flag (0/1),
# skipped flag (0/1), and reconcile drift pods
DIMS: tuple[str, ...] = ("cost", "load_std", "degraded", "skipped", "drift")
NUM_DIMS = len(DIMS)
# quantile points, in rollup order (nearest-rank; "max" is the T-th)
QUANTS: tuple[str, ...] = ("p50", "p90", "p99", "max")
NUM_QUANTS = len(QUANTS)


def rollup_size(top_k: int) -> int:
    """Flat length of one rollup vector: per dimension, the quantile
    points, one sum, and top-k (value, tenant-index) pairs."""
    return NUM_DIMS * (NUM_QUANTS + 1 + 2 * top_k)


def _quantile_positions(tenants: int) -> tuple[int, ...]:
    """Nearest-rank positions into an ascending sort of T values —
    static per shape, shared verbatim by the device and numpy halves so
    their quantiles agree exactly (modulo f32 sort order)."""
    return tuple(
        min(max(math.ceil(q * tenants) - 1, 0), tenants - 1)
        for q in (0.50, 0.90, 0.99)
    ) + (tenants - 1,)


def rollup_matrix(matrix, *, top_k: int):
    """The jittable rollup: ``f32[T, NUM_DIMS]`` → one flat
    ``f32[rollup_size(top_k)]`` vector (quantiles, sums, top-k worst
    values, top-k worst tenant indices — each dimension-major). "Worst"
    is HIGHEST for every dimension (cost, load imbalance, flags, drift
    all read that way); ties resolve to the lower tenant index
    (``lax.top_k``'s documented order, matching the numpy twin's stable
    argsort). ``top_k`` must already be clamped to ``<= T``."""
    import jax.numpy as jnp
    from jax import lax

    tenants = matrix.shape[0]
    cols = jnp.swapaxes(matrix, 0, 1).astype(jnp.float32)  # [D, T]
    pos = jnp.asarray(_quantile_positions(tenants))
    quants = jnp.sort(cols, axis=1)[:, pos]                # [D, Q]
    sums = jnp.sum(cols, axis=1)                           # [D]
    vals, idx = lax.top_k(cols, top_k)                     # [D, k] each
    return jnp.concatenate(
        [
            jnp.ravel(quants),
            sums,
            jnp.ravel(vals),
            jnp.ravel(idx.astype(jnp.float32)),
        ]
    )


def rollup_numpy(matrix: np.ndarray, *, top_k: int) -> np.ndarray:
    """Host-side recompute of :func:`rollup_matrix` — the oracle the
    acceptance soak compares the device rollup against (f32 tolerance;
    identical quantile definition and tie order by construction)."""
    m = np.asarray(matrix, dtype=np.float32)
    tenants = m.shape[0]
    pos = list(_quantile_positions(tenants))
    quants = np.empty((NUM_DIMS, NUM_QUANTS), np.float32)
    vals = np.empty((NUM_DIMS, top_k), np.float32)
    idx = np.empty((NUM_DIMS, top_k), np.float32)
    for d in range(NUM_DIMS):
        col = m[:, d]
        quants[d] = np.sort(col)[pos]
        order = np.argsort(-col, kind="stable")[:top_k]
        vals[d] = col[order]
        idx[d] = order.astype(np.float32)
    sums = m.sum(axis=0, dtype=np.float32)
    return np.concatenate(
        [quants.ravel(), sums, vals.ravel(), idx.ravel()]
    )


def decode_rollup(flat, *, top_k: int) -> dict[str, Any]:
    """Unpack one pulled rollup vector into the structured dict the
    publishers, the watchdog rule, and the events consume."""
    flat = np.asarray(flat, dtype=np.float32)
    if flat.size != rollup_size(top_k):
        raise ValueError(
            f"rollup vector of {flat.size} values does not decode at "
            f"top_k={top_k} (expected {rollup_size(top_k)})"
        )
    nq = NUM_DIMS * NUM_QUANTS
    quants = flat[:nq].reshape(NUM_DIMS, NUM_QUANTS)
    sums = flat[nq : nq + NUM_DIMS]
    off = nq + NUM_DIMS
    vals = flat[off : off + NUM_DIMS * top_k].reshape(NUM_DIMS, top_k)
    idx = (
        flat[off + NUM_DIMS * top_k :]
        .reshape(NUM_DIMS, top_k)
        .astype(np.int64)
    )
    return {
        "top_k": top_k,
        "dims": {
            dim: {
                "quantiles": {
                    q: float(quants[d, j]) for j, q in enumerate(QUANTS)
                },
                "sum": float(sums[d]),
                "worst": [
                    {"tenant": int(idx[d, r]), "value": float(vals[d, r])}
                    for r in range(top_k)
                ],
            }
            for d, dim in enumerate(DIMS)
        },
    }


# ---------------- device half: the fleet round-end bundle ----------------

_BUNDLE_KERNEL = None


def _fleet_round_bundle(states, graphs, last_pair, flags, active, *, top_k):
    """The fleet round's closing dispatch with rollups on: the batched
    per-tenant metrics pair (``solver.fleet._fleet_metrics`` — the same
    f32 path as the rollup-off kernel, so active tenants' recorded
    values stay bit-identical) followed by the fleet rollup. Tenants
    outside ``active`` (open breaker, dark backend) contribute their
    HOST-carried last-good pair to the rollup instead of the filler
    row's garbage; ``flags`` is the host's ``f32[T, 3]`` (degraded,
    skipped, drift) column block."""
    import jax.numpy as jnp

    from kubernetes_rescheduling_tpu.solver.fleet import _fleet_metrics

    pair = _fleet_metrics(states, graphs)  # f32[T, 2]
    merged = jnp.where(active[:, None], pair, last_pair)
    matrix = jnp.concatenate([merged, flags], axis=1)  # f32[T, NUM_DIMS]
    return jnp.concatenate(
        [jnp.ravel(pair), rollup_matrix(matrix, top_k=top_k)]
    )


def dispatch_fleet_bundle(states, graphs, last_pair, flags, active, *, top_k):
    """Async dispatch of the instrumented fleet round bundle
    (``fn="fleet_round_bundle"`` — the usual 1-steady-state-trace
    invariant; built lazily so this module imports jax-free)."""
    global _BUNDLE_KERNEL
    if _BUNDLE_KERNEL is None:
        from kubernetes_rescheduling_tpu.telemetry.accounting import (
            instrument_jit,
        )

        _BUNDLE_KERNEL = instrument_jit(
            _fleet_round_bundle,
            name="fleet_round_bundle",
            static_argnames=("top_k",),
        )
    return _BUNDLE_KERNEL(
        states, graphs, last_pair, flags, active, top_k=top_k
    )


def decode_fleet_bundle(
    flat, *, tenants: int, top_k: int
) -> tuple[np.ndarray, dict[str, Any]]:
    """Split one pulled fleet round bundle back into the per-tenant
    metrics pair ``f32[T, 2]`` and the decoded rollup."""
    flat = np.asarray(flat, dtype=np.float32)
    n_pair = tenants * 2
    if flat.size != n_pair + rollup_size(top_k):
        raise ValueError(
            f"fleet round bundle of {flat.size} values does not decode "
            f"at tenants={tenants}, top_k={top_k}"
        )
    metrics = flat[:n_pair].reshape(tenants, 2)
    return metrics, decode_rollup(flat[n_pair:], top_k=top_k)


# ---------------- host half: bounded families ----------------

def publish_rollup(registry, rollup: dict[str, Any]) -> None:
    """Decode → bounded metric families. Series count is k·dims +
    quantile points + a handful of fleet totals — independent of T.
    The value-bearing dims get their own quantile families (registered
    with literal names, the ``check_metrics_documented`` convention);
    the 0/1 flag dims publish as fleet-total counts instead (a median
    of flags is not an operator quantity — "how many right now" is)."""
    dims = rollup["dims"]
    quantile_gauges = (
        (
            "cost",
            registry.gauge(
                "fleet_cost_quantile",
                "fleet-wide communication-cost quantile across tenants "
                "after the most recent fleet round (q = p50|p90|p99|max)",
                labelnames=("q",),
            ),
        ),
        (
            "load_std",
            registry.gauge(
                "fleet_load_std_quantile",
                "fleet-wide node-load-std quantile across tenants after "
                "the most recent fleet round (q = p50|p90|p99|max)",
                labelnames=("q",),
            ),
        ),
        (
            "drift",
            registry.gauge(
                "fleet_drift_quantile",
                "fleet-wide reconcile-drift-pods quantile across tenants "
                "after the most recent fleet round (q = p50|p90|p99|max)",
                labelnames=("q",),
            ),
        ),
    )
    for dim, g in quantile_gauges:
        for q, v in dims[dim]["quantiles"].items():
            g.labels(q=q).set(v)
    registry.gauge(
        "fleet_degraded_tenants",
        "tenants whose most recent fleet round finished degraded "
        "(failed post-move monitor)",
    ).set(dims["degraded"]["sum"])
    registry.gauge(
        "fleet_skipped_tenants",
        "tenants whose most recent fleet round was a counted skip "
        "(open breaker or dark backend)",
    ).set(dims["skipped"]["sum"])
    registry.gauge(
        "fleet_drift_pods",
        "fleet-total pods currently diverged from their tenant's "
        "reconcile intent (sum over tenants)",
    ).set(dims["drift"]["sum"])
    worst = registry.gauge(
        "fleet_worst_tenant",
        "metric value of the rank-th worst tenant per rollup dimension "
        "(dim = cost|load_std|degraded|skipped|drift); tenant NAMES "
        "ride the fleet_rollup event payload and /tenants, never label "
        "keys (the cardinality-budget convention)",
        labelnames=("rank", "dim"),
    )
    for dim in DIMS:
        for rank, row in enumerate(dims[dim]["worst"]):
            worst.labels(rank=str(rank), dim=dim).set(row["value"])


def rollup_event(
    rollup: dict[str, Any],
    tenant_names,
    *,
    round: int | None = None,
) -> dict[str, Any]:
    """The JSON-able ``fleet_rollup`` event payload: quantiles and sums
    per dimension plus the worst-k rows WITH tenant names attached —
    the one place per-tenant identity legally rides (event payloads are
    unindexed; metric label keys are not)."""
    dims = rollup["dims"]
    return {
        **({"round": round} if round is not None else {}),
        "top_k": rollup["top_k"],
        "quantiles": {
            dim: dict(dims[dim]["quantiles"]) for dim in DIMS
        },
        "sums": {dim: dims[dim]["sum"] for dim in DIMS},
        "worst": [
            {
                "dim": dim,
                "rank": rank,
                "tenant": (
                    tenant_names[row["tenant"]]
                    if 0 <= row["tenant"] < len(tenant_names)
                    else str(row["tenant"])
                ),
                "value": row["value"],
            }
            for dim in DIMS
            for rank, row in enumerate(dims[dim]["worst"])
        ],
    }


# ---------------- the cardinality budget gate ----------------


class TenantSeries:
    """THE budget-gated gateway for tenant-labeled metric families.

    ``scripts/check_label_cardinality.py`` statically pins every
    ``labelnames=("tenant",)`` registration in the package to this
    module, so per-tenant series can only come into existence through
    this gate. At or under ``budget`` tenants the legacy families emit
    exactly as they always did (bit-identical, golden-pinned —
    ``budget=None`` means unlimited, the solo ledger's path); over
    budget every update is suppressed and counted
    ``tenant_series_suppressed_total{family}``, so an operator can see
    both THAT detail was dropped and which families to read the
    bounded rollups for instead.
    """

    def __init__(self, registry, *, tenants: int, budget: int | None):
        self.registry = registry
        self.tenants = int(tenants)
        self.budget = budget
        self.enabled = budget is None or self.tenants <= int(budget)

    def _suppress(self, family: str) -> None:
        self.registry.counter(
            "tenant_series_suppressed_total",
            "per-tenant metric series updates suppressed by the "
            "ObsConfig.tenant_label_budget cardinality gate — the fleet "
            "is over budget; read the bounded fleet rollup families "
            "(fleet_*_quantile, fleet_worst_tenant) instead",
            labelnames=("family",),
        ).labels(family=family).inc()

    def counter_inc(
        self, name: str, help: str, tenant: str, amount: float = 1.0
    ) -> None:
        if self.enabled:
            self.registry.counter(
                name, help, labelnames=("tenant",)
            ).labels(tenant=tenant).inc(amount)
        else:
            self._suppress(name)

    def gauge_set(
        self, name: str, help: str, tenant: str, value: float
    ) -> None:
        if self.enabled:
            self.registry.gauge(
                name, help, labelnames=("tenant",)
            ).labels(tenant=tenant).set(value)
        else:
            self._suppress(name)


# ---------------- the live plane's bounded views ----------------


class TenantSummaryRing:
    """Bounded per-tenant live summaries behind ``/tenants`` and
    ``/tenants/<name>``: the drill-down that replaces O(T) metric
    series. Each entry holds the tenant's LAST round summary, breaker
    state, reconcile drift, and a capped window of recent comm costs;
    the store itself is LRU-bounded (``max_tenants``) so unbounded
    tenant churn cannot grow it without limit. Thread-safe — the ops
    server reads it from request threads mid-round."""

    def __init__(
        self, *, cost_window: int = 32, max_tenants: int = 1024
    ) -> None:
        if cost_window < 1 or max_tenants < 1:
            raise ValueError("cost_window and max_tenants must be >= 1")
        self.cost_window = cost_window
        self.max_tenants = max_tenants
        self.evicted = 0
        self._entries: collections.OrderedDict[str, dict] = (
            collections.OrderedDict()
        )
        self._lock = threading.Lock()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def observe(
        self,
        tenant: str,
        *,
        record: dict | None = None,
        breaker: str | None = None,
        drift: int | None = None,
        skipped: bool = False,
    ) -> None:
        with self._lock:
            e = self._entries.get(tenant)
            if e is None:
                e = {
                    "tenant": tenant,
                    "rounds": 0,
                    "skipped_rounds": 0,
                    "degraded_rounds": 0,
                    "breaker": None,
                    "drift": 0,
                    "last": None,
                    "costs": collections.deque(maxlen=self.cost_window),
                }
            self._entries[tenant] = e
            self._entries.move_to_end(tenant)
            if skipped:
                e["skipped_rounds"] += 1
            if record is not None:
                e["rounds"] += 1
                if record.get("degraded"):
                    e["degraded_rounds"] += 1
                e["last"] = dict(record)
                cost = record.get("communication_cost")
                if cost is not None:
                    e["costs"].append(float(cost))
            if breaker is not None:
                e["breaker"] = breaker
            if drift is not None:
                e["drift"] = int(drift)
            while len(self._entries) > self.max_tenants:
                self._entries.popitem(last=False)
                self.evicted += 1

    def overview(self) -> list[dict]:
        """The ``/tenants`` listing: one compact row per tracked tenant
        (newest-updated last, the LRU order)."""
        with self._lock:
            return [
                {
                    "tenant": e["tenant"],
                    "breaker": e["breaker"],
                    "rounds": e["rounds"],
                    "skipped_rounds": e["skipped_rounds"],
                    "degraded_rounds": e["degraded_rounds"],
                    "drift": e["drift"],
                    "communication_cost": (
                        e["costs"][-1] if e["costs"] else None
                    ),
                }
                for e in self._entries.values()
            ]

    def detail(self, tenant: str) -> dict | None:
        """The ``/tenants/<name>`` drill-down (None = never seen or
        LRU-evicted)."""
        with self._lock:
            e = self._entries.get(tenant)
            if e is None:
                return None
            out = dict(e)
            out["costs"] = list(e["costs"])
            return out


def fleet_health_block(
    rows: dict[str, dict],
    *,
    budget: int | None,
    event: dict[str, Any] | None = None,
) -> dict:
    """The ``/healthz`` fleet block, budget-gated: at or under budget
    the per-tenant rows pass through UNCHANGED (the bit-identity
    contract with the pre-budget plane); over budget the block is a
    bounded summary — breaker-state counts, fleet totals, and — when
    ``event`` (the latest :func:`rollup_event` payload) is given — the
    rollup's quantiles and worst-k rows (with names — a JSON payload,
    not a metric label) — so ``/healthz`` stays O(k) however many
    tenants serve."""
    if budget is None or len(rows) <= budget:
        return rows
    breakers: collections.Counter = collections.Counter(
        str(r.get("breaker")) for r in rows.values()
    )
    out: dict[str, Any] = {
        "tenants": len(rows),
        "suppressed": True,
        "tenant_label_budget": budget,
        "breaker_states": dict(sorted(breakers.items())),
        "rounds": sum(r.get("rounds", 0) for r in rows.values()),
        "skipped_rounds": sum(
            r.get("skipped_rounds", 0) for r in rows.values()
        ),
        "degraded_rounds": sum(
            r.get("degraded_rounds", 0) for r in rows.values()
        ),
    }
    if event is not None:
        out["quantiles"] = event.get("quantiles")
        out["worst"] = event.get("worst")
    return out
