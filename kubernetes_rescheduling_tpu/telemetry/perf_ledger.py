"""Append-only performance ledger with rolling-window regression detection.

Every benchmark reading so far died in its own artifact: the bench
harness's ``summary.json`` per session, the driver's ``BENCH_r*.json``
snapshots per round — no trend view, no gate, a perf cliff between runs
invisible until someone eyeballs JSON. The ledger is the one
append-only JSONL file they all land in:

- one record per measurement, keyed by
  ``(metric, scenario, device_kind, config_digest)`` — the series key:
  readings only ever compare against readings of the same thing on the
  same kind of device under the same config;
- per-file monotone ``seq`` numbers (the schema checker's invariant —
  an interleaved or rewritten ledger is corrupt, not merely stale);
- ``better`` records the metric's direction (``"lower"`` for
  latencies, ``"higher"`` for decisions/sec), so the detector never
  needs a side table of metric semantics.

:func:`detect` is the rolling-window regression detector: the newest
reading of each series against the median (or best) of the window of
prior readings, with a configurable threshold fraction. Its verdicts —
``improved`` / ``flat`` / ``regressed`` — feed the ``telemetry perf``
trend table, the SLO watchdog's ``perf_regression`` rule
(``perf_regressions_total{metric}``), and ``/healthz``.

:func:`ingest_bench_file` converts the historical driver snapshots
(``BENCH_r*.json`` — a ``parsed`` headline block — and
``MULTICHIP_r*.json`` — a dry-run pass/fail) into ledger entries, so
five rounds of existing history become the first window.

jax-free, like the registry: the ledger is plain JSON bookkeeping.
"""

from __future__ import annotations

import hashlib
import json
import math
import threading
from pathlib import Path
from typing import Any, Iterable

LEDGER_SCHEMA = 1

#: keys every ledger record must carry (the schema checker's contract)
REQUIRED_KEYS: tuple[str, ...] = (
    "schema",
    "seq",
    "metric",
    "value",
    "unit",
    "scenario",
    "device_kind",
    "config_digest",
    "better",
)


def config_digest(config: Any) -> str:
    """Short stable digest of a config mapping — the ledger's "same
    config" key. Key order never matters; unserializable values stringify."""
    blob = json.dumps(config, sort_keys=True, default=str)
    return hashlib.sha1(blob.encode()).hexdigest()[:12]


def series_key(rec: dict[str, Any]) -> tuple[str, str, str, str]:
    return (
        str(rec.get("metric")),
        str(rec.get("scenario")),
        str(rec.get("device_kind")),
        str(rec.get("config_digest")),
    )


def validate_entry(rec: dict[str, Any]) -> list[str]:
    """Schema violations of one record (empty = valid)."""
    out = []
    for key in REQUIRED_KEYS:
        if key not in rec:
            out.append(f"missing key {key!r}")
    v = rec.get("value")
    if isinstance(v, (int, float)):
        if isinstance(v, float) and not math.isfinite(v):
            out.append(f"non-finite value {v!r}")
    elif "value" in rec:
        out.append(f"value must be a number, got {type(v).__name__}")
    if rec.get("better") not in (None, "lower", "higher"):
        out.append(f"better must be 'lower'|'higher', got {rec.get('better')!r}")
    seq = rec.get("seq")
    if "seq" in rec and (not isinstance(seq, int) or seq < 0):
        out.append(f"seq must be a non-negative int, got {seq!r}")
    return out


class PerfLedger:
    """One append-only JSONL ledger file.

    ``append`` assigns the next monotone ``seq`` (resuming from the file's
    current tail, so sessions appending to a shared ledger keep the
    invariant), validates the record, and fsync-appends one line."""

    def __init__(self, path: str | Path) -> None:
        self.path = Path(path)
        self._lock = threading.Lock()
        self._next_seq: int | None = None

    def _tail_seq(self) -> int:
        if not self.path.is_file():
            return -1
        last = -1
        for rec in self.entries():
            if isinstance(rec.get("seq"), int):
                last = max(last, rec["seq"])
        return last

    def append(
        self,
        *,
        metric: str,
        value: float,
        unit: str = "",
        scenario: str = "default",
        device_kind: str = "unknown",
        config: Any = None,
        digest: str | None = None,
        better: str = "lower",
        ts: float | None = None,
        **extra: Any,
    ) -> dict[str, Any]:
        """Append one reading; returns the record written."""
        with self._lock:
            if self._next_seq is None:
                self._next_seq = self._tail_seq() + 1
            rec = {
                "schema": LEDGER_SCHEMA,
                "seq": self._next_seq,
                "metric": metric,
                "value": float(value),
                "unit": unit,
                "scenario": scenario,
                "device_kind": device_kind,
                "config_digest": (
                    digest if digest is not None else config_digest(config)
                ),
                "better": better,
            }
            if ts is not None:
                rec["ts"] = ts
            if extra:
                rec["extra"] = extra
            bad = validate_entry(rec)
            if bad:
                raise ValueError(f"invalid ledger entry: {bad}")
            self.path.parent.mkdir(parents=True, exist_ok=True)
            with self.path.open("a") as f:
                f.write(json.dumps(rec, default=float) + "\n")
            self._next_seq += 1
            return rec

    def entries(self) -> list[dict[str, Any]]:
        if not self.path.is_file():
            return []
        return load_entries(self.path)


def load_entries(path: str | Path) -> list[dict[str, Any]]:
    out = []
    for line in Path(path).read_text().splitlines():
        line = line.strip()
        if line:
            out.append(json.loads(line))
    return out


# ---------------- historical-snapshot ingestion ----------------


def ingest_bench_file(path: str | Path) -> list[dict[str, Any]]:
    """One driver snapshot → ledger-shaped records (seq assigned by the
    caller/ledger). ``BENCH_r*.json`` carries a ``parsed`` headline
    ``{metric, value, unit, extra}``; ``MULTICHIP_r*.json`` is either a
    legacy dry-run verdict (r01–r05) or, from r06, a measured record —
    headline plus nested ``*_reading`` series, all keyed by the mesh
    identity (``device_kind`` × ``n_devices``) so forced-host CPU and
    real-slice runs never share a series. Anything else yields no
    records."""
    p = Path(path)
    try:
        doc = json.loads(p.read_text())
    except (OSError, json.JSONDecodeError):
        return []
    if not isinstance(doc, dict):
        return []
    # MULTICHIP records FIRST: a measured record (r06+) carries both the
    # driver's {n_devices, ok} envelope AND a parsed headline, and must
    # NOT fall into the generic BENCH branch below — that branch keys by
    # first device name and hardcodes better="lower", which would let a
    # forced-host CPU run share a trend series with a real slice (and
    # read a rounds/sec gain as a regression). The mesh identity
    # (device_kind × n_devices) is part of the series key here.
    n_devices = doc.get("n_devices")
    if n_devices is not None and "ok" in doc:
        parsed = doc.get("parsed")
        if (
            isinstance(parsed, dict)
            and "metric" in parsed
            and "value" in parsed
        ):
            kind = str(doc.get("device_kind") or f"unknownx{n_devices}")
            digest = config_digest({"n_devices": n_devices})

            def _rec(block: dict) -> dict[str, Any]:
                extra = block.get("extra") or {}
                return {
                    "schema": LEDGER_SCHEMA,
                    "seq": 0,
                    "metric": str(block["metric"]),
                    "value": float(block["value"]),
                    "unit": str(block.get("unit", "")),
                    "scenario": str(extra.get("scenario", "multichip")),
                    "device_kind": kind,
                    "config_digest": digest,
                    "better": str(block.get("better", "higher")),
                    "extra": {
                        "source": p.name,
                        "n_devices": n_devices,
                        "vs_baseline": block.get("vs_baseline"),
                    },
                }

            recs = [_rec(parsed)]
            for k, v in parsed.items():
                if (
                    k.endswith("_reading")
                    and isinstance(v, dict)
                    and "metric" in v
                    and "value" in v
                ):
                    recs.append(_rec(v))
            return recs
        # legacy dryrun receipt (r01–r05): unchanged shape
        return [
            {
                "schema": LEDGER_SCHEMA,
                "seq": 0,
                "metric": "multichip_dryrun_ok",
                "value": 1.0 if doc.get("ok") else 0.0,
                "unit": "bool",
                "scenario": f"n{doc.get('n_devices')}",
                "device_kind": "mesh",
                "config_digest": config_digest(
                    {"n_devices": doc.get("n_devices")}
                ),
                "better": "higher",
                "extra": {"source": p.name, "rc": doc.get("rc")},
            }
        ]
    parsed = doc.get("parsed")
    if isinstance(parsed, dict) and "metric" in parsed and "value" in parsed:
        extra = parsed.get("extra") or {}
        devices = extra.get("devices") or []
        return [
            {
                "schema": LEDGER_SCHEMA,
                "seq": 0,
                "metric": str(parsed["metric"]),
                "value": float(parsed["value"]),
                "unit": str(parsed.get("unit", "")),
                "scenario": str(extra.get("scenario", "bench")),
                "device_kind": str(devices[0]) if devices else "unknown",
                # deliberately CONSTANT: the driver's headline snapshots are
                # one evolving series per metric — digesting their drifting
                # knob set would shatter the history into singletons
                "config_digest": "bench-history",
                "better": "lower",  # headline benches are latencies (ms)
                "extra": {"source": p.name, "vs_baseline": parsed.get("vs_baseline")},
            }
        ]
    return []


def ingest_history(
    paths: Iterable[str | Path], ledger: PerfLedger | None = None
) -> list[dict[str, Any]]:
    """Ingest driver snapshots in order; appended to ``ledger`` when given
    (seq re-assigned by the ledger), else returned with sequential seq."""
    records: list[dict[str, Any]] = []
    for p in paths:
        records.extend(ingest_bench_file(p))
    if ledger is None:
        for i, rec in enumerate(records):
            rec["seq"] = i
        return records
    out = []
    for rec in records:
        out.append(
            ledger.append(
                metric=rec["metric"],
                value=rec["value"],
                unit=rec["unit"],
                scenario=rec["scenario"],
                device_kind=rec["device_kind"],
                digest=rec["config_digest"],
                better=rec["better"],
                **rec.get("extra", {}),
            )
        )
    return out


# ---------------- regression detection ----------------


def detect(
    entries: Iterable[dict[str, Any]],
    *,
    window: int = 5,
    threshold_frac: float = 0.2,
    baseline: str = "median",
    min_history: int = 2,
) -> dict[str, dict[str, Any]]:
    """Rolling-window verdict per series.

    For each series (same metric/scenario/device/config), the NEWEST
    reading is judged against the ``median`` (or ``best``) of up to
    ``window`` prior readings. A series with fewer than ``min_history``
    prior readings yields ``"fresh"`` — no judgement, never a false
    alarm on the first run of a new cell. Direction comes from the
    entries' ``better`` field.

    Returns ``{display_key: verdict}`` where the verdict carries
    ``status`` (improved|flat|regressed|fresh), current, baseline,
    ratio, and the series identity."""
    if baseline not in ("median", "best"):
        raise ValueError(f"baseline must be 'median'|'best', got {baseline!r}")
    series: dict[tuple, list[dict[str, Any]]] = {}
    for rec in entries:
        series.setdefault(series_key(rec), []).append(rec)
    # display keys: metric@scenario alone while unambiguous; when several
    # series share it (same cell on two device kinds, or across a config
    # change), qualify with device kind + digest so no verdict is silently
    # overwritten — a lost "regressed" would defeat the whole gate
    base_count: dict[str, int] = {}
    for metric, scenario, _, _ in series:
        base = f"{metric}@{scenario}"
        base_count[base] = base_count.get(base, 0) + 1
    out: dict[str, dict[str, Any]] = {}
    for key, recs in series.items():
        recs = sorted(recs, key=lambda r: r.get("seq", 0))
        metric, scenario, device_kind, digest = key
        display = f"{metric}@{scenario}"
        if base_count[display] > 1:
            display = f"{display}@{device_kind}#{digest[:6]}"
        current = float(recs[-1]["value"])
        better = recs[-1].get("better", "lower")
        prior = [float(r["value"]) for r in recs[:-1]][-window:]
        verdict: dict[str, Any] = {
            "metric": metric,
            "scenario": scenario,
            "device_kind": device_kind,
            "config_digest": digest,
            "better": better,
            "current": current,
            "n": len(recs),
        }
        if len(prior) < min_history:
            verdict.update(status="fresh", baseline=None, ratio=None)
            out[display] = verdict
            continue
        if baseline == "median":
            s = sorted(prior)
            mid = len(s) // 2
            base = (
                s[mid] if len(s) % 2 else 0.5 * (s[mid - 1] + s[mid])
            )
        else:
            base = min(prior) if better == "lower" else max(prior)
        ratio = current / base if base else math.inf if current else 1.0
        # normalize to "bigger ratio = worse" whatever the direction
        worse = ratio if better == "lower" else (1.0 / ratio if ratio else math.inf)
        if worse > 1.0 + threshold_frac:
            status = "regressed"
        elif worse < 1.0 - threshold_frac:
            status = "improved"
        else:
            status = "flat"
        verdict.update(status=status, baseline=base, ratio=ratio)
        out[display] = verdict
    return out


def regressions(verdicts: dict[str, dict[str, Any]]) -> dict[str, dict[str, Any]]:
    return {k: v for k, v in verdicts.items() if v.get("status") == "regressed"}


# ---------------- rendering ----------------


def render_table(verdicts: dict[str, dict[str, Any]]) -> list[str]:
    """The ``telemetry perf`` trend table, one row per series."""
    if not verdicts:
        return ["  (no perf series)"]
    rows = [
        (
            k,
            v["device_kind"][:24],
            str(v["n"]),
            f"{v['current']:.4g}",
            "-" if v.get("baseline") is None else f"{v['baseline']:.4g}",
            "-" if v.get("ratio") is None else f"{v['ratio']:.3f}",
            v["status"].upper() if v["status"] == "regressed" else v["status"],
        )
        for k, v in sorted(verdicts.items())
    ]
    header = ("series", "device", "n", "current", "baseline", "ratio", "verdict")
    widths = [
        max(len(header[i]), *(len(r[i]) for r in rows)) for i in range(len(header))
    ]
    fmt = "  " + "  ".join(f"{{:<{w}}}" for w in widths)
    lines = [fmt.format(*header)]
    lines.extend(fmt.format(*r) for r in rows)
    bad = regressions(verdicts)
    lines.append(
        f"  regressed: {len(bad)}/{len(verdicts)}"
        + (f" — {', '.join(sorted(bad))}" if bad else "")
    )
    return lines
