"""SLO v2: error budgets and multi-window multi-burn-rate alerting.

The PR-18 watchdog rules are point detectors — "p99 over 50 ms right
now". This module adds the Google-SRE maturation step: each
:class:`SloSpec` declares an *objective* over good/bad events, the
engine accounts the remaining **error budget** over a long window, and
alerts on the **burn rate** — how many multiples of the sustainable
error rate we are currently consuming — measured over paired windows:

- ``slo_fast_burn`` — the *page*: a high burn threshold over a short
  window, confirmed by an even shorter window (the classic 5m/1h pair,
  expressed in rounds/requests because the sim clock is not wall time).
  Fires earlier than any static threshold on a hard overload, which is
  the point: budget math detects "p99 will be blown soon" before p99 is
  blown.
- ``slo_slow_burn`` — the *ticket*: a lower threshold over a longer
  window, catching slow leaks a page-level rule would sleep through.

Both windows of a pair must agree before the rule fires (the
multi-window trick that kills the one-bad-round false positive), and
burn math runs on :class:`~telemetry.timeseries.SeriesStore` deltas, so
it inherits the history plane's reset tolerance and memory bounds.

Burn entries feed the existing watchdog as a new rule kind
(``Watchdog.observe_slo_burn``) so /healthz, ``slo_violations_total``,
structured logs, and the flight recorder all work unchanged; per-tenant
budget gauges route through the PR-13 ``TenantSeries`` gate — over the
tenant budget they are suppressed and counted, never registered.

One accounting caveat, accepted for simplicity: the ``rounds_success``
default spec counts degraded rounds in both ``rounds_total`` (good) and
``degraded_rounds_total`` (bad), slightly inflating the denominator
under degradation; clean soaks still read exactly 1.0 budget.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Sequence

from kubernetes_rescheduling_tpu.telemetry.registry import (
    MetricsRegistry,
    get_registry,
)
from kubernetes_rescheduling_tpu.telemetry.timeseries import SeriesStore

# watchdog rule names contributed by this module (picked up by
# scripts/check_watchdog_rules_documented.py alongside watchdog.py's)
RULE_FAST_BURN = "slo_fast_burn"
RULE_SLOW_BURN = "slo_slow_burn"

# a selector is (metric, ((label_key, label_value), ...)); empty labels
# match (and sum) every series of the family
Selector = tuple[str, tuple[tuple[str, str], ...]]


def _short_window(window: int) -> int:
    """The confirm window of a burn pair: 1/12 of the long window (the
    SRE-workbook 5m-of-1h ratio), floored at one tick."""
    return max(int(window) // 12, 1)


def budget_burn_frac(good: float, bad: float, objective: float) -> float:
    """Fraction of the error budget consumed by a finished run:
    bad / ((1 - objective) * total), clamped to [0, inf). The bench
    ledger's ``slo_budget_burn_frac`` reading (1.0 = budget exactly
    spent, >1 = SLO violated)."""
    total = good + bad
    if total <= 0:
        return 0.0
    allowed = (1.0 - objective) * total
    if allowed <= 0:
        return math.inf if bad > 0 else 0.0
    return bad / allowed


@dataclasses.dataclass(frozen=True)
class SloSpec:
    """One declarative SLO.

    ``kind="events"`` counts good/bad selector deltas; ``kind="latency"``
    derives them from a histogram family: good = requests at or under
    ``threshold_s`` (the cumulative count of the smallest bucket whose
    upper bound covers the threshold), bad = the rest."""

    name: str
    objective: float = 0.99
    kind: str = "events"
    good: tuple[Selector, ...] = ()
    bad: tuple[Selector, ...] = ()
    family: str = ""
    labels: tuple[tuple[str, str], ...] = ()
    threshold_s: float = 0.0

    def validate(self) -> "SloSpec":
        if not self.name:
            raise ValueError("SloSpec.name must be non-empty")
        if not 0.0 < self.objective < 1.0:
            raise ValueError(
                f"SloSpec {self.name}: objective must be in (0, 1)"
            )
        if self.kind not in ("events", "latency"):
            raise ValueError(
                f"SloSpec {self.name}: kind must be 'events' or 'latency'"
            )
        if self.kind == "events" and not (self.good or self.bad):
            raise ValueError(
                f"SloSpec {self.name}: events kind needs good/bad selectors"
            )
        if self.kind == "latency" and (
            not self.family or self.threshold_s <= 0
        ):
            raise ValueError(
                f"SloSpec {self.name}: latency kind needs family and "
                "threshold_s > 0"
            )
        return self


def default_specs(
    *, objective: float = 0.99, latency_threshold_ms: float = 0.0
) -> tuple[SloSpec, ...]:
    """The stock SLOs every wired loop gets: serving availability
    (placed or honestly-empty vs shed/timeout), control-loop round
    success, and — when a latency threshold is configured — serving
    latency over the total-stage histogram."""
    specs = [
        SloSpec(
            name="serving_availability",
            objective=objective,
            good=(
                ("serving_placements_total", (("outcome", "placed"),)),
                ("serving_placements_total", (("outcome", "no_candidate"),)),
            ),
            bad=(
                ("serving_placements_total", (("outcome", "shed"),)),
                ("serving_placements_total", (("outcome", "timeout"),)),
            ),
        ),
        SloSpec(
            name="rounds_success",
            objective=objective,
            good=(("rounds_total", ()),),
            bad=(
                ("rounds_skipped_total", ()),
                ("degraded_rounds_total", ()),
            ),
        ),
    ]
    if latency_threshold_ms > 0:
        specs.append(
            SloSpec(
                name="serving_latency",
                objective=objective,
                kind="latency",
                family="serving_request_seconds",
                labels=(("stage", "total"),),
                threshold_s=latency_threshold_ms / 1000.0,
            )
        )
    return tuple(s.validate() for s in specs)


class SloEngine:
    """Compiles :class:`SloSpec`s against a :class:`SeriesStore` and
    evaluates budget + burn each tick.

    ``evaluate`` returns the burn-rule entries for
    ``Watchdog.observe_slo_burn`` (worst spec wins per rule) and
    publishes ``slo_budget_remaining_frac{slo}`` /
    ``slo_burn_rate{slo,window}`` gauges; ``table()`` is the /slo
    endpoint's cached readout."""

    def __init__(
        self,
        specs: Sequence[SloSpec],
        store: SeriesStore,
        *,
        registry: MetricsRegistry | None = None,
        budget_window: int = 512,
        fast_window: int = 48,
        fast_burn: float = 14.4,
        slow_window: int = 288,
        slow_burn: float = 6.0,
        tenant_series: Any = None,
    ) -> None:
        self.specs = tuple(s.validate() for s in specs)
        self.store = store
        self.registry = registry
        self.budget_window = int(budget_window)
        self.fast_window = int(fast_window)
        self.fast_burn = float(fast_burn)
        self.slow_window = int(slow_window)
        self.slow_burn = float(slow_burn)
        self.tenant_series = tenant_series
        self._table: list[dict[str, Any]] = []
        # tenant -> [good, bad]; populated ONLY while the TenantSeries
        # gate is enabled (tenants <= budget), so it is budget-bounded
        self._tenant_events: dict[str, list[float]] = {}

    def _reg(self) -> MetricsRegistry:
        return self.registry if self.registry is not None else get_registry()

    # ---- event extraction ----

    def _selector_delta(self, sel: Selector, window: int) -> float:
        metric, labels = sel
        return sum(
            self.store.delta(name, window)
            for name in self.store.match(metric, labels)
        )

    def _events(self, spec: SloSpec, window: int) -> tuple[float, float]:
        """(good, bad) event counts over the trailing ``window`` ticks."""
        if spec.kind == "events":
            good = sum(self._selector_delta(s, window) for s in spec.good)
            bad = sum(self._selector_delta(s, window) for s in spec.bad)
            return good, bad
        # latency kind: per underlying histogram series, good = the
        # cumulative count of the smallest bucket covering the
        # threshold, bad = total count minus that
        buckets: dict[str, list[tuple[float, str]]] = {}
        totals: dict[str, str] = {}
        for name in self.store.match(spec.family, spec.labels):
            label_part = "{" + name.split("{", 1)[1] if "{" in name else ""
            if ":le:" in name:
                ub = name.partition(":le:")[2].split("{", 1)[0]
                buckets.setdefault(label_part, []).append((float(ub), name))
            elif ":" not in name.split("{", 1)[0].removeprefix(spec.family):
                totals[label_part] = name  # bare count series, not :sum
        good = bad = 0.0
        for label_part, edges in buckets.items():
            covering = min(
                (e for e in edges if e[0] + 1e-12 >= spec.threshold_s),
                default=None,
            )
            total_name = totals.get(label_part)
            if covering is None or total_name is None:
                continue
            under = self.store.delta(covering[1], window)
            total = self.store.delta(total_name, window)
            good += under
            bad += max(total - under, 0.0)
        return good, bad

    # ---- evaluation ----

    def burn_rate(self, spec: SloSpec, window: int) -> float:
        """error_frac / (1 - objective) over the trailing window: 1.0
        burns the budget exactly at its sustainable rate, 0 with no
        traffic."""
        good, bad = self._events(spec, window)
        total = good + bad
        if total <= 0:
            return 0.0
        return (bad / total) / (1.0 - spec.objective)

    def evaluate(self, tick: int) -> dict[str, dict[str, Any]]:
        """One evaluation pass at ``tick``; call after the store sampled
        this tick's snapshot. Returns ``{rule: detail}`` burn entries
        for the watchdog (empty when nothing burns)."""
        reg = self._reg()
        table: list[dict[str, Any]] = []
        entries: dict[str, dict[str, Any]] = {}
        for spec in self.specs:
            good_b, bad_b = self._events(spec, self.budget_window)
            total_b = good_b + bad_b
            allowed = (1.0 - spec.objective) * total_b
            remaining = (
                1.0
                if total_b <= 0
                else max(0.0, min(1.0, 1.0 - bad_b / max(allowed, 1e-12)))
            )
            fast = self.burn_rate(spec, self.fast_window)
            fast_short = self.burn_rate(spec, _short_window(self.fast_window))
            slow = self.burn_rate(spec, self.slow_window)
            slow_short = self.burn_rate(spec, _short_window(self.slow_window))
            # ticks until the remaining budget is gone at the current
            # fast-window bad-event rate (None when not burning)
            tte = None
            if bad_b > 0:
                bad_rate = self._events(spec, self.fast_window)[1] / max(
                    self.fast_window, 1
                )
                if bad_rate > 0:
                    tte = max(allowed - bad_b, 0.0) / bad_rate
            row = {
                "slo": spec.name,
                "objective": spec.objective,
                "budget_remaining_frac": round(remaining, 6),
                "burn_fast": round(fast, 4),
                "burn_fast_short": round(fast_short, 4),
                "burn_slow": round(slow, 4),
                "burn_slow_short": round(slow_short, 4),
                "good": good_b,
                "bad": bad_b,
                "budget_window": self.budget_window,
                "time_to_exhaustion": (
                    round(tte, 1) if tte is not None else None
                ),
                "tick": int(tick),
            }
            table.append(row)
            reg.gauge(
                "slo_budget_remaining_frac",
                "fraction of the SLO error budget remaining over the "
                "budget window (1.0 = untouched)",
                labelnames=("slo",),
            ).labels(slo=spec.name).set(round(remaining, 6))
            burn_gauge = reg.gauge(
                "slo_burn_rate",
                "error-budget burn rate over the paired alert windows "
                "(1.0 = sustainable consumption)",
                labelnames=("slo", "window"),
            )
            burn_gauge.labels(slo=spec.name, window="fast").set(round(fast, 4))
            burn_gauge.labels(slo=spec.name, window="slow").set(round(slow, 4))
            for rule, burn, short, window, threshold in (
                (RULE_FAST_BURN, fast, fast_short, self.fast_window, self.fast_burn),
                (RULE_SLOW_BURN, slow, slow_short, self.slow_window, self.slow_burn),
            ):
                if threshold <= 0:
                    continue
                # multi-window confirm: both the long window and its
                # 1/12 confirm window must exceed the threshold
                if burn >= threshold and short >= threshold:
                    detail = {
                        "slo": spec.name,
                        "burn_rate": round(burn, 4),
                        "burn_rate_short": round(short, 4),
                        "window": window,
                        "short_window": _short_window(window),
                        "threshold": threshold,
                        "budget_remaining_frac": round(remaining, 6),
                        "time_to_exhaustion": row["time_to_exhaustion"],
                        "value": round(burn, 4),
                    }
                    prev = entries.get(rule)
                    if prev is None or detail["burn_rate"] > prev["burn_rate"]:
                        entries[rule] = detail
        self._table = table
        return entries

    def table(self) -> list[dict[str, Any]]:
        """The last evaluation's budget/burn table (the /slo payload)."""
        return [dict(row) for row in self._table]

    # ---- per-tenant budgets (fleet mode) ----

    def observe_tenant_round(self, tenant: str, ok: bool) -> None:
        """Account one tenant round against the per-tenant budget and
        publish ``slo_tenant_budget_remaining_frac`` through the
        TenantSeries gate. With the gate disabled (tenant count over
        the label budget) nothing is stored — the suppressed publish is
        counted by the gate itself, keeping this T-independent."""
        ts = self.tenant_series
        if ts is None or not getattr(ts, "enabled", False):
            if ts is not None:
                # over budget: route one (suppressed, counted) publish
                # through the gate so the drop is observable
                ts.gauge_set(
                    "slo_tenant_budget_remaining_frac",
                    "per-tenant SLO error budget remaining "
                    "(TenantSeries-gated)",
                    tenant,
                    1.0,
                )
            return
        good_bad = self._tenant_events.setdefault(tenant, [0.0, 0.0])
        good_bad[0 if ok else 1] += 1.0
        good, bad = good_bad
        objective = self.specs[0].objective if self.specs else 0.99
        allowed = (1.0 - objective) * (good + bad)
        remaining = (
            1.0
            if good + bad <= 0
            else max(0.0, min(1.0, 1.0 - bad / max(allowed, 1e-12)))
        )
        ts.gauge_set(
            "slo_tenant_budget_remaining_frac",
            "per-tenant SLO error budget remaining (TenantSeries-gated)",
            tenant,
            round(remaining, 6),
        )

    def tenant_budgets(self) -> dict[str, float]:
        out = {}
        for tenant, (good, bad) in sorted(self._tenant_events.items()):
            objective = self.specs[0].objective if self.specs else 0.99
            allowed = (1.0 - objective) * (good + bad)
            out[tenant] = (
                1.0
                if good + bad <= 0
                else max(0.0, min(1.0, 1.0 - bad / max(allowed, 1e-12)))
            )
        return out
