"""In-block tripwires: device-side health detection for the scanned
schedules.

The scanned schedule (``bench/scan.py``) is the repo's fastest path and
its blindest: every health signal the control loop reacts to — hazard
persistence, cost regressions, corrupted readings — is only visible to
the watchdog and breaker AFTER the block's single pull, so a fault in
round 1 of a 64-round block is detected K rounds late with its decisions
already committed. This module moves detection INTO the trace:

- **Device half** — :func:`tripwire_step` (solo) /
  :func:`fleet_tripwire_step` (per-tenant, vmapped): per-round rule
  predicates evaluated inside the ``lax.scan`` body, POST-apply, against
  the round's new state and metrics. Four rules, each a bit in the
  round's rule mask:

  - ``non_finite`` (bit 1) — any non-finite value in the VALID slots of
    the evolving sim state, or a non-finite cost/load reading (always
    armed while the plane is on: a NaN is never policy);
  - ``cost_regression`` (bit 2) — communication cost rising more than a
    configured fraction above the BLOCK-START baseline (carried in the
    scan carry, so the comparison is in-trace and free);
  - ``load_std_spike`` (bit 4) — node-load std exceeding a configured
    factor of the block-start baseline;
  - ``hazard_streak`` (bit 8) — the SAME node detected most-hazardous
    for a configured number of consecutive rounds (the decide loop is
    stuck on a hazard it cannot drain).

  Thresholds ride a TRACED f32 config vector (:func:`trip_config_array`)
  — re-tuning a threshold never retraces the block kernel. Once any rule
  trips, the carry LATCHES: every remaining round in the block becomes a
  no-move identity round in-trace (the scan kernels mask the decide
  outputs to the apply's ``-1`` no-op sentinel), so a poisoned lane
  freezes instead of compounding. The fleet variant latches PER TENANT —
  one bad tenant freezes only its own lane.

- **The bundle ride** — the per-round rule bitmasks plus the final
  carry's (trip round, trip mask) append to the EXISTING block bundle:
  zero new transfers (the block's one counted ``round_end`` pull is
  test-pinned unchanged). :func:`split_tripwire` /
  :func:`split_fleet_tripwire` strip the appended block host-side and
  hand the untouched core bundle to the existing decoders.

- **Host half** — :class:`TripReport` (what tripped, where),
  :func:`count_tripwire` (``scan_tripwires_total{rule}``). The
  controller truncates the replay at the trip round (post-trip identity
  rounds are never replayed into the backend), drains under the counted
  ``tripwire`` reason, and feeds the ops plane
  (``OpsPlane.observe_scan_block`` → the ``scan_tripwire`` SLO rule on
  /healthz plus a flight-recorder dump scoped to the partial block).

With the plane off — and on every trip-free block — the scan kernels'
outputs are bit-identical to the pre-tripwire path (golden-pinned in
tests/test_tripwire.py). Module import stays jax-free (the fleet_rollup
convention); the device functions import jax lazily at trace time.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

# rule bits, in mask order (TRIPWIRE_RULES[i] <-> bit 1 << i)
TRIP_NON_FINITE = 1
TRIP_COST_REGRESSION = 2
TRIP_LOAD_STD_SPIKE = 4
TRIP_HAZARD_STREAK = 8
TRIPWIRE_RULES: tuple[str, ...] = (
    "non_finite",
    "cost_regression",
    "load_std_spike",
    "hazard_streak",
)
# traced config vector layout (f32[3]): a zero disables its rule
CFG_COST_FRAC, CFG_LOAD_FACTOR, CFG_HAZARD_STREAK = range(3)


def rules_from_mask(mask: int) -> tuple[str, ...]:
    """Decode a rule bitmask into rule names, bit order."""
    return tuple(
        name for i, name in enumerate(TRIPWIRE_RULES) if mask & (1 << i)
    )


def trip_config_array(obs):
    """The traced threshold vector from an ``ObsConfig`` block — traced,
    not static, so tuning a threshold reuses the compiled block kernel
    (the 1-steady-state-trace invariant survives re-tuning)."""
    import jax.numpy as jnp

    return jnp.asarray(
        [
            float(getattr(obs, "tripwire_cost_frac", 0.0)),
            float(getattr(obs, "tripwire_load_factor", 0.0)),
            float(getattr(obs, "tripwire_hazard_streak", 0)),
        ],
        jnp.float32,
    )


def _finite_state(st):
    """True while every VALID slot of the sim state is finite — masked
    exactly like the decision kernels (``pod_valid`` / ``node_valid``
    gate the reads), so padded slots can never trip the wire."""
    import jax.numpy as jnp

    pod = jnp.where(st.pod_valid, st.pod_cpu + st.pod_mem, 0.0)
    node = jnp.where(
        st.node_valid,
        st.node_cpu_cap + st.node_mem_cap + st.node_base_cpu
        + st.node_base_mem,
        0.0,
    )
    return jnp.all(jnp.isfinite(pod)) & jnp.all(jnp.isfinite(node))


def tripwire_init(cost0, load0):
    """The scan-carry tripwire slot at block start: unlatched, no trip
    recorded, the block-start (cost, load) baselines, no hazard streak.
    Shape-generic — scalars for the solo scan, ``[T]`` vectors for the
    fleet's per-tenant latches."""
    import jax.numpy as jnp

    cost0 = jnp.asarray(cost0, jnp.float32)
    z = jnp.zeros(jnp.shape(cost0), jnp.int32)
    return (
        jnp.zeros(jnp.shape(cost0), bool),        # latched
        z - 1,                                    # trip round (block-rel)
        z,                                        # trip rule mask
        cost0,                                    # baseline cost
        jnp.asarray(load0, jnp.float32),          # baseline load std
        z - 1,                                    # previous most-hazard
        z,                                        # hazard streak length
        z,                                        # block-relative index
    )


def tripwire_step(carry, st, cost, load_std, most, cfg):
    """One round's tripwire evaluation, POST-apply: the new state ``st``
    and its metrics judge; a newly tripped round records its
    block-relative index and rule mask in the carry and sets the latch
    the scan body reads NEXT round (the trip round itself executed — the
    replay truncation is the host's job). Latched rounds evaluate
    nothing (bits 0): their lane is frozen identity rounds. Returns
    ``(new_carry, bits)`` — ``bits`` is the round's i32 rule mask."""
    import jax.numpy as jnp

    latched, trip_rnd, trip_mask, base_cost, base_load, prev, streak, idx = (
        carry
    )
    finite = (
        _finite_state(st) & jnp.isfinite(cost) & jnp.isfinite(load_std)
    )
    bits = jnp.where(finite, 0, TRIP_NON_FINITE).astype(jnp.int32)
    cost_frac = cfg[CFG_COST_FRAC]
    bits = bits | jnp.where(
        (cost_frac > 0)
        & (base_cost > 0)
        & (cost > (1.0 + cost_frac) * base_cost),
        TRIP_COST_REGRESSION,
        0,
    ).astype(jnp.int32)
    load_factor = cfg[CFG_LOAD_FACTOR]
    bits = bits | jnp.where(
        (load_factor > 0)
        & (base_load > 0)
        & (load_std > load_factor * base_load),
        TRIP_LOAD_STD_SPIKE,
        0,
    ).astype(jnp.int32)
    # same-hazard-node persistence: a valid most-hazard equal to last
    # round's extends the streak, a different one restarts it, none
    # clears it
    new_streak = jnp.where(
        most >= 0,
        jnp.where(most == prev, streak + 1, 1),
        0,
    ).astype(jnp.int32)
    hz = cfg[CFG_HAZARD_STREAK]
    bits = bits | jnp.where(
        (hz > 0) & (new_streak >= hz.astype(jnp.int32)),
        TRIP_HAZARD_STREAK,
        0,
    ).astype(jnp.int32)
    bits = jnp.where(latched, 0, bits).astype(jnp.int32)
    tripped = bits != 0
    return (
        (
            latched | tripped,
            jnp.where(tripped, idx, trip_rnd),
            jnp.where(tripped, bits, trip_mask),
            base_cost,
            base_load,
            jnp.asarray(most, jnp.int32),
            new_streak,
            idx + 1,
        ),
        bits,
    )


def fleet_tripwire_step(carry, states, metrics, most, cfg):
    """The fleet composition: :func:`tripwire_step` vmapped over the
    leading tenant axis — per-tenant latches, baselines, and streaks
    (``metrics`` is the fleet round's ``f32[T, 2]`` (cost, load_std)
    pair). One bad tenant freezes only its own lane."""
    import jax

    return jax.vmap(
        lambda c, s, co, ld, m: tripwire_step(c, s, co, ld, m, cfg)
    )(carry, states, metrics[:, 0], metrics[:, 1], most)


# ---------------- host half: decode + accounting ----------------


@dataclass(frozen=True)
class TripReport:
    """One block's decoded tripwire verdict. ``trip_round`` is
    BLOCK-relative (-1 = the block ran clean); in the fleet variant the
    fields are per-tenant arrays and :attr:`tripped` means ANY tenant
    tripped."""

    bits: np.ndarray          # i64[K] (solo) / i64[K, T] (fleet)
    trip_round: int | np.ndarray
    trip_mask: int | np.ndarray

    @property
    def tripped(self) -> bool:
        return bool(np.any(np.asarray(self.trip_round) >= 0))

    @property
    def rules(self) -> tuple[str, ...]:
        """Rule names in the (union, for fleet) trip mask."""
        mask = int(np.bitwise_or.reduce(
            np.atleast_1d(np.asarray(self.trip_mask, np.int64))
        ))
        return rules_from_mask(mask)


def split_tripwire(
    flat: np.ndarray, *, rounds: int
) -> tuple[np.ndarray, TripReport]:
    """Strip the appended tripwire block — per-round bits ``[K]`` plus
    the final carry's ``(trip_round, trip_mask)`` — off a solo scan
    bundle, returning the untouched core for ``decode_block``."""
    flat = np.asarray(flat, dtype=np.float32)
    tail = rounds + 2
    if flat.size <= tail:
        raise ValueError(
            f"scan bundle of {flat.size} values has no tripwire block at "
            f"rounds={rounds}"
        )
    trail = flat[-tail:]
    return flat[:-tail], TripReport(
        bits=trail[:rounds].astype(np.int64),
        trip_round=int(trail[rounds]),
        trip_mask=int(trail[rounds + 1]),
    )


def split_fleet_tripwire(
    flat: np.ndarray, *, rounds: int, tenants: int
) -> tuple[np.ndarray, TripReport]:
    """The fleet twin: bits ``[K, T]`` plus per-tenant
    ``trip_round[T]`` / ``trip_mask[T]`` trail the fleet bundle."""
    flat = np.asarray(flat, dtype=np.float32)
    tail = rounds * tenants + 2 * tenants
    if flat.size <= tail:
        raise ValueError(
            f"fleet scan bundle of {flat.size} values has no tripwire "
            f"block at rounds={rounds}, tenants={tenants}"
        )
    trail = flat[-tail:]
    n_bits = rounds * tenants
    return flat[:-tail], TripReport(
        bits=trail[:n_bits].reshape(rounds, tenants).astype(np.int64),
        trip_round=trail[n_bits : n_bits + tenants].astype(np.int64),
        trip_mask=trail[n_bits + tenants :].astype(np.int64),
    )


def count_tripwire(registry, rules) -> None:
    """One tripped block's rule accounting: each rule in the trip mask
    counts once in ``scan_tripwires_total{rule}``."""
    fam = registry.counter(
        "scan_tripwires_total",
        "scan blocks tripped by the in-block tripwire plane, by rule "
        "(a block tripping on multiple rules counts once per rule)",
        labelnames=("rule",),
    )
    for rule in rules:
        fam.labels(rule=rule).inc()
