"""Per-run provenance manifest: config, devices, versions, git rev.

A metrics dump without the run that produced it is noise; the manifest
makes every ``--metrics-out``/``--trace-out`` artifact self-describing —
what command ran, on which devices, with which jax, from which commit.
"""

from __future__ import annotations

import json
import os
import platform
import subprocess
import sys
import time
from pathlib import Path
from typing import Any


def _git_rev(cwd: str | None = None) -> dict[str, Any] | None:
    try:
        rev = subprocess.run(
            ["git", "rev-parse", "HEAD"],
            capture_output=True, text=True, timeout=5, cwd=cwd,
        )
        if rev.returncode != 0:
            return None
        dirty = subprocess.run(
            ["git", "status", "--porcelain"],
            capture_output=True, text=True, timeout=5, cwd=cwd,
        )
        return {
            "rev": rev.stdout.strip(),
            "dirty": bool(dirty.stdout.strip()) if dirty.returncode == 0 else None,
        }
    except Exception:
        return None


def _jax_info() -> dict[str, Any]:
    """Device inventory WITHOUT importing jax on a process that has not
    already paid for it — importing jax here would initialize a backend
    as a side effect of writing a manifest."""
    jax = sys.modules.get("jax")
    if jax is None:
        return {"imported": False}
    try:
        devices = jax.devices()
        return {
            "imported": True,
            "version": jax.__version__,
            "backend": jax.default_backend(),
            "device_count": len(devices),
            "devices": [str(d) for d in devices],
        }
    except Exception as e:  # backend init can fail on misconfigured hosts
        return {"imported": True, "version": jax.__version__, "error": str(e)}


def _device_costs() -> dict[str, Any]:
    """Compiled-cost snapshots captured so far (costmodel.CostBook) plus a
    live device-memory sample — the device-side half of the provenance: a
    latency number without the kernel's flops/HBM footprint next to it is
    not reproducible evidence. jax-free (the book is plain dicts; the
    memory sample reads ``sys.modules`` like :func:`_jax_info`)."""
    from kubernetes_rescheduling_tpu.telemetry.costmodel import (
        get_costbook,
        sample_device_memory,
    )
    from kubernetes_rescheduling_tpu.telemetry.registry import MetricsRegistry

    try:
        return {
            "kernels": get_costbook().as_dict(),
            # scratch registry: writing a manifest must not mutate the
            # process registry's gauges as a side effect
            "device_memory": sample_device_memory(MetricsRegistry()),
        }
    except Exception:  # noqa: BLE001 — provenance must not fail the run
        return {"kernels": {}, "device_memory": []}


def _attribution_book() -> dict[str, Any]:
    """Latest per-algorithm cost-attribution summary (where the
    communication cost sits: total, top edge, moves tracked) — the
    topology-plane half of the provenance. jax-free, best-effort."""
    from kubernetes_rescheduling_tpu.telemetry.attribution import (
        get_attribution_book,
    )

    try:
        return get_attribution_book().as_dict()
    except Exception:  # noqa: BLE001 — provenance must not fail the run
        return {}


def run_manifest(config: dict[str, Any] | None = None) -> dict[str, Any]:
    import numpy as np

    return {
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
        "argv": list(sys.argv),
        "config": config,
        "python": sys.version.split()[0],
        "platform": platform.platform(),
        "hostname": platform.node(),
        "pid": os.getpid(),
        "numpy": np.__version__,
        "jax": _jax_info(),
        "device_costs": _device_costs(),
        "attribution": _attribution_book(),
        "git": _git_rev(cwd=str(Path(__file__).resolve().parent)),
    }


def write_manifest(
    path: str | Path, config: dict[str, Any] | None = None
) -> dict[str, Any]:
    m = run_manifest(config)
    p = Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(json.dumps(m, indent=2, default=str))
    return m
