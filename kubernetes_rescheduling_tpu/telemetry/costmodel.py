"""Compiled-cost introspection and HBM accounting for instrumented kernels.

The host-side telemetry (spans, retrace counters, round latency) says how
long a kernel TOOK; nothing so far says what XLA actually compiled — how
many FLOPs the decision kernel costs, how much HBM its temporaries hold,
whether a round is compute- or bandwidth-bound. This module closes that
gap:

- :func:`capture_compiled_cost` — at the FIRST compile of an
  ``instrument_jit``-ed kernel (the hook lives in ``accounting.py``), AOT
  lower+compile the raw function at the same call signature and record
  the executable's ``cost_analysis()`` (flops, bytes accessed) and
  ``memory_analysis()`` (argument/output/temp/generated-code bytes) into
  the process :class:`CostBook` and the metrics registry
  (``jax_cost_*{fn}`` / ``jax_hbm_*{fn}`` gauges,
  ``jax_cost_captures_total{fn}``). Capture is once per function — cache
  hits and later retraces never re-pay the extra compile.
- :func:`publish_roofline` — achieved FLOP/s and bytes/s for a fenced
  device timing against the captured static cost, plus the kernel's
  arithmetic intensity (flops / bytes accessed): the roofline
  coordinates that say which wall a round is near.
- :func:`sample_device_memory` — live ``device.memory_stats()``
  (``bytes_in_use`` / ``peak_bytes_in_use``) as per-device gauges; the
  controller samples once per round. Backends without memory stats
  (CPU) simply contribute no samples.

Everything is best-effort by contract: a backend that cannot answer a
cost query must never take down the loop it is instrumenting. The module
imports jax lazily, so the jax-free consumers of :class:`CostBook`
(manifest, flight recorder) stay jax-free. Set ``KRT_COST_CAPTURE=0`` to
disable the capture-time extra compile entirely.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Any, Callable, Mapping

from kubernetes_rescheduling_tpu.telemetry.registry import (
    MetricsRegistry,
    get_registry,
)

# one row per cost_analysis/memory_analysis field we surface; the gauge
# names are the operator-facing contract (inventoried in OBSERVABILITY.md).
# publish_cost_gauges registers each name LITERALLY (the inventory checker
# reads registration sites statically) — keep this table and that function
# in sync; tests iterate the table against the exposed text.
COST_GAUGES: tuple[tuple[str, str, str], ...] = (
    ("flops", "jax_cost_flops",
     "XLA cost-analysis FLOPs of the compiled kernel"),
    ("bytes_accessed", "jax_cost_bytes_accessed",
     "XLA cost-analysis bytes accessed by the compiled kernel"),
    ("argument_bytes", "jax_hbm_argument_bytes",
     "device memory held by the compiled kernel's arguments"),
    ("output_bytes", "jax_hbm_output_bytes",
     "device memory held by the compiled kernel's outputs"),
    ("temp_bytes", "jax_hbm_temp_bytes",
     "device scratch memory of the compiled kernel (temporaries)"),
    ("generated_code_bytes", "jax_hbm_generated_code_bytes",
     "generated-code size of the compiled kernel"),
)


def capture_enabled() -> bool:
    return os.environ.get("KRT_COST_CAPTURE", "1") not in ("0", "false", "off")


class CostBook:
    """Process-wide snapshots of compiled-kernel cost, keyed by fn label.

    The book outlives any one registry: tests (and the bench harness)
    swap fresh registries mid-process, while a module-level kernel only
    compiles once — republishing from the book is what keeps the gauges
    visible in whichever registry is current."""

    def __init__(self) -> None:
        self._snaps: dict[str, dict[str, float]] = {}
        self._lock = threading.Lock()

    def record(self, fn_label: str, snap: Mapping[str, float]) -> None:
        with self._lock:
            self._snaps[fn_label] = dict(snap)

    def get(self, fn_label: str) -> dict[str, float] | None:
        with self._lock:
            snap = self._snaps.get(fn_label)
            return dict(snap) if snap is not None else None

    def labels(self) -> list[str]:
        with self._lock:
            return sorted(self._snaps)

    def as_dict(self) -> dict[str, dict[str, float]]:
        """fn label -> cost snapshot (the manifest / bundle surface)."""
        with self._lock:
            return {k: dict(v) for k, v in sorted(self._snaps.items())}

    def clear(self) -> None:
        with self._lock:
            self._snaps.clear()


_default_book = CostBook()


def get_costbook() -> CostBook:
    return _default_book


def has_tracers(args: tuple, kwargs: dict) -> bool:
    """True when the call carries jax tracers — i.e. the instrumented
    wrapper was invoked inside an OUTER trace; capture must wait for a
    concrete call (lowering tracer avals AOT is not meaningful)."""
    jax = sys.modules.get("jax")
    if jax is None:
        return False
    try:
        return any(
            isinstance(leaf, jax.core.Tracer)
            for leaf in jax.tree_util.tree_leaves((args, kwargs))
        )
    except Exception:  # noqa: BLE001 — never let introspection crash a call
        return False


def _normalize_cost_analysis(ca: Any) -> dict[str, float]:
    """``Compiled.cost_analysis()`` returns a dict on recent jax and a
    one-element list of dicts on older releases — flatten either."""
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return dict(ca or {})


def capture_compiled_cost(
    fn: Callable,
    fn_label: str,
    args: tuple,
    kwargs: dict,
    *,
    jit_kwargs: dict | None = None,
    registry: MetricsRegistry | None = None,
) -> dict[str, float] | None:
    """AOT lower+compile ``fn`` at this call signature and record its
    static cost. Returns the snapshot, or None when capture is off, the
    args are tracers (the wrapper was called inside an outer trace —
    retried at the next concrete call), or the backend cannot answer.

    Uses a FRESH ``jax.jit`` of the raw function, never the instrumented
    wrapper's own jit: lowering the wrapper would re-run its traced body
    and corrupt the ``jax_traces_total`` invariant the accounting exists
    to pin."""
    if not capture_enabled():
        return None
    try:
        import jax

        leaves = jax.tree_util.tree_leaves((args, kwargs))
        if any(isinstance(leaf, jax.core.Tracer) for leaf in leaves):
            return None
        compiled = (
            jax.jit(fn, **(jit_kwargs or {})).lower(*args, **kwargs).compile()
        )
        ca = _normalize_cost_analysis(compiled.cost_analysis())
        ma = compiled.memory_analysis()
        snap = {
            "flops": float(ca.get("flops", 0.0) or 0.0),
            "bytes_accessed": float(ca.get("bytes accessed", 0.0) or 0.0),
            "argument_bytes": float(
                getattr(ma, "argument_size_in_bytes", 0) or 0
            ),
            "output_bytes": float(getattr(ma, "output_size_in_bytes", 0) or 0),
            "temp_bytes": float(getattr(ma, "temp_size_in_bytes", 0) or 0),
            "generated_code_bytes": float(
                getattr(ma, "generated_code_size_in_bytes", 0) or 0
            ),
        }
    except Exception:  # noqa: BLE001 — introspection must never crash the kernel
        return None
    get_costbook().record(fn_label, snap)
    reg = registry if registry is not None else get_registry()
    reg.counter(
        "jax_cost_captures_total",
        "compiled-cost snapshots captured (once per instrumented fn)",
        labelnames=("fn",),
    ).labels(fn=fn_label).inc()
    publish_cost_gauges(reg, fn_label, snap)
    return snap


def publish_cost_gauges(
    registry: MetricsRegistry, fn_label: str, snap: Mapping[str, float]
) -> None:
    # names stay LITERAL at the registration site — the inventory checker
    # (scripts/check_metrics_documented.py) reads them statically
    def val(field: str) -> float:
        return float(snap.get(field, 0.0))

    registry.gauge(
        "jax_cost_flops",
        "XLA cost-analysis FLOPs of the compiled kernel",
        labelnames=("fn",),
    ).labels(fn=fn_label).set(val("flops"))
    registry.gauge(
        "jax_cost_bytes_accessed",
        "XLA cost-analysis bytes accessed by the compiled kernel",
        labelnames=("fn",),
    ).labels(fn=fn_label).set(val("bytes_accessed"))
    registry.gauge(
        "jax_hbm_argument_bytes",
        "device memory held by the compiled kernel's arguments",
        labelnames=("fn",),
    ).labels(fn=fn_label).set(val("argument_bytes"))
    registry.gauge(
        "jax_hbm_output_bytes",
        "device memory held by the compiled kernel's outputs",
        labelnames=("fn",),
    ).labels(fn=fn_label).set(val("output_bytes"))
    registry.gauge(
        "jax_hbm_temp_bytes",
        "device scratch memory of the compiled kernel (temporaries)",
        labelnames=("fn",),
    ).labels(fn=fn_label).set(val("temp_bytes"))
    registry.gauge(
        "jax_hbm_generated_code_bytes",
        "generated-code size of the compiled kernel",
        labelnames=("fn",),
    ).labels(fn=fn_label).set(val("generated_code_bytes"))


def republish(fn_label: str, registry: MetricsRegistry | None = None) -> bool:
    """Re-set the cost gauges for one fn from the book into ``registry``
    (the current default when None) — the per-call hook that keeps
    swapped-in registries populated without re-capturing."""
    snap = get_costbook().get(fn_label)
    if snap is None:
        return False
    publish_cost_gauges(
        registry if registry is not None else get_registry(), fn_label, snap
    )
    return True


def publish_roofline(
    registry: MetricsRegistry,
    fn_label: str,
    seconds: float,
) -> dict[str, float] | None:
    """Achieved FLOP/s and bytes/s of one fenced execution of ``fn_label``
    against its captured static cost, plus arithmetic intensity. Returns
    the numbers published, or None without a snapshot / a usable timing.

    The timing is the controller's fenced per-round decision latency, so
    on a tunneled rig the achieved numbers include dispatch + RTT — they
    are a lower bound on device throughput, honest for trend-watching."""
    if seconds <= 0:
        return None
    snap = get_costbook().get(fn_label)
    if snap is None:
        return None
    flops = snap.get("flops", 0.0)
    bytes_accessed = snap.get("bytes_accessed", 0.0)
    out = {
        "achieved_flops_per_s": flops / seconds,
        "achieved_bytes_per_s": bytes_accessed / seconds,
        "arithmetic_intensity": (
            flops / bytes_accessed if bytes_accessed > 0 else 0.0
        ),
    }
    registry.gauge(
        "jax_achieved_flops_per_s",
        "achieved FLOP/s of the last fenced round (static flops / latency)",
        labelnames=("fn",),
    ).labels(fn=fn_label).set(out["achieved_flops_per_s"])
    registry.gauge(
        "jax_achieved_bytes_per_s",
        "achieved bytes/s of the last fenced round (static bytes / latency)",
        labelnames=("fn",),
    ).labels(fn=fn_label).set(out["achieved_bytes_per_s"])
    registry.gauge(
        "jax_arithmetic_intensity",
        "compiled kernel arithmetic intensity (flops per byte accessed)",
        labelnames=("fn",),
    ).labels(fn=fn_label).set(out["arithmetic_intensity"])
    return out


def sample_device_memory(
    registry: MetricsRegistry | None = None,
) -> list[dict[str, Any]]:
    """Live per-device memory stats as gauges; returns what was sampled.

    Reads ``sys.modules`` like the manifest does — sampling must not
    initialize a jax backend on a process that never imported jax. CPU
    devices answer ``memory_stats() -> None`` and contribute nothing."""
    jax = sys.modules.get("jax")
    if jax is None:
        return []
    samples: list[dict[str, Any]] = []
    reg = registry if registry is not None else get_registry()
    try:
        devices = jax.devices()
    except Exception:  # backend init can fail on misconfigured hosts
        return []
    for dev in devices:
        try:
            stats = dev.memory_stats()
        except Exception:  # noqa: BLE001 — optional PJRT surface
            stats = None
        if not stats:
            continue
        in_use = stats.get("bytes_in_use")
        peak = stats.get("peak_bytes_in_use")
        label = str(dev)
        if in_use is not None:
            reg.gauge(
                "device_hbm_bytes_in_use",
                "live device memory in use (device.memory_stats)",
                labelnames=("device",),
            ).labels(device=label).set(float(in_use))
        if peak is not None:
            reg.gauge(
                "device_hbm_peak_bytes_in_use",
                "peak device memory in use (device.memory_stats)",
                labelnames=("device",),
            ).labels(device=label).set(float(peak))
        samples.append(
            {"device": label, "bytes_in_use": in_use, "peak_bytes_in_use": peak}
        )
    return samples


def observe_round_device(
    registry: MetricsRegistry | None = None,
    *,
    fn_labels: tuple[str, ...] = (),
    seconds: float = 0.0,
) -> None:
    """The controller's once-per-round hook: sample live device memory
    and publish the roofline for the first candidate kernel label with a
    captured cost snapshot (which label ran depends on algorithm/explain
    mode — the caller passes the candidates in preference order)."""
    reg = registry if registry is not None else get_registry()
    sample_device_memory(reg)
    for label in fn_labels:
        if publish_roofline(reg, label, seconds) is not None:
            break
