"""Human-readable summaries of a run's telemetry artifacts.

Backs the ``telemetry`` CLI subcommand: point it at the JSONL files a run
produced (``--metrics-out`` dumps, ``StructuredLogger`` event logs, a
manifest) and it prints what an operator wants to know — rounds, moves,
cost trajectory, latency percentiles, retrace counts — without jq
archaeology. Input kind is detected per file from the record shape.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any


def _read_jsonl(path: Path) -> list[dict[str, Any]]:
    records = []
    for line in path.read_text().splitlines():
        line = line.strip()
        if line:
            records.append(json.loads(line))
    return records


def _fmt_hist(rec: dict[str, Any]) -> str:
    count = rec.get("count", 0)
    if not count:
        return "count=0"
    mean = rec["sum"] / count
    return (
        f"count={count} mean={mean * 1e3:.3f}ms "
        f"min={rec['min'] * 1e3:.3f}ms max={rec['max'] * 1e3:.3f}ms"
    )


def _labels_str(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    return "{" + ",".join(f"{k}={v}" for k, v in sorted(labels.items())) + "}"


def _scan_plane_lines(latest: dict[tuple, dict[str, Any]]) -> list[str]:
    """The scan-plane digest an operator reads before the raw series: block
    size, blocks dispatched, and the drain/tripwire breakdowns — only when
    the run actually scanned (``scan_blocks_total`` present)."""
    blocks = block = None
    drains: dict[str, float] = {}
    trips: dict[str, float] = {}
    for (metric, _), rec in latest.items():
        labels = rec.get("labels") or {}
        if metric == "scan_blocks_total":
            blocks = rec.get("value")
        elif metric == "scan_rounds_per_dispatch":
            block = rec.get("value")
        elif metric == "scan_drains_total":
            drains[str(labels.get("reason"))] = rec.get("value", 0)
        elif metric in ("scan_tripwires_total", "fleet_scan_tripwires_total"):
            key = str(labels.get("rule") or labels.get("tenant"))
            trips[key] = trips.get(key, 0) + (rec.get("value") or 0)
    if blocks is None:
        return []
    out = [f"  scan plane: blocks={blocks:g}" + (
        f" block_rounds={block:g}" if block is not None else ""
    )]
    if drains:
        out.append(
            "    drains: "
            + ", ".join(f"{k}×{v:g}" for k, v in sorted(drains.items()))
        )
    if trips:
        out.append(
            "    tripwires: "
            + ", ".join(f"{k}×{v:g}" for k, v in sorted(trips.items()))
        )
    return out


def _hist_quantile(rec: dict[str, Any], q: float) -> float | None:
    """Approximate a quantile from a snapshot histogram record's
    per-bucket counts (linear interpolation inside the covering bucket;
    the +Inf bucket answers with the recorded max)."""
    count = rec.get("count") or 0
    if not count:
        return None
    target = q * count
    buckets = rec.get("buckets") or {}
    edges = sorted((float(ub), n) for ub, n in buckets.items())
    cum = 0.0
    lo = rec.get("min") or 0.0
    for ub, n in edges:
        if cum + n >= target and n > 0:
            frac = (target - cum) / n
            return lo + frac * (ub - lo)
        cum += n
        lo = ub
    return rec.get("max")


def _serving_plane_lines(
    latest: dict[tuple, dict[str, Any]], records: list[dict[str, Any]]
) -> list[str]:
    """The serving-plane digest: outcome totals, end-to-end latency
    percentiles interpolated from the total-stage histogram, the shed
    breakdown, and the batch-size distribution — only when the run
    actually served (``serving_placements_total`` present). Placement
    rate needs a time axis, so it renders only when the dump appended
    >= 2 snapshots (their ``ts`` stamps are the axis)."""
    outcomes: dict[str, float] = {}
    shed: dict[str, float] = {}
    total_hist = None
    batch_hist = None
    inflight = None
    for (metric, _), rec in latest.items():
        labels = rec.get("labels") or {}
        if metric == "serving_placements_total":
            outcomes[str(labels.get("outcome"))] = rec.get("value", 0)
        elif metric == "serving_shed_total":
            shed[str(labels.get("reason"))] = rec.get("value", 0)
        elif (
            metric == "serving_request_seconds"
            and labels.get("stage") == "total"
        ):
            total_hist = rec
        elif metric == "serving_batch_size":
            batch_hist = rec
        elif metric == "serving_inflight":
            inflight = rec.get("value")
    if not outcomes:
        return []
    out = [
        "  serving plane: "
        + " ".join(f"{k}={v:g}" for k, v in sorted(outcomes.items()))
        + (f" inflight={inflight:g}" if inflight is not None else "")
    ]
    if total_hist is not None and total_hist.get("count"):
        p50 = _hist_quantile(total_hist, 0.50)
        p95 = _hist_quantile(total_hist, 0.95)
        p99 = _hist_quantile(total_hist, 0.99)
        mean = total_hist["sum"] / total_hist["count"]
        out.append(
            f"    latency(total): p50={p50 * 1e3:.2f}ms "
            f"p95={p95 * 1e3:.2f}ms p99={p99 * 1e3:.2f}ms "
            f"mean={mean * 1e3:.2f}ms count={total_hist['count']}"
        )
    # rate needs a time axis: diff the first/last appended snapshot of
    # the total-stage count over their dump timestamps
    snaps = [
        r
        for r in records
        if r.get("metric") == "serving_request_seconds"
        and (r.get("labels") or {}).get("stage") == "total"
    ]
    if len(snaps) >= 2:
        dt = (snaps[-1].get("ts") or 0) - (snaps[0].get("ts") or 0)
        dc = (snaps[-1].get("count") or 0) - (snaps[0].get("count") or 0)
        if dt > 0 and dc >= 0:
            out.append(f"    placements/sec: {dc / dt:.2f} (over {dt:.2f}s)")
    if shed:
        out.append(
            "    shed: "
            + ", ".join(f"{k}×{v:g}" for k, v in sorted(shed.items()))
        )
    if batch_hist is not None and batch_hist.get("count"):
        dist = ", ".join(
            f"≤{float(ub):g}×{n:g}"
            for ub, n in sorted(
                (batch_hist.get("buckets") or {}).items(),
                key=lambda kv: float(kv[0]),
            )
            if n
        )
        if batch_hist.get("inf"):
            dist += f", +Inf×{batch_hist['inf']:g}"
        out.append(f"    batch sizes: {dist} (count={batch_hist['count']})")
    return out


def summarize_metrics(records: list[dict[str, Any]]) -> list[str]:
    """Registry-dump JSONL (``MetricsRegistry.dump_jsonl``) → text lines.
    When a run appended several snapshots, the LAST sample per series
    wins (values are cumulative)."""
    latest: dict[tuple, dict[str, Any]] = {}
    for rec in records:
        key = (rec["metric"], tuple(sorted((rec.get("labels") or {}).items())))
        latest[key] = rec
    lines = _scan_plane_lines(latest)
    lines += _serving_plane_lines(latest, records)
    for (metric, _), rec in sorted(latest.items()):
        labels = _labels_str(rec.get("labels") or {})
        if rec.get("type") == "histogram":
            lines.append(f"  {metric}{labels}  {_fmt_hist(rec)}")
        else:
            lines.append(f"  {metric}{labels} = {rec.get('value')}")
    return lines


def summarize_events(records: list[dict[str, Any]]) -> list[str]:
    """StructuredLogger JSONL → text lines; per-round ``round`` events get
    the full trajectory treatment, everything else a count by event."""
    rounds = [r for r in records if r.get("event") == "round"]
    by_event: dict[str, int] = {}
    for r in records:
        by_event[r.get("event", "?")] = by_event.get(r.get("event", "?"), 0) + 1
    lines = [
        f"  events: "
        + ", ".join(f"{k}×{v}" for k, v in sorted(by_event.items()))
    ]
    if rounds:
        moved = sum(1 for r in rounds if r.get("moved"))
        costs = [
            r["communication_cost"]
            for r in rounds
            if r.get("communication_cost") is not None
        ]
        lats = sorted(
            r["decision_latency_s"]
            for r in rounds
            if r.get("decision_latency_s") is not None
        )
        lines.append(f"  rounds: {len(rounds)}  moved: {moved}")
        if costs:
            lines.append(
                f"  communication_cost: {costs[0]:.2f} -> {costs[-1]:.2f}"
            )
        if lats:
            def pct(q):
                return lats[min(int(q / 100 * len(lats)), len(lats) - 1)]

            lines.append(
                f"  decision latency: p50={pct(50) * 1e3:.2f}ms "
                f"p90={pct(90) * 1e3:.2f}ms max={lats[-1] * 1e3:.2f}ms"
            )
    # resilience: breaker transitions, skipped/degraded rounds, boundary
    # failures — the degraded-mode trajectory an operator reads first when
    # a run looks wrong
    transitions = [r for r in records if r.get("event") == "breaker"]
    if transitions:
        arrows = ", ".join(
            f"{t.get('from', '?')}->{t.get('to', '?')}@r{t.get('round', '?')}"
            for t in transitions
        )
        lines.append(f"  breaker: {arrows}")
    skipped = by_event.get("round_skipped", 0)
    degraded = sum(1 for r in rounds if r.get("degraded"))
    failures = sum(1 for r in records if r.get("event") == "boundary_failure")
    if skipped or degraded or failures:
        lines.append(
            f"  resilience: skipped={skipped} degraded={degraded} "
            f"boundary_failures={failures}"
        )
    trips = [r for r in records if r.get("event") == "scan_tripwire"]
    if trips:
        lines.append(
            "  scan tripwires: "
            + ", ".join(
                f"r{t.get('round', '?')} "
                f"({'+'.join(t.get('rules') or ()) or '?'})"
                for t in trips
            )
        )
    return lines


def summarize_manifest(m: dict[str, Any]) -> list[str]:
    jx = m.get("jax") or {}
    git = m.get("git") or {}
    lines = [
        f"  run: {m.get('timestamp')}  host: {m.get('hostname')}",
        f"  argv: {' '.join(m.get('argv') or [])}",
        f"  python {m.get('python')}  jax {jx.get('version', '?')} "
        f"({jx.get('backend', '?')} ×{jx.get('device_count', '?')})",
    ]
    if git:
        rev = git.get("rev", "?")[:12]
        lines.append(f"  git: {rev}{' (dirty)' if git.get('dirty') else ''}")
    return lines


def summarize_bundle(bundle: dict[str, Any]) -> list[str]:
    """Flight-recorder bundle → text: trigger, ring contents, and the
    explain-consistency verdict over every recorded decision."""
    from kubernetes_rescheduling_tpu.telemetry.explain import (
        check_decisions,
        iter_decisions,
    )

    rounds = bundle.get("rounds") or []
    executed = [r for r in rounds if not r.get("skipped")]
    skipped = len(rounds) - len(executed)
    lines = [
        f"  flight-recorder bundle: reason={bundle.get('reason')}"
        + (f" ({bundle.get('error')})" if bundle.get("error") else ""),
        f"  rounds ringed: {len(rounds)} ({len(executed)} executed, "
        f"{skipped} skipped)",
    ]
    for r in executed:
        rec = r.get("record") or {}
        lines.append(
            f"    r{r.get('round')}: digest={r.get('digest')} "
            f"moved={rec.get('moved')} breaker={rec.get('breaker_state')} "
            f"cost={rec.get('communication_cost'):.4g}"
            if rec.get("communication_cost") is not None
            else f"    r{r.get('round')}: digest={r.get('digest')}"
        )
    decisions = iter_decisions(rounds)
    checked, bad = check_decisions(decisions)
    lines.append(
        f"  decisions: {checked} recorded, "
        f"{checked - len(bad)} explain-consistent"
        + ("" if not bad else f" — {len(bad)} INCONSISTENT")
    )
    from kubernetes_rescheduling_tpu.telemetry.attribution import (
        check_attribution,
    )

    a_checked, a_bad = check_attribution(rounds)
    if a_checked:
        lines.append(
            f"  attribution: {a_checked} recorded, "
            f"{a_checked - len(a_bad)} sum-consistent"
            + ("" if not a_bad else f" — {len(a_bad)} INCONSISTENT")
        )
    metrics = bundle.get("metrics") or []
    lines.append(f"  metrics snapshot: {len(metrics)} series")
    manifest = bundle.get("manifest") or {}
    if manifest:
        lines.append(
            f"  from: {manifest.get('hostname')} pid {manifest.get('pid')} "
            f"at {manifest.get('timestamp')}"
        )
    return lines


def summarize_file(path: str | Path) -> str:
    """Detect the artifact kind from its record shape and summarize."""
    p = Path(path)
    if not p.is_file():
        return f"{p}: not a file"
    header = [f"== {p} =="]
    text = p.read_text().strip()
    if not text:
        return "\n".join(header + ["  (empty)"])
    if text.startswith("{") and "\n" not in text.split("}")[0] or p.suffix == ".json":
        # whole-file JSON: a manifest, a Chrome trace, or a bundle
        try:
            obj = json.loads(text)
        except json.JSONDecodeError:
            obj = None
        if isinstance(obj, dict):
            if obj.get("kind") == "flight_recorder_bundle":
                return "\n".join(header + summarize_bundle(obj))
            if "traceEvents" in obj:
                return "\n".join(
                    header + [f"  chrome trace: {len(obj['traceEvents'])} spans"]
                )
            if "argv" in obj or "jax" in obj:
                return "\n".join(header + summarize_manifest(obj))
    records = _read_jsonl(p)
    if records and "metric" in records[0]:
        return "\n".join(header + summarize_metrics(records))
    if records and "event" in records[0]:
        return "\n".join(header + summarize_events(records))
    return "\n".join(header + [f"  {len(records)} records (unknown schema)"])


def report(paths: list[str]) -> str:
    return "\n".join(summarize_file(p) for p in paths)


def report_explain(paths: list[str]) -> str:
    """The ``telemetry explain`` report: decision explanations (from
    ``decision`` events or a bundle's ring), re-derived and rendered."""
    from kubernetes_rescheduling_tpu.telemetry.explain import (
        load_decisions,
        summarize_decisions,
    )

    out = []
    for p in paths:
        out.append(f"== {p} ==")
        out.extend(summarize_decisions(load_decisions(p)))
    return "\n".join(out)


def report_perf(
    paths: list[str],
    *,
    window: int = 5,
    threshold_frac: float = 0.2,
    baseline: str = "median",
) -> str:
    """The ``telemetry perf`` report: load perf-ledger JSONL files and/or
    historical driver snapshots (``BENCH_r*.json`` / ``MULTICHIP_r*.json``
    — auto-detected and ingested), judge every series with the
    rolling-window detector, and render the trend table with per-metric
    verdicts."""
    from kubernetes_rescheduling_tpu.telemetry import perf_ledger as pl

    ledger_recs: list[dict[str, Any]] = []
    history: list[dict[str, Any]] = []
    loaded: list[str] = []
    for p in paths:
        path = Path(p)
        if not path.is_file():
            loaded.append(f"  {p}: not a file")
            continue
        ingested = pl.ingest_bench_file(path)
        if ingested:
            history.extend(ingested)
            loaded.append(f"  {p}: {len(ingested)} snapshot record(s)")
            continue
        try:
            records = _read_jsonl(path)
        except json.JSONDecodeError:
            loaded.append(f"  {p}: not JSONL")
            continue
        recs = [
            r
            for r in records
            if isinstance(r, dict) and "metric" in r and "seq" in r
        ]
        if recs:
            ledger_recs.extend(recs)
            loaded.append(f"  {p}: {len(recs)} ledger record(s)")
        else:
            loaded.append(f"  {p}: no perf records")
    # ingested snapshots are HISTORY by definition: rank them (in CLI arg
    # order) strictly before every ledger record via negative seqs, so a
    # ledger that shares a series with the snapshots (BENCH_LEDGER) is
    # judged today-against-history, never history-against-today
    for i, rec in enumerate(history):
        rec["seq"] = i - len(history)
    entries = history + ledger_recs
    out = ["== perf ledger =="] + loaded
    verdicts = pl.detect(
        entries, window=window, threshold_frac=threshold_frac,
        baseline=baseline,
    )
    out.extend(pl.render_table(verdicts))
    return "\n".join(out)


def _topo_rounds(path: Path) -> list[dict[str, Any]]:
    """Round records (dicts carrying `attribution`) from a rounds.jsonl
    file or a flight-recorder bundle's ring."""
    text = path.read_text().strip()
    if not text:
        return []
    if text.startswith("{") and path.suffix == ".json":
        try:
            obj = json.loads(text)
        except json.JSONDecodeError:
            return []
        if isinstance(obj, dict) and obj.get("kind") == "flight_recorder_bundle":
            return list(obj.get("rounds") or ())
        return [obj] if isinstance(obj, dict) else []
    return _read_jsonl(path)


def report_topo(paths: list[str]) -> str:
    """The ``telemetry topo`` report: cost attribution & topology — the
    latest round's edge-attribution table and node-pair heatmap, the
    placement/provenance trail over all rounds, and the sum-consistency
    verdict (per-edge contributions re-derive the recorded cost scalar;
    per-move deltas re-derive the objective delta)."""
    from kubernetes_rescheduling_tpu.telemetry.attribution import (
        check_attribution,
        iter_attributions,
        render_edges,
        render_heatmap,
        render_provenance,
        render_residency,
        residency_from_rounds,
    )

    out = []
    for p in paths:
        out.append(f"== {p} ==")
        path = Path(p)
        if not path.is_file():
            out.append("  not a file")
            continue
        rounds = _topo_rounds(path)
        attrs = iter_attributions(rounds)
        if not attrs:
            out.append("  no attribution records (was obs.attribution off?)")
            continue
        latest = attrs[-1][0]
        rnd = latest.get("round", "?")
        total = latest.get("total")
        out.append(
            f"  rounds with attribution: {len(attrs)}; latest r{rnd} "
            f"total cost {total:.4g}"
        )
        out.extend(render_edges(latest))
        out.extend(render_heatmap(latest))
        out.append("  residency (service -> node over rounds):")
        out.extend(
            f"  {ln}" for ln in render_residency(residency_from_rounds(rounds))
        )
        out.append("  move provenance:")
        out.extend(render_provenance(rounds))
        checked, bad = check_attribution(rounds)
        out.append(
            f"  consistency: {checked - len(bad)}/{checked} rounds re-derive "
            f"their cost scalar and move deltas from the recorded attribution"
        )
        for a in bad:
            out.append(
                f"    INCONSISTENT: r{a.get('round', '?')} total="
                f"{a.get('total')} does not re-derive from its parts"
            )
    return "\n".join(out)


def report_shadow(paths: list[str]) -> str:
    """The ``telemetry shadow`` report: the head-to-head table of a
    shadow run — per scored round, our counterfactual cost vs the
    trace's actual scheduler, the running win-rate, and the edges where
    we beat it — from ``rounds.jsonl`` files or flight-recorder
    bundles."""
    out = []
    for p in paths:
        out.append(f"== {p} ==")
        path = Path(p)
        if not path.is_file():
            out.append("  not a file")
            continue
        rounds = _topo_rounds(path)
        blocks = []
        for r in rounds:
            rec = r.get("record") if isinstance(r.get("record"), dict) else r
            if isinstance(rec, dict) and isinstance(rec.get("shadow"), dict):
                blocks.append(rec["shadow"])
        if not blocks:
            out.append("  no shadow records (was this a --shadow run?)")
            continue
        out.append(
            "  round  recd  cost_actual  cost_shadow      delta  win"
        )
        for b in blocks:
            out.append(
                f"  {b.get('round', '?'):>5}  {b.get('recommended', 0):>4}"
                f"  {b.get('cost_actual', float('nan')):>11.4g}"
                f"  {b.get('cost_shadow', float('nan')):>11.4g}"
                f"  {b.get('cost_delta', float('nan')):>+9.4g}"
                f"  {'WIN' if b.get('win') else 'loss'}"
            )
        last = blocks[-1]
        deltas = [
            b["cost_delta"] for b in blocks if b.get("cost_delta") is not None
        ]
        mean_delta = sum(deltas) / len(deltas) if deltas else float("nan")
        out.append(
            f"  scored {last.get('scored', len(blocks))} rounds: "
            f"win_rate {last.get('win_rate', float('nan')):.3f}, "
            f"mean delta {mean_delta:+.4g} "
            f"(positive = we beat the cluster's actual scheduler)"
        )
        winning = [
            e
            for b in blocks
            for e in (b.get("edges_delta") or ())
            if e.get("delta", 0.0) > 0
        ]
        if winning:
            best: dict[tuple, float] = {}
            for e in winning:
                key = (e.get("src_service"), e.get("dst_service"))
                best[key] = max(best.get(key, 0.0), float(e["delta"]))
            top = sorted(best.items(), key=lambda kv: kv[1], reverse=True)[:5]
            out.append(
                "  edges where we win: "
                + ", ".join(f"{a}~{b} {d:+.4g}" for (a, b), d in top)
            )
    return "\n".join(out)


def _fleet_rollup_events(path: Path) -> list[dict[str, Any]]:
    """``fleet_rollup`` events from a structured-event JSONL file or a
    flight-recorder bundle (ring events + the breaker-open dump's
    top-level ``fleet_rollup`` payload)."""
    rounds = _topo_rounds(path)
    out: list[dict[str, Any]] = []
    for r in rounds:
        if r.get("event") == "fleet_rollup":
            out.append(r)
        for e in r.get("events") or ():
            if isinstance(e, dict) and e.get("event") == "fleet_rollup":
                out.append(e)
    if not out and path.suffix == ".json":
        try:
            obj = json.loads(path.read_text())
        except json.JSONDecodeError:
            obj = None
        if isinstance(obj, dict) and isinstance(
            obj.get("fleet_rollup"), dict
        ):
            out.append(obj["fleet_rollup"])
    return out


def report_fleet(paths: list[str]) -> str:
    """The ``telemetry fleet`` report: the bounded fleet-observability
    plane rendered from recorded ``fleet_rollup`` events (a fleet run's
    event JSONL, or flight-recorder bundles) — the per-dimension
    quantile trend across rounds, the latest fleet totals, and the
    offender table (which tenants kept landing in the worst-k, by
    dimension) that replaces scrolling O(T) per-tenant series."""
    out = []
    for p in paths:
        out.append(f"== {p} ==")
        path = Path(p)
        if not path.is_file():
            out.append("  not a file")
            continue
        evs = _fleet_rollup_events(path)
        if not evs:
            out.append(
                "  no fleet_rollup events (was this a fleet run with "
                "obs.fleet_rollup on?)"
            )
            continue
        first, last = evs[0], evs[-1]
        out.append(
            f"  fleet rollups: {len(evs)} rounds "
            f"(r{first.get('round', '?')} -> r{last.get('round', '?')}, "
            f"top_k={last.get('top_k', '?')})"
        )
        out.append(
            "  dim              p50 first->last      p99 first->last"
            "      max first->last"
        )
        for dim in ("cost", "load_std", "drift"):
            fq = (first.get("quantiles") or {}).get(dim) or {}
            lq = (last.get("quantiles") or {}).get(dim) or {}
            cells = "".join(
                f"  {fq.get(q, float('nan')):>8.4g} -> {lq.get(q, float('nan')):<8.4g}"
                for q in ("p50", "p99", "max")
            )
            out.append(f"  {dim:<15}{cells}")
        sums = last.get("sums") or {}
        out.append(
            f"  latest fleet totals: degraded={sums.get('degraded', 0):g} "
            f"skipped={sums.get('skipped', 0):g} "
            f"drift_pods={sums.get('drift', 0):g}"
        )
        # offender table: appearances in the worst-k across all rounds
        seen: dict[str, dict[str, list[float]]] = {}
        for ev in evs:
            for row in ev.get("worst") or ():
                tenant = str(row.get("tenant"))
                seen.setdefault(tenant, {}).setdefault(
                    str(row.get("dim")), []
                ).append(float(row.get("value", 0.0)))
        ranked = sorted(
            seen.items(),
            key=lambda kv: sum(len(v) for v in kv[1].values()),
            reverse=True,
        )[:10]
        if ranked:
            out.append("  worst offenders (appearances in the top-k):")
            for tenant, dims in ranked:
                cells = " ".join(
                    f"{dim}×{len(vals)} (max {max(vals):.4g})"
                    for dim, vals in sorted(dims.items())
                )
                out.append(f"    {tenant:<16} {cells}")
    return "\n".join(out)


_SPARK = "▁▂▃▄▅▆▇█"


def _sparkline(values: list[float]) -> str:
    """Unicode sparkline scaled to the series' own max (a flat zero
    series renders all-low — exactly what a clean soak should show)."""
    if not values:
        return ""
    top = max(values)
    if top <= 0:
        return _SPARK[0] * len(values)
    return "".join(
        _SPARK[min(int(v / top * (len(_SPARK) - 1) + 0.5), len(_SPARK) - 1)]
        for v in values
    )


def report_slo(paths: list[str]) -> str:
    """The ``telemetry slo`` report: the error-budget table plus burn
    sparklines. Feeds on either artifact kind — a metrics dump JSONL
    (``slo_budget_remaining_frac``/``slo_burn_rate`` samples, sparklines
    over the appended snapshots in file order) or an events JSONL
    (burn-rule ``slo_violation``/``slo_recovered`` entries)."""
    out = []
    for p in paths:
        out.append(f"== {p} ==")
        path = Path(p)
        if not path.is_file():
            out.append("  not a file")
            continue
        try:
            records = _read_jsonl(path)
        except (OSError, json.JSONDecodeError) as e:
            out.append(f"  unreadable: {e}")
            continue
        # metrics-dump shape: budget gauges + burn-rate history
        budgets: dict[str, float] = {}
        burns: dict[tuple[str, str], list[float]] = {}
        for rec in records:
            metric = rec.get("metric")
            labels = rec.get("labels") or {}
            if metric == "slo_budget_remaining_frac":
                budgets[str(labels.get("slo"))] = rec.get("value", 0.0)
            elif metric == "slo_burn_rate":
                burns.setdefault(
                    (str(labels.get("slo")), str(labels.get("window"))), []
                ).append(rec.get("value", 0.0))
        if budgets:
            out.append(
                "  slo                      budget     burn(fast)  burn(slow)"
            )
            for slo in sorted(budgets):
                fast = burns.get((slo, "fast")) or [0.0]
                slow = burns.get((slo, "slow")) or [0.0]
                out.append(
                    f"  {slo:<24} {budgets[slo] * 100:>7.2f}%  "
                    f"{fast[-1]:>9.2f}  {slow[-1]:>9.2f}"
                )
            for (slo, window), vals in sorted(burns.items()):
                out.append(
                    f"    burn {slo}/{window}: {_sparkline(vals[-64:])} "
                    f"(last {vals[-1]:.2f})"
                )
            continue
        # events shape: the burn rules' violation/recovery trail
        burn_events = [
            r
            for r in records
            if r.get("event") in ("slo_violation", "slo_recovered")
            and str(r.get("rule", "")).startswith("slo_")
        ]
        if not burn_events:
            out.append(
                "  no slo samples or burn events (was this run started "
                "with --slo?)"
            )
            continue
        for ev in burn_events:
            if ev.get("event") == "slo_violation":
                out.append(
                    f"  VIOLATION {ev.get('rule')} slo={ev.get('slo', '?')} "
                    f"burn={ev.get('burn_rate', '?')} over "
                    f"{ev.get('window', '?')}t "
                    f"(budget {float(ev.get('budget_remaining_frac', 0)) * 100:.1f}% left)"
                )
            else:
                out.append(f"  recovered {ev.get('rule')}")
    return "\n".join(out)


def report_bundle(paths: list[str]) -> str:
    """The ``telemetry bundle`` report: summarize flight-recorder bundles."""
    out = []
    for p in paths:
        out.append(f"== {p} ==")
        try:
            obj = json.loads(Path(p).read_text())
        except (OSError, json.JSONDecodeError) as e:
            out.append(f"  unreadable: {e}")
            continue
        if not isinstance(obj, dict) or obj.get("kind") != "flight_recorder_bundle":
            out.append("  not a flight-recorder bundle")
            continue
        out.extend(summarize_bundle(obj))
    return "\n".join(out)
