"""Labeled metric series with Prometheus exposition and a JSONL sink.

Three metric kinds, mirroring the Prometheus data model:

- :class:`Counter` — monotone ``inc``.
- :class:`Gauge` — ``set``/``inc``/``dec``.
- :class:`Histogram` — FIXED-bucket streaming: per-bucket counts plus
  sum/count/min/max. Memory is O(buckets) regardless of sample volume —
  the replacement for ``utils.profiling.LatencyHistogram``'s unbounded
  sample list. Percentiles interpolate linearly within a bucket, so their
  error is bounded by the bucket width (the standard Prometheus
  ``histogram_quantile`` trade-off).

Labels: a metric declared with ``labelnames`` is a family; ``.labels(...)``
returns (and memoizes) the child series for one label-value tuple, so two
lookups with the same values hit the SAME series — identity is by value,
never by call site. A metric with no labelnames is its own single series.

The registry is deliberately jax-free: backends/k8s.py (which never
imports jax) instruments through it, and importing telemetry must not
initialize a device backend.
"""

from __future__ import annotations

import json
import math
import threading
import time
from pathlib import Path
from typing import Any, Iterable

# Latency-shaped default buckets (seconds): sub-ms device rounds up to
# multi-second reconcile waits all land in a resolved bucket.
DEFAULT_BUCKETS: tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)

# Request-scale micro buckets (seconds), 50µs–250ms: the serving plane's
# per-request stage spans live one to three orders of magnitude below the
# round-scale DEFAULT_BUCKETS — queue-wait and decode are tens of
# microseconds, a coalesced device dispatch single-digit milliseconds —
# and under the default preset every stage would collapse into the two
# bottom buckets, making the interpolated p50/p99 meaningless. Selectable
# at registration (``registry.histogram(..., buckets=MICRO_BUCKETS)``);
# the registry's bucket-mismatch check guarantees a family can never mix
# presets across call sites. Used by all serving_request_seconds{stage}
# families.
MICRO_BUCKETS: tuple[float, ...] = (
    50e-6, 100e-6, 250e-6, 500e-6,
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
)


def _escape_label_value(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _format_labels(labels: dict[str, str], extra: dict[str, str] | None = None) -> str:
    items = {**labels, **(extra or {})}
    if not items:
        return ""
    body = ",".join(
        f'{k}="{_escape_label_value(str(v))}"' for k, v in items.items()
    )
    return "{" + body + "}"


def _fmt_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    f = float(v)
    return repr(int(f)) if f.is_integer() and abs(f) < 1e15 else repr(f)


class _Metric:
    """One metric family: shared name/help/labelnames, per-label children."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "", labelnames: tuple[str, ...] = ()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._children: dict[tuple[str, ...], _Metric] = {}
        self._lock = threading.Lock()

    def labels(self, **labelvalues: Any) -> "_Metric":
        if set(labelvalues) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, "
                f"got {tuple(labelvalues)}"
            )
        key = tuple(str(labelvalues[n]) for n in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._make_child()
                self._children[key] = child
        return child

    def _make_child(self) -> "_Metric":
        raise NotImplementedError

    def _series(self) -> Iterable[tuple[dict[str, str], "_Metric"]]:
        """(labels, leaf) pairs — the family itself when unlabeled."""
        if self.labelnames:
            with self._lock:
                items = list(self._children.items())
            for key, child in items:
                yield dict(zip(self.labelnames, key)), child
        else:
            yield {}, self

    def _require_unlabeled(self) -> None:
        if self.labelnames:
            raise ValueError(
                f"{self.name} has labels {self.labelnames}; call "
                f".labels(...) first"
            )


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name: str = "", help: str = "", labelnames=()):
        super().__init__(name, help, labelnames)
        self.value = 0.0

    def _make_child(self) -> "Counter":
        return Counter(self.name)

    def inc(self, amount: float = 1.0) -> None:
        self._require_unlabeled()
        if amount < 0:
            raise ValueError(f"{self.name}: counters only go up")
        with self._lock:
            self.value += amount

    def expose(self, labels: dict[str, str]) -> list[str]:
        return [f"{self.name}{_format_labels(labels)} {_fmt_value(self.value)}"]

    def sample(self) -> dict[str, Any]:
        return {"value": self.value}


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name: str = "", help: str = "", labelnames=()):
        super().__init__(name, help, labelnames)
        self.value = 0.0

    def _make_child(self) -> "Gauge":
        return Gauge(self.name)

    def set(self, value: float) -> None:
        self._require_unlabeled()
        with self._lock:
            self.value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        self._require_unlabeled()
        with self._lock:
            self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    def expose(self, labels: dict[str, str]) -> list[str]:
        return [f"{self.name}{_format_labels(labels)} {_fmt_value(self.value)}"]

    def sample(self) -> dict[str, Any]:
        return {"value": self.value}


class Histogram(_Metric):
    kind = "histogram"

    def __init__(
        self,
        name: str = "",
        help: str = "",
        labelnames=(),
        buckets: tuple[float, ...] = DEFAULT_BUCKETS,
    ):
        super().__init__(name, help, labelnames)
        b = tuple(sorted(float(x) for x in buckets))
        if not b:
            raise ValueError(f"{name}: histogram needs at least one bucket")
        self.buckets = b
        self.counts = [0] * (len(b) + 1)  # +1 for the implicit +Inf bucket
        self.sum = 0.0
        self.count = 0
        self.min = math.inf
        self.max = -math.inf

    def _make_child(self) -> "Histogram":
        return Histogram(self.name, buckets=self.buckets)

    def observe(self, value: float) -> None:
        self._require_unlabeled()
        v = float(value)
        with self._lock:
            i = 0
            for i, ub in enumerate(self.buckets):
                if v <= ub:
                    break
            else:
                i = len(self.buckets)
            self.counts[i] += 1
            self.sum += v
            self.count += 1
            self.min = min(self.min, v)
            self.max = max(self.max, v)

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0

    def percentile(self, q: float) -> float:
        """q ∈ [0, 100]. Linear interpolation inside the landing bucket;
        clamped to the observed min/max so the estimate never leaves the
        data's actual range (the bound the accuracy test asserts)."""
        if self.count == 0:
            return 0.0
        rank = q / 100.0 * self.count
        cum = 0
        lo = 0.0
        for i, ub in enumerate(self.buckets):
            c = self.counts[i]
            if cum + c >= rank and c > 0:
                frac = (rank - cum) / c
                est = lo + frac * (ub - lo)
                return min(max(est, self.min), self.max)
            cum += c
            lo = ub
        return self.max  # landed in the +Inf bucket

    def summary(self) -> dict[str, float]:
        """The ``LatencyHistogram.summary`` schema (ms-denominated), so
        existing consumers migrate by swapping the class."""
        if self.count == 0:
            return {"count": 0}
        return {
            "count": self.count,
            "mean_ms": self.mean * 1e3,
            "p50_ms": self.percentile(50) * 1e3,
            "p90_ms": self.percentile(90) * 1e3,
            "p99_ms": self.percentile(99) * 1e3,
            "max_ms": self.max * 1e3,
            "decisions_per_sec": (1.0 / self.mean) if self.mean > 0 else 0.0,
        }

    def expose(self, labels: dict[str, str]) -> list[str]:
        lines = []
        cum = 0
        for i, ub in enumerate(self.buckets):
            cum += self.counts[i]
            le = _format_labels(labels, {"le": _fmt_value(ub)})
            lines.append(f"{self.name}_bucket{le} {cum}")
        le = _format_labels(labels, {"le": "+Inf"})
        lines.append(f"{self.name}_bucket{le} {self.count}")
        lab = _format_labels(labels)
        lines.append(f"{self.name}_sum{lab} {_fmt_value(self.sum)}")
        lines.append(f"{self.name}_count{lab} {self.count}")
        return lines

    def sample(self) -> dict[str, Any]:
        return {
            "count": self.count,
            "sum": self.sum,
            "min": self.min if self.count else None,
            "max": self.max if self.count else None,
            "buckets": {
                _fmt_value(ub): self.counts[i]
                for i, ub in enumerate(self.buckets)
            },
            "inf": self.counts[-1],
        }


class MetricsRegistry:
    """Get-or-create metric families; exposition + JSONL dump over all."""

    def __init__(self) -> None:
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def _get_or_create(self, cls, name, help, labelnames, **kwargs) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help, tuple(labelnames), **kwargs)
                self._metrics[name] = m
                return m
        if not isinstance(m, cls):
            raise ValueError(
                f"{name} already registered as {m.kind}, not {cls.kind}"
            )
        if m.labelnames != tuple(labelnames):
            raise ValueError(
                f"{name} already registered with labels {m.labelnames}, "
                f"not {tuple(labelnames)}"
            )
        return m

    def counter(self, name: str, help: str = "", labelnames=()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "", labelnames=()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(
        self, name: str, help: str = "", labelnames=(),
        buckets: tuple[float, ...] = DEFAULT_BUCKETS,
    ) -> Histogram:
        m = self._get_or_create(
            Histogram, name, help, labelnames, buckets=buckets
        )
        want = tuple(sorted(float(x) for x in buckets))
        if m.buckets != want:
            raise ValueError(
                f"{name} already registered with buckets {m.buckets}, "
                f"not {want}"
            )
        return m

    def expose(self) -> str:
        """Prometheus text exposition (format version 0.0.4)."""
        out: list[str] = []
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        for m in metrics:
            if m.help:
                out.append(f"# HELP {m.name} {m.help}")
            out.append(f"# TYPE {m.name} {m.kind}")
            for labels, leaf in m._series():
                out.extend(leaf.expose(labels))
        return "\n".join(out) + ("\n" if out else "")

    def snapshot(self) -> list[dict[str, Any]]:
        """One plain dict per series — the JSONL record shape."""
        ts = time.time()
        out = []
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        for m in metrics:
            for labels, leaf in m._series():
                out.append(
                    {
                        "ts": ts,
                        "metric": m.name,
                        "type": m.kind,
                        "labels": labels,
                        **leaf.sample(),
                    }
                )
        return out

    def dump_jsonl(self, path: str | Path) -> None:
        p = Path(path)
        p.parent.mkdir(parents=True, exist_ok=True)
        with p.open("a") as f:
            for rec in self.snapshot():
                f.write(json.dumps(rec, default=float) + "\n")

    def write_exposition(self, path: str | Path) -> None:
        p = Path(path)
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(self.expose())

    def reset(self) -> None:
        with self._lock:
            self._metrics.clear()


_default_registry = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _default_registry


def set_registry(registry: MetricsRegistry) -> MetricsRegistry:
    """Swap the process-default registry (tests isolate with this);
    returns the previous one so callers can restore it."""
    global _default_registry
    prev = _default_registry
    _default_registry = registry
    return prev
