"""The history plane: a bounded in-process time-series ring store.

Every health signal before this module was a point-in-time value — the
registry holds the *current* counter totals, the watchdog judges small
rolling windows, /healthz flips on the latest sample. :class:`SeriesStore`
adds the missing axis: it samples SELECTED registry families at
round/batch grain into fixed-capacity rings, so the SLO engine
(:mod:`telemetry.slo`) can ask "how many bad events in the last W
ticks?" — the primitive error budgets and burn rates are built from.

Cardinality discipline (the PR-13 rules, applied to history):

- **fixed per-series capacity** — each ring is a bounded deque of
  ``(tick, value)`` pairs; memory per series is O(capacity) however long
  the run;
- **hard global series budget** — at most ``max_series`` rings exist at
  once; admitting a new series beyond the budget evicts the
  least-recently-updated ring and counts it
  (``timeseries_evictions_total``), so a 1k-tenant fleet soak holds the
  same bytes as a solo run (T-independence, test-pinned);
- **family allowlist** — only the families named at construction are
  sampled at all; an exploding label space in some other family can
  never reach the store.

Counter extraction is **reset-tolerant**: a sampled value that DROPS
below its predecessor (a registry rebase, a fresh cell binding) is read
as a restart — the new value IS the delta, the classic Prometheus
``increase()`` convention — so burn windows never go negative across
rebases.

Histograms sample as derived ``:count`` / ``:sum`` series, plus
per-bucket cumulative counts for the families in ``bucket_families``
(the latency-threshold SLO mode needs "requests at or under X ms", which
is exactly a cumulative bucket count).

jax-free; everything here reads host-side values the registry already
holds. Feeding the store adds zero device transfers by construction.
"""

from __future__ import annotations

import collections
from typing import Any, Iterable

from kubernetes_rescheduling_tpu.telemetry.registry import (
    MetricsRegistry,
    get_registry,
)

# the round/batch-grain families the default store samples: serving
# outcomes + stage latency, the wall-clock round headline, breaker /
# degraded / skip accounting, the bounded fleet rollup quantiles, and
# the watchdog's own violation counter
DEFAULT_FAMILIES = (
    "serving_placements_total",
    "serving_shed_total",
    "serving_request_seconds",
    "wall_round_ms",
    "rounds_total",
    "rounds_skipped_total",
    "degraded_rounds_total",
    "circuit_breaker_transitions_total",
    "fleet_cost_quantile",
    "fleet_load_std_quantile",
    "fleet_drift_quantile",
    "slo_violations_total",
)

# histogram families whose cumulative bucket counts are sampled too
# (bounded: one extra series per declared bucket edge)
DEFAULT_BUCKET_FAMILIES = ("serving_request_seconds",)


def series_key(metric: str, labels: dict[str, str] | None, part: str = "") -> str:
    """The canonical series name: ``metric[:part]{k="v",...}`` with
    sorted labels — what /query takes and the SLO selectors resolve to."""
    base = f"{metric}:{part}" if part else metric
    if not labels:
        return base
    inner = ",".join(f'{k}="{labels[k]}"' for k in sorted(labels))
    return f"{base}{{{inner}}}"


class SeriesStore:
    """Bounded ring store over selected registry families.

    ``capacity`` is points per series; ``max_series`` the hard global
    budget (LRU-evicted, counted). ``families=None`` samples every
    record offered — the golden fixture's mode; production stores pass
    the allowlist."""

    def __init__(
        self,
        *,
        capacity: int = 512,
        max_series: int = 256,
        families: Iterable[str] | None = DEFAULT_FAMILIES,
        bucket_families: Iterable[str] = DEFAULT_BUCKET_FAMILIES,
        registry: MetricsRegistry | None = None,
    ) -> None:
        if capacity < 2:
            raise ValueError("timeseries capacity must be >= 2")
        if max_series < 1:
            raise ValueError("timeseries max_series must be >= 1")
        self.capacity = int(capacity)
        self.max_series = int(max_series)
        self.families = None if families is None else frozenset(families)
        self.bucket_families = frozenset(bucket_families)
        self.registry = registry
        # name -> deque[(tick, value)]; insertion order doubles as the
        # LRU order (move_to_end on every update)
        self._series: collections.OrderedDict[
            str, collections.deque[tuple[int, float]]
        ] = collections.OrderedDict()
        # name -> (metric, labels) so selectors match without re-parsing
        self._meta: dict[str, tuple[str, dict[str, str]]] = {}
        self.evictions = 0
        self.last_tick = 0

    def _reg(self) -> MetricsRegistry:
        return self.registry if self.registry is not None else get_registry()

    def __len__(self) -> int:
        return len(self._series)

    def names(self) -> list[str]:
        return list(self._series)

    def points(self) -> int:
        """Total retained points — the ring-bytes bound's proxy."""
        return sum(len(d) for d in self._series.values())

    # ---- writes ----

    def record(
        self,
        metric: str,
        labels: dict[str, str] | None,
        tick: int,
        value: float,
        part: str = "",
    ) -> None:
        """Append one point to one series, admitting (and budget-gating)
        the series if new. The grain-level entry point ``sample`` fans
        into."""
        name = series_key(metric, labels, part)
        ring = self._series.get(name)
        if ring is None:
            while len(self._series) >= self.max_series:
                victim, _ = self._series.popitem(last=False)
                self._meta.pop(victim, None)
                self.evictions += 1
                self._reg().counter(
                    "timeseries_evictions_total",
                    "history-plane series evicted by the hard global "
                    "series budget (least-recently-updated first)",
                ).inc()
            ring = self._series[name] = collections.deque(
                maxlen=self.capacity
            )
            self._meta[name] = (metric, dict(labels or {}))
        else:
            self._series.move_to_end(name)
        ring.append((int(tick), float(value)))

    def sample(self, records: list[dict[str, Any]], tick: int) -> None:
        """Ingest one registry snapshot (``MetricsRegistry.snapshot()``
        record dicts) at ``tick``. Only allowlisted families are kept;
        counters/gauges store their value, histograms their count/sum
        (plus cumulative bucket counts for ``bucket_families``)."""
        tick = int(tick)
        self.last_tick = max(self.last_tick, tick)
        for rec in records:
            metric = rec.get("metric")
            if self.families is not None and metric not in self.families:
                continue
            labels = rec.get("labels") or {}
            if rec.get("type") == "histogram":
                self.record(metric, labels, tick, rec.get("count", 0))
                # ":count" is the canonical total; the bare name above
                # stays for symmetry with /query's counter readout
                self.record(
                    metric, labels, tick, rec.get("sum", 0.0), part="sum"
                )
                if metric in self.bucket_families:
                    running = 0.0
                    for ub, n in (rec.get("buckets") or {}).items():
                        running += n
                        self.record(
                            metric, labels, tick, running, part=f"le:{ub}"
                        )
            else:
                self.record(metric, labels, tick, rec.get("value", 0.0))
        self._reg().gauge(
            "timeseries_series",
            "history-plane series currently retained (bounded by the "
            "hard max_series budget)",
        ).set(len(self._series))

    # ---- reads ----

    def query(self, name: str, n: int | None = None) -> list[tuple[int, float]]:
        """The last ``n`` points of one series (the /query endpoint's
        readout); the full bounded ring when ``n`` is None. Raises
        ``KeyError`` for an unknown (or evicted) series."""
        ring = self._series[name]
        pts = list(ring)
        if n is not None:
            n = max(int(n), 0)
            pts = pts[len(pts) - min(n, len(pts)):]
        return pts

    def match(
        self, metric: str, labels: Iterable[tuple[str, str]] = ()
    ) -> list[str]:
        """Series names whose metric matches and whose labels contain
        every given (key, value) pair — the SLO selectors' resolver."""
        want = dict(labels)
        out = []
        for name, (m, lbls) in self._meta.items():
            if m != metric:
                continue
            if all(lbls.get(k) == v for k, v in want.items()):
                out.append(name)
        return out

    def delta(self, name: str, window: int, now: int | None = None) -> float:
        """Reset-tolerant increase of a monotone series over the last
        ``window`` ticks: consecutive drops read as restarts (the new
        value IS the delta), so rebases never produce negative burn.
        Unknown series contribute 0 — a family that never fed (a solo
        run with no serving engine) is simply zero events."""
        ring = self._series.get(name)
        if not ring:
            return 0.0
        now = self.last_tick if now is None else int(now)
        floor = now - int(window)
        prev: float | None = None
        total = 0.0
        for tick, value in ring:
            if tick <= floor:
                prev = value  # the base point just outside the window
                continue
            if prev is None:
                # the window predates the ring: the first retained point
                # is all we can attribute (capacity-bounded honesty)
                total += value if tick <= floor + 1 else 0.0
            else:
                total += value - prev if value >= prev else value
            prev = value
        return total

    def value(self, name: str) -> float | None:
        """The latest sampled value of one series (gauge-style read)."""
        ring = self._series.get(name)
        return ring[-1][1] if ring else None
