"""Rolling-window SLO watchdog for the control loop.

Three rule families, each optional, evaluated after every executed round
over a bounded window of recent rounds:

- **round latency p95** — the p95 of per-round device decision latency
  exceeds ``latency_p95_s`` (0 disables);
- **comm-cost regression** — the latest round's communication cost rose
  more than ``cost_regression_frac`` above the window's best (0 disables);
- **retrace** — any ``instrument_jit``-ed function re-traced while being
  watched: its ``jax_traces_total`` rose ``max_retraces`` or more above
  the BASELINE captured when the watchdog first saw it (0 disables; the
  steady-state invariant is no new traces — one more means every round
  is paying a recompile). Baselines — and the rolling windows — reset on
  :meth:`Watchdog.rebase`, which the ops plane calls when a new run
  binds, so a bench session's later cells compiling fresh shapes are not
  misread as retraces. Under elastic churn a counted **bucket
  promotion** (``RoundRecord.churn["promotions"]``) legitimately
  recompiles every kernel once — each promotion observed since the
  baseline raises the per-fn allowance by one, so the rule flags only
  retraces a promotion does NOT explain.

A fourth rule is fed EXTERNALLY rather than per round: **perf
regression** (:meth:`Watchdog.observe_perf`) takes the perf ledger's
rolling-window verdicts (``telemetry.perf_ledger.detect``) — the bench
harness calls it after each cell. A newly regressed metric increments
``perf_regressions_total{metric}`` (plus the generic
``slo_violations_total{rule="perf_regression"}`` on rule entry), and the
rule stays active until a later verdict set clears the metric. Unlike the
per-round windows, perf state survives :meth:`rebase` — it describes the
ledger's cross-run history, not the current run's window.

A fifth rule kind is the SLO v2 burn pair (``slo_fast_burn`` /
``slo_slow_burn``): the error-budget engine (:mod:`telemetry.slo`)
evaluates multi-window burn rates over the history plane and feeds the
currently-firing entries via :meth:`Watchdog.observe_slo_burn` — the
watchdog just does the entry/recovery bookkeeping, so burn alerts count,
log, and flip /healthz exactly like the native rules.

Entering violation increments ``slo_violations_total{rule}`` and logs an
``slo_violation`` event; leaving logs ``slo_recovered``. The set of
currently-active violations (:attr:`Watchdog.active`) is what flips
``/healthz`` unhealthy — a rule that recovers un-flips it. Every active
entry carries the uniform ``{rule, value, threshold, since}`` quartet on
top of its rule-specific detail, so /healthz consumers render legacy
threshold rules and burn-rate rules identically.

jax-free by design, like the registry it reads.
"""

from __future__ import annotations

import collections
import math
import time
from dataclasses import dataclass
from typing import Any

from kubernetes_rescheduling_tpu.telemetry.registry import (
    MetricsRegistry,
    get_registry,
)

RULE_LATENCY = "round_latency_p95"
RULE_COST = "comm_cost_regression"
RULE_RETRACE = "retrace"
RULE_PERF = "perf_regression"
RULE_ATTRIBUTION = "attribution_drift"
RULE_FORECAST = "forecast_skill"
RULE_PIPELINE = "pipeline_overlap"
RULE_RECONCILE = "reconcile_divergence"
RULE_SHADOW = "shadow_win_rate"
RULE_FLEET_TAIL = "fleet_tail_cost"
RULE_SCAN_TRIPWIRE = "scan_tripwire"
RULE_SERVING = "serving_p99"
RULE_MESH = "mesh_imbalance"


@dataclass(frozen=True)
class SLORules:
    """Thresholds; a zero threshold disables its rule."""

    window: int = 20
    min_samples: int = 5            # rounds before latency/cost rules judge
    latency_p95_s: float = 0.0
    cost_regression_frac: float = 0.0
    max_retraces: int = 1
    # attribution drift: the top-1 service edge's share of total
    # communication cost exceeding this fraction means one edge dominates
    # the objective — the placement (or the traffic estimate feeding it)
    # has collapsed onto a single hot pair (0 disables; needs per-round
    # attribution records — see telemetry.attribution)
    attribution_drift_frac: float = 0.0
    # forecast skill: a TRAINED forecaster whose running skill (1 −
    # mae_model/mae_persistence) sits below this threshold is losing to
    # the free persistence baseline — the proactive policy is paying
    # model risk for nothing (the controller's device-side gate has
    # already degraded those rounds to reactive CAR; this rule makes the
    # condition a visible SLO). Only rounds carrying forecast data are
    # judged, so reactive runs can never trip it. The natural threshold
    # is 0.0 — "at least tie persistence".
    forecast_min_skill: float = 0.0
    # pipeline overlap collapse: the rolling mean overlap_ratio of
    # pipelined rounds (RoundRecord.pipeline — the fraction of background
    # boundary time hidden behind foreground work) sitting below this
    # means the pipelined loop has degenerated to sequential round-trips
    # — the wall-clock win the perf ledger's wall_round_ms series gates
    # is silently gone (0 disables; only rounds carrying pipeline
    # telemetry are judged, so sequential runs can never trip it).
    pipeline_min_overlap: float = 0.0
    # reconcile divergence: the latest round's reconcile block
    # (RoundRecord.reconcile — the intent ledger's accounting) reports at
    # least this many pods STILL diverged from the controller's intent
    # after the round's corrective moves — drift is outrunning the repair
    # budget, or repairs cannot land (0 disables; 1 = any persistent
    # drift; only rounds carrying reconcile data are judged, so runs with
    # the plane off can never trip it).
    reconcile_max_drift_pods: int = 0
    # fleet tail cost: the p99 of the fleet's per-tenant communication
    # cost rollup (telemetry.fleet_rollup — observe_fleet_rollup feeds
    # it) rising more than this fraction above the rolling window's
    # best means the fleet's WORST tenants are regressing even if the
    # median looks fine — exactly the signal per-tenant series used to
    # carry and the cardinality budget suppressed (0 disables; only
    # runs feeding rollups are judged; the window resets on rebase,
    # like the cost rule, so a new run's cost scale is never misjudged)
    fleet_tail_frac: float = 0.0
    # fleet tenant-state TTL: per-source state keyed by tenant (the
    # reconcile blocks) is pruned once a tenant goes unseen for this
    # many observed rounds — under tenant churn the dict would
    # otherwise grow without bound (counted
    # watchdog_tenants_pruned_total; 0 disables pruning)
    tenant_ttl_rounds: int = 100
    # shadow win-rate: the latest scored shadow round's RUNNING win-rate
    # against the replayed trace's actual scheduler sitting below this
    # means the shadow run is losing the head-to-head — promoting these
    # recommendations to a live cluster would make placement worse (0
    # disables; only rounds carrying shadow data are judged, so live
    # runs can never trip it; min_samples scored rounds before judging).
    shadow_min_win_rate: float = 0.0
    # scan tripwire: a scan block whose in-trace tripwire plane tripped
    # (telemetry.tripwire — the controller feeds the decoded trip via
    # observe_scan_block) is an active violation until a CLEAN block
    # lands — the device itself judged the block unhealthy, so /healthz
    # must say so (False disables; only scan runs feed blocks, so the
    # per-round path can never trip it)
    scan_tripwire: bool = True
    # serving p99: the serving plane's rolling-window p99 request latency
    # (ms, end-to-end: queue-wait through decode — ServingEngine feeds a
    # summary after every dispatched batch via observe_serving) exceeding
    # this threshold is a violation; the window draining back under it
    # recovers. Judged only once the rolling window holds min_samples
    # completed requests, so a cold-start compile spike on the first
    # request cannot flip /healthz on its own (0 disables; only serving
    # runs feed summaries, so round-only runs can never trip it).
    serving_p99_ms: float = 0.0
    # mesh imbalance: the device plane's worst/median attributed
    # per-device step-time ratio (telemetry.mesh — the controller feeds
    # the latest device-rollup summary via observe_mesh) exceeding this
    # means one dp device is pacing the whole mesh — a straggler chip, a
    # skewed tenant block, or a failing interconnect. Judged only on
    # meshes with >= 2 devices, so single-chip runs (where the ratio is
    # definitionally 1) can never trip it; a later balanced round
    # recovers. 0 disables; thresholds below 1 are rejected (the ratio
    # can never sit below 1).
    mesh_imbalance_ratio: float = 0.0

    def validate(self) -> "SLORules":
        if self.window < 2:
            raise ValueError("window must be >= 2")
        if self.min_samples < 1:
            raise ValueError("min_samples must be >= 1")
        for name in ("latency_p95_s", "cost_regression_frac"):
            if getattr(self, name) < 0:
                raise ValueError(f"{name} must be >= 0")
        if self.max_retraces < 0:
            raise ValueError("max_retraces must be >= 0")
        if not (0.0 <= self.attribution_drift_frac <= 1.0):
            raise ValueError("attribution_drift_frac must be in [0, 1]")
        if self.forecast_min_skill > 1.0:
            raise ValueError(
                "forecast_min_skill must be <= 1.0 (skill is bounded "
                "above by 1)"
            )
        if not (0.0 <= self.pipeline_min_overlap <= 1.0):
            raise ValueError(
                "pipeline_min_overlap must be in [0, 1] (overlap_ratio "
                "is a fraction)"
            )
        if self.reconcile_max_drift_pods < 0:
            raise ValueError(
                "reconcile_max_drift_pods must be >= 0 (0 disables the "
                "reconcile_divergence rule)"
            )
        if not (0.0 <= self.shadow_min_win_rate <= 1.0):
            raise ValueError(
                "shadow_min_win_rate must be in [0, 1] (a win-rate "
                "fraction; 0 disables the shadow_win_rate rule)"
            )
        if self.fleet_tail_frac < 0:
            raise ValueError(
                "fleet_tail_frac must be >= 0 (0 disables the "
                "fleet_tail_cost rule)"
            )
        if self.tenant_ttl_rounds < 0:
            raise ValueError(
                "tenant_ttl_rounds must be >= 0 (0 disables per-tenant "
                "state pruning)"
            )
        if self.serving_p99_ms < 0:
            raise ValueError(
                "serving_p99_ms must be >= 0 (0 disables the serving_p99 "
                "rule)"
            )
        if self.mesh_imbalance_ratio != 0.0 and self.mesh_imbalance_ratio < 1.0:
            raise ValueError(
                "mesh_imbalance_ratio must be 0 (rule off) or >= 1 "
                "(worst/median step time can never sit below 1)"
            )
        return self


def _p95(samples: list[float]) -> float:
    s = sorted(samples)
    idx = max(math.ceil(0.95 * len(s)) - 1, 0)
    return s[idx]


class Watchdog:
    """Feed it one completed round at a time; read ``active`` for health."""

    def __init__(
        self,
        rules: SLORules | None = None,
        *,
        registry: MetricsRegistry | None = None,
        logger=None,
    ) -> None:
        self.rules = (rules or SLORules()).validate()
        self.registry = registry
        self.logger = logger
        self._lat: collections.deque[float] = collections.deque(
            maxlen=self.rules.window
        )
        self._cost: collections.deque[float] = collections.deque(
            maxlen=self.rules.window
        )
        self._trace_base: dict[str, float] = {}
        # elastic churn: cumulative bucket promotions last seen / the
        # allowance accrued since (re)base — each promotion excuses one
        # retrace per fn (the ONLY legal churn recompile)
        self._promo_seen: int | None = None
        self._promo_allow: int = 0
        self._perf_active: dict[str, dict[str, Any]] = {}
        self._attr: dict[str, Any] | None = None  # latest round's attribution
        self._forecast: dict[str, Any] | None = None  # latest round's forecast
        # latest reconcile block PER SOURCE (solo runs key None; fleet
        # tenants key their name): the rule judges the worst source, so
        # one tenant's convergence can never mask another's drift
        self._reconcile: dict[str | None, dict[str, Any]] = {}
        # last round index each tenant was seen at — per-tenant state is
        # PRUNED once unseen for tenant_ttl_rounds (counted), so tenant
        # churn cannot grow the per-source dicts without bound
        self._tenant_seen: dict[str, int] = {}
        self._last_round: int = 0
        self._shadow: dict[str, Any] | None = None  # latest shadow block
        # latest scan block's decoded trip (None = last block was clean
        # or no scan block observed yet) — observe_scan_block feeds it
        self._scan_trip: dict[str, Any] | None = None
        # latest serving-plane summary (observe_serving feeds it after
        # every dispatched batch; its p99_ms/count judge the serving rule)
        self._serving: dict[str, Any] | None = None
        # latest device-rollup summary (observe_mesh feeds it once per
        # fleet round/scan block; its ratio/n_devices judge the
        # mesh_imbalance rule)
        self._mesh: dict[str, Any] | None = None
        # latest SLO-engine burn entries (observe_slo_burn feeds them
        # each history-plane tick; merged into `now` verbatim so burn
        # rules ride the same entry/recovery bookkeeping)
        self._slo_burn: dict[str, dict[str, Any]] = {}
        # rule -> wall time it entered violation (the structured
        # /healthz verdicts' `since` field; cleared on recovery)
        self._since: dict[str, float] = {}
        # fleet cost-rollup tail (p99 per fleet round) — rolling window
        self._fleet_tail: collections.deque[float] = collections.deque(
            maxlen=self.rules.window
        )
        # pipelined rounds' overlap ratios (rolling window)
        self._overlap: collections.deque[float] = collections.deque(
            maxlen=self.rules.window
        )
        self.active: dict[str, dict[str, Any]] = {}
        self.violations_seen = 0

    def rebase(self) -> None:
        """Start a fresh observation window: clear the rolling latency/
        cost windows, retrace baselines, and active violations. Called
        when a new run binds to the ops plane — cross-run comparisons
        (a different algorithm's cost scale, a new shape's first
        compile) are not SLO signals. Perf-ledger regressions are NOT
        cleared: they judge cross-run history by design, and a new cell
        binding must not mask yesterday's cliff — only a recovered
        verdict set (:meth:`observe_perf`) clears them."""
        self._lat.clear()
        self._cost.clear()
        self._trace_base.clear()
        self._promo_seen = None
        self._promo_allow = 0
        self._attr = None
        self._forecast = None
        self._reconcile = {}
        self._tenant_seen = {}
        self._last_round = 0
        self._shadow = None
        self._scan_trip = None
        self._serving = None
        self._mesh = None
        self._slo_burn = {}
        self._overlap.clear()
        self._fleet_tail.clear()
        self.active = (
            {RULE_PERF: self.active[RULE_PERF]}
            if RULE_PERF in self.active
            else {}
        )
        self._since = {
            rule: t for rule, t in self._since.items() if rule in self.active
        }

    def _reg(self) -> MetricsRegistry:
        return self.registry if self.registry is not None else get_registry()

    def observe_round(self, record, tenant=None) -> list[dict[str, Any]]:
        """Record one executed round and re-evaluate every rule. Returns
        the NEWLY raised violations (already counted and logged).
        ``tenant`` names the fleet tenant the round belongs to (None for
        solo runs) — per-source state like the reconcile block keys on
        it so interleaved tenant rounds never mask each other."""
        self._lat.append(float(record.decision_latency_s))
        self._cost.append(float(record.communication_cost))
        attr = getattr(record, "attribution", None)
        if isinstance(attr, dict):
            self._attr = attr
        forecast = getattr(record, "forecast", None)
        if isinstance(forecast, dict):
            self._forecast = forecast
        reconcile = getattr(record, "reconcile", None)
        if isinstance(reconcile, dict):
            self._reconcile[tenant] = reconcile
        rnd = getattr(record, "round", None)
        advanced = isinstance(rnd, (int, float)) and int(rnd) > self._last_round
        if advanced:
            self._last_round = int(rnd)
        if tenant is not None:
            self._tenant_seen[tenant] = self._last_round
        if advanced:
            # prune once per ROUND, not per tenant-observation: a fleet
            # round fans T observe_round calls through here, and nothing
            # new can expire until the round index moves
            self._prune_tenants()
        shadow = getattr(record, "shadow", None)
        if isinstance(shadow, dict):
            self._shadow = shadow
        pipeline = getattr(record, "pipeline", None)
        if isinstance(pipeline, dict) and "overlap_ratio" in pipeline:
            self._overlap.append(float(pipeline["overlap_ratio"]))
        churn = getattr(record, "churn", None)
        if isinstance(churn, dict):
            p = churn.get("promotions")
            if isinstance(p, (int, float)):
                p = int(p)
                if self._promo_seen is None:
                    # promotions that pre-date the watch are baselined
                    # away, exactly like the trace baselines
                    self._promo_seen = p
                elif p > self._promo_seen:
                    self._promo_allow += p - self._promo_seen
                    self._promo_seen = p
        return self.check()

    def _prune_tenants(self) -> None:
        """Drop per-tenant state (the reconcile blocks) for tenants
        unseen for ``tenant_ttl_rounds`` rounds — the churn-proofing
        half of the per-source design: without it a fleet that retires
        tenants would grow the dicts forever, and a long-gone tenant's
        stale drift block could hold the reconcile rule in violation."""
        ttl = self.rules.tenant_ttl_rounds
        if ttl <= 0 or not self._tenant_seen:
            return
        dead = [
            t
            for t, seen in self._tenant_seen.items()
            if self._last_round - seen > ttl
        ]
        for t in dead:
            self._tenant_seen.pop(t, None)
            self._reconcile.pop(t, None)
            self._reg().counter(
                "watchdog_tenants_pruned_total",
                "per-tenant watchdog state entries pruned after the "
                "tenant went unseen for tenant_ttl_rounds rounds",
            ).inc()

    def observe_fleet_rollup(
        self, rollup: dict[str, Any]
    ) -> list[dict[str, Any]]:
        """Feed one fleet round's decoded tenant rollup
        (``telemetry.fleet_rollup.decode_rollup``): the p99 of the
        per-tenant cost dimension joins the rolling tail window the
        ``fleet_tail_cost`` rule judges. Returns the newly raised
        violations, like :meth:`observe_round`."""
        try:
            p99 = float(rollup["dims"]["cost"]["quantiles"]["p99"])
        except (KeyError, TypeError):
            return []
        self._fleet_tail.append(p99)
        return self.check()

    def observe_scan_block(
        self, trip: dict[str, Any] | None
    ) -> list[dict[str, Any]]:
        """Feed one scan block's tripwire verdict (the controller's
        decoded trip dict, or None for a clean block). A tripped block
        arms the ``scan_tripwire`` rule; the next clean block clears it
        — the device's own health verdict, surfaced on /healthz.
        Returns the newly raised violations, like
        :meth:`observe_round`."""
        self._scan_trip = dict(trip) if trip is not None else None
        return self.check()

    def observe_serving(
        self, summary: dict[str, Any] | None
    ) -> list[dict[str, Any]]:
        """Feed the serving plane's latest rolling-window summary
        (``ServingEngine.summary()`` — the engine calls this through
        ``OpsPlane.observe_serving`` after every dispatched batch). The
        summary's ``p99_ms`` over ``count`` completed requests judges the
        ``serving_p99`` rule; a later summary whose window has drained
        back under the threshold recovers it. Returns the newly raised
        violations, like :meth:`observe_round`."""
        self._serving = dict(summary) if summary is not None else None
        return self.check()

    def observe_mesh(
        self, summary: dict[str, Any] | None
    ) -> list[dict[str, Any]]:
        """Feed the device plane's latest rollup summary
        (``telemetry.mesh.MeshPlane.observe_block`` — the fleet loop
        calls this through ``OpsPlane.observe_device_rollup`` once per
        round/block). The summary's worst/median step-time ``ratio``
        over ``n_devices`` judges the ``mesh_imbalance`` rule; a later
        balanced round recovers it. Returns the newly raised
        violations, like :meth:`observe_round`."""
        self._mesh = dict(summary) if summary is not None else None
        return self.check()

    def observe_slo_burn(
        self, entries: dict[str, dict[str, Any]]
    ) -> list[dict[str, Any]]:
        """Feed the SLO engine's burn-rule entries for this tick
        (``telemetry.slo.SloEngine.evaluate`` — rule name to detail dict,
        empty when nothing burns). Burn rules ride the same
        entry/recovery bookkeeping as every other rule: newly burning
        counts ``slo_violations_total{rule}``, the burn dropping back
        under threshold recovers. Returns the newly raised violations,
        like :meth:`observe_round`."""
        self._slo_burn = {
            rule: dict(detail) for rule, detail in (entries or {}).items()
        }
        return self.check()

    def observe_perf(self, verdicts: dict[str, dict[str, Any]]) -> list[dict[str, Any]]:
        """Feed one perf-ledger verdict set (``perf_ledger.detect``).
        Metrics whose status is ``regressed`` arm the ``perf_regression``
        rule; each NEWLY regressed metric counts once in
        ``perf_regressions_total{metric}``. A verdict set with no
        regressions clears the rule (the recovery path). Returns the
        newly raised violations, like :meth:`observe_round`."""
        regressed = {
            k: v for k, v in (verdicts or {}).items()
            if v.get("status") == "regressed"
        }
        for key in regressed:
            if key not in self._perf_active:
                self._reg().counter(
                    "perf_regressions_total",
                    "perf-ledger metrics newly judged regressed",
                    labelnames=("metric",),
                ).labels(metric=key).inc()
        self._perf_active = regressed
        return self.check()

    def _uniform(
        self, rule: str, detail: dict[str, Any]
    ) -> tuple[float, float]:
        """(value, threshold) for the uniform verdict shape — the
        measured quantity that tripped the rule and the boundary it
        crossed. Rules whose detail already carries the pair (the burn
        rules) are left alone by the setdefault in :meth:`check`."""
        r = self.rules
        if rule == RULE_LATENCY:
            return detail.get("p95_s", 0.0), detail.get("threshold_s", 0.0)
        if rule == RULE_COST:
            base = detail.get("baseline", 0.0)
            return (
                detail.get("cost", 0.0),
                base * (1.0 + detail.get("threshold_frac", 0.0)),
            )
        if rule == RULE_RETRACE:
            return float(len(detail.get("fns") or ())), float(
                detail.get("max_retraces", r.max_retraces)
            )
        if rule == RULE_ATTRIBUTION:
            return detail.get("share", 0.0), detail.get("threshold_frac", 0.0)
        if rule == RULE_FORECAST:
            return detail.get("skill", 0.0), detail.get("threshold", 0.0)
        if rule == RULE_PIPELINE:
            return (
                detail.get("overlap_ratio_mean", 0.0),
                detail.get("threshold", 0.0),
            )
        if rule == RULE_RECONCILE:
            return float(detail.get("drift_pods", 0)), float(
                detail.get("threshold", 0)
            )
        if rule == RULE_FLEET_TAIL:
            base = detail.get("baseline", 0.0)
            return (
                detail.get("p99_cost", 0.0),
                base * (1.0 + detail.get("threshold_frac", 0.0)),
            )
        if rule == RULE_SHADOW:
            return detail.get("win_rate", 0.0), detail.get("threshold", 0.0)
        if rule == RULE_SERVING:
            return detail.get("p99_ms", 0.0), detail.get("threshold_ms", 0.0)
        if rule == RULE_MESH:
            return detail.get("ratio", 0.0), detail.get(
                "threshold_ratio", 0.0
            )
        if rule == RULE_PERF:
            return float(detail.get("count", 0)), 0.0
        # scan_tripwire and anything without a numeric axis: the device
        # latched a boolean verdict — 1 over a 0 threshold
        return 1.0, 0.0

    def check(self) -> list[dict[str, Any]]:
        r = self.rules
        now: dict[str, dict[str, Any]] = {}
        if r.latency_p95_s > 0 and len(self._lat) >= r.min_samples:
            p95 = _p95(list(self._lat))
            if p95 > r.latency_p95_s:
                now[RULE_LATENCY] = {
                    "p95_s": p95, "threshold_s": r.latency_p95_s,
                    "window": len(self._lat),
                }
        # the baseline excludes the latest sample, so the rule needs at
        # least 2 samples whatever min_samples says
        if r.cost_regression_frac > 0 and len(self._cost) >= max(r.min_samples, 2):
            latest = self._cost[-1]
            baseline = min(list(self._cost)[:-1])
            if baseline > 0 and latest > (1.0 + r.cost_regression_frac) * baseline:
                now[RULE_COST] = {
                    "cost": latest, "baseline": baseline,
                    "threshold_frac": r.cost_regression_frac,
                }
        if r.max_retraces > 0:
            # compare against the count first seen for each fn, not the
            # cumulative total: a fresh shape compiling once (a later
            # bench cell, the explain kernel's first round) is not a
            # retrace — only growth while under watch is
            retraced = {}
            for rec in self._reg().snapshot():
                if rec["metric"] != "jax_traces_total":
                    continue
                fn = rec["labels"].get("fn", "?")
                v = rec.get("value", 0)
                base = self._trace_base.setdefault(fn, v)
                # each counted bucket promotion explains one retrace per
                # fn — only growth BEYOND the promotion allowance is an
                # SLO signal (the elastic invariant: 1 steady-state
                # trace plus exactly the counted promotions)
                if v - base - self._promo_allow >= r.max_retraces:
                    retraced[fn] = v
            if retraced:
                now[RULE_RETRACE] = {
                    "fns": retraced, "max_retraces": r.max_retraces,
                    "promotions_allowed": self._promo_allow,
                }
        if r.attribution_drift_frac > 0 and self._attr is not None:
            # the LATEST round's attribution judges: one edge carrying
            # more than the configured fraction of total cost means the
            # objective has collapsed onto a single hot pair
            edges = self._attr.get("edges") or ()
            total = self._attr.get("total") or 0.0
            if edges and total > 0:
                top = edges[0]
                share = top.get("cost", 0.0) / total
                if share > r.attribution_drift_frac:
                    now[RULE_ATTRIBUTION] = {
                        "edge": f"{top.get('src_service')}->{top.get('dst_service')}",
                        "share": share,
                        "threshold_frac": r.attribution_drift_frac,
                        "total": total,
                    }
        if self._forecast is not None and self._forecast.get("trained"):
            # the LATEST round's forecast block judges: a trained model
            # below the skill floor is losing to the free persistence
            # baseline (the controller's device gate has already
            # degraded the delta — this surfaces it on /healthz)
            skill = float(self._forecast.get("skill", 0.0))
            if skill < r.forecast_min_skill:
                now[RULE_FORECAST] = {
                    "skill": skill,
                    "threshold": r.forecast_min_skill,
                    "mae_model": self._forecast.get("mae_model"),
                    "mae_persistence": self._forecast.get("mae_persistence"),
                    "mode": self._forecast.get("mode"),
                }
        if r.pipeline_min_overlap > 0 and len(self._overlap) >= r.min_samples:
            # overlap collapse: the rolling MEAN of pipelined rounds'
            # hidden-background fraction — one slow flush is noise, a
            # window of them means the pipeline is sequential again
            mean = sum(self._overlap) / len(self._overlap)
            if mean < r.pipeline_min_overlap:
                now[RULE_PIPELINE] = {
                    "overlap_ratio_mean": mean,
                    "threshold": r.pipeline_min_overlap,
                    "window": len(self._overlap),
                }
        if r.reconcile_max_drift_pods > 0 and self._reconcile:
            # each source's LATEST round carrying reconcile data judges,
            # and the WORST source decides: pods still diverged from
            # intent after that round's corrective moves means drift is
            # outrunning the repair budget (or repairs cannot land — a
            # dead target, a frozen boundary). In fleet mode sources are
            # tenants, so one tenant converging (drift_pods=0) can never
            # mask another tenant's persistent drift
            tenant, worst = max(
                self._reconcile.items(),
                key=lambda kv: int(kv[1].get("drift_pods") or 0),
            )
            drift = int(worst.get("drift_pods") or 0)
            if drift >= r.reconcile_max_drift_pods:
                now[RULE_RECONCILE] = {
                    "drift_pods": drift,
                    "threshold": r.reconcile_max_drift_pods,
                    "divergences": len(worst.get("divergences") or ()),
                    "repairs_issued": len(worst.get("repairs") or ()),
                    **({"tenant": tenant} if tenant is not None else {}),
                }
        if r.fleet_tail_frac > 0 and len(self._fleet_tail) >= max(
            r.min_samples, 2
        ):
            # the cost-regression rule's shape, applied to the fleet's
            # TAIL: the latest round's p99 cost rollup vs the window's
            # best — the worst tenants regressing is an SLO signal even
            # while the fleet median holds (the baseline excludes the
            # latest sample, so >= 2 samples whatever min_samples says)
            latest = self._fleet_tail[-1]
            baseline = min(list(self._fleet_tail)[:-1])
            if baseline > 0 and latest > (1.0 + r.fleet_tail_frac) * baseline:
                now[RULE_FLEET_TAIL] = {
                    "p99_cost": latest,
                    "baseline": baseline,
                    "threshold_frac": r.fleet_tail_frac,
                    "window": len(self._fleet_tail),
                }
        if (
            r.shadow_min_win_rate > 0
            and self._shadow is not None
            and int(self._shadow.get("scored") or 0) >= r.min_samples
        ):
            # the latest scored round's RUNNING win-rate judges: a
            # shadow run losing the head-to-head means promoting these
            # recommendations would make real placement worse
            win_rate = float(self._shadow.get("win_rate") or 0.0)
            if win_rate < r.shadow_min_win_rate:
                now[RULE_SHADOW] = {
                    "win_rate": win_rate,
                    "threshold": r.shadow_min_win_rate,
                    "scored": int(self._shadow.get("scored") or 0),
                    "cost_delta": self._shadow.get("cost_delta"),
                }
        if r.serving_p99_ms > 0 and self._serving is not None:
            # the latest serving summary judges: its p99 is already a
            # rolling-window statistic (the engine's bounded recent-total
            # deque), so fast requests pushing slow ones out of the
            # window IS the recovery path — no second window here
            count = int(self._serving.get("count") or 0)
            p99 = float(self._serving.get("p99_ms") or 0.0)
            if count >= r.min_samples and p99 > r.serving_p99_ms:
                now[RULE_SERVING] = {
                    "p99_ms": p99,
                    "threshold_ms": r.serving_p99_ms,
                    "count": count,
                    "p50_ms": self._serving.get("p50_ms"),
                    "rate_rps": self._serving.get("rate_rps"),
                }
        if r.mesh_imbalance_ratio > 0 and self._mesh is not None:
            # the LATEST device rollup judges: the ratio is already a
            # whole-mesh statistic over the round's attributed step
            # times, and a mesh of one device is definitionally balanced
            # (ratio 1) — only real dp meshes are judged
            n_devices = int(self._mesh.get("n_devices") or 0)
            ratio = float(self._mesh.get("ratio") or 0.0)
            if n_devices >= 2 and ratio > r.mesh_imbalance_ratio:
                now[RULE_MESH] = {
                    "ratio": ratio,
                    "threshold_ratio": r.mesh_imbalance_ratio,
                    "n_devices": n_devices,
                    "worst_device": self._mesh.get("worst_device"),
                    "step_ms_p50": self._mesh.get("step_ms_p50"),
                    "step_ms_max": self._mesh.get("step_ms_max"),
                }
        if r.scan_tripwire and self._scan_trip is not None:
            # the LATEST scan block judges: its in-trace tripwire
            # latched, the replay was truncated at the trip round, and
            # the block drained — an active violation until a clean
            # block lands (observe_scan_block(None) clears)
            now[RULE_SCAN_TRIPWIRE] = dict(self._scan_trip)
        if self._perf_active:
            now[RULE_PERF] = {
                "metrics": {
                    k: {
                        "current": v.get("current"),
                        "baseline": v.get("baseline"),
                        "ratio": v.get("ratio"),
                    }
                    for k, v in sorted(self._perf_active.items())
                },
                "count": len(self._perf_active),
            }
        for rule, detail in self._slo_burn.items():
            now[rule] = dict(detail)

        # uniform verdict shape: every active rule carries value /
        # threshold / since alongside its rule-specific detail, so the
        # /healthz consumer renders burn-rate and legacy threshold rules
        # identically without knowing either's keys
        t_now = time.time()
        for rule, detail in now.items():
            value, threshold = self._uniform(rule, detail)
            detail.setdefault("value", value)
            detail.setdefault("threshold", threshold)
            detail["since"] = self._since.setdefault(rule, t_now)
        for rule in list(self._since):
            if rule not in now:
                del self._since[rule]

        raised = []
        for rule, detail in now.items():
            if rule not in self.active:
                raised.append({"rule": rule, **detail})
                self.violations_seen += 1
                self._reg().counter(
                    "slo_violations_total",
                    "SLO watchdog rules newly entering violation",
                    labelnames=("rule",),
                ).labels(rule=rule).inc()
                if self.logger is not None:
                    self.logger.warn("slo_violation", rule=rule, **detail)
        for rule in self.active:
            if rule not in now and self.logger is not None:
                self.logger.info("slo_recovered", rule=rule)
        self.active = now
        return raised

    @property
    def healthy(self) -> bool:
        return not self.active

    def status(self) -> dict[str, Any]:
        """The /healthz 'slo' block."""
        return {
            "healthy": self.healthy,
            "active": [
                {"rule": rule, **detail}
                for rule, detail in sorted(self.active.items())
            ],
            "violations_seen": self.violations_seen,
        }
