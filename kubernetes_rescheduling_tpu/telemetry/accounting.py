"""JAX-aware accounting: retrace/compile counters and transfer counts.

The scan'd round loop and the controller's jitted decision kernel are only
fast while they compile ONCE; a silently shape-polymorphic argument turns
every call into a full retrace + recompile, and nothing in the program
output says so (the exact failure mode the module-level-jit comments in
``bench/trace.py`` guard against by hand). :func:`instrument_jit` makes it
a metric:

- ``jax_traces_total{fn=...}`` — +1 every time the Python body is traced
  (i.e. every compilation of a new input signature);
- ``jax_trace_seconds{fn=...}`` — wall time spent inside the traced body
  (tracing/lowering, not XLA backend compilation);
- ``jax_compile_seconds{fn=...}`` — wall time of calls during which a
  trace occurred (tracing + lowering + XLA compile + the first run);
- ``jax_calls_total{fn=...}`` — total dispatches.

A steady-state loop therefore shows ``jax_calls_total = N`` and
``jax_traces_total = 1`` — and a test can assert exactly that.

:func:`pull` counts device→host transfers (the tunnel round trips that
dominate small-problem latency) as ``device_transfers_total{site=...}``.

On the FIRST trace of each instrumented function the wrapper additionally
captures the compiled executable's static cost — XLA ``cost_analysis()``
flops/bytes and ``memory_analysis()`` argument/output/temp bytes — into
the :mod:`costmodel` book and the ``jax_cost_*``/``jax_hbm_*`` gauges
(one extra AOT compile per function per process, never re-paid on cache
hits or later retraces; ``KRT_COST_CAPTURE=0`` disables it).
"""

from __future__ import annotations

import contextlib
import functools
import time
from typing import Any, Callable

import numpy as np

from kubernetes_rescheduling_tpu.telemetry import costmodel
from kubernetes_rescheduling_tpu.telemetry.registry import (
    MetricsRegistry,
    get_registry,
)


def instrument_jit(
    fn: Callable | None = None,
    *,
    name: str | None = None,
    registry: MetricsRegistry | None = None,
    **jit_kwargs: Any,
):
    """``jax.jit`` with trace/compile accounting; usable as a decorator
    (``@instrument_jit``) or a wrapper (``instrument_jit(f, name=...)``).

    ``registry=None`` resolves the process-default registry AT CALL TIME,
    so a module-level instrumented jit (e.g. the controller's decision
    kernel) reports into whatever registry is current when it runs —
    tests that swap in a fresh registry see the counts.
    """
    if fn is None:
        return functools.partial(
            instrument_jit, name=name, registry=registry, **jit_kwargs
        )

    import jax

    fn_label = name or getattr(fn, "__name__", "jit_fn")
    state = {"traces": 0}

    def _reg() -> MetricsRegistry:
        return registry if registry is not None else get_registry()

    @functools.wraps(fn)
    def traced_body(*args, **kwargs):
        # executes ONLY while jax traces a new input signature
        reg = _reg()
        state["traces"] += 1
        reg.counter(
            "jax_traces_total",
            "times a jitted function was traced (= compilations)",
            labelnames=("fn",),
        ).labels(fn=fn_label).inc()
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        reg.counter(
            "jax_trace_seconds",
            "wall time spent tracing/lowering jitted functions",
            labelnames=("fn",),
        ).labels(fn=fn_label).inc(time.perf_counter() - t0)
        return out

    jitted = jax.jit(traced_body, **jit_kwargs)

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        reg = _reg()
        before = state["traces"]
        t0 = time.perf_counter()
        out = jitted(*args, **kwargs)
        dt = time.perf_counter() - t0
        reg.counter(
            "jax_calls_total", "jitted function dispatches", labelnames=("fn",)
        ).labels(fn=fn_label).inc()
        if state["traces"] > before:
            reg.histogram(
                "jax_compile_seconds",
                "wall time of calls that triggered a trace+compile",
                labelnames=("fn",),
            ).labels(fn=fn_label).observe(dt)
        if not state.get("cost_done"):
            # compiled-cost capture: ONE AOT compile per fn LABEL per
            # process — the book is the dedup, so distinct wrappers
            # sharing a label (the sharded-restarts cache builds one per
            # (mesh, config)) never re-pay the compile. Tracer args (this
            # call ran inside an outer trace) defer the attempt to the
            # next concrete call; a concrete attempt — success or failure
            # — settles it for good, so a backend that cannot answer is
            # asked exactly once.
            if costmodel.get_costbook().get(fn_label) is not None:
                state["cost_done"] = True
                costmodel.republish(fn_label, reg)
            elif state["traces"] > 0 and not costmodel.has_tracers(args, kwargs):
                costmodel.capture_compiled_cost(
                    fn, fn_label, args, kwargs,
                    jit_kwargs=jit_kwargs, registry=reg,
                )
                state["cost_done"] = True
        elif state["traces"] == before and state.get("pub_reg") is not reg:
            # registries are swapped mid-process (tests, bench cells) while
            # this kernel stays compiled — republish the captured gauges so
            # the CURRENT registry's /metrics carries them. Memoized per
            # registry object: steady-state hot loops must not re-set six
            # gauges on every dispatch
            if costmodel.republish(fn_label, reg):
                state["pub_reg"] = reg
        return out

    wrapper.traces = lambda: state["traces"]
    wrapper.fn_label = fn_label
    wrapper._jitted = jitted
    return wrapper


def pull(
    x,
    site: str = "unnamed",
    registry: MetricsRegistry | None = None,
) -> np.ndarray:
    """Materialize a device value on the host (``np.asarray``) and count
    the transfer as ``device_transfers_total{site=...}`` — the per-round
    tunnel round trips become a visible budget instead of ambient cost."""
    reg = registry if registry is not None else get_registry()
    reg.counter(
        "device_transfers_total",
        "device->host pulls through telemetry.pull",
        labelnames=("site",),
    ).labels(site=site).inc()
    out = np.asarray(x)
    # byte twin of the count: the mesh plane attributes these bytes
    # across devices per block, and the ledger's dispatch/RTT
    # attribution reads the same series — counted AFTER the pull so the
    # bytes reflect what actually crossed, and only host-side (no
    # device work rides the accounting)
    reg.counter(
        "device_transfer_bytes_total",
        "bytes pulled device->host through telemetry.pull",
        labelnames=("site",),
    ).labels(site=site).inc(float(out.nbytes))
    return out


@contextlib.contextmanager
def timed_call(
    backend: str,
    call: str,
    registry: MetricsRegistry | None = None,
):
    """Count one backend API call and observe its latency — the shared
    instrumentation convention for ``backends/sim.py`` and
    ``backends/k8s.py`` (``backend_calls_total`` /
    ``backend_call_seconds``, labeled by backend and call). jax-free, so
    the never-traced k8s adapter can use it."""
    reg = registry if registry is not None else get_registry()
    reg.counter(
        "backend_calls_total",
        "backend API calls",
        labelnames=("backend", "call"),
    ).labels(backend=backend, call=call).inc()
    t0 = time.perf_counter()
    try:
        yield
    finally:
        reg.histogram(
            "backend_call_seconds",
            "backend API call latency",
            labelnames=("backend", "call"),
        ).labels(backend=backend, call=call).observe(time.perf_counter() - t0)


def count_reconcile(
    backend: str,
    pods: int,
    registry: MetricsRegistry | None = None,
) -> None:
    """One reconcile wave (a Deployment re-create or a batched pod-move
    wave) that restarted ``pods`` pods."""
    reg = registry if registry is not None else get_registry()
    reg.counter(
        "backend_reconciles_total",
        "reconcile waves applied by a backend",
        labelnames=("backend",),
    ).labels(backend=backend).inc()
    reg.counter(
        "backend_pods_restarted_total",
        "pods restarted by reconcile waves",
        labelnames=("backend",),
    ).labels(backend=backend).inc(max(int(pods), 0))


def publish_round_telemetry(
    tel,
    *,
    algorithm: str = "unknown",
    registry: MetricsRegistry | None = None,
) -> dict[str, float]:
    """Surface a ``solver.round_loop.RoundTelemetry`` (single round or the
    scan's stacked rounds) through the registry. One host pull for the
    whole pytree; returns the summary it published."""
    reg = registry if registry is not None else get_registry()
    moved = pull(tel.moved, site="round_telemetry", registry=reg)
    cost = np.asarray(tel.communication_cost, dtype=np.float64)
    lstd = np.asarray(tel.load_std, dtype=np.float64)
    rounds = int(moved.size)
    moves = int(np.sum(moved))
    reg.counter(
        "rounds_total", "rescheduling rounds executed", labelnames=("algorithm",)
    ).labels(algorithm=algorithm).inc(rounds)
    reg.counter(
        "moves_total", "rounds that moved a deployment", labelnames=("algorithm",)
    ).labels(algorithm=algorithm).inc(moves)
    g_cost = reg.gauge(
        "communication_cost",
        "communication cost after the most recent round",
        labelnames=("algorithm",),
    ).labels(algorithm=algorithm)
    g_std = reg.gauge(
        "load_std",
        "node CPU-% standard deviation after the most recent round",
        labelnames=("algorithm",),
    ).labels(algorithm=algorithm)
    g_cost.set(float(cost.reshape(-1)[-1]))
    g_std.set(float(lstd.reshape(-1)[-1]))
    return {
        "rounds": rounds,
        "moves": moves,
        "communication_cost": float(cost.reshape(-1)[-1]),
        "load_std": float(lstd.reshape(-1)[-1]),
    }
