"""Mesh & device plane: per-device telemetry, device-axis rollups, and
on-demand profiler capture.

PR 13 gave the TENANT axis a cardinality-budgeted observability plane
(``telemetry/fleet_rollup.py``); this module is the exact sibling for
the DEVICE axis the dp fleet planes run on (``parallel/fleet.py``,
``parallel/sharded*.py``, ``bench/multichip.py``):

- **Attribution model** — :func:`attribute_dispatch`: true per-device
  step time is unmeasurable from the host (one fenced dispatch covers
  the whole mesh), so the plane attributes the HOST-measured dispatch
  wall across the dp shards weighted by each shard's share of the
  per-tenant cost column that already rides the round-end pull (tenants
  map blockwise to dp shards). It is an attribution, not a measurement
  — the docs and the MULTICHIP record say so — and it costs **zero new
  transfers**: every input is host-resident already
  (``scripts/check_apply_boundary.py`` holds this module sync-free).
- **Device rollup** — :func:`device_rollup_matrix` /
  :func:`decode_device_rollup`: the PR-13 ``rollup_matrix`` pattern on
  the device axis — per-dimension quantiles (p50/p90/p99/max,
  nearest-rank, shared positions with the tenant rollup), sums, and the
  worst-k devices. Computed host-side in numpy (the matrix is
  ``[n_devices, 3]`` — device-side reduction would buy nothing and cost
  a transfer). Published as BOUNDED families
  (``mesh_step_ms_quantile{q}``, ``mesh_worst_device{rank,dim}``, …);
  device NAMES ride events and the ``/devices`` endpoint, never
  unbounded label keys.
- **The budget gate** — :class:`DeviceSeries`: the ``device``-labeled
  twin of ``TenantSeries`` (statically pinned by
  ``scripts/check_label_cardinality.py``). Meshes at or under
  ``ObsConfig.device_label_budget`` keep per-device series; larger
  meshes suppress them, counted
  ``device_series_suppressed_total{family}``.
- **MeshPlane** — the per-run accumulator: feed it each round's
  dispatch wall + pulled-bundle bytes + per-tenant cost weights, it
  samples ``memory_stats()`` across local devices
  (``costmodel.sample_device_memory`` — host metadata, no transfer),
  publishes the rollup, and serves the ``/healthz`` ``mesh`` stanza and
  the ``/devices`` drill-down. Its imbalance summary (worst/median
  device step time) feeds the watchdog's ``mesh_imbalance`` rule.
- **ProfilerGate** — on-demand ``jax.profiler`` capture around exactly
  one scan block or N fleet rounds, armed by ``POST /profile`` or
  ``--profile-rounds``. Artifacts land in the flight-recorder bundle
  dir (``profile_NNN/``), hard-capped: one capture in flight,
  ``profile_max_captures`` per process, ``profile_max_mb`` per artifact
  (oversize artifacts are deleted, not kept) — counted
  ``profile_captures_total{status}``, and each completed capture is
  referenced from a ``profile_capture`` flight-recorder bundle.

Module import stays jax-free (the ops server imports it);
``ProfilerGate`` imports ``jax.profiler`` lazily at capture time.
"""

from __future__ import annotations

import os
import shutil
import threading
import time
from typing import Any

import numpy as np

from kubernetes_rescheduling_tpu.telemetry import costmodel
from kubernetes_rescheduling_tpu.telemetry.fleet_rollup import (
    _quantile_positions,
)
from kubernetes_rescheduling_tpu.telemetry.registry import (
    MetricsRegistry,
    get_registry,
)

# the device rollup's dimensions, in matrix-column order: per device,
# this round's attributed step time, attributed round-end transfer
# volume, and live HBM in use (0 on backends without memory stats)
DEVICE_DIMS: tuple[str, ...] = ("step_ms", "transfer_mb", "hbm_mb")
NUM_DEVICE_DIMS = len(DEVICE_DIMS)
# quantile points, shared with the tenant rollup (nearest-rank)
DEVICE_QUANTS: tuple[str, ...] = ("p50", "p90", "p99", "max")
NUM_DEVICE_QUANTS = len(DEVICE_QUANTS)


def device_rollup_size(worst_k: int) -> int:
    """Flat length of one device rollup vector: per dimension, the
    quantile points, one sum, and worst-k (value, device-index) pairs —
    the tenant rollup's layout on the device axis."""
    return NUM_DEVICE_DIMS * (NUM_DEVICE_QUANTS + 1 + 2 * worst_k)


def device_rollup_matrix(matrix: np.ndarray, *, worst_k: int) -> np.ndarray:
    """``f32[n_devices, NUM_DEVICE_DIMS]`` → one flat rollup vector
    (quantiles, sums, worst-k values, worst-k device indices, each
    dimension-major) — ``fleet_rollup.rollup_numpy`` on the device axis,
    with the same nearest-rank quantile definition and stable tie order
    (ties resolve to the lower device index). ``worst_k`` must already
    be clamped to ``<= n_devices``."""
    m = np.asarray(matrix, dtype=np.float32)
    n = m.shape[0]
    if m.ndim != 2 or m.shape[1] != NUM_DEVICE_DIMS:
        raise ValueError(
            f"device rollup needs [n_devices, {NUM_DEVICE_DIMS}], "
            f"got {m.shape}"
        )
    if not (1 <= worst_k <= n):
        raise ValueError(f"worst_k must be in [1, {n}], got {worst_k}")
    pos = list(_quantile_positions(n))
    quants = np.empty((NUM_DEVICE_DIMS, NUM_DEVICE_QUANTS), np.float32)
    vals = np.empty((NUM_DEVICE_DIMS, worst_k), np.float32)
    idx = np.empty((NUM_DEVICE_DIMS, worst_k), np.float32)
    for d in range(NUM_DEVICE_DIMS):
        col = m[:, d]
        quants[d] = np.sort(col)[pos]
        order = np.argsort(-col, kind="stable")[:worst_k]
        vals[d] = col[order]
        idx[d] = order.astype(np.float32)
    sums = m.sum(axis=0, dtype=np.float32)
    return np.concatenate([quants.ravel(), sums, vals.ravel(), idx.ravel()])


def decode_device_rollup(flat, *, worst_k: int) -> dict[str, Any]:
    """Unpack one device rollup vector into the structured dict the
    publisher, the ``mesh_imbalance`` rule, and the events consume."""
    flat = np.asarray(flat, dtype=np.float32)
    if flat.size != device_rollup_size(worst_k):
        raise ValueError(
            f"device rollup vector of {flat.size} values does not decode "
            f"at worst_k={worst_k} (expected {device_rollup_size(worst_k)})"
        )
    nq = NUM_DEVICE_DIMS * NUM_DEVICE_QUANTS
    quants = flat[:nq].reshape(NUM_DEVICE_DIMS, NUM_DEVICE_QUANTS)
    sums = flat[nq : nq + NUM_DEVICE_DIMS]
    off = nq + NUM_DEVICE_DIMS
    vals = flat[off : off + NUM_DEVICE_DIMS * worst_k].reshape(
        NUM_DEVICE_DIMS, worst_k
    )
    idx = (
        flat[off + NUM_DEVICE_DIMS * worst_k :]
        .reshape(NUM_DEVICE_DIMS, worst_k)
        .astype(np.int64)
    )
    return {
        "worst_k": worst_k,
        "dims": {
            dim: {
                "quantiles": {
                    q: float(quants[d, j])
                    for j, q in enumerate(DEVICE_QUANTS)
                },
                "sum": float(sums[d]),
                "worst": [
                    {"device": int(idx[d, r]), "value": float(vals[d, r])}
                    for r in range(worst_k)
                ],
            }
            for d, dim in enumerate(DEVICE_DIMS)
        },
    }


def attribute_dispatch(total: float, weights, *, n: int) -> np.ndarray:
    """Attribute one host-measured quantity (the fenced dispatch wall,
    the pulled bundle's byte count) across ``n`` dp devices.

    Tenants map BLOCKWISE to dp shards (shard ``j`` owns tenants
    ``[j·T/n, (j+1)·T/n)`` — ``decode_fleet_global_dp``'s layout), so a
    per-tenant weight column (the cost metrics already pulled at round
    end) folds to per-shard shares by blockwise sum. Degenerate weights
    — absent, wrong length, non-finite, non-positive sum — fall back to
    a uniform split, so the rollup is always defined. This is an
    ATTRIBUTION of a whole-mesh measurement, not a per-device clock."""
    if n < 1:
        raise ValueError(f"n must be >= 1, got {n}")
    out = np.full(n, float(total) / n, dtype=np.float64)
    if weights is None:
        return out
    w = np.asarray(weights, dtype=np.float64).ravel()
    if w.size < n or w.size % n:
        return out
    if not np.all(np.isfinite(w)) or np.any(w < 0):
        return out
    shard = w.reshape(n, -1).sum(axis=1)
    s = float(shard.sum())
    if s <= 0.0:
        return out
    return float(total) * shard / s


def publish_device_rollup(
    registry: MetricsRegistry, rollup: dict[str, Any], *, n_devices: int
) -> float:
    """Decode → bounded metric families; returns the imbalance ratio
    (worst/median device step time; 0 when the median is 0). Series
    count is k·dims + quantile points + 2 gauges — independent of mesh
    size. Device NAMES ride events and ``/devices``, never label keys
    (the cardinality-budget convention)."""
    dims = rollup["dims"]
    quantile_gauges = (
        (
            "step_ms",
            registry.gauge(
                "mesh_step_ms_quantile",
                "per-device attributed step-time quantile across the dp "
                "mesh for the most recent fleet round "
                "(q = p50|p90|p99|max; dispatch-wall attribution, not a "
                "per-device clock)",
                labelnames=("q",),
            ),
        ),
        (
            "transfer_mb",
            registry.gauge(
                "mesh_transfer_mb_quantile",
                "per-device attributed round-end transfer-volume "
                "quantile across the dp mesh (q = p50|p90|p99|max)",
                labelnames=("q",),
            ),
        ),
        (
            "hbm_mb",
            registry.gauge(
                "mesh_hbm_mb_quantile",
                "per-device live HBM-in-use quantile across the dp mesh "
                "(q = p50|p90|p99|max; 0 on backends without "
                "memory_stats, e.g. CPU)",
                labelnames=("q",),
            ),
        ),
    )
    for dim, g in quantile_gauges:
        for q, v in dims[dim]["quantiles"].items():
            g.labels(q=q).set(v)
    worst = registry.gauge(
        "mesh_worst_device",
        "metric value of the rank-th worst device per rollup dimension "
        "(dim = step_ms|transfer_mb|hbm_mb); device NAMES ride the "
        "device_rollup event payload and /devices, never label keys",
        labelnames=("rank", "dim"),
    )
    for dim in DEVICE_DIMS:
        for rank, row in enumerate(dims[dim]["worst"]):
            worst.labels(rank=str(rank), dim=dim).set(row["value"])
    step = dims["step_ms"]["quantiles"]
    median = step["p50"]
    ratio = step["max"] / median if median > 0 else 0.0
    registry.gauge(
        "mesh_imbalance_ratio",
        "worst/median attributed device step time for the most recent "
        "fleet round — the mesh_imbalance watchdog rule's input "
        "(0 until a round is observed or while the median is 0)",
    ).set(ratio)
    registry.gauge(
        "mesh_devices",
        "devices carrying the dp fleet plane (cardinality bound for "
        "every device-labeled family)",
    ).set(float(n_devices))
    return ratio


def device_rollup_event(
    rollup: dict[str, Any],
    device_names,
    *,
    round: int | None = None,
) -> dict[str, Any]:
    """The JSON-able ``device_rollup`` event payload: quantiles and sums
    per dimension plus the worst-k rows WITH device names attached —
    the one place per-device identity legally rides."""
    dims = rollup["dims"]
    return {
        **({"round": round} if round is not None else {}),
        "worst_k": rollup["worst_k"],
        "quantiles": {
            dim: dict(dims[dim]["quantiles"]) for dim in DEVICE_DIMS
        },
        "sums": {dim: dims[dim]["sum"] for dim in DEVICE_DIMS},
        "worst": [
            {
                "dim": dim,
                "rank": rank,
                "device": (
                    str(device_names[row["device"]])
                    if 0 <= row["device"] < len(device_names)
                    else str(row["device"])
                ),
                "value": row["value"],
            }
            for dim in DEVICE_DIMS
            for rank, row in enumerate(dims[dim]["worst"])
        ],
    }


class DeviceSeries:
    """THE budget-gated gateway for device-labeled metric families —
    ``TenantSeries`` on the device axis, statically pinned by
    ``scripts/check_label_cardinality.py``. At or under ``budget``
    devices the per-device families emit (``budget=None`` = unlimited);
    over budget every update is suppressed and counted
    ``device_series_suppressed_total{family}`` — a pod-scale mesh reads
    the bounded ``mesh_*`` rollup families instead."""

    def __init__(self, registry, *, devices: int, budget: int | None):
        self.registry = registry
        self.devices = int(devices)
        self.budget = budget
        self.enabled = budget is None or self.devices <= int(budget)

    def _suppress(self, family: str) -> None:
        self.registry.counter(
            "device_series_suppressed_total",
            "per-device metric series updates suppressed by the "
            "ObsConfig.device_label_budget cardinality gate — the mesh "
            "is over budget; read the bounded mesh rollup families "
            "(mesh_*_quantile, mesh_worst_device) instead",
            labelnames=("family",),
        ).labels(family=family).inc()

    def counter_inc(
        self, name: str, help: str, device: str, amount: float = 1.0
    ) -> None:
        if self.enabled:
            self.registry.counter(
                name, help, labelnames=("device",)
            ).labels(device=device).inc(amount)
        else:
            self._suppress(name)

    def gauge_set(
        self, name: str, help: str, device: str, value: float
    ) -> None:
        if self.enabled:
            self.registry.gauge(
                name, help, labelnames=("device",)
            ).labels(device=device).set(value)
        else:
            self._suppress(name)


class MeshPlane:
    """The device plane's per-run accumulator.

    Fed once per fleet round (or scan block) with host-side values that
    already exist — the fenced dispatch wall, the pulled bundle's byte
    count, and the per-tenant cost column from the round-end metrics —
    it attributes them across the dp devices, samples live
    ``memory_stats()``, publishes the bounded rollup families and the
    budget-gated per-device series, and holds the latest rollup for the
    ``/healthz`` ``mesh`` stanza, the ``/devices`` drill-down, and the
    ``mesh_imbalance`` watchdog feed. Thread-safe reads — the ops
    server walks it from request threads mid-round."""

    def __init__(
        self,
        registry: MetricsRegistry | None = None,
        *,
        device_names,
        budget: int | None = None,
        worst_k: int = 3,
        sample_memory: bool = True,
    ) -> None:
        self.registry = registry if registry is not None else get_registry()
        self.device_names = tuple(str(d) for d in device_names)
        if not self.device_names:
            raise ValueError("MeshPlane needs at least one device name")
        n = len(self.device_names)
        self.worst_k = max(1, min(int(worst_k), n))
        self.series = DeviceSeries(self.registry, devices=n, budget=budget)
        self.sample_memory = sample_memory
        self.rounds = 0
        self.blocks = 0
        self._step_ms = np.zeros(n, np.float64)
        self._transfer_mb_total = np.zeros(n, np.float64)
        self._hbm_mb = np.zeros(n, np.float64)
        self._latest: dict[str, Any] | None = None
        self._latest_event: dict[str, Any] | None = None
        self._imbalance = 0.0
        self._lock = threading.Lock()

    @property
    def n_devices(self) -> int:
        return len(self.device_names)

    def _sample_hbm_mb(self) -> np.ndarray:
        out = np.zeros(self.n_devices, np.float64)
        if not self.sample_memory:
            return out
        by_name = {
            s["device"]: s
            for s in costmodel.sample_device_memory(self.registry)
        }
        for i, name in enumerate(self.device_names):
            s = by_name.get(name)
            if s and s.get("bytes_in_use") is not None:
                out[i] = float(s["bytes_in_use"]) / 2**20
        return out

    def observe_block(
        self,
        *,
        dispatch_s: float,
        transfer_bytes: float,
        weights=None,
        rounds: int = 1,
        round: int | None = None,
    ) -> tuple[dict[str, Any], dict[str, Any]]:
        """One round-end observation: attribute the block's dispatch
        wall and transfer bytes across the mesh, roll up, publish.
        Returns ``(summary, event)`` — the summary is the watchdog feed
        and the ``/healthz`` stanza; the event carries device names.
        Every input is already host-resident (zero new transfers)."""
        n = self.n_devices
        rounds = max(1, int(rounds))
        step_ms = (
            attribute_dispatch(dispatch_s, weights, n=n) / rounds * 1e3
        )
        transfer_mb = (
            attribute_dispatch(transfer_bytes, weights, n=n) / 2**20
        )
        hbm_mb = self._sample_hbm_mb()
        matrix = np.stack([step_ms, transfer_mb, hbm_mb], axis=1)
        rollup = decode_device_rollup(
            device_rollup_matrix(matrix, worst_k=self.worst_k),
            worst_k=self.worst_k,
        )
        ratio = publish_device_rollup(
            self.registry, rollup, n_devices=n
        )
        for i, name in enumerate(self.device_names):
            self.series.gauge_set(
                "mesh_device_step_ms",
                "attributed per-round step time of one dp device for "
                "the most recent fleet round (budget-gated; over "
                "ObsConfig.device_label_budget read the mesh_* rollups)",
                name,
                float(step_ms[i]),
            )
            self.series.counter_inc(
                "mesh_device_transfer_mb_total",
                "round-end transfer volume attributed to one dp device "
                "(budget-gated twin of device_transfer_bytes_total's "
                "site-keyed totals)",
                name,
                float(transfer_mb[i]),
            )
        worst_i = int(np.argmax(step_ms))
        event = device_rollup_event(
            rollup, self.device_names, round=round
        )
        summary = {
            **({"round": round} if round is not None else {}),
            "n_devices": n,
            "ratio": float(ratio),
            "worst_device": self.device_names[worst_i],
            "step_ms_p50": rollup["dims"]["step_ms"]["quantiles"]["p50"],
            "step_ms_max": rollup["dims"]["step_ms"]["quantiles"]["max"],
        }
        with self._lock:
            self.rounds += rounds
            self.blocks += 1
            self._step_ms = step_ms
            self._transfer_mb_total += transfer_mb
            self._hbm_mb = hbm_mb
            self._latest = rollup
            self._latest_event = event
            self._imbalance = float(ratio)
        return summary, event

    def health_block(self) -> dict[str, Any]:
        """The ``/healthz`` ``mesh`` stanza: bounded whatever the mesh
        size (quantiles + the worst device by name)."""
        with self._lock:
            out: dict[str, Any] = {
                "devices": self.n_devices,
                "rounds": self.rounds,
                "blocks": self.blocks,
                "imbalance_ratio": round(self._imbalance, 4),
            }
            if self._latest is not None:
                out["step_ms"] = {
                    q: round(v, 4)
                    for q, v in self._latest["dims"]["step_ms"][
                        "quantiles"
                    ].items()
                }
                out["worst_device"] = self.device_names[
                    int(np.argmax(self._step_ms))
                ]
            return out

    def overview(self) -> dict[str, Any]:
        """The ``/devices`` drill-down: one named row per device (the
        device axis is physically bounded, so names are safe HERE —
        this is a JSON payload, not a metric label key)."""
        with self._lock:
            return {
                "devices": [
                    {
                        "device": name,
                        "step_ms": round(float(self._step_ms[i]), 4),
                        "transfer_mb_total": round(
                            float(self._transfer_mb_total[i]), 4
                        ),
                        "hbm_mb": round(float(self._hbm_mb[i]), 4),
                    }
                    for i, name in enumerate(self.device_names)
                ],
                "rounds": self.rounds,
                "blocks": self.blocks,
                "imbalance_ratio": round(self._imbalance, 4),
                "budget_enabled": self.series.enabled,
                "rollup": self._latest_event,
            }


class ProfilerBusy(RuntimeError):
    """A capture is already armed or in flight (one at a time)."""


class ProfilerExhausted(RuntimeError):
    """The process's ``profile_max_captures`` hard cap is spent."""


class ProfilerGate:
    """On-demand ``jax.profiler`` capture with hard caps.

    ``request(rounds)`` arms the gate (``POST /profile`` and
    ``--profile-rounds`` both land here); the run loop calls
    ``maybe_start`` at a capture boundary and ``advance`` after each
    committed round, so a capture covers exactly one scan block or N
    per-round fleet rounds. Caps are HARD: one capture armed-or-active
    at a time (:class:`ProfilerBusy`), at most ``max_captures`` per
    process (:class:`ProfilerExhausted`), and artifacts over ``max_mb``
    are DELETED, not kept (a runaway trace must not fill the bundle
    dir). Every finished capture counts
    ``profile_captures_total{status}`` and dumps a ``profile_capture``
    flight-recorder bundle referencing the artifact."""

    def __init__(
        self,
        registry: MetricsRegistry | None = None,
        *,
        artifact_dir: str,
        max_captures: int = 4,
        max_mb: float = 256.0,
        recorder=None,
        logger=None,
    ) -> None:
        self.registry = registry if registry is not None else get_registry()
        self.artifact_dir = str(artifact_dir)
        self.max_captures = int(max_captures)
        self.max_mb = float(max_mb)
        self.recorder = recorder
        self.logger = logger
        self.captures: list[dict[str, Any]] = []
        self._pending = 0
        self._active: dict[str, Any] | None = None
        self._seq = 0
        self._lock = threading.Lock()

    # seams for the capture backend — tests monkeypatch these; the run
    # path uses the real programmatic profiler
    def _start_backend(self, log_dir: str) -> None:
        import jax.profiler

        jax.profiler.start_trace(log_dir)

    def _stop_backend(self) -> None:
        import jax.profiler

        jax.profiler.stop_trace()

    def request(self, rounds: int = 1) -> dict[str, Any]:
        """Arm the next capture for ``rounds`` rounds (a scan block
        rounds this up to the block). Raises on a busy gate or a spent
        cap — the HTTP front maps both to 409."""
        rounds = int(rounds)
        if rounds < 1:
            raise ValueError(f"rounds must be >= 1, got {rounds}")
        with self._lock:
            if self._pending or self._active is not None:
                raise ProfilerBusy(
                    "a profiler capture is already armed or in flight "
                    "(one at a time)"
                )
            if self._seq >= self.max_captures:
                raise ProfilerExhausted(
                    f"profile_max_captures={self.max_captures} captures "
                    "already taken this process"
                )
            self._pending = rounds
            return {
                "armed": True,
                "rounds": rounds,
                "capture": self._seq,
                "captures_left": self.max_captures - self._seq,
            }

    def maybe_start(
        self,
        *,
        label: str,
        rounds: int | None = None,
        round: int | None = None,
    ) -> bool:
        """Start the armed capture, if any. ``rounds`` overrides the
        requested span when the capture boundary is coarser (a scan
        block is atomic — the capture covers the whole block)."""
        with self._lock:
            if not self._pending or self._active is not None:
                return False
            span = int(rounds) if rounds is not None else self._pending
            self._pending = 0
            seq = self._seq
            self._seq += 1
        log_dir = os.path.join(self.artifact_dir, f"profile_{seq:03d}")
        os.makedirs(log_dir, exist_ok=True)
        try:
            self._start_backend(log_dir)
        except Exception as e:  # noqa: BLE001 — profiler is optional
            self._record(
                {
                    "capture": seq,
                    "label": label,
                    "dir": log_dir,
                    "rounds": span,
                    "start_round": round,
                    "bytes": 0,
                    "status": "error",
                    "error": f"{type(e).__name__}: {e}",
                }
            )
            return False
        with self._lock:
            self._active = {
                "capture": seq,
                "label": label,
                "dir": log_dir,
                "rounds": span,
                "rounds_left": span,
                "start_round": round,
                "t0": time.perf_counter(),
            }
        return True

    def advance(self, rounds: int = 1) -> None:
        """Count ``rounds`` committed rounds against the active capture;
        finishes it when the span is covered."""
        with self._lock:
            a = self._active
            if a is None:
                return
            a["rounds_left"] -= int(rounds)
            if a["rounds_left"] > 0:
                return
            self._active = None
        self._finish(a)

    def _finish(self, a: dict[str, Any]) -> None:
        wall_s = time.perf_counter() - a.pop("t0")
        a.pop("rounds_left", None)
        try:
            self._stop_backend()
            size = _dir_bytes(a["dir"])
            status = "ok"
            if size / 2**20 > self.max_mb:
                # hard size cap: an artifact the bundle dir cannot
                # afford is evidence lost, loudly — never disk filled
                shutil.rmtree(a["dir"], ignore_errors=True)
                status = "oversize"
        except Exception as e:  # noqa: BLE001
            size = 0
            status = "error"
            a["error"] = f"{type(e).__name__}: {e}"
        self._record(
            {**a, "bytes": size, "status": status, "wall_s": round(wall_s, 4)}
        )

    def _record(self, summary: dict[str, Any]) -> None:
        self.registry.counter(
            "profile_captures_total",
            "on-demand jax.profiler captures finished, by status "
            "(ok | oversize — artifact exceeded profile_max_mb and was "
            "deleted | error)",
            labelnames=("status",),
        ).labels(status=summary["status"]).inc()
        self.captures.append(summary)
        if self.logger is not None:
            self.logger.info("profile_capture", **summary)
        if self.recorder is not None:
            # the bundle is the reference: an operator finding the
            # flight-recorder dir sees which profile_NNN dir belongs to
            # which capture, and whether it survived the size cap
            self.recorder.dump("profile_capture", profile=dict(summary))

    def status(self) -> dict[str, Any]:
        with self._lock:
            return {
                "pending_rounds": self._pending,
                "active": (
                    {
                        k: v
                        for k, v in self._active.items()
                        if k != "t0"
                    }
                    if self._active is not None
                    else None
                ),
                "captures": [dict(c) for c in self.captures],
                "max_captures": self.max_captures,
                "max_mb": self.max_mb,
            }


def _dir_bytes(path: str) -> int:
    total = 0
    for root, _dirs, files in os.walk(path):
        for f in files:
            try:
                total += os.path.getsize(os.path.join(root, f))
            except OSError:
                pass
    return total
