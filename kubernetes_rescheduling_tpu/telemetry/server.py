"""The live ops plane: an in-process, stdlib-only HTTP endpoint plus the
aggregate (:class:`OpsPlane`) that wires it to the control loop.

Endpoints (``--serve PORT`` on ``reschedule``/``bench``):

- ``GET /metrics``  — live Prometheus text exposition straight from the
  process :class:`~.registry.MetricsRegistry` (format 0.0.4), scrapeable
  mid-run — this replaces the old "dump a .prom file and python -m
  http.server it" workaround.
- ``GET /healthz``  — JSON health: circuit-breaker state, last-round
  age, executed/skipped/degraded counts, and the SLO watchdog verdict.
  Returns **503** while unhealthy (breaker open, an active SLO
  violation, or a stale loop), 200 otherwise — a liveness probe or the
  chaos soak can watch the loop degrade and recover in real time.
- ``GET /events``   — the newest structured-log events as JSON
  (``?n=`` tail-limits for cheap polling; default = the full ring,
  which is itself bounded) — the StructuredLogger ring, without
  grepping JSONL files mid-incident.
- ``GET /tenants`` / ``GET /tenants/<name>`` — fleet drill-down from
  the bounded per-tenant summary ring
  (``telemetry.fleet_rollup.TenantSummaryRing``): the per-tenant detail
  the cardinality budget keeps OUT of ``/metrics`` label space (last
  round, breaker, drift, a capped cost window). 404s when no fleet run
  is attached or the tenant is unknown/evicted.
- ``POST /place`` — the serving plane's front (``serving/``): admit one
  pod/deployment spec (``{"service": name, "deadline_ms"?: float}``),
  score it against the device-resident state through the bounded
  batcher, answer with the placement + explain bundle + per-stage
  timings. 400 on bad JSON / unknown service, 200 on
  placed/no_candidate, 503 on shed/timeout (back off) or when no engine
  is attached. Slow scrapes cannot head-of-line-block it: the heavy
  read paths share a lock, /place does not take it.
- ``GET /slo`` — the SLO v2 budget/burn table (``telemetry.slo``): per
  SLO the objective, error-budget remaining, fast/slow burn rates, and
  time-to-exhaustion. 404 when the slo plane is disabled.
- ``GET /query?series=&n=`` — bounded raw readout of one history-plane
  ring (``telemetry.timeseries.SeriesStore``); a bare /query lists the
  retained series names. 404 when disabled or the series is unknown.
- ``GET /devices`` — the mesh/device plane's per-device overview
  (``telemetry.mesh.MeshPlane``): attributed step ms, cumulative
  transfer MB, sampled HBM, and the latest device rollup — device
  *names* live here and in events, never in metric label space. 404
  until a dp fleet run binds a mesh plane.
- ``POST /profile`` — arm one on-demand ``jax.profiler`` capture
  (``{"rounds"?: int}``, default 1) around the next N fleet rounds or
  the next scan block; the artifact lands in the flight-recorder
  bundle dir. 400 on a bad body, 409 while a capture is pending/active
  or the per-run budget is spent, 503 when no profiler is attached.

The server runs daemon threads and binds 127.0.0.1 by default; port 0
picks an ephemeral port (tests). Handlers never write to stdout/stderr —
request accounting goes through ``ops_http_requests_total{endpoint}``.

:class:`OpsPlane` bundles the registry, event logger, SLO watchdog,
flight recorder, health state, and server into the single object
``run_controller(ops=...)`` consumes; ``OpsPlane.from_config`` builds it
from the ``RescheduleConfig.obs`` block. SIGUSR1 (when the plane starts
on the main thread) dumps a flight-recorder bundle on demand.
"""

from __future__ import annotations

import json
import signal
import threading
import time
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any
from urllib.parse import parse_qs, urlsplit

from kubernetes_rescheduling_tpu.telemetry.flight_recorder import (
    FlightRecorder,
    state_digest,
)
from kubernetes_rescheduling_tpu.telemetry.mesh import (
    ProfilerBusy,
    ProfilerExhausted,
    ProfilerGate,
)
from kubernetes_rescheduling_tpu.telemetry.registry import (
    MetricsRegistry,
    get_registry,
)
from kubernetes_rescheduling_tpu.telemetry.slo import RULE_FAST_BURN
from kubernetes_rescheduling_tpu.telemetry.spans import get_tracer
from kubernetes_rescheduling_tpu.telemetry.watchdog import SLORules, Watchdog


class HealthState:
    """Live-readable loop health; the controller updates counts, the
    breaker/watchdog are read at request time so /healthz can go
    unhealthy (and recover) BETWEEN rounds, not only after one."""

    def __init__(self, *, max_round_age_s: float = 0.0) -> None:
        self.max_round_age_s = max_round_age_s
        self.breaker = None
        self.watchdog: Watchdog | None = None
        self.algorithm: str | None = None
        # ages/uptime compute from the MONOTONIC clock — an NTP step must
        # neither force a spurious 503 nor mask genuine staleness; the
        # wall-clock twins exist for display only
        self.started_ts = time.time()
        self._started_mono = time.monotonic()
        self.last_round_ts: float | None = None
        self._last_round_mono: float | None = None
        self.rounds = 0
        self.skipped_rounds = 0
        self.degraded_rounds = 0
        # latest perf-ledger verdict summary (OpsPlane.observe_perf) —
        # unhealthiness itself flows through the watchdog's
        # perf_regression rule; this is the human-readable "what & why"
        self.perf: dict | None = None
        # fleet mode: per-tenant health rows (bench.fleet updates this
        # each round). A single tenant's open breaker is DEGRADED fleet
        # service, not a dead plane — it shows here without flipping the
        # endpoint to 503 (per-tenant isolation extends to the probe).
        self.fleet: dict[str, dict] | None = None
        # scan-plane summary (OpsPlane.observe_scan_block/observe_scan_
        # drain): block size, blocks dispatched, drain breakdown, latest
        # trip — rendered on /healthz when a scanned schedule runs
        self.scan: dict[str, Any] | None = None
        # serving-plane summary (OpsPlane.observe_serving): request rate,
        # rolling p50/p95/p99, batch-size distribution, shed counts —
        # rendered on /healthz when a serving engine is attached; the
        # serving_p99 watchdog rule flips the endpoint itself
        self.serving: dict[str, Any] | None = None
        # mesh & device-plane summary (OpsPlane.observe_device_rollup):
        # device count, rounds observed, the attributed step-time
        # quantiles, and the worst/median imbalance ratio — rendered on
        # /healthz when the device plane runs; the mesh_imbalance
        # watchdog rule flips the endpoint itself
        self.mesh: dict[str, Any] | None = None
        # a dispatched scan block is K rounds of healthy silence:
        # mark_round only fires as the replay flushes, so while a block
        # is in flight the staleness budget scales by its expected
        # rounds instead of spuriously 503ing a healthy loop
        self._inflight_rounds = 0

    def mark_round(self) -> None:
        """Stamp 'a round just finished' on both clocks."""
        self.last_round_ts = time.time()
        self._last_round_mono = time.monotonic()
        self._inflight_rounds = 0

    def mark_block_inflight(self, rounds: int) -> None:
        """A scan block of ``rounds`` rounds just dispatched: scale the
        staleness budget until its replay flushes (any mark_round or
        :meth:`mark_block_done` clears the scaling)."""
        self._inflight_rounds = max(int(rounds), 1)

    def mark_block_done(self) -> None:
        """The block's replay finished (however many rounds committed):
        back to the per-round staleness budget."""
        self._inflight_rounds = 0

    def snapshot(self) -> tuple[dict[str, Any], bool]:
        breaker_state = getattr(self.breaker, "state", None)
        age = (
            time.monotonic() - self._last_round_mono
            if self._last_round_mono is not None
            else None
        )
        age_budget = self.max_round_age_s * max(self._inflight_rounds, 1)
        stale = (
            age_budget > 0
            and age is not None
            and age > age_budget
        )
        slo = self.watchdog.status() if self.watchdog is not None else None
        healthy = (
            breaker_state != "open"
            and not stale
            and (slo is None or slo["healthy"])
        )
        return (
            {
                "status": "ok" if healthy else "unhealthy",
                "algorithm": self.algorithm,
                "breaker": breaker_state,
                "rounds": self.rounds,
                "skipped_rounds": self.skipped_rounds,
                "degraded_rounds": self.degraded_rounds,
                "last_round_age_s": age,
                "last_round_ts": self.last_round_ts,  # wall anchor, display
                "stale": stale,
                "uptime_s": time.monotonic() - self._started_mono,
                "slo": slo,
                "perf": self.perf,
                **({"scan": self.scan} if self.scan is not None else {}),
                **(
                    {"serving": self.serving}
                    if self.serving is not None
                    else {}
                ),
                **({"fleet": self.fleet} if self.fleet is not None else {}),
                **({"mesh": self.mesh} if self.mesh is not None else {}),
            },
            healthy,
        )


class OpsServer:
    """Threaded stdlib HTTP server over (registry, health, events)."""

    def __init__(
        self,
        *,
        port: int = 0,
        host: str = "127.0.0.1",
        registry: MetricsRegistry | None = None,
        health: HealthState | None = None,
        events_source=None,  # zero-arg callable -> list[dict]
        tenants_source=None,  # zero-arg callable -> TenantSummaryRing | None
        serving_source=None,  # zero-arg callable -> ServingEngine | None
        slo_source=None,  # zero-arg callable -> budget/burn table | None
        query_source=None,  # callable(series, n) -> (payload, code)
        devices_source=None,  # zero-arg callable -> device overview | None
        profile_sink=None,  # callable(rounds) -> (payload, code)
    ) -> None:
        self._port = port
        self.host = host
        self.registry = registry
        self.health = health
        self.events_source = events_source
        self.tenants_source = tenants_source
        self.serving_source = serving_source
        self.slo_source = slo_source
        self.query_source = query_source
        self.devices_source = devices_source
        self.profile_sink = profile_sink
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None
        # serializes the SLOW read paths (full-registry exposition, event/
        # tenant ring walks) against each other so a scrape storm degrades
        # scrapes, not serving: POST /place and /healthz deliberately do
        # NOT take it — each ThreadingHTTPServer request has its own
        # thread, so a multi-ms /metrics render can never head-of-line-
        # block an in-flight placement request
        self._read_lock = threading.Lock()

    @property
    def port(self) -> int:
        return (
            self._httpd.server_address[1]
            if self._httpd is not None
            else self._port
        )

    def _reg(self) -> MetricsRegistry:
        return self.registry if self.registry is not None else get_registry()

    def start(self) -> int:
        if self._httpd is not None:
            return self.port
        handler = _make_handler(self)
        self._httpd = ThreadingHTTPServer((self.host, self._port), handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="krt-ops-server",
            daemon=True,
        )
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._httpd = None
        self._thread = None


def _make_handler(ops: OpsServer):
    class Handler(BaseHTTPRequestHandler):
        server_version = "krt-ops/1"
        protocol_version = "HTTP/1.1"

        def log_message(self, format, *args):  # noqa: A002 — stdlib signature
            pass  # request accounting is a metric, not a stderr line

        def _respond(self, code: int, body: bytes, content_type: str) -> None:
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _count(self, endpoint: str) -> None:
            # request accounting must stay cardinality-bounded: the
            # drill-down's tenant name is a PATH, never a label value —
            # and arbitrary 404 paths (favicon probes, port scanners)
            # must not mint one memoized series each. /place joins the
            # allowlist (GET and POST count into the same series: the
            # endpoint IS the cardinality unit, not the method).
            if endpoint.startswith("/tenants/"):
                counted = "/tenants/<name>"
            elif endpoint in ("/", "/metrics", "/healthz", "/events",
                              "/tenants", "/place", "/slo", "/query",
                              "/devices", "/profile"):
                counted = endpoint
            else:
                counted = "<other>"
            ops._reg().counter(
                "ops_http_requests_total",
                "requests served by the live ops endpoint",
                labelnames=("endpoint",),
            ).labels(endpoint=counted).inc()

        def do_GET(self) -> None:  # noqa: N802 — stdlib signature
            url = urlsplit(self.path)
            endpoint = url.path.rstrip("/") or "/"
            self._count(endpoint)
            if endpoint == "/metrics":
                with ops._read_lock:
                    body = ops._reg().expose().encode()
                self._respond(
                    200, body, "text/plain; version=0.0.4; charset=utf-8"
                )
            elif endpoint == "/healthz":
                if ops.health is None:
                    payload, healthy = {"status": "ok", "detail": "no loop"}, True
                else:
                    payload, healthy = ops.health.snapshot()
                body = json.dumps(payload, default=float).encode()
                self._respond(
                    200 if healthy else 503, body, "application/json"
                )
            elif endpoint == "/events":
                with ops._read_lock:
                    events = (
                        list(ops.events_source() or [])
                        if ops.events_source is not None
                        else []
                    )
                # ?n= tail-limits the response (cheap polling of the last
                # few events); default is the FULL ring — which is itself
                # bounded (StructuredLogger's in-memory view is a ring
                # buffer), so an unqualified GET cannot grow unboundedly
                raw = parse_qs(url.query).get("n")
                try:
                    n = min(max(int(raw[0]), 0), len(events)) if raw else len(events)
                except ValueError:
                    n = len(events)
                body = json.dumps(
                    events[len(events) - n:], default=float
                ).encode()
                self._respond(200, body, "application/json")
            elif endpoint == "/tenants" or endpoint.startswith("/tenants/"):
                with ops._read_lock:
                    ring = (
                        ops.tenants_source()
                        if ops.tenants_source is not None
                        else None
                    )
                    if ring is None:
                        payload, code = {"error": "no fleet run attached"}, 404
                    elif endpoint == "/tenants":
                        payload, code = ring.overview(), 200
                    else:
                        name = endpoint[len("/tenants/"):]
                        detail = ring.detail(name)
                        if detail is None:
                            payload, code = {
                                "error": f"unknown tenant {name!r} "
                                         "(never seen, or evicted from "
                                         "the bounded summary ring)"
                            }, 404
                        else:
                            payload, code = detail, 200
                self._respond(
                    code,
                    json.dumps(payload, default=float).encode(),
                    "application/json",
                )
            elif endpoint == "/slo":
                with ops._read_lock:
                    table = (
                        ops.slo_source()
                        if ops.slo_source is not None
                        else None
                    )
                if table is None:
                    payload, code = {
                        "error": "slo plane disabled (start with --slo / "
                                 "an enabled [slo] block)"
                    }, 404
                else:
                    payload, code = {"slos": table}, 200
                self._respond(
                    code,
                    json.dumps(payload, default=float).encode(),
                    "application/json",
                )
            elif endpoint == "/query":
                if ops.query_source is None:
                    payload, code = {
                        "error": "slo plane disabled (start with --slo / "
                                 "an enabled [slo] block)"
                    }, 404
                else:
                    qs = parse_qs(url.query)
                    series = (qs.get("series") or [None])[0]
                    raw = qs.get("n")
                    try:
                        n = max(int(raw[0]), 0) if raw else None
                    except ValueError:
                        n = None
                    with ops._read_lock:
                        payload, code = ops.query_source(series, n)
                self._respond(
                    code,
                    json.dumps(payload, default=float).encode(),
                    "application/json",
                )
            elif endpoint == "/devices":
                with ops._read_lock:
                    overview = (
                        ops.devices_source()
                        if ops.devices_source is not None
                        else None
                    )
                if overview is None:
                    payload, code = {
                        "error": "no mesh plane attached (device "
                                 "telemetry runs with the dp fleet "
                                 "planes)"
                    }, 404
                else:
                    payload, code = overview, 200
                self._respond(
                    code,
                    json.dumps(payload, default=float).encode(),
                    "application/json",
                )
            elif endpoint == "/place":
                body = json.dumps(
                    {"error": "method not allowed: POST a placement "
                              "request to /place"}
                ).encode()
                self.send_response(405)
                self.send_header("Allow", "POST")
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif endpoint == "/profile":
                body = json.dumps(
                    {"error": "method not allowed: POST a capture "
                              "request to /profile"}
                ).encode()
                self.send_response(405)
                self.send_header("Allow", "POST")
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self._respond(
                    404,
                    json.dumps(
                        {"error": "not found",
                         "endpoints": ["/metrics", "/healthz", "/events",
                                       "/tenants", "/tenants/<name>",
                                       "/place", "/slo", "/query",
                                       "/devices", "/profile"]}
                    ).encode(),
                    "application/json",
                )

        def do_POST(self) -> None:  # noqa: N802 — stdlib signature
            url = urlsplit(self.path)
            endpoint = url.path.rstrip("/") or "/"
            self._count(endpoint)
            if endpoint == "/profile":
                self._post_profile()
                return
            if endpoint != "/place":
                self._respond(
                    404,
                    json.dumps(
                        {"error": "not found",
                         "endpoints": ["/place", "/profile"]}
                    ).encode(),
                    "application/json",
                )
                return
            engine = (
                ops.serving_source()
                if ops.serving_source is not None
                else None
            )
            if engine is None:
                self._respond(
                    503,
                    json.dumps(
                        {"error": "no serving engine attached "
                                  "(start with serving enabled)"}
                    ).encode(),
                    "application/json",
                )
                return
            try:
                length = int(self.headers.get("Content-Length") or 0)
                raw = self.rfile.read(length) if length > 0 else b""
                payload = json.loads(raw.decode() or "{}")
                if not isinstance(payload, dict):
                    raise ValueError("request body must be a JSON object")
                service = payload.get("service")
                if not isinstance(service, str) or not service:
                    raise ValueError(
                        "missing required string field 'service'"
                    )
                deadline_ms = payload.get("deadline_ms")
                if deadline_ms is not None:
                    if isinstance(deadline_ms, bool) or not isinstance(
                        deadline_ms, (int, float)
                    ):
                        raise ValueError(
                            "'deadline_ms' must be a JSON number"
                        )
                    deadline_ms = float(deadline_ms)
            # TypeError joins the tuple as a backstop: the documented
            # contract is 400 on ANY malformed body, never a handler crash
            except (TypeError, ValueError, UnicodeDecodeError) as exc:
                self._respond(
                    400,
                    json.dumps({"error": str(exc)}).encode(),
                    "application/json",
                )
                return
            try:
                result = engine.place(service, deadline_ms=deadline_ms)
            except (ValueError, KeyError) as exc:
                # unknown service: a client error, nothing was submitted
                self._respond(
                    400,
                    json.dumps({"error": str(exc)}).encode(),
                    "application/json",
                )
                return
            # placed and no_candidate are both successful ANSWERS (the
            # latter a true "every valid node is hazardous" verdict);
            # shed/timeout mean the plane could not answer in time — 503
            # so open-loop clients and load balancers back off
            code = 200 if result.outcome in ("placed", "no_candidate") else 503
            self._respond(
                code,
                json.dumps(result.as_dict(), default=float).encode(),
                "application/json",
            )

        def _post_profile(self) -> None:
            if ops.profile_sink is None:
                self._respond(
                    503,
                    json.dumps(
                        {"error": "no profiler attached (profiler "
                                  "capture runs with the ops plane)"}
                    ).encode(),
                    "application/json",
                )
                return
            try:
                length = int(self.headers.get("Content-Length") or 0)
                raw = self.rfile.read(length) if length > 0 else b""
                payload = json.loads(raw.decode() or "{}")
                if not isinstance(payload, dict):
                    raise ValueError("request body must be a JSON object")
                rounds = payload.get("rounds", 1)
                if isinstance(rounds, bool) or not isinstance(rounds, int):
                    raise ValueError("'rounds' must be a JSON integer")
            # TypeError joins the tuple as a backstop: the documented
            # contract is 400 on ANY malformed body, never a handler crash
            except (TypeError, ValueError, UnicodeDecodeError) as exc:
                self._respond(
                    400,
                    json.dumps({"error": str(exc)}).encode(),
                    "application/json",
                )
                return
            result, code = ops.profile_sink(rounds)
            self._respond(
                code,
                json.dumps(result, default=float).encode(),
                "application/json",
            )

    return Handler


@dataclass
class OpsPlane:
    """Everything the live ops plane needs, in one handle the controller
    consumes: per-round observation fans out to the watchdog, the flight
    recorder, and the health state; breaker-open and crashes trigger
    bundle dumps.

    Feeds arrive from more than one thread once a serving engine is
    bound (the controller's round loop plus request-grain serving
    threads), and :class:`~.watchdog.Watchdog` is not itself
    thread-safe, so ONE plane-level lock serializes every
    ``watchdog.observe_*``/``rebase`` call — round-vs-serving as well as
    serving-vs-serving."""

    registry: MetricsRegistry | None = None
    logger: Any = None
    watchdog: Watchdog | None = None
    recorder: FlightRecorder | None = None
    health: HealthState = field(default_factory=HealthState)
    server: OpsServer | None = None
    # fleet mode: the bounded per-tenant summary store behind /tenants
    # (telemetry.fleet_rollup.TenantSummaryRing) and the latest decoded
    # rollup — breaker-open bundles ship both, scoped to the offender
    tenant_ring: Any = None
    latest_fleet_rollup: Any = field(default=None, repr=False)
    # serving mode: the engine behind POST /place (bind_serving attaches
    # it); its bounded recent-request ring rides breaker-open and
    # serving_p99 flight-recorder bundles
    serving_engine: Any = field(default=None, repr=False)
    # mesh mode: the device plane behind GET /devices (bind_mesh
    # attaches it) and the profiler gate behind POST /profile — the
    # gate is built by from_config whenever a flight-recorder bundle
    # dir exists, so captures always land next to the bundles that
    # reference them
    mesh_plane: Any = field(default=None, repr=False)
    profiler: Any = field(default=None, repr=False)
    # SLO v2: the bounded history plane (telemetry.timeseries.SeriesStore)
    # and the error-budget engine (telemetry.slo.SloEngine) — both None
    # unless [slo] is enabled; every observe_* tick samples the registry
    # host-side into the store and re-evaluates burn under the lock
    series_store: Any = field(default=None, repr=False)
    slo_engine: Any = field(default=None, repr=False)
    _slo_ticks: int = field(default=0, repr=False)
    span_tail: int = 12
    _prev_sigusr1: Any = field(default=None, repr=False)
    _sig_installed: bool = field(default=False, repr=False)
    # serializes every watchdog feed across the threads that issue them
    # (controller round loop, serving request threads, the bench harness)
    _watchdog_lock: threading.Lock = field(
        default_factory=threading.Lock, repr=False
    )

    @classmethod
    def from_config(
        cls,
        obs,
        *,
        slo=None,
        registry: MetricsRegistry | None = None,
        logger=None,
        bundle_dir: str | None = None,
    ) -> "OpsPlane":
        """Build from a ``config.ObsConfig`` block (the CLI/harness
        path). ``slo`` optionally passes a ``config.SloConfig`` — an
        enabled one attaches the history plane + error-budget engine."""
        health = HealthState(max_round_age_s=obs.max_round_age_s)
        watchdog = Watchdog(
            SLORules(
                window=obs.slo_window,
                min_samples=obs.slo_min_samples,
                latency_p95_s=obs.slo_latency_p95_s,
                cost_regression_frac=obs.slo_cost_regression_frac,
                max_retraces=obs.slo_max_retraces,
                attribution_drift_frac=getattr(
                    obs, "attribution_drift_frac", 0.0
                ),
                forecast_min_skill=getattr(
                    obs, "slo_forecast_min_skill", 0.0
                ),
                pipeline_min_overlap=getattr(
                    obs, "slo_pipeline_min_overlap", 0.0
                ),
                reconcile_max_drift_pods=getattr(
                    obs, "slo_reconcile_drift_pods", 0
                ),
                shadow_min_win_rate=getattr(
                    obs, "slo_shadow_min_win_rate", 0.0
                ),
                fleet_tail_frac=getattr(obs, "slo_fleet_tail_frac", 0.0),
                scan_tripwire=getattr(obs, "slo_scan_tripwire", True),
                serving_p99_ms=getattr(obs, "slo_serving_p99_ms", 0.0),
                mesh_imbalance_ratio=getattr(
                    obs, "slo_mesh_imbalance_ratio", 0.0
                ),
            ),
            registry=registry,
            logger=logger,
        )
        recorder = FlightRecorder(
            capacity=obs.flight_recorder_rounds,
            bundle_dir=bundle_dir if bundle_dir is not None else obs.bundle_dir,
            registry=registry,
            logger=logger,
        )
        from kubernetes_rescheduling_tpu.telemetry.fleet_rollup import (
            TenantSummaryRing,
        )

        series_store = slo_engine = None
        if slo is not None and getattr(slo, "enabled", False):
            from kubernetes_rescheduling_tpu.telemetry.slo import (
                SloEngine,
                default_specs,
            )
            from kubernetes_rescheduling_tpu.telemetry.timeseries import (
                SeriesStore,
            )

            series_store = SeriesStore(
                capacity=slo.series_capacity,
                max_series=slo.max_series,
                registry=registry,
            )
            slo_engine = SloEngine(
                default_specs(
                    objective=slo.objective,
                    latency_threshold_ms=slo.latency_threshold_ms,
                ),
                series_store,
                registry=registry,
                budget_window=slo.budget_window,
                fast_window=slo.fast_window,
                fast_burn=slo.fast_burn,
                slow_window=slo.slow_window,
                slow_burn=slo.slow_burn,
            )
        plane = cls(
            registry=registry,
            logger=logger,
            watchdog=watchdog,
            recorder=recorder,
            health=health,
            tenant_ring=TenantSummaryRing(),
            series_store=series_store,
            slo_engine=slo_engine,
        )
        # profiler captures land INSIDE the flight-recorder bundle dir:
        # the capture summary rides a bundle dump, and the artifact it
        # names sits next to the bundle that references it
        plane.profiler = ProfilerGate(
            registry,
            artifact_dir=(
                bundle_dir if bundle_dir is not None else obs.bundle_dir
            ),
            max_captures=getattr(obs, "profile_max_captures", 4),
            max_mb=getattr(obs, "profile_max_mb", 256.0),
            recorder=recorder,
            logger=logger,
        )
        profile_rounds = int(getattr(obs, "profile_rounds", 0) or 0)
        if profile_rounds > 0:
            # --profile-rounds N arms one capture before the loop starts
            plane.profiler.request(rounds=profile_rounds)
        if obs.serve_port is not None:
            plane.server = OpsServer(
                port=obs.serve_port,
                registry=registry,
                health=health,
                events_source=plane._events,
                tenants_source=plane._tenants,
                serving_source=plane._serving,
                slo_source=plane._slo_table,
                query_source=plane._series_query,
                devices_source=plane._devices,
                profile_sink=plane._profile,
            )
        return plane

    def _events(self) -> list[dict]:
        return self.logger.records if self.logger is not None else []

    def _serving(self):
        """The POST /place source: the bound serving engine, if any."""
        return self.serving_engine

    def _tenants(self):
        """The /tenants source: the ring once a fleet run has fed it
        (a solo run's empty ring reads as 'no fleet attached')."""
        ring = self.tenant_ring
        return ring if ring is not None and len(ring) else None

    def _devices(self):
        """The /devices source: the bound mesh plane's per-device
        overview (None — mapped to 404 — until a dp fleet run binds
        one)."""
        plane = self.mesh_plane
        return plane.overview() if plane is not None else None

    def _profile(self, rounds):
        """The POST /profile sink: (payload, http code). Arms one
        capture on the gate — 503 with no gate, 400 on a bad round
        count, 409 (with the gate's status) when a capture is already
        pending/active or the per-run budget is spent."""
        gate = self.profiler
        if gate is None:
            return {
                "error": "no profiler attached (profiler capture runs "
                         "with the ops plane)"
            }, 503
        try:
            return gate.request(rounds=rounds), 200
        except ValueError as exc:
            return {"error": str(exc)}, 400
        except (ProfilerBusy, ProfilerExhausted) as exc:
            return {"error": str(exc), "status": gate.status()}, 409

    def _slo_table(self):
        """The /slo source: the engine's last budget/burn evaluation
        (None when the slo plane is off, which the handler maps to 404)."""
        if self.slo_engine is None:
            return None
        with self._watchdog_lock:
            return self.slo_engine.table()

    def _series_query(self, series, n):
        """The /query source: (payload, http code). A bare /query lists
        the retained series names (bounded by max_series); naming one
        returns its last ``n`` ring points. Reads under the watchdog
        lock — the same lock every sampling tick holds — so an HTTP
        walk never races a concurrent eviction."""
        store = self.series_store
        if store is None:
            return {
                "error": "slo plane disabled (start with --slo / an "
                         "enabled [slo] block)"
            }, 404
        with self._watchdog_lock:
            if not series:
                return {"series": store.names()}, 200
            try:
                pts = store.query(series, n)
            except KeyError:
                return {
                    "error": f"unknown series {series!r} (never sampled, "
                             "or evicted by the series budget)"
                }, 404
            return {
                "series": series,
                "points": [[t, v] for t, v in pts],
            }, 200

    def _slo_tick_locked(self) -> list[dict]:
        """One history-plane tick — caller MUST hold ``_watchdog_lock``.
        Samples the registry snapshot (host-side values only: zero
        device transfers by construction) into the store, re-evaluates
        every SLO's budget/burn, and feeds the firing burn rules to the
        watchdog. Returns the newly raised violations so the caller can
        dump page bundles OUTSIDE the lock."""
        if self.slo_engine is None or self.series_store is None:
            return []
        self._slo_ticks += 1
        tick = self._slo_ticks
        reg = (
            self.registry
            if self.registry is not None
            else get_registry()
        )
        self.series_store.sample(reg.snapshot(), tick)
        entries = self.slo_engine.evaluate(tick)
        if self.watchdog is None:
            return []
        return self.watchdog.observe_slo_burn(entries)

    def _dump_burn_pages(self, newly: list[dict]) -> None:
        """Page-level burn entry dumps a flight-recorder bundle — file
        I/O, so called outside the lock with the exactly-once ``newly``
        list (the serving_p99 dump's no-double-dump discipline)."""
        if self.recorder is None:
            return
        for violation in newly:
            if violation.get("rule") == RULE_FAST_BURN:
                self.recorder.dump(
                    "slo_burn_page",
                    slo=dict(violation),
                    table=(
                        self._slo_table() or []
                    ),
                )

    # ---- lifecycle ----

    def start(self) -> "OpsPlane":
        self.health.watchdog = self.watchdog
        if self.server is not None:
            if self.server.health is None:
                self.server.health = self.health
            if self.server.events_source is None:
                self.server.events_source = self._events
            if self.server.tenants_source is None:
                self.server.tenants_source = self._tenants
            if self.server.serving_source is None:
                self.server.serving_source = self._serving
            if self.server.slo_source is None:
                self.server.slo_source = self._slo_table
            if self.server.query_source is None:
                self.server.query_source = self._series_query
            if self.server.devices_source is None:
                self.server.devices_source = self._devices
            if self.server.profile_sink is None:
                self.server.profile_sink = self._profile
            self.server.start()
        if (
            self.recorder is not None
            and threading.current_thread() is threading.main_thread()
            and not self._sig_installed
        ):
            try:
                self._prev_sigusr1 = signal.signal(
                    signal.SIGUSR1,
                    lambda signum, frame: self.recorder.dump("sigusr1"),
                )
                self._sig_installed = True
            except (ValueError, OSError, AttributeError):
                pass  # non-main thread / platform without SIGUSR1
        return self

    def close(self) -> None:
        if self.server is not None:
            self.server.stop()
        if self._sig_installed:
            try:
                signal.signal(signal.SIGUSR1, self._prev_sigusr1 or signal.SIG_DFL)
            except (ValueError, OSError):
                pass
            self._sig_installed = False

    def __enter__(self) -> "OpsPlane":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # ---- controller hooks ----

    def bind(self, *, breaker=None, logger=None, algorithm=None) -> None:
        """Attach the current run's breaker/logger/algorithm (the plane
        can outlive a single run — the bench harness reuses one across
        matrix cells)."""
        if breaker is not None:
            self.health.breaker = breaker
        if logger is not None:
            self.logger = logger
            if self.watchdog is not None:
                self.watchdog.logger = logger
            if self.recorder is not None:
                self.recorder.logger = logger
        if algorithm is not None:
            self.health.algorithm = algorithm
        self.health.watchdog = self.watchdog
        if self.watchdog is not None:
            # a new run binding = a fresh observation window: another
            # cell's cost scale or a new shape's first compile must not
            # read as an SLO violation
            with self._watchdog_lock:
                self.watchdog.rebase()

    def observe_round(self, record, state=None, events=(), tenant=None) -> None:
        self.health.rounds += 1
        self.health.mark_round()
        if record.degraded:
            self.health.degraded_rounds += 1
        newly_burn: list[dict] = []
        with self._watchdog_lock:
            if self.watchdog is not None:
                self.watchdog.observe_round(record, tenant=tenant)
            newly_burn = self._slo_tick_locked()
        self._dump_burn_pages(newly_burn)
        if self.recorder is not None:
            spans = [
                {
                    "name": ev.name,
                    "dur_us": ev.dur_us,
                    "depth": ev.depth,
                    "args": ev.args,
                }
                for ev in get_tracer().tail(self.span_tail)
            ]
            self.recorder.record_round(
                round=record.round,
                digest=state_digest(state) if state is not None else None,
                record=record.as_dict(),
                events=list(events),
                spans=spans,
            )

    def observe_scan_block(
        self, *, rounds: int, trip: dict | None = None
    ) -> None:
        """One scan block's replay finished: update the /healthz scan
        summary, clear the in-flight staleness scaling, and feed the
        watchdog's ``scan_tripwire`` rule (a clean block — ``trip=None``
        — clears it). A tripped block additionally dumps a
        flight-recorder bundle scoped to the partial block: the trip
        dict carries the trip round and decoded rule bitmask, and the
        ring holds exactly the rounds the replay committed."""
        scan = self.health.scan
        if scan is None:
            scan = self.health.scan = {
                "block": int(rounds),
                "blocks": 0,
                "tripped_blocks": 0,
                "last_trip": None,
                "drains": {},
            }
        scan["block"] = int(rounds)
        scan["blocks"] += 1
        self.health.mark_block_done()
        if trip is not None:
            scan["tripped_blocks"] += 1
            scan["last_trip"] = dict(trip)
            if self.recorder is not None:
                self.recorder.dump("scan_tripwire", trip=dict(trip))
        if self.watchdog is not None:
            with self._watchdog_lock:
                self.watchdog.observe_scan_block(trip)

    def observe_scan_drain(self, reason: str) -> None:
        """One round drained from the scanned schedule to the per-round
        path: the /healthz scan summary's reason breakdown (the metric
        twin is ``scan_drains_total{reason}``)."""
        scan = self.health.scan
        if scan is None:
            scan = self.health.scan = {
                "block": None,
                "blocks": 0,
                "tripped_blocks": 0,
                "last_trip": None,
                "drains": {},
            }
        drains = scan["drains"]
        drains[reason] = drains.get(reason, 0) + 1

    def bind_serving(self, engine) -> None:
        """Attach a serving engine: it becomes the POST /place source,
        its summaries flow to /healthz and the ``serving_p99`` watchdog
        rule via :meth:`observe_serving`, and its recent-request ring
        rides breaker-open bundles."""
        self.serving_engine = engine
        engine.ops = self

    def bind_mesh(self, mesh_plane) -> None:
        """Attach the run's device plane (``telemetry.mesh.MeshPlane``):
        it becomes the GET /devices source, and its per-block summaries
        flow to the /healthz ``mesh`` stanza and the ``mesh_imbalance``
        watchdog rule via :meth:`observe_device_rollup`."""
        self.mesh_plane = mesh_plane

    def observe_device_rollup(
        self, summary: dict | None, event: dict | None = None
    ) -> None:
        """Feed one block's device-axis summary (the dp fleet loop calls
        this after every decoded pull): updates the /healthz ``mesh``
        stanza and judges the ``mesh_imbalance`` rule. The named-device
        ``event`` payload stays out of the watchdog (names are event/
        endpoint data, never label or rule state)."""
        newly_burn: list[dict] = []
        with self._watchdog_lock:
            plane = self.mesh_plane
            self.health.mesh = (
                plane.health_block()
                if plane is not None
                else (dict(summary) if summary is not None else None)
            )
            if self.watchdog is not None:
                self.watchdog.observe_mesh(summary)
                newly_burn = self._slo_tick_locked()
        self._dump_burn_pages(newly_burn)

    def bind_tenant_series(self, tseries) -> None:
        """Fleet mode: attach the run's ``TenantSeries`` cardinality
        gate so per-tenant SLO budget gauges publish through it —
        bit-identical at or under the label budget, suppressed and
        counted over it. No-op when the slo plane is off."""
        if self.slo_engine is not None:
            with self._watchdog_lock:
                self.slo_engine.tenant_series = tseries

    def observe_serving(
        self, summary: dict | None, requests: list | None = None
    ) -> None:
        """Feed the serving plane's rolling summary (the engine calls
        this after every dispatched batch and admission-time shed):
        updates the /healthz ``serving`` stanza, judges the
        ``serving_p99`` rule, and — the moment the rule ENTERS violation
        — dumps a flight-recorder bundle carrying the summary plus the
        in-flight request ring (the evidence an operator needs while the
        tail spike is still in memory)."""
        with self._watchdog_lock:
            self.health.serving = (
                dict(summary) if summary is not None else None
            )
            if self.watchdog is None:
                return
            newly = self.watchdog.observe_serving(summary)
            # the history-plane tick rides the SAME lock hold: burn is
            # judged on the state that includes this batch's counters,
            # so a fast burn can page on the very feed that crossed it
            newly += self._slo_tick_locked()
        # the bundle dump (file I/O) happens outside the lock: `newly`
        # reports rule ENTRY exactly once, so concurrent feeders cannot
        # double-dump
        for violation in newly:
            if (
                violation.get("rule") == "serving_p99"
                and self.recorder is not None
            ):
                self.recorder.dump(
                    "serving_p99",
                    serving=dict(summary or {}),
                    requests=list(requests or []),
                )
        self._dump_burn_pages(newly)

    def observe_perf(self, verdicts: dict) -> None:
        """Feed a perf-ledger verdict set (``perf_ledger.detect``): arms/
        clears the watchdog's ``perf_regression`` rule and records the
        latest verdict summary on ``/healthz`` (the bench harness calls
        this after each cell's ledger append)."""
        statuses = sorted(
            (k, v.get("status")) for k, v in (verdicts or {}).items()
        )
        regressed = [k for k, s in statuses if s == "regressed"]
        self.health.perf = {
            "verdict": "regressed" if regressed else "ok",
            "regressed": regressed,
            "series": dict(statuses),
        }
        if self.watchdog is not None:
            with self._watchdog_lock:
                self.watchdog.observe_perf(verdicts)

    def observe_fleet_rollup(self, rollup: dict, event: dict | None = None) -> None:
        """Feed one fleet round's decoded tenant rollup
        (``telemetry.fleet_rollup.decode_rollup``): arms the watchdog's
        ``fleet_tail_cost`` rule and keeps the latest named event
        payload for breaker-open bundles and the over-budget
        ``/healthz`` fleet summary."""
        self.latest_fleet_rollup = event if event is not None else rollup
        newly_burn: list[dict] = []
        with self._watchdog_lock:
            if self.watchdog is not None:
                self.watchdog.observe_fleet_rollup(rollup)
            newly_burn = self._slo_tick_locked()
        self._dump_burn_pages(newly_burn)

    def observe_tenant(
        self,
        tenant: str,
        *,
        record: dict | None = None,
        breaker: str | None = None,
        drift: int | None = None,
        skipped: bool = False,
    ) -> None:
        """Update one tenant's row in the bounded summary ring (the
        /tenants drill-down source). No-op when the plane has no ring
        (a hand-built plane). With the slo plane attached, the round
        also accounts against the tenant's per-tenant error budget
        (published through the TenantSeries cardinality gate)."""
        if self.tenant_ring is not None:
            self.tenant_ring.observe(
                tenant,
                record=record,
                breaker=breaker,
                drift=drift,
                skipped=skipped,
            )
        if self.slo_engine is not None and (record is not None or skipped):
            ok = not skipped and not bool((record or {}).get("degraded"))
            with self._watchdog_lock:
                self.slo_engine.observe_tenant_round(tenant, ok)

    def observe_skip(self, rnd: int, breaker_state: str | None = None) -> None:
        self.health.skipped_rounds += 1
        self.health.mark_round()
        if self.recorder is not None:
            self.recorder.record_skip(rnd, breaker=breaker_state)

    def on_breaker_transition(self, rec: dict) -> None:
        """Wired to ``CircuitBreaker.on_transition``: an OPEN transition
        dumps a bundle — the moment an operator will want the last N
        rounds, captured while they are still in memory. A fleet
        tenant's transition (the fleet loop tags ``rec["tenant"]``)
        ships the latest fleet rollup plus ONLY the offending tenant's
        summary-ring entry — the bounded-bundle discipline: never all T
        tenants' state for one tenant's incident."""
        if rec.get("to") == "open" and self.recorder is not None:
            extra: dict[str, Any] = {}
            tenant = rec.get("tenant")
            if tenant is not None:
                if self.latest_fleet_rollup is not None:
                    extra["fleet_rollup"] = self.latest_fleet_rollup
                if self.tenant_ring is not None:
                    summary = self.tenant_ring.detail(tenant)
                    if summary is not None:
                        extra["tenant_summary"] = summary
            if self.serving_engine is not None:
                # an open breaker starves the serving snapshot too —
                # capture what the plane had in flight at that moment
                extra["serving_requests"] = self.serving_engine.ring()
            self.recorder.dump("breaker_open", transition=rec, **extra)

    def on_crash(self, exc: BaseException) -> None:
        if self.recorder is not None:
            self.recorder.dump("crash", error=repr(exc))
