"""Decision explainability: the host half.

The controller's rounds record WHY each move happened — which node was
hazardous, which targets were considered, what each scored, and why the
winner won. The device side (``solver.round_loop.decide_explain``) ships
one compact f32 bundle per decision; this module turns that bundle into a
``DecisionExplanation`` dict, emits it as a structured ``decision`` event,
and — crucially — can RE-DERIVE the chosen move as the argmax of the
recorded candidate scores. That re-derivation (:func:`explanation_consistent`)
is the audit invariant the flight-recorder bundle check and the chaos-soak
acceptance test pin: an explanation that cannot reproduce its own decision
is a bug, not a rendering problem.

Explanations are plain dicts (JSONL-safe) with a ``kind`` discriminator:

- ``greedy`` — one per decide: hazard top-k, candidate top-k with
  primary/tie-break scores and margins, chosen target.
- ``global`` / ``pod`` — one per solver round: the applied moves as
  candidates scored by their individual objective gain (global) or
  replicas relocated (pod), plus the solver's before/after objectives.

Everything here is jax-free: the device bundle arrives as a plain
ndarray through ``telemetry.pull``.
"""

from __future__ import annotations

import json
import math
from pathlib import Path
from typing import Any, Iterable

_NEG_INF = float("-inf")


def _finite(v: float) -> float | None:
    return None if v is None or not math.isfinite(v) else float(v)


def greedy_explanation(
    bundle,
    node_names: list[str],
    *,
    round: int,
    seq: int,
    policy: str,
    service: str | None,
    hazard_node: str | None,
    chosen: str | None,
) -> dict[str, Any]:
    """Build the ``greedy`` DecisionExplanation from the pulled device
    bundle (f32[6, k] — see ``decide_explain``). ``chosen`` is the node
    the decision picked (None on a no-op path)."""
    hz_i, hz_v, c_i, c_k1, c_k2, c_ok = (list(map(float, row)) for row in bundle)
    n = len(node_names)
    hazard = [
        {"node": node_names[int(i)], "cpu_pct": v}
        for i, v in zip(hz_i, hz_v)
        if math.isfinite(v) and 0 <= int(i) < n
    ]
    chosen_score = None
    candidates = []
    for i, s, t, ok in zip(c_i, c_k1, c_k2, c_ok):
        if not ok or not (0 <= int(i) < n) or not math.isfinite(s):
            continue
        name = node_names[int(i)]
        candidates.append(
            {
                "node": name,
                "node_index": int(i),
                "score": float(s),
                "tiebreak": _finite(t),
            }
        )
        if name == chosen:
            chosen_score = float(s)
    for c in candidates:
        c["margin"] = (
            chosen_score - c["score"] if chosen_score is not None else None
        )
    if chosen is None:
        if hazard_node is None:
            why = "no node at/over the hazard threshold"
        elif not candidates:
            why = "every valid node is hazardous — move skipped"
        else:
            why = "hazard node has no movable pod"
    else:
        runner = next(
            (c for c in candidates if c["node"] != chosen), None
        )
        margin = (
            chosen_score - runner["score"]
            if runner is not None and chosen_score is not None
            else None
        )
        why = (
            f"drain {service!r} from {hazard_node}: {policy} scored "
            f"{chosen} highest"
            + (f" (margin {margin:.4g} over {runner['node']})" if margin is not None else "")
        )
    return {
        "kind": "greedy",
        "round": round,
        "seq": seq,
        "policy": policy,
        "service": service,
        "hazard_node": hazard_node,
        "hazard": hazard,
        "candidates": candidates,
        "chosen": chosen,
        "why": why,
    }


def solver_explanation(
    *,
    kind: str,
    round: int,
    policy: str,
    candidates: list[dict[str, Any]],
    objective_before: float | None,
    objective_after: float | None,
    applied: int,
    proposed: int,
) -> dict[str, Any]:
    """The ``global``/``pod`` round explanation: applied moves as scored
    candidates (individual objective gain, or replicas relocated), chosen
    = the top-scored one."""
    best = None
    for c in candidates:
        if best is None or (
            c["score"],
            -(c.get("node_index") or 0),
        ) > (best["score"], -(best.get("node_index") or 0)):
            best = c
    chosen = best["node"] if best is not None else None
    obj = ""
    if objective_before is not None and objective_after is not None:
        obj = f"; objective {objective_before:.4g} -> {objective_after:.4g}"
    return {
        "kind": kind,
        "round": round,
        "policy": policy,
        "service": best.get("service") if best is not None else None,
        "candidates": candidates,
        "chosen": chosen,
        "objective_before": objective_before,
        "objective_after": objective_after,
        "why": f"batched solve proposed {proposed} moves, applied {applied}{obj}",
    }


def explanation_consistent(expl: dict[str, Any]) -> bool:
    """Re-derive the chosen move as the argmax of the recorded candidate
    scores — the audit invariant. A no-move explanation (``chosen`` None)
    is vacuously consistent; otherwise the chosen entry must exist among
    the candidates and dominate them under (score, tiebreak, lowest node
    index) — exactly the device kernel's masked lexicographic argmax
    order for ``greedy``, plain max-score for solver rounds."""
    chosen = expl.get("chosen")
    if chosen is None:
        return True
    candidates = expl.get("candidates") or []
    if not any(c.get("node") == chosen for c in candidates):
        return False

    def rank(c: dict[str, Any]):
        tb = c.get("tiebreak")
        return (
            c.get("score", _NEG_INF),
            _NEG_INF if tb is None else tb,
            -(c.get("node_index") or 0),
        )

    best = max(candidates, key=rank)
    return best.get("node") == chosen


def iter_decisions(records: Iterable[dict[str, Any]]) -> list[dict[str, Any]]:
    """Decision explanations from a mixed record stream: structured
    ``decision`` events, flight-recorder round entries, or bare
    explanation dicts (``kind`` + ``candidates``)."""
    out = []
    for r in records:
        if r.get("event") == "decision" or (
            "kind" in r and "candidates" in r
        ):
            out.append(r)
        for d in r.get("decisions") or ():
            out.append(d)
        rec = r.get("record")
        if isinstance(rec, dict):
            for d in rec.get("explanations") or ():
                out.append(d)
    return out


def check_decisions(
    decisions: Iterable[dict[str, Any]],
) -> tuple[int, list[dict[str, Any]]]:
    """(checked, inconsistent) over a decision stream — the bundle
    summarizer's and the acceptance test's shared verdict."""
    checked = 0
    bad = []
    for d in decisions:
        checked += 1
        if not explanation_consistent(d):
            bad.append(d)
    return checked, bad


def summarize_decisions(decisions: list[dict[str, Any]]) -> list[str]:
    """Human-readable ``telemetry explain`` rendering."""
    if not decisions:
        return ["  no decision records"]
    lines = []
    for d in decisions:
        head = (
            f"  r{d.get('round', '?')}"
            + (f".{d['seq']}" if d.get("seq") is not None else "")
            + f" [{d.get('kind', '?')}/{d.get('policy', '?')}]"
        )
        lines.append(f"{head} {d.get('why', '')}")
        for c in d.get("candidates") or []:
            mark = "->" if c.get("node") == d.get("chosen") else "  "
            margin = c.get("margin")
            lines.append(
                f"      {mark} {c.get('node')}"
                + (f" service={c['service']}" if c.get("service") else "")
                + f" score={c.get('score'):.6g}"
                + (f" margin={margin:.4g}" if margin not in (None, 0.0) else "")
            )
    checked, bad = check_decisions(decisions)
    lines.append(
        f"  consistency: {checked - len(bad)}/{checked} decisions re-derive "
        f"their chosen move from the recorded scores"
    )
    for d in bad:
        lines.append(
            f"    INCONSISTENT: r{d.get('round')}.{d.get('seq')} chose "
            f"{d.get('chosen')} but recorded scores argmax elsewhere"
        )
    return lines


def load_decisions(path: str | Path) -> list[dict[str, Any]]:
    """Decisions from an events JSONL file or a flight-recorder bundle."""
    p = Path(path)
    text = p.read_text().strip()
    if not text:
        return []
    if text.startswith("{") and p.suffix == ".json":
        bundle = json.loads(text)
        return iter_decisions(bundle.get("rounds") or [])
    records = [json.loads(ln) for ln in text.splitlines() if ln.strip()]
    return iter_decisions(records)
