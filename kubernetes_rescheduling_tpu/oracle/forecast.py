"""Numpy reference twin of the JAX forecaster (``forecast/model.py``).

The ``oracle/optimum.py`` precedent: every learned/solved quantity the
device plane produces gets an independent host-side re-derivation that
tests pin the JAX implementation against within f32 tolerance. Here that
covers the batched masked ridge fit, the lag-feature prediction, and the
persistence baseline / skill accounting — so a silent regression in the
jitted kernel (a transposed einsum, a mask dropped from the normal
equations) fails a bit-level comparison instead of quietly degrading
placement quality.

Everything is plain numpy: the ``telemetry dataset`` CLI mode uses this
module to fit and score recorded soaks without importing jax.
"""

from __future__ import annotations

import numpy as np


def lag_windows(
    series: np.ndarray, mask: np.ndarray | None, lags: int
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Supervised one-step windows from per-series history.

    ``series``: f[T, B] (time-major, one column per series), ``mask``:
    bool[T, B] observation validity (None = all observed). Returns
    ``(X, y, w)``: X f32[B, T-L, L+1] lag features (+bias), y f32[B, T-L]
    targets, w f32[B, T-L] sample weights — a window is valid only when
    every lag AND the target were observed, so churned slots never
    poison the fit.
    """
    series = np.asarray(series, dtype=np.float64)
    if series.ndim != 2:
        raise ValueError(f"series must be [T, B], got shape {series.shape}")
    t, b = series.shape
    if lags < 1:
        raise ValueError(f"lags must be >= 1, got {lags}")
    if t <= lags:
        return (
            np.zeros((b, 0, lags + 1), np.float32),
            np.zeros((b, 0), np.float32),
            np.zeros((b, 0), np.float32),
        )
    m = (
        np.ones((t, b), dtype=bool)
        if mask is None
        else np.asarray(mask, dtype=bool)
    )
    n_win = t - lags
    X = np.ones((b, n_win, lags + 1), dtype=np.float64)
    w = np.ones((b, n_win), dtype=bool)
    for k in range(lags):
        X[:, :, k] = series[k : k + n_win].T
        w &= m[k : k + n_win].T
    y = series[lags:].T
    w &= m[lags:].T
    return X.astype(np.float32), y.astype(np.float32), w.astype(np.float32)


def difference_windows(
    series: np.ndarray, mask: np.ndarray | None, lags: int
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """The MODEL-form windows: persistence-plus-trend supervision.

    The forecaster regresses the next DELTA on the last ``lags`` deltas
    (plus bias), predicting ``ŷ_{t+1} = y_t + w·φ`` — so ridge shrinkage
    degrades to persistence, not to zero. Returns ``(X, y_delta, base,
    w)``: X f32[B, T-L-1, L+1] difference features, y_delta the target
    deltas, base the levels ``y_t`` persistence would carry forward, w
    the window validity (every level in the window AND the target must
    have been observed).
    """
    series = np.asarray(series, dtype=np.float64)
    if series.ndim != 2:
        raise ValueError(f"series must be [T, B], got shape {series.shape}")
    t, b = series.shape
    if lags < 1:
        raise ValueError(f"lags must be >= 1, got {lags}")
    n_win = t - lags - 1
    if n_win <= 0:
        z = np.zeros((b, 0), np.float32)
        return np.zeros((b, 0, lags + 1), np.float32), z, z, z
    m = (
        np.ones((t, b), dtype=bool)
        if mask is None
        else np.asarray(mask, dtype=bool)
    )
    diffs = series[1:] - series[:-1]                 # [T-1, B]
    X = np.ones((b, n_win, lags + 1), dtype=np.float64)
    w = np.ones((b, n_win), dtype=bool)
    for k in range(lags):
        X[:, :, k] = diffs[k : k + n_win].T
        w &= m[k : k + n_win].T
    w &= m[lags : lags + n_win].T                    # window's last level
    w &= m[lags + 1 : lags + 1 + n_win].T            # the target
    base = series[lags : lags + n_win].T             # y_t per window
    y_delta = series[lags + 1 :].T - base
    return (
        X.astype(np.float32),
        y_delta.astype(np.float32),
        base.astype(np.float32),
        w.astype(np.float32),
    )


def fit_ridge_np(
    X: np.ndarray, y: np.ndarray, mask: np.ndarray, ridge: float
) -> np.ndarray:
    """Per-series masked ridge fit — the twin of ``forecast.model.fit_ridge``.

    Same normal-equation form, solved per series with numpy: ``W[i] =
    (X_iᵀ diag(w_i) X_i + λI)⁻¹ X_iᵀ diag(w_i) y_i``. Returns f64[B, F]
    (callers compare against the f32 device result with tolerance).
    """
    X = np.asarray(X, dtype=np.float64)
    y = np.asarray(y, dtype=np.float64)
    w = np.asarray(mask, dtype=np.float64)
    b_series, _, feat = X.shape
    eye = np.eye(feat)
    W = np.zeros((b_series, feat))
    for i in range(b_series):
        Xw = X[i] * w[i][:, None]
        A = Xw.T @ X[i] + ridge * eye
        rhs = Xw.T @ y[i]
        W[i] = np.linalg.solve(A, rhs)
    return W


def predict_np(W: np.ndarray, X: np.ndarray) -> np.ndarray:
    """Apply per-series weights over window arrays: [B, F] × [B, T, F]
    → [B, T] (T may be absent: [B, F] × [B, F] → [B])."""
    X = np.asarray(X, np.float64)
    W = np.asarray(W, np.float64)
    if X.ndim == W.ndim:
        return np.einsum("bf,bf->b", X, W)
    return np.einsum("btf,bf->bt", X, W)


def eval_forecast_np(
    series: np.ndarray,
    mask: np.ndarray | None,
    *,
    lags: int,
    ridge: float = 1e-3,
) -> dict:
    """Fit + score one target family — the offline half of the
    ``forecast_skill`` metric, used by the ``telemetry dataset`` report.

    Fits the model-form (persistence-plus-trend) windows and reports
    masked MAEs of the model prediction ``base + W·x`` and the
    persistence baseline ``base`` against the observed next levels, with
    ``skill = 1 − mae_model/mae_persistence`` (positive = the learned
    model beats carrying yesterday forward). Persistence MAE is the mean
    |target delta| by construction.
    """
    X, y_delta, _base, w = difference_windows(series, mask, lags)
    n = float(w.sum())
    if n == 0:
        return {
            "series": int(X.shape[0]),
            "windows": 0,
            "mae_model": 0.0,
            "mae_persistence": 0.0,
            "skill": 0.0,
        }
    W = fit_ridge_np(X, y_delta, w, ridge)
    mae_model = float(np.sum(np.abs(predict_np(W, X) - y_delta) * w) / n)
    mae_pers = float(np.sum(np.abs(y_delta) * w) / n)
    skill = 1.0 - mae_model / mae_pers if mae_pers > 1e-12 else 0.0
    return {
        "series": int(X.shape[0]),
        "windows": int(n),
        "mae_model": mae_model,
        "mae_persistence": mae_pers,
        "skill": skill,
    }
