"""Pure-Python oracle implementing the reference's decision semantics.

Used only in tests: the TPU kernels must match these functions
decision-for-decision (SURVEY.md §4 "metric-parity tests").
"""

from kubernetes_rescheduling_tpu.oracle.reference_oracle import (
    Snapshot,
    to_snapshot,
    detection,
    pick_max_pod,
    choose_spread,
    choose_binpack,
    choose_random,
    choose_kubescheduling,
    choose_communication,
    communication_cost,
    node_std,
)

__all__ = [
    "Snapshot",
    "to_snapshot",
    "detection",
    "pick_max_pod",
    "choose_spread",
    "choose_binpack",
    "choose_random",
    "choose_kubescheduling",
    "choose_communication",
    "communication_cost",
    "node_std",
]
