"""Dict-world oracle with the reference's exact decision semantics.

This is a clean-room re-statement of the *behavior* documented in SURVEY.md
§2/§3 (with ``file:line`` citations below), written against our own snapshot
schema. It exists so that every TPU kernel has a slow, obviously-correct
Python twin to test against, including the tie-break subtleties:

- hazard detection uses the **rounded** cpu_pct the monitor stores
  (reference get_resource_usage.py:37, harzard_detect.py:12) and picks the
  first max in node order (reference harzard_detect.py:24, dict-insertion
  order = node list order);
- spread minimizes (pod count, node name) (reference rescheduling.py:101);
- binpack maximizes (cpu_pct, node name) (reference rescheduling.py:133);
- CAR maximizes related-pod count, tie → max remaining CPU with strict ``>``
  so the first max in node order wins (reference rescheduling.py:199-214);
- victim = first max-CPU pod on the hazard node in pod-list order
  (reference delete_replaced_pod.py:47-57);
- comm cost collapses a deployment to the node of its last-listed pod and
  counts absent peers as cross-node (reference communicationcost.py:22-45).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from kubernetes_rescheduling_tpu.core.state import ClusterState, CommGraph


@dataclass
class PodInfo:
    name: str
    service: str
    node: str
    cpu: float
    mem: float
    index: int


@dataclass
class Snapshot:
    """Dict-world cluster snapshot (schema of reference podmonitor.py:17-37)."""

    nodes_name: list[str]
    pods: list[PodInfo]
    cluster: dict[str, dict]  # per-node: cpu/mem cap+usage+pct and pod list


def to_snapshot(state: ClusterState, graph: CommGraph) -> Snapshot:
    """Convert an array state to the dict world the oracle reasons in."""
    node_valid = np.asarray(state.node_valid)
    pod_valid = np.asarray(state.pod_valid)
    pod_node = np.asarray(state.pod_node)
    pod_service = np.asarray(state.pod_service)
    pod_cpu = np.asarray(state.pod_cpu)
    pod_mem = np.asarray(state.pod_mem)
    cpu_cap = np.asarray(state.node_cpu_cap)
    mem_cap = np.asarray(state.node_mem_cap)
    cpu_used = np.asarray(state.node_cpu_used())
    mem_used = np.asarray(state.node_mem_used())

    nodes_name = [n for i, n in enumerate(state.node_names) if node_valid[i]]
    pods: list[PodInfo] = []
    for i in range(len(pod_node)):
        if not pod_valid[i] or pod_node[i] < 0:
            continue
        pods.append(
            PodInfo(
                name=state.pod_names[i] if i < len(state.pod_names) else f"pod{i}",
                service=graph.names[pod_service[i]],
                node=state.node_names[pod_node[i]],
                cpu=float(pod_cpu[i]),
                mem=float(pod_mem[i]),
                index=i,
            )
        )

    cluster: dict[str, dict] = {}
    for i, name in enumerate(state.node_names):
        if not node_valid[i]:
            continue
        pct = (
            int(round(cpu_used[i] / cpu_cap[i] * 100)) if cpu_cap[i] else -1
        )  # rounded, as stored by the monitor (reference get_resource_usage.py:37)
        mem_pct = int(round(mem_used[i] / mem_cap[i] * 100)) if mem_cap[i] else -1
        cluster[name] = {
            "node_cpu_capacity": float(cpu_cap[i]),
            "node_cpu_usage": float(cpu_used[i]),
            "cpu_pct": pct,
            "node_mem_capacity": float(mem_cap[i]),
            "node_mem_usage": float(mem_used[i]),
            "mem_pct": mem_pct,
            "pods": [
                {
                    "podname": p.name,
                    "deploymentname": p.service,
                    "pod_cpu_usage": p.cpu,
                    "pod_mem_usage": p.mem,
                }
                for p in pods
                if p.node == name
            ],
        }
    return Snapshot(nodes_name=nodes_name, pods=pods, cluster=cluster)


def detection(
    snapshot: Snapshot, threshold: float = 30.0
) -> tuple[str, list[str]]:
    """Hazard nodes (rounded cpu_pct >= threshold) + first-max pick
    (reference harzard_detect.py:3-27)."""
    hazard = [
        n for n in snapshot.nodes_name if snapshot.cluster[n]["cpu_pct"] >= threshold
    ]
    most = ""
    if hazard:
        best = None
        for n in hazard:  # max() over dict → first max in insertion order
            pct = snapshot.cluster[n]["cpu_pct"]
            if best is None or pct > snapshot.cluster[best]["cpu_pct"]:
                best = n
        most = best
    return most, hazard


def pick_max_pod(snapshot: Snapshot, node: str) -> PodInfo | None:
    """First max-CPU pod on ``node`` in pod-list order
    (reference delete_replaced_pod.py:41-61, strict ``>``)."""
    best: PodInfo | None = None
    best_cpu = -1.0
    for p in snapshot.pods:
        if p.node != node:
            continue
        if p.cpu > best_cpu:
            best = p
            best_cpu = p.cpu
    return best


def _candidates(snapshot: Snapshot, hazard: list[str]) -> list[str]:
    cands = [n for n in snapshot.nodes_name if n not in hazard]
    if not cands:
        raise RuntimeError("No candidate nodes available (all nodes are hazardous).")
    return cands


def choose_spread(snapshot: Snapshot, hazard: list[str]) -> str:
    """Min pod count, tie → lexicographic-min name (reference rescheduling.py:89-103)."""
    cands = _candidates(snapshot, hazard)
    return min(cands, key=lambda n: (len(snapshot.cluster[n]["pods"]), n))


def choose_binpack(snapshot: Snapshot, hazard: list[str]) -> str:
    """Max cpu_pct, tie → lexicographic-max name (reference rescheduling.py:121-135)."""
    cands = _candidates(snapshot, hazard)
    return max(cands, key=lambda n: (snapshot.cluster[n]["cpu_pct"], n))


def choose_random(
    snapshot: Snapshot, hazard: list[str], rng: np.random.Generator
) -> str:
    """Uniform over non-hazard nodes (reference rescheduling.py:149-153).
    Parity with the device kernel is distribution-level (SURVEY.md §7)."""
    cands = _candidates(snapshot, hazard)
    return cands[int(rng.integers(len(cands)))]


def choose_kubescheduling(snapshot: Snapshot, hazard: list[str]) -> str:
    """OUR model of the default kube-scheduler (the reference only patches
    anti-affinity and lets kube-scheduler place — reference
    rescheduling.py:159-171): least-allocated scoring — max remaining CPU
    fraction, tie → first in node order. The device kernel implements the
    same model, so this oracle is self-consistency, not reference parity."""
    cands = _candidates(snapshot, hazard)
    best, best_free = None, -np.inf
    for n in cands:
        c = snapshot.cluster[n]
        cap = c["node_cpu_capacity"]
        free = (cap - c["node_cpu_usage"]) / cap if cap else 0.0
        if free > best_free:
            best, best_free = n, free
    return best


def choose_communication(
    snapshot: Snapshot,
    relation: dict[str, list[str]],
    service: str,
    hazard: list[str],
) -> str:
    """CAR: max related-pod count per node; tie → max remaining CPU, strict
    ``>`` so the first max in node order wins (reference rescheduling.py:183-216)."""
    rel = relation.get(service, [])
    score: dict[str, int] = {}
    for n in snapshot.nodes_name:
        if n in hazard:
            continue
        score[n] = sum(
            1 for pod in snapshot.cluster[n]["pods"] if pod["deploymentname"] in rel
        )
    if not score:
        raise RuntimeError("No candidate nodes available (all nodes are hazardous).")
    max_score = max(score.values())
    best_nodes = [n for n, s in score.items() if s == max_score]
    if len(best_nodes) > 1:
        target, best_free = None, -1.0
        for n in best_nodes:
            c = snapshot.cluster[n]
            free = c["node_cpu_capacity"] - c["node_cpu_usage"]
            if free > best_free:
                target, best_free = n, free
        return target
    return best_nodes[0]


def communication_cost(
    snapshot: Snapshot, relation: dict[str, list[str]]
) -> float:
    """Deployment-level cross-node edges / 2, last pod wins, absent peer
    counts as cross-node (reference communicationcost.py:6-49)."""
    dep_node: dict[str, str] = {}
    for p in snapshot.pods:  # later pods overwrite — "last pod wins"
        dep_node[p.service] = p.node
    cost = 0
    for dep, node in dep_node.items():
        for rel in relation.get(dep, []):
            if node != dep_node.get(rel):
                cost += 1
    return cost / 2


def node_std(snapshot: Snapshot) -> float:
    """Population std of unrounded CPU % over nodes with cap > 0
    (reference nodemonitor.py:24-49)."""
    pcts = [
        c["node_cpu_usage"] / c["node_cpu_capacity"] * 100.0
        for c in snapshot.cluster.values()
        if c["node_cpu_capacity"] > 0
    ]
    return float(np.std(pcts)) if pcts else 0.0
