"""True-optimum oracles for measuring the solver's optimality gap.

BASELINE.md promises "globally-optimal placement"; the solver's quality
gates so far were "never worse than the input" and "beats greedy CAR" —
neither says how far from *optimal* the chunked best-response lands. These
oracles provide ground truth at two scales:

- :func:`brute_force_optimum` — exhaustive N^S enumeration (vectorized,
  batched). Exact for the FULL solver objective (comm + balance + overload
  + hard capacity), feasible up to ~N^S ≈ 10^7 (S≤10, N≤4 comfortably).
- :func:`milp_optimum` — exact integer-program optimum of the COMM
  objective (cut weight) under capacity constraints, via scipy's HiGHS
  branch-and-bound. The cut linearization: binary x[s,n], continuous
  z[e] ∈ [0,1] with z_e ≥ x[s,n] − x[t,n] for every node — for any
  assignment, the node where s sits and t doesn't forces z_e = 1 iff the
  edge is cut. Scales to S ≈ 100-200 services — a regime the brute force
  cannot touch. Balance terms are nonlinear (std of loads), so MILP gap
  measurements run the solver with balance_weight=0.

Gap results and the re-justification of the sweeps/noise defaults live in
RESULTS.md (§ optimality gap); the regression test pins the measured
small-instance gap so a solver change that silently loses quality fails CI.

Reference objective being bounded: communicationcost.py:40-45.
"""

from __future__ import annotations

import numpy as np

from kubernetes_rescheduling_tpu.core.state import ClusterState, CommGraph


def _problem_arrays(state: ClusterState, graph: CommGraph):
    """Collapse to service-level arrays (the solver's decision space):
    pair weights W = adj·rv·rv over services with pods, per-service CPU,
    node budgets."""
    S = graph.num_services
    svc = np.asarray(state.pod_service)
    valid = np.asarray(state.pod_valid)
    pod_cpu = np.asarray(state.pod_cpu)
    rv = np.zeros(S)
    cpu = np.zeros(S)
    for i in np.flatnonzero(valid):
        s = int(svc[i])
        if 0 <= s < S:
            rv[s] += 1.0
            cpu[s] += float(pod_cpu[i])
    adj = np.asarray(graph.adj)[:S, :S]
    W = adj * rv[:, None] * rv[None, :]
    placed = rv > 0
    node_valid = np.asarray(state.node_valid)
    cap = np.asarray(state.node_cpu_cap).astype(float)
    base = np.asarray(state.node_base_cpu).astype(float)
    return W, cpu, placed, node_valid, cap, base


def brute_force_optimum(
    state: ClusterState,
    graph: CommGraph,
    *,
    balance_weight: float = 0.0,
    overload_weight: float = 10.0,
    capacity_frac: float = 1.0,
    enforce_capacity: bool = True,
    batch: int = 65536,
) -> tuple[np.ndarray, float]:
    """Exhaustive optimum of the solver's exact objective.

    Returns ``(assign[S], objective)`` where infeasible assignments (any
    service on a node whose budget it busts, when enforcing capacity) are
    excluded — matching the solver's hard feasibility veto. Services
    without pods keep assignment 0 and contribute nothing.
    """
    W, cpu, placed, node_valid, cap, base = _problem_arrays(state, graph)
    # mirror the solver's accounting: over-budget repulsion only exists
    # alongside budget enforcement (global_solver.global_assign zeroes
    # overload_weight when enforce_capacity=False) — without this gate the
    # oracle would measure a different objective than the solver optimizes
    if not enforce_capacity:
        overload_weight = 0.0
    S = len(cpu)
    nodes = np.flatnonzero(node_valid)
    N = len(nodes)
    if N ** int(placed.sum()) > 50_000_000:
        raise ValueError(
            f"N^S = {N}^{int(placed.sum())} too large for brute force"
        )
    budget = np.where(cap > 0, cap, 1.0) * capacity_frac
    movers = np.flatnonzero(placed)
    M = len(movers)
    total = N**M
    best_obj = np.inf
    best = None
    Wm = W[np.ix_(movers, movers)]
    cm = cpu[movers]
    for start in range(0, total, batch):
        idx = np.arange(start, min(start + batch, total))
        # mixed-radix decode: column m = node choice of movers[m]
        a = (idx[:, None] // N ** np.arange(M)[None, :]) % N  # [B, M]
        an = nodes[a]
        # cut weight: sum over pairs with different nodes
        diff = (an[:, :, None] != an[:, None, :]).astype(float)
        comm = 0.5 * np.einsum("st,bst->b", Wm, diff)
        loads = base[None, nodes] + np.zeros((len(idx), N))
        np.add.at(
            loads.reshape(-1),
            (np.arange(len(idx))[:, None] * N + a).reshape(-1),
            np.broadcast_to(cm[None, :], a.shape).reshape(-1),
        )
        pct = loads / budget[None, nodes] * 100.0
        obj = comm.copy()
        if balance_weight:
            obj += balance_weight * pct.std(axis=1)
        obj += overload_weight * np.maximum(pct - 100.0, 0.0).sum(axis=1)
        if enforce_capacity:
            feasible = (loads <= budget[None, nodes]).all(axis=1)
            obj = np.where(feasible, obj, np.inf)
        i = int(np.argmin(obj))
        if obj[i] < best_obj:
            best_obj = float(obj[i])
            full = np.zeros(S, dtype=np.int64)
            full[movers] = an[i]
            best = full
    return best, best_obj


def milp_optimum(
    state: ClusterState,
    graph: CommGraph,
    *,
    capacity_frac: float = 1.0,
    enforce_capacity: bool = True,
    time_limit_s: float = 120.0,
) -> tuple[float, bool]:
    """Exact MILP optimum of the COMM objective under capacity constraints
    (HiGHS branch-and-bound via scipy). Returns ``(optimal_cut, proven)``
    — ``proven`` is False if the time limit stopped the search first (the
    value is then the incumbent, still a valid upper bound on the optimum).
    """
    from scipy.optimize import Bounds, LinearConstraint, milp
    from scipy.sparse import lil_matrix

    W, cpu, placed, node_valid, cap, base = _problem_arrays(state, graph)
    nodes = np.flatnonzero(node_valid)
    N = len(nodes)
    movers = np.flatnonzero(placed)
    M = len(movers)
    iu, ju = np.nonzero(np.triu(W[np.ix_(movers, movers)], k=1))
    E = len(iu)
    wts = W[np.ix_(movers, movers)][iu, ju]
    nx = M * N  # x[s, n] flattened s-major
    nv = nx + E

    c = np.zeros(nv)
    c[nx:] = wts
    integrality = np.concatenate([np.ones(nx), np.zeros(E)])
    bounds = Bounds(np.zeros(nv), np.ones(nv))

    constraints = []
    # assignment: each mover on exactly one node
    A = lil_matrix((M, nv))
    for m in range(M):
        A[m, m * N : (m + 1) * N] = 1.0
    constraints.append(LinearConstraint(A.tocsr(), 1.0, 1.0))
    # cut linearization: z_e − x[s,n] + x[t,n] ≥ 0 for every node
    A = lil_matrix((E * N, nv))
    for e in range(E):
        for n in range(N):
            row = e * N + n
            A[row, nx + e] = 1.0
            A[row, iu[e] * N + n] = -1.0
            A[row, ju[e] * N + n] = 1.0
    constraints.append(LinearConstraint(A.tocsr(), 0.0, np.inf))
    if enforce_capacity:
        budget = np.where(cap > 0, cap, 1.0) * capacity_frac
        A = lil_matrix((N, nv))
        for n in range(N):
            for m in range(M):
                A[n, m * N + n] = cpu[movers[m]]
        constraints.append(
            LinearConstraint(
                A.tocsr(), -np.inf, budget[nodes] - base[nodes]
            )
        )

    res = milp(
        c,
        constraints=constraints,
        integrality=integrality,
        bounds=bounds,
        options={"time_limit": time_limit_s},
    )
    if res.x is None:
        raise RuntimeError(f"MILP failed: {res.message}")
    proven = res.status == 0
    return float(res.fun), proven
