"""Forecast plane: predictive scheduling trained on our own telemetry.

- :mod:`model` — the pure-JAX online lag-feature ridge forecaster
  (per-node, batched, mask-aware) with its persistence baseline and
  device-side skill gate;
- :mod:`plane` — the controller-facing :class:`ForecastPlane` (one
  instrumented kernel dispatch + one counted transfer per round,
  forecast metric families);
- :mod:`dataset` — numpy-only extraction of per-node load / per-edge
  traffic training windows from recorded ``rounds.jsonl`` soaks (the
  ``telemetry dataset`` CLI mode).

The numpy twin lives in :mod:`oracle.forecast` (the ``oracle/optimum``
precedent); the ``proactive`` algorithm consuming the predictions lives
in :mod:`policies.proactive` + ``bench/controller.py``.

``model``/``plane`` import jax + flax at module load, so their names
resolve lazily (PEP 562, the ``utils/__init__`` precedent): importing
``forecast.dataset`` — the numpy-only half the ``telemetry dataset``
CLI mode uses — does not pay the jax/flax import through this package.
(Module-level hygiene only: the top-level package ``__init__`` imports
jax anyway.)
"""

_LAZY = {
    "ForecastState": "model",
    "fit_ridge": "model",
    "forecast_step": "model",
    "init_forecast_state": "model",
    "node_loads": "model",
    "repad_forecast_state": "model",
    "ridge_predict": "model",
    "ForecastPlane": "plane",
    "FORECAST_SITE": "plane",
}


def __getattr__(name: str):
    if name in _LAZY:
        import importlib

        mod = importlib.import_module(
            f"kubernetes_rescheduling_tpu.forecast.{_LAZY[name]}"
        )
        return getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "ForecastState",
    "fit_ridge",
    "forecast_step",
    "init_forecast_state",
    "node_loads",
    "repad_forecast_state",
    "ridge_predict",
    "ForecastPlane",
    "FORECAST_SITE",
]
