"""Host-side forecast plane: the controller's handle on the online model.

Owns the :class:`~forecast.model.ForecastState` pytree across rounds,
dispatches the ONE jitted forecast kernel per round (instrumented as
``controller_forecast`` — the 1-steady-state-trace invariant applies,
retracing only on a counted bucket promotion, which this plane absorbs
by re-padding its node axis), pulls the diagnostic vector as ONE counted
transfer (``site="forecast"``), and publishes the forecast-error metric
families:

- ``forecast_mae{target}`` / ``forecast_skill{target}`` gauges — running
  model vs persistence error and the skill ratio;
- ``forecast_rounds_total{mode}`` — rounds by path: ``cold`` (still
  warming up, persistence applied), ``predictive`` (trained model
  steering the decision), ``degraded`` (trained but losing to
  persistence — the skill gate zeroed the applied delta, so the round
  is reactive CAR again).

The per-round record (:meth:`round_info`) rides
``RoundRecord.forecast`` → rounds.jsonl, where the watchdog's
``forecast_skill`` rule reads it.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from kubernetes_rescheduling_tpu.forecast.model import (
    DIAG_FRAC_MODEL,
    DIAG_MAE_MODEL,
    DIAG_MAE_PERSIST,
    DIAG_ROUNDS,
    DIAG_SKILL,
    DIAG_TRAINED,
    forecast_step,
    init_forecast_state,
    repad_forecast_state,
)
from kubernetes_rescheduling_tpu.telemetry import instrument_jit, pull

FORECAST_SITE = "forecast"

# the online update+solve+predict kernel: one dispatch per proactive
# round. Same steady-state contract as the decision kernels —
# jax_traces_total{fn="controller_forecast"} == 1 + bucket promotions
# (the node axis re-pads on promotion; nothing else changes shape).
#
# The forecast state is a DONATED carry (donate_argnums=1): every leaf
# of the output ForecastState has exactly the input's shape, the plane
# replaces its handle with the output every round, and the old state is
# never read again — so XLA aliases the recursive-least-squares
# statistics (the per-node normal-equation matrices, the largest
# resident piece of the plane) in place instead of holding both
# generations. Visible in the jax_hbm_* gauges captured at first
# compile; test-pinned in tests/test_pipeline.py.
_forecast_step = instrument_jit(
    forecast_step, name="controller_forecast", donate_argnums=(1,)
)


class ForecastPlane:
    """One per proactive run; never shared across tenants."""

    def __init__(self, config, *, registry=None) -> None:
        self.config = config
        self.registry = registry
        self._fstate = None
        self._last: dict | None = None
        # traced scalars (not Python floats) so every configuration of
        # the plane reuses the one compiled kernel signature
        self._ridge = jnp.float32(config.ridge)
        self._min_skill = jnp.float32(config.min_skill)
        self._min_history = jnp.float32(config.min_history)
        self._decay = jnp.float32(config.decay)
        self._fit_decay = jnp.float32(config.fit_decay)

    def observe_and_predict(self, state, *, closer=None) -> jax.Array:
        """Fold ``state``'s observed node loads into the model and
        return the predicted-load ``delta`` (f32[N], device-resident)
        for this round's proactive decision. Handles bucket promotions
        by re-padding the forecaster's node axis (one legal retrace).

        With ``closer`` (the controller's per-round
        :class:`~bench.round_end.RoundCloser`) the diag vector stays
        device-resident and rides the round's single ``round_end``
        transfer — the decode lands on ``self._last`` at flush, before
        ``round_info`` is read. Without it (direct callers, tests) the
        diag is pulled immediately as its own counted ``forecast``
        transfer, the historical behavior."""
        n = state.num_nodes
        if self._fstate is None:
            self._fstate = init_forecast_state(self.config.lags, n)
        elif self._fstate.num_nodes != n:
            self._fstate = repad_forecast_state(self._fstate, n)
        self._fstate, delta, diag = _forecast_step(
            state, self._fstate, self._ridge, self._min_skill,
            self._min_history, self._decay, self._fit_decay,
        )
        if closer is not None:
            closer.defer(diag, self._decode_diag)
        else:
            self._decode_diag(
                pull(diag, site=FORECAST_SITE, registry=self.registry)
            )
        return delta

    def _decode_diag(self, d) -> None:
        trained = bool(d[DIAG_TRAINED] > 0)
        frac = float(d[DIAG_FRAC_MODEL])
        skill = float(d[DIAG_SKILL])
        if not trained:
            mode = "cold"
        elif frac > 0:
            mode = "predictive"
        else:
            mode = "degraded"
        self._last = {
            "skill": skill,
            "mae_model": float(d[DIAG_MAE_MODEL]),
            "mae_persistence": float(d[DIAG_MAE_PERSIST]),
            "scored_weight": float(d[DIAG_ROUNDS]),
            "model_node_frac": frac,
            "trained": trained,
            "mode": mode,
            "target": "node_load",
        }

    def round_info(self) -> dict | None:
        """The latest round's forecast block (RoundRecord.forecast)."""
        return dict(self._last) if self._last is not None else None

    def publish(self, registry) -> None:
        """One metric sample set per proactive round."""
        if self._last is None:
            return
        lab = {"target": "node_load"}
        registry.gauge(
            "forecast_mae",
            "running mean absolute one-step forecast error (model vs "
            "observed), by target family",
            labelnames=("target",),
        ).labels(**lab).set(self._last["mae_model"])
        registry.gauge(
            "forecast_skill",
            "1 - mae_model/mae_persistence: >0 means the learned "
            "forecaster beats the persistence baseline",
            labelnames=("target",),
        ).labels(**lab).set(self._last["skill"])
        registry.counter(
            "forecast_rounds_total",
            "proactive rounds by forecast path (cold = warming up, "
            "predictive = model steering, degraded = skill gate fell "
            "back to reactive)",
            labelnames=("mode",),
        ).labels(mode=self._last["mode"]).inc()
