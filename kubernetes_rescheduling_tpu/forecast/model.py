"""The device-side forecaster: lag-feature ridge regression per series.

The reactive controller reschedules against the *last observed* snapshot,
so under the bursty/diurnal churn profiles it is always one window behind
the load it is placing against. This module closes that gap with the
smallest learned model that can: one lag-feature linear (ridge) predictor
PER NODE, batched over the node axis, trained ONLINE from the loop's own
snapshots — no external training system, no stored datasets, everything
inside one jitted kernel per round.

Model. For a series ``y`` (a node's CPU load fraction, or — in the
offline path — a service edge's traffic share), the one-step prediction
is persistence plus a learned trend over the last L DIFFERENCES:

    ŷ_{t+1} = y_t + w · [Δy_{t-L+1}, …, Δy_t, 1],   Δy_t = y_t − y_{t-1}

with ``w`` the ridge solution ``(XᵀX + λI)⁻¹ XᵀΔy`` over every observed
difference window. Differencing is the robustness choice, not a detail:
ridge shrinkage pulls ``w`` toward ZERO, and a zero trend model IS the
persistence baseline — so a series with no learnable structure (or a
freshly trained model) degrades toward skill ≈ 0 instead of extrapolating
raw levels badly, and a trending/diurnal series is where the model earns
positive skill. Online, the kernel keeps the sufficient statistics
``A ← A + x xᵀ`` / ``b ← b + x Δy`` per node and re-solves the tiny
(L+1)² system each round — O(N·(L+1)²) work, batched over nodes in one
``jnp.linalg.solve``.

Mask-awareness (the elastic contract). Padded bucket slots and churned
nodes must never poison the fit: every accumulation is weighted by
``state.node_valid``, a slot whose validity FLIPS ON (a drained slot
re-used, a node added) restarts its series from zero, and invalid slots
always predict persistence with a zero applied delta — so a padded +
masked problem is bit-exact with its unpadded twin (the mask-twin tests
pin it).

Persistence baseline & skill. The model must BEAT the free predictor
``ŷ_{t+1} = y_t`` to earn the right to steer placement:
``forecast_skill = 1 − MAE(model)/MAE(persistence)`` over every round
where a trained model prediction existed. The kernel gates the applied
delta on ``skill ≥ min_skill`` DEVICE-SIDE, so a forecaster that loses
to persistence degrades the proactive policy to reactive CAR (delta 0 →
bit-identical decisions) without a host round trip — and keeps scoring
its shadow predictions so it can re-earn the gate back.

Cold start. Until ``min_history`` observations per node the prediction
IS persistence: the applied delta is exactly 0.0, so a proactive round
with an untrained forecaster is bit-identical to a plain reactive round
(test-pinned) — never NaN, never a crash (the ridge term keeps every
solve well-posed even for all-zero slots).

The numpy twin in ``oracle/forecast.py`` re-implements the fit and the
baseline for test-pinning (the ``oracle/optimum.py`` precedent).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from kubernetes_rescheduling_tpu.core.state import ClusterState

# diagnostic vector layout (one device→host pull per round, site="forecast")
DIAG_SKILL = 0          # 1 - mae_model/mae_persistence (0 until scored)
DIAG_MAE_MODEL = 1      # running mean |model pred - observed|, in load
                        # FRACTION of node capacity (the model's units)
DIAG_MAE_PERSIST = 2    # running mean |persistence pred - observed|
DIAG_ROUNDS = 3         # decayed weight of rounds contributing to the
                        # error window (~min(rounds, 1/(1-decay)))
DIAG_FRAC_MODEL = 4     # fraction of valid nodes on the model path
DIAG_TRAINED = 5        # 1.0 once any node has min_history observations
DIAG_SIZE = 6


@struct.dataclass
class ForecastState:
    """Online per-node forecaster state (all arrays carry the node axis).

    ``history`` is a rolling window, row 0 oldest, row L-1 the most
    recent observation. ``A``/``b`` are the ridge normal-equation
    sufficient statistics per node. ``prev_model_pred`` is last round's
    SHADOW model prediction (kept even while the skill gate degrades the
    applied delta to zero, so a bad model keeps being scored and can
    recover). Scalars accumulate the masked per-round mean absolute
    errors for the skill metric.
    """

    history: jax.Array          # f32[L+1, N] — L+1 levels yield L differences
    count: jax.Array            # f32[N] observations since the slot was (re)validated
    A: jax.Array                # f32[N, F, F], F = L+1
    b: jax.Array                # f32[N, F]
    prev_model_pred: jax.Array  # f32[N]
    prev_model_valid: jax.Array  # bool[N] — shadow prediction existed last round
    prev_valid: jax.Array       # bool[N] — node validity last round
    err_model_sum: jax.Array    # f32[] masked-mean |model - obs| summed over rounds
    err_persist_sum: jax.Array  # f32[]
    err_rounds: jax.Array       # f32[]
    steps: jax.Array            # i32[] rounds observed

    @property
    def lags(self) -> int:
        return int(self.history.shape[0]) - 1

    @property
    def num_nodes(self) -> int:
        return int(self.history.shape[1])


def init_forecast_state(lags: int, num_nodes: int) -> ForecastState:
    """A fresh (all-cold) forecaster over ``num_nodes`` series."""
    if lags < 1:
        raise ValueError(f"lags must be >= 1, got {lags}")
    f = lags + 1
    z = jnp.zeros
    return ForecastState(
        history=z((lags + 1, num_nodes), jnp.float32),
        count=z((num_nodes,), jnp.float32),
        A=z((num_nodes, f, f), jnp.float32),
        b=z((num_nodes, f), jnp.float32),
        prev_model_pred=z((num_nodes,), jnp.float32),
        prev_model_valid=z((num_nodes,), bool),
        prev_valid=z((num_nodes,), bool),
        err_model_sum=jnp.float32(0.0),
        err_persist_sum=jnp.float32(0.0),
        err_rounds=jnp.float32(0.0),
        steps=jnp.int32(0),
    )


def repad_forecast_state(fstate: ForecastState, num_nodes: int) -> ForecastState:
    """Grow the node axis to a promoted bucket capacity.

    New slots arrive cold (zero stats, invalid) — exactly the state a
    freshly validated node would be reset to by the kernel's slot
    hygiene, so a bucket promotion costs one retrace (new shapes) and
    nothing else. Shrinking is rejected: buckets never demote.
    """
    n_old = fstate.num_nodes
    if num_nodes < n_old:
        raise ValueError(
            f"forecast state cannot shrink ({n_old} -> {num_nodes}); "
            "shape buckets never demote"
        )
    if num_nodes == n_old:
        return fstate
    pad = num_nodes - n_old

    def pad_last(x):
        width = [(0, 0)] * (x.ndim - 1) + [(0, pad)]
        return jnp.pad(x, width)

    return fstate.replace(
        history=pad_last(fstate.history),
        count=pad_last(fstate.count),
        A=jnp.pad(fstate.A, ((0, pad), (0, 0), (0, 0))),
        b=jnp.pad(fstate.b, ((0, pad), (0, 0))),
        prev_model_pred=pad_last(fstate.prev_model_pred),
        prev_model_valid=pad_last(fstate.prev_model_valid),
        prev_valid=pad_last(fstate.prev_valid),
    )


def fit_ridge(
    X: jax.Array, y: jax.Array, mask: jax.Array, ridge: float | jax.Array
) -> jax.Array:
    """Batched masked ridge fit — the OFFLINE form of the same math the
    online kernel accumulates incrementally.

    ``X``: f32[B, T, F] lag-feature windows per series, ``y``: f32[B, T]
    targets, ``mask``: [B, T] sample validity (0-weighted samples
    contribute nothing — churned slots never poison the fit). Returns
    the per-series weights ``W``: f32[B, F]. The ridge term keeps every
    solve well-posed even for all-masked series (W = 0 there).
    """
    X = jnp.asarray(X, jnp.float32)
    y = jnp.asarray(y, jnp.float32)
    w = jnp.asarray(mask, jnp.float32)
    A = jnp.einsum("btf,btg,bt->bfg", X, X, w)
    b = jnp.einsum("btf,bt,bt->bf", X, y, w)
    eye = jnp.eye(X.shape[-1], dtype=jnp.float32)
    ridge_a = A + jnp.asarray(ridge, jnp.float32) * eye
    return jnp.linalg.solve(ridge_a, b[..., None])[..., 0]


def ridge_predict(W: jax.Array, X: jax.Array) -> jax.Array:
    """Apply per-series weights over window arrays: f32[B, F] ×
    f32[B, T, F] → f32[B, T] (T may be absent)."""
    X = jnp.asarray(X, jnp.float32)
    if X.ndim == W.ndim:
        return jnp.einsum("bf,bf->b", X, W)
    return jnp.einsum("btf,bf->bt", X, W)


def node_loads(state: ClusterState) -> jax.Array:
    """The observed per-node series the online forecaster trains on:
    CPU load as a FRACTION of node capacity, masked to valid nodes.

    Fractions, not millicores: the normal equations accumulate x·xᵀ, so
    raw millicore magnitudes (~2e4 on the reference cluster) square into
    ~4e8 f32 entries where the ridge term vanishes and the solve goes
    ill-conditioned. Capacity-normalized series keep features O(1), the
    ridge meaningful, and the fit scale-free across scenarios.
    """
    cap = jnp.where(state.node_cpu_cap > 0, state.node_cpu_cap, 1.0)
    return jnp.where(state.node_valid, state.node_cpu_used() / cap, 0.0)


def forecast_step(
    state: ClusterState,
    fstate: ForecastState,
    ridge: jax.Array,
    min_skill: jax.Array,
    min_history: jax.Array,
    decay: jax.Array,
    fit_decay: jax.Array,
) -> tuple[ForecastState, jax.Array, jax.Array]:
    """One online round: score last round's predictions, fold the new
    observation into the ridge statistics, and predict the next window.

    The series deliberately includes the controller's OWN move-induced
    jumps, as observations, features, and training targets alike: a
    landed deployment's load spike tends to mean-revert (CAR drains it
    again, autoscaling rebalances), which is exactly the kind of
    structure the difference model can learn — and the persistence
    baseline faces the same jumps, so the skill comparison stays fair.
    (An earlier design excluded "intervention-contaminated" samples; it
    measurably LOWERED skill by deleting the most learnable deltas.)

    Returns ``(fstate', delta, diag)``:

    - ``delta``: f32[N] — the load adjustment the proactive policy adds
      to ``node_base_cpu`` so hazard detection and ``policy_scores`` run
      against the PREDICTED next-window state. Exactly 0.0 wherever the
      model is cold, gated off by skill, or the slot is invalid — the
      reactive-equivalence contract.
    - ``diag``: f32[DIAG_SIZE] — skill / MAEs / accounting for the one
      per-round host pull.

    Fully traced and mask-aware; see the module docstring for the
    contract each piece honors.
    """
    loads = node_loads(state)                        # f32[N]
    valid = state.node_valid
    lags = fstate.history.shape[0] - 1
    feat = lags + 1

    # slot hygiene: a slot whose validity flips ON this round is a NEW
    # series (drained slot re-used, node added) — its history, counts,
    # and normal-equation stats restart from zero so the old tenant's
    # series can never leak into the new one's fit
    fresh = valid & ~fstate.prev_valid & (fstate.steps > 0)
    keep = (~fresh).astype(jnp.float32)
    history = fstate.history * keep[None, :]
    count = fstate.count * keep
    A = fstate.A * keep[:, None, None]
    b = fstate.b * keep[:, None]

    # score LAST round's predictions against today's observation — the
    # shadow model prediction vs the free persistence predictor (last
    # observed value). Only nodes that had a trained prediction AND kept
    # their identity contribute, so the two MAEs are computed over the
    # same sample set and the skill ratio is apples-to-apples.
    prev_obs = history[-1]
    acct_mask = valid & fstate.prev_model_valid & (~fresh)
    acct = acct_mask.astype(jnp.float32)
    n_acct = jnp.sum(acct)
    # where(), not multiply-by-mask: a non-finite shadow prediction on a
    # masked slot would turn inf·0 into NaN and poison the scalar sums
    em = jnp.sum(
        jnp.where(acct_mask, jnp.abs(fstate.prev_model_pred - loads), 0.0)
    )
    ep = jnp.sum(jnp.where(acct_mask, jnp.abs(prev_obs - loads), 0.0))
    has = n_acct > 0
    denom = jnp.maximum(n_acct, 1.0)
    # exponentially-decayed error window (per SCORED round): recent
    # rounds dominate with effective length ~1/(1-decay), so a model
    # that learns re-earns the skill gate instead of dragging its
    # cold-start misses forever. decay == 1 degenerates to cumulative.
    err_model_sum = jnp.where(
        has, decay * fstate.err_model_sum + em / denom, fstate.err_model_sum
    )
    err_persist_sum = jnp.where(
        has, decay * fstate.err_persist_sum + ep / denom,
        fstate.err_persist_sum,
    )
    err_rounds = jnp.where(
        has, decay * fstate.err_rounds + 1.0, fstate.err_rounds
    )

    # ridge accumulation: a node with a full DIFFERENCE window
    # contributes one (features, target) sample — features are the L
    # differences of the window BEFORE today's observation (+ bias), the
    # target is today's observed delta. Regressing deltas on deltas is
    # what makes ridge shrinkage degrade to persistence, not to zero.
    ones_row = jnp.ones((1, history.shape[1]), jnp.float32)

    def features(hist):
        return jnp.concatenate([hist[1:] - hist[:-1], ones_row])

    x_feat = features(history)
    target_delta = loads - history[-1]
    # recursive-least-squares forgetting: contributing nodes decay their
    # statistics so the fit tracks the CURRENT regime instead of
    # averaging over every regime the series ever visited — with a
    # LONGER memory than the skill window (fit_decay vs decay): the
    # noise mean-reversion the model exploits is stationary and rewards
    # accumulated samples, while the skill verdict must react fast
    upd = (valid & (count >= lags + 1)).astype(jnp.float32)
    rho = 1.0 - upd * (1.0 - fit_decay)              # decay where updating
    A = rho[:, None, None] * A + upd[:, None, None] * jnp.einsum(
        "fn,gn->nfg", x_feat, x_feat
    )
    b = rho[:, None] * b + upd[:, None] * (x_feat * target_delta[None, :]).T

    # push today's observation into the rolling window
    history = jnp.concatenate(
        [history[1:], jnp.where(valid, loads, 0.0)[None, :]]
    )
    count = count + valid.astype(jnp.float32)

    # solve the per-node ridge systems and predict the NEXT window from
    # the post-push difference features; negative load predictions clip
    # to zero
    eye = jnp.eye(feat, dtype=jnp.float32)
    W = jnp.linalg.solve(A + ridge * eye, b[..., None])[..., 0]  # f32[N, F]
    x_next = features(history)
    model_pred = jnp.maximum(
        loads + jnp.einsum("nf,fn->n", W, x_next), 0.0
    )
    # the never-NaN contract: a pathological slot (ill-conditioned f32
    # solve despite the ridge) falls back to persistence for THAT node
    # instead of poisoning the round
    model_pred = jnp.where(jnp.isfinite(model_pred), model_pred, loads)

    node_trained = valid & (count >= min_history)
    scored = err_rounds > 0
    skill = jnp.where(
        err_persist_sum > 1e-9,
        1.0 - err_model_sum / jnp.where(err_persist_sum > 1e-9, err_persist_sum, 1.0),
        # no persistence error at all: a perfectly flat (or unscored)
        # series — the model is at worst even, never "winning"
        jnp.where(err_model_sum > 1e-9, -1.0, 0.0),
    )
    skill = jnp.where(scored, skill, 0.0)
    use_model = node_trained & (skill >= min_skill)
    pred = jnp.where(use_model, model_pred, loads)
    # the model works in capacity fractions; the applied delta converts
    # back to millicores so it folds into node_base_cpu. A persistence
    # prediction gives (loads - loads) * cap = exactly 0.0 — the
    # reactive-equivalence contract.
    cap = jnp.where(state.node_cpu_cap > 0, state.node_cpu_cap, 1.0)
    delta = jnp.where(valid, (pred - loads) * cap, 0.0)

    trained_any = jnp.any(node_trained)
    n_valid = jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
    diag = jnp.stack(
        [
            skill,
            err_model_sum / jnp.maximum(err_rounds, 1.0),
            err_persist_sum / jnp.maximum(err_rounds, 1.0),
            err_rounds,
            jnp.sum(use_model.astype(jnp.float32)) / n_valid,
            trained_any.astype(jnp.float32),
        ]
    )
    new_fstate = fstate.replace(
        history=history,
        count=count,
        A=A,
        b=b,
        prev_model_pred=model_pred,
        prev_model_valid=node_trained,
        prev_valid=valid,
        err_model_sum=err_model_sum,
        err_persist_sum=err_persist_sum,
        err_rounds=err_rounds,
        steps=fstate.steps + 1,
    )
    return new_fstate, delta, diag
