"""Fleet mode, forecast plane: per-tenant RLS state batched over tenants.

PR 8's online forecaster carries per-node state (rolling history, the
recursive-least-squares normal-equation statistics, the skill window) —
a pytree that stacks naturally to ``[T, N, ...]``. This module owns that
stacked state and dispatches ONE device program per fleet round that
scores, updates, solves, and predicts for every tenant at once, which is
what lets the multiplexed loop serve ``algorithm='proactive'`` without
paying the per-solve fixed cost per tenant.

Batching is ``lax.map`` over the tenant axis, deliberately NOT ``vmap``:
the map body is the solo ``forecast_step`` traced at exactly the solo
shapes, so every tenant's model state, applied delta, skill verdict,
and diagnostic vector are BIT-EXACT with a solo proactive run under the
same snapshots (vmap re-fuses the elementwise RLS updates and drifts at
the ulp level — measured, and enough to break the parity pin). The
per-tenant work is O(N·F²); a device-side scan over tenants amortizes
the dispatch exactly like the batched decide kernel.

Masking: each tenant's slot carries an ``active`` flag — a skipped
tenant round (open breaker, dark backend) must not fold a filler
snapshot into that tenant's model, exactly as the solo loop's skipped
rounds never reach the forecast plane. Inactive slots pass their state
through untouched and emit a zero delta + zero diag.

The stacked state is a DONATED carry (the solo plane's rule): every
output leaf has the input's shape and the plane replaces its handle
each round, so XLA aliases the ``[T, N, F, F]`` normal-equation block —
the largest resident piece — in place.

The diag matrix (``f32[T, DIAG_SIZE]``) stays device-resident and rides
the fleet round's single counted decision-bundle pull
(``bench.fleet``); :meth:`FleetForecastPlane.decode_diag` turns the
pulled rows into the per-tenant ``RoundRecord.forecast`` blocks.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from kubernetes_rescheduling_tpu.forecast.model import (
    DIAG_FRAC_MODEL,
    DIAG_MAE_MODEL,
    DIAG_MAE_PERSIST,
    DIAG_ROUNDS,
    DIAG_SKILL,
    DIAG_TRAINED,
    ForecastState,
    forecast_step,
    init_forecast_state,
)
from kubernetes_rescheduling_tpu.telemetry.accounting import instrument_jit


def init_fleet_forecast_state(
    lags: int, tenants: int, num_nodes: int
) -> ForecastState:
    """A fresh all-cold forecaster per tenant, stacked ``[T, ...]``."""
    if tenants < 1:
        raise ValueError(f"tenants must be >= 1, got {tenants}")
    f0 = init_forecast_state(lags, num_nodes)
    return jax.tree_util.tree_map(
        lambda x: jnp.tile(x[None], (tenants,) + (1,) * x.ndim), f0
    )


def repad_fleet_forecast_state(
    fstates: ForecastState, num_nodes: int
) -> ForecastState:
    """Grow every tenant's node axis to a promoted bucket capacity —
    the stacked twin of ``repad_forecast_state`` (new slots arrive cold
    and invalid; buckets never demote)."""
    n_old = int(fstates.history.shape[2])
    if num_nodes < n_old:
        raise ValueError(
            f"fleet forecast state cannot shrink ({n_old} -> {num_nodes}); "
            "shape buckets never demote"
        )
    if num_nodes == n_old:
        return fstates
    pad = num_nodes - n_old

    def pad_nodes(x, axis):
        width = [(0, 0)] * x.ndim
        width[axis] = (0, pad)
        return jnp.pad(x, width)

    return fstates.replace(
        history=pad_nodes(fstates.history, 2),
        count=pad_nodes(fstates.count, 1),
        A=pad_nodes(fstates.A, 1),
        b=pad_nodes(fstates.b, 1),
        prev_model_pred=pad_nodes(fstates.prev_model_pred, 1),
        prev_model_valid=pad_nodes(fstates.prev_model_valid, 1),
        prev_valid=pad_nodes(fstates.prev_valid, 1),
    )


def _fleet_forecast_step(
    states,
    fstates: ForecastState,
    tenant_mask: jax.Array,
    ridge: jax.Array,
    min_skill: jax.Array,
    min_history: jax.Array,
    decay: jax.Array,
    fit_decay: jax.Array,
):
    """One fleet forecast round: the solo ``forecast_step`` mapped over
    the tenant axis (see module docstring for why ``lax.map``). Returns
    ``(fstates', deltas f32[T, N], diags f32[T, DIAG_SIZE])``; inactive
    slots (``tenant_mask`` False) pass through untouched with zero
    delta/diag — a skipped tenant round never trains.

    Masking is SELECT-based (compute, then keep the old state),
    deliberately not ``lax.cond``: outlining the step into a cond branch
    re-fuses the RLS accumulation and drifts the statistics at the ulp
    level vs the solo jit (measured — enough to break the bit-exactness
    pin), while a post-step select leaves the step's own computation
    untouched. The discarded work on a masked slot is one tiny
    O(N·F²) solve — skipped tenant rounds are the rare case."""

    def one(args):
        state, fstate, active = args
        new_fstate, delta, diag = forecast_step(
            state, fstate, ridge, min_skill, min_history, decay, fit_decay
        )
        kept = jax.tree_util.tree_map(
            lambda new, old: jnp.where(active, new, old), new_fstate, fstate
        )
        zero = jnp.float32(0.0)
        return (
            kept,
            jnp.where(active, delta, zero),
            jnp.where(active, diag, zero),
        )

    return lax.map(one, (states, fstates, tenant_mask))


# one dispatch per proactive fleet round; donated stacked RLS carry
# (donate_argnums=1 — the solo plane's aliasing rule, fleet-shaped).
# Steady state: jax_traces_total{fn="fleet_forecast"} == 1 + counted
# bucket promotions (the node axis re-pads; nothing else changes shape).
_fleet_forecast = instrument_jit(
    _fleet_forecast_step, name="fleet_forecast", donate_argnums=(1,)
)


class FleetForecastPlane:
    """One per proactive fleet run: owns the stacked per-tenant model
    state across rounds, absorbs bucket promotions by re-padding the
    node axis, and decodes the pulled diag rows into the per-tenant
    forecast blocks the records and metric families consume."""

    def __init__(self, config, tenants: int) -> None:
        self.config = config
        self.tenants = int(tenants)
        self._fstates: ForecastState | None = None
        # traced scalars so every configuration reuses one compiled
        # kernel signature (the solo plane's rule)
        self._ridge = jnp.float32(config.ridge)
        self._min_skill = jnp.float32(config.min_skill)
        self._min_history = jnp.float32(config.min_history)
        self._decay = jnp.float32(config.decay)
        self._fit_decay = jnp.float32(config.fit_decay)

    def observe_and_predict(self, states, tenant_mask: jax.Array):
        """Fold every ACTIVE tenant's observed loads into its model and
        return ``(deltas f32[T, N], diag f32[T, DIAG_SIZE])``, both
        device-resident — the diag must ride the fleet round's single
        counted bundle pull, never its own transfer."""
        n = int(states.node_valid.shape[1])
        if self._fstates is None:
            self._fstates = init_fleet_forecast_state(
                self.config.lags, self.tenants, n
            )
        elif int(self._fstates.history.shape[2]) != n:
            # bucket promotion: one legal retrace (counted elsewhere)
            self._fstates = repad_fleet_forecast_state(self._fstates, n)
        self._fstates, deltas, diag = _fleet_forecast(
            states, self._fstates, tenant_mask, self._ridge,
            self._min_skill, self._min_history, self._decay,
            self._fit_decay,
        )
        return deltas, diag

    @staticmethod
    def decode_diag(row) -> dict:
        """One tenant's pulled diag row -> its ``RoundRecord.forecast``
        block (the solo plane's ``_decode_diag``, per tenant)."""
        trained = bool(row[DIAG_TRAINED] > 0)
        frac = float(row[DIAG_FRAC_MODEL])
        if not trained:
            mode = "cold"
        elif frac > 0:
            mode = "predictive"
        else:
            mode = "degraded"
        return {
            "skill": float(row[DIAG_SKILL]),
            "mae_model": float(row[DIAG_MAE_MODEL]),
            "mae_persistence": float(row[DIAG_MAE_PERSIST]),
            "scored_weight": float(row[DIAG_ROUNDS]),
            "model_node_frac": frac,
            "trained": trained,
            "mode": mode,
            "target": "node_load",
        }
