"""Training datasets from the stack's own telemetry (numpy-only).

Nothing in this module — or the oracle fitter it delegates to — imports
jax, and the ``forecast`` package resolves its jax halves lazily, so the
``telemetry dataset`` CLI mode pays no jax/flax import through this
path (module-level hygiene, like the telemetry package: the CLI process
still loads jax via the package root).

Every soak the harness runs already records what a learned scheduling
plane needs: ``rounds.jsonl`` carries one record per executed round with
the attribution bundle PR 5 writes — per-node ingress/egress shares of
communication cost and the top-k service-edge costs. This module turns a
set of recorded soaks into supervised lag-feature datasets:

- **per-node load series** — each node's total traffic share
  (ingress + egress) per round; a node absent from a round's attribution
  (drained, padded, not yet deployed) is MASKED, not zero-filled, so
  churn never fabricates observations;
- **per-edge traffic series** — each recorded service edge's cost per
  round, keyed ``src->dst``; an edge outside a round's top-k is masked
  (top-k truncation is censoring, not a zero reading).

``difference_windows`` (from :mod:`oracle.forecast`) then yields the
model-form supervision — difference features, delta targets,
persistence base levels, and window validity — that both the numpy
oracle fit and the JAX ``forecast.model.fit_ridge`` consume: one window
shape, two fitters, test-pinned against each other.

The ``telemetry dataset`` CLI mode (:func:`report_dataset`) extracts,
fits the numpy oracle ridge on both families, and reports MAE vs the
persistence baseline — the offline answer to "would a forecaster have
beaten persistence on this recorded run?".
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Iterable

import numpy as np

from kubernetes_rescheduling_tpu.oracle.forecast import (
    difference_windows,
    eval_forecast_np,
    lag_windows,
)

__all__ = [
    "load_rounds",
    "node_load_series",
    "edge_traffic_series",
    "lag_windows",
    "difference_windows",
    "build_dataset",
    "report_dataset",
]


def load_rounds(paths: Iterable[str | Path]) -> list[dict[str, Any]]:
    """Round records from ``rounds.jsonl`` files (or flight-recorder
    bundle JSONs, whose ring nests each record under ``"record"``),
    in file order then line order."""
    out: list[dict[str, Any]] = []
    for path in paths:
        p = Path(path)
        text = p.read_text()
        if p.suffix == ".json":
            doc = json.loads(text)
            ring = doc.get("ring") if isinstance(doc, dict) else None
            for entry in ring or ():
                rec = entry.get("record") if isinstance(entry, dict) else None
                if isinstance(rec, dict):
                    out.append(rec)
            continue
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if isinstance(rec, dict):
                out.append(rec)
    return out


def _attributions(rounds: Iterable[dict[str, Any]]) -> list[dict[str, Any]]:
    return [
        r["attribution"]
        for r in rounds
        if isinstance(r.get("attribution"), dict)
    ]


def node_load_series(
    rounds: Iterable[dict[str, Any]],
) -> tuple[list[str], np.ndarray, np.ndarray]:
    """Per-node traffic-load series from the attribution records.

    Returns ``(names, series, mask)``: series f64[T, B] of
    ingress+egress per node per attributed round, mask bool[T, B] —
    False where the node carried no reading that round (churned away or
    not yet present). Node order is first-appearance order.
    """
    attrs = _attributions(rounds)
    names: list[str] = []
    index: dict[str, int] = {}
    for a in attrs:
        for n in list(a.get("ingress") or ()) + list(a.get("egress") or ()):
            if n not in index:
                index[n] = len(names)
                names.append(n)
    t = len(attrs)
    series = np.zeros((t, len(names)))
    mask = np.zeros((t, len(names)), dtype=bool)
    for i, a in enumerate(attrs):
        ing = a.get("ingress") or {}
        egr = a.get("egress") or {}
        for n in set(ing) | set(egr):
            j = index[n]
            series[i, j] = float(ing.get(n, 0.0)) + float(egr.get(n, 0.0))
            mask[i, j] = True
    return names, series, mask


def edge_traffic_series(
    rounds: Iterable[dict[str, Any]],
) -> tuple[list[str], np.ndarray, np.ndarray]:
    """Per-service-edge traffic series from the attribution top-k rows.

    Returns ``(keys, series, mask)`` with keys ``"src->dst"``; an edge
    missing from a round's recorded top-k is masked (censored by
    truncation), never read as zero traffic.
    """
    attrs = _attributions(rounds)
    keys: list[str] = []
    index: dict[str, int] = {}
    for a in attrs:
        for e in a.get("edges") or ():
            k = f"{e.get('src_service')}->{e.get('dst_service')}"
            if k not in index:
                index[k] = len(keys)
                keys.append(k)
    t = len(attrs)
    series = np.zeros((t, len(keys)))
    mask = np.zeros((t, len(keys)), dtype=bool)
    for i, a in enumerate(attrs):
        for e in a.get("edges") or ():
            k = f"{e.get('src_service')}->{e.get('dst_service')}"
            j = index[k]
            series[i, j] = float(e.get("cost", 0.0))
            mask[i, j] = True
    return keys, series, mask


def build_dataset(
    rounds: Iterable[dict[str, Any]], *, lags: int = 4
) -> dict[str, Any]:
    """Both target families as supervised lag-window arrays.

    Returns ``{"node_load": {...}, "edge_traffic": {...}}`` where each
    family carries ``names``, ``series``/``mask`` (time-major), and the
    ``X``/``y``/``w`` window triples ready for either fitter.
    """
    rounds = list(rounds)
    out: dict[str, Any] = {"lags": lags, "rounds": len(rounds)}
    for family, extract in (
        ("node_load", node_load_series),
        ("edge_traffic", edge_traffic_series),
    ):
        names, series, mask = extract(rounds)
        X, y_delta, base, w = difference_windows(series, mask, lags)
        out[family] = {
            "names": names,
            "series": series,
            "mask": mask,
            "X": X,
            "y_delta": y_delta,
            "base": base,
            "w": w,
        }
    return out


def report_dataset(
    paths: Iterable[str | Path], *, lags: int = 4, ridge: float = 1e-3
) -> str:
    """The ``telemetry dataset`` renderer: extract both families from
    recorded soaks, fit the numpy oracle ridge, and report MAE vs the
    persistence baseline per family. jax-free (oracle fitter only)."""
    rounds = load_rounds(paths)
    attributed = len(_attributions(rounds))
    lines = [
        "forecast dataset",
        f"  rounds: {len(rounds)} ({attributed} with attribution)",
        f"  lags: {lags}  ridge: {ridge}",
    ]
    if attributed == 0:
        lines.append(
            "  no attribution records — run the soak with obs.attribution "
            "on and a logger/ops plane attached (OBSERVABILITY.md)"
        )
        return "\n".join(lines)
    for family, extract in (
        ("node_load", node_load_series),
        ("edge_traffic", edge_traffic_series),
    ):
        names, series, mask = extract(rounds)
        stats = eval_forecast_np(series, mask, lags=lags, ridge=ridge)
        verdict = (
            "beats persistence"
            if stats["skill"] > 0
            else "does NOT beat persistence"
        )
        lines.append(
            f"  {family}: {len(names)} series, {stats['windows']} windows | "
            f"mae model {stats['mae_model']:.4f} vs persistence "
            f"{stats['mae_persistence']:.4f} | skill {stats['skill']:+.3f} "
            f"({verdict})"
        )
    return "\n".join(lines)
