"""Command-line interface.

Fixes the reference's CLI mismatch (README.md:68 documents
``--algorithm CAR`` but main.py:118-125 takes a positional name and calls
CAR ``communication`` — SURVEY.md §2 quirk 6): flags are explicit, ``car``
is accepted as an alias, and the backend/scenario/device are selectable.

Subcommands:
  reschedule  run the control loop once (reference ``python3 main.py <algo>``)
  bench       run the experiment matrix (reference auto_full_pipeline_repeat.sh)
  solve       one-shot global solve on a scenario, printing objectives
  trace       streaming trace replay (external workmodel/trace streams
              or the builtin Bookinfo canary; BASELINE config 5)
  telemetry   summarize a run's telemetry artifacts (metrics JSONL,
              event logs, manifests, Chrome traces, flight-recorder
              bundles) as a report; ``telemetry explain`` renders
              decision explanations, ``telemetry bundle`` summarizes a
              flight-recorder bundle, ``telemetry topo`` renders cost
              attribution / node-pair topology / move provenance

``reschedule``/``bench``/``trace`` take ``--metrics-out``/``--trace-out``:
see OBSERVABILITY.md for the artifact set each flag produces.
``reschedule``/``bench`` additionally take ``--serve PORT`` — the live
ops plane (/metrics, /healthz, /events + flight recorder + SLO watchdog).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

ALGO_ALIASES = {"car": "communication"}


def _norm_algo(name: str) -> str:
    name = name.strip().lower()
    return ALGO_ALIASES.get(name, name)


def _moves_per_round(value: str) -> int | str:
    if value == "all":
        return "all"
    try:
        n = int(value)
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"expected a positive int or 'all', got {value!r}"
        )
    if n < 1:
        raise argparse.ArgumentTypeError("must be >= 1 (or 'all')")
    return n


def _add_resilience_flags(parser: argparse.ArgumentParser) -> None:
    """Fault-injection + degraded-mode knobs, shared by reschedule/bench."""
    parser.add_argument(
        "--chaos-profile", default="none", metavar="NAME",
        help="wrap the loop's backend in the fault-injecting ChaosBackend "
             "under this named profile (none|flaky-monitor|flaky-moves|"
             "node-flap|soak|reconcile); faults are seeded and counted as "
             "chaos_faults_total{kind}",
    )
    parser.add_argument(
        "--chaos-seed", type=int, default=0,
        help="seed for the injected fault stream (reproducible chaos)",
    )
    parser.add_argument(
        "--max-consecutive-failures", type=int, default=5,
        help="circuit breaker threshold: consecutive boundary failures "
             "before the controller opens into safe mode (0 disables the "
             "breaker; retries still apply)",
    )
    parser.add_argument(
        "--churn-profile", default="none", metavar="NAME",
        help="elastic topology churn: mutate the cluster between rounds "
             "under this named seeded profile (none|steady|"
             "diurnal-autoscale|deploy-waves|node-flap) — services "
             "deploy/tear down, replicas autoscale with traffic, nodes "
             "drain/join; shape buckets keep the device kernels at 1 "
             "steady-state trace (sim backend only)",
    )
    parser.add_argument(
        "--churn-seed", type=int, default=0,
        help="seed for the churn event stream (reproducible elasticity)",
    )
    # the reconciliation & admission plane ([reconcile] TOML block):
    # defaults come FROM ReconcileConfig so CLI and programmatic runs
    # can never drift onto different trust boundaries
    from kubernetes_rescheduling_tpu.config import ReconcileConfig

    d = ReconcileConfig()
    parser.add_argument(
        "--no-admission", action="store_true",
        help="disable the snapshot admission guard (bench/admission.py): "
             "monitor() results reach device state UNCLASSIFIED — "
             "NaN/Inf/negative/over-capacity loads, duplicate pods, and "
             "unknown node references go unquarantined (debug only)",
    )
    parser.add_argument(
        "--no-reconcile", action="store_true",
        help="disable the intent ledger (bench/reconcile.py): divergences "
             "between intended and observed placement — lost moves, "
             "wrong-node landings, external drift — go undetected and "
             "unrepaired (debug only)",
    )
    parser.add_argument(
        "--repair-budget", type=int, default=d.repair_budget_per_round,
        help="corrective moves the reconciliation plane may issue per "
             "round to converge observed placement back to intent "
             "(0 = detect and count only, never repair)",
    )


def _add_forecast_flags(parser: argparse.ArgumentParser) -> None:
    """The forecast plane behind --algorithm proactive (reschedule/bench).

    Defaults come FROM the ``ForecastConfig`` dataclass, so a bare CLI
    proactive run and a programmatic/TOML/bench-cell run can never drift
    onto different forecasters (config import stays jax-free)."""
    from kubernetes_rescheduling_tpu.config import ForecastConfig

    d = ForecastConfig()
    parser.add_argument(
        "--forecast-lags", type=int, default=d.lags,
        help="lag-feature window of the online per-node ridge forecaster "
             "(proactive algorithm)",
    )
    parser.add_argument(
        "--forecast-decay", type=float, default=d.decay,
        help="exponential weight of the rolling skill window per scored "
             "round (~1/(1-decay) rounds dominate; 1.0 = cumulative)",
    )
    parser.add_argument(
        "--forecast-ridge", type=float, default=d.ridge,
        help="L2 regularization of the per-node ridge fits (keeps cold "
             "solves well-posed)",
    )
    parser.add_argument(
        "--forecast-min-history", type=int, default=d.min_history,
        help="observations a node needs before its model prediction is "
             "trusted; until then proactive rounds are bit-identical to "
             "reactive CAR (persistence prediction)",
    )
    parser.add_argument(
        "--forecast-min-skill", type=float, default=d.min_skill,
        help="degrade gate: when forecast_skill (1 - mae_model/"
             "mae_persistence) drops below this, proactive rounds fall "
             "back to reactive CAR while the shadow model keeps scoring",
    )


def _forecast_config(args):
    from kubernetes_rescheduling_tpu.config import ForecastConfig

    return ForecastConfig(
        lags=args.forecast_lags,
        ridge=args.forecast_ridge,
        min_history=args.forecast_min_history,
        min_skill=args.forecast_min_skill,
        decay=args.forecast_decay,
    )


def _add_pipeline_flags(parser: argparse.ArgumentParser) -> None:
    """The software-pipelined control loop (reschedule/bench)."""
    parser.add_argument(
        "--pipeline", action="store_true",
        help="run the software-pipelined control loop: the previous "
             "round's single-bundle round-end transfer + record tail "
             "overlap this round's device compute, and the post-move "
             "monitor runs in a background thread — decisions are "
             "bit-identical to the sequential loop (the backend sees "
             "the same call order); only wall-clock changes. Rounds the "
             "pipeline cannot honor (open breaker, pending churn, "
             "streaming graph) drain and run sequentially",
    )
    parser.add_argument(
        "--pipeline-depth", type=int, default=2,
        help="snapshot double-buffer depth of the pipelined loop; only "
             "2 (one round closing while the next decides) is "
             "implemented — other values are rejected so telemetry "
             "never reports a schedule that did not run",
    )
    parser.add_argument(
        "--scan-block", type=int, default=0,
        help="device-resident round scan: advance K steady-state rounds "
             "per compiled dispatch (decide + sim-twin apply + round-end "
             "metrics fused in one lax.scan, ONE counted round_end "
             "transfer per block). Rounds the scan cannot honor — "
             "churn, breaker events, checkpoints, chaos/live backends — "
             "drain to the per-round path "
             "(scan_drains_total{reason}). Requires a pinning greedy "
             "algorithm with one move per round on the sim backend; "
             "mutually exclusive with --pipeline. 0 = off",
    )
    parser.add_argument(
        "--no-scan-tripwires", action="store_true",
        help="disable the in-block tripwire plane (device-side health "
             "predicates inside the scan body: non-finite state/cost "
             "always armed, plus the threshold rules below; a trip "
             "latches the rest of the block to no-move rounds in-trace, "
             "truncates the replay at the trip round, and drains under "
             "scan_drains_total{reason=\"tripwire\"})",
    )
    parser.add_argument(
        "--tripwire-cost-frac", type=float, default=0.0,
        help="tripwire cost_regression rule: communication cost rising "
             "more than this fraction above the block-start baseline "
             "trips the block (0 = rule off)",
    )
    parser.add_argument(
        "--tripwire-load-factor", type=float, default=0.0,
        help="tripwire load_std_spike rule: node-load std exceeding "
             "this factor of the block-start baseline trips the block "
             "(0 = rule off)",
    )
    parser.add_argument(
        "--tripwire-hazard-streak", type=int, default=0,
        help="tripwire hazard_streak rule: the same node detected "
             "most-hazardous this many consecutive rounds trips the "
             "block (0 = rule off)",
    )


def _obs_config(args, **overrides):
    """The ObsConfig a run command builds from its flags (currently the
    tripwire knobs; callers pass fleet overrides like the label budget)."""
    from kubernetes_rescheduling_tpu.config import ObsConfig

    return ObsConfig(
        scan_tripwires=not args.no_scan_tripwires,
        tripwire_cost_frac=args.tripwire_cost_frac,
        tripwire_load_factor=args.tripwire_load_factor,
        tripwire_hazard_streak=args.tripwire_hazard_streak,
        slo_serving_p99_ms=getattr(args, "slo_serving_p99_ms", 0.0),
        slo_mesh_imbalance_ratio=getattr(
            args, "slo_mesh_imbalance_ratio", 0.0
        ),
        profile_rounds=getattr(args, "profile_rounds", 0),
        **overrides,
    )


def _serving_config(args):
    """The ServingConfig a run command builds from its --place* flags
    (None flags fall through to the frozen block's defaults)."""
    from kubernetes_rescheduling_tpu.config import ServingConfig

    base = ServingConfig(enabled=bool(getattr(args, "place", False)))
    overrides = {
        k: v
        for k, v in (
            ("max_batch", getattr(args, "place_max_batch", None)),
            ("queue_depth", getattr(args, "place_queue_depth", None)),
            ("batch_window_ms", getattr(args, "place_window_ms", None)),
            ("deadline_ms", getattr(args, "place_deadline_ms", None)),
        )
        if v is not None
    }
    import dataclasses as _dc

    return _dc.replace(base, **overrides) if overrides else base


def _pipeline_config(args):
    from kubernetes_rescheduling_tpu.config import ControllerConfig

    return ControllerConfig(
        pipeline=args.pipeline, depth=args.pipeline_depth,
        scan_block=args.scan_block,
    )


def _reconcile_config(args):
    from kubernetes_rescheduling_tpu.config import ReconcileConfig

    return ReconcileConfig(
        admission=not args.no_admission,
        enabled=not args.no_reconcile,
        repair_budget_per_round=args.repair_budget,
    )


def _add_telemetry_flags(parser: argparse.ArgumentParser) -> None:
    """The unified observability outputs, shared by every run command."""
    parser.add_argument(
        "--metrics-out", default=None, metavar="PATH",
        help="write the metrics registry as JSONL here, plus a Prometheus "
             "text exposition at <PATH stem>.prom and a run manifest at "
             "<PATH stem>.manifest.json",
    )
    parser.add_argument(
        "--trace-out", default=None, metavar="PATH",
        help="write host-side spans as Chrome trace-event JSON here "
             "(load in ui.perfetto.dev); also triggers the run manifest",
    )


def _add_serve_flags(parser: argparse.ArgumentParser) -> None:
    """The live ops plane (reschedule/bench)."""
    parser.add_argument(
        "--serve", type=int, default=None, metavar="PORT",
        help="serve the live ops plane on 127.0.0.1:PORT while the run "
             "executes: /metrics (Prometheus exposition from the live "
             "registry), /healthz (breaker + SLO + staleness; 503 when "
             "unhealthy), /events (recent structured events), POST /place "
             "(with --place). 0 picks an ephemeral port. Also arms the "
             "flight recorder (bundle on breaker-open/crash/SIGUSR1) and "
             "the SLO watchdog",
    )
    parser.add_argument(
        "--bundle-dir", default=None, metavar="DIR",
        help="where flight-recorder bundles land (default: the obs "
             "config's bundle_dir, ./flight_recorder)",
    )
    parser.add_argument(
        "--place", action="store_true",
        help="serving mode: attach the request-grain placement service "
             "(serving/) behind POST /place on the ops server — admit one "
             "pod/deployment spec per request, score it against the "
             "device-resident state with the run's greedy policy, answer "
             "with placement + explain bundle + per-stage timings. "
             "Requires --serve and a greedy algorithm",
    )
    parser.add_argument(
        "--place-max-batch", type=int, default=None, metavar="B",
        help="serving batcher: static batch shape coalesced dispatches "
             "pad to (default: the [serving] block's max_batch, 8)",
    )
    parser.add_argument(
        "--place-queue-depth", type=int, default=None, metavar="N",
        help="serving admission queue bound; arrivals beyond it shed "
             "immediately (default: the [serving] block's queue_depth, 64)",
    )
    parser.add_argument(
        "--place-window-ms", type=float, default=None, metavar="MS",
        help="serving batch-formation window: how long the batcher holds "
             "the first dequeued request open for company (default: the "
             "[serving] block's batch_window_ms, 2.0)",
    )
    parser.add_argument(
        "--place-deadline-ms", type=float, default=None, metavar="MS",
        help="default per-request deadline; requests still queued past "
             "it complete 'timeout' without occupying a batch slot "
             "(default: the [serving] block's deadline_ms, 250; 0 = none)",
    )
    parser.add_argument(
        "--slo-serving-p99-ms", type=float, default=0.0, metavar="MS",
        help="serving_p99 watchdog rule: rolling-window p99 request "
             "latency above this many ms flips /healthz to 503 and dumps "
             "a flight-recorder bundle with the in-flight request ring "
             "(0 = rule off)",
    )
    parser.add_argument(
        "--profile-rounds", type=int, default=0, metavar="N",
        help="arm one on-demand jax.profiler capture covering the next "
             "N committed rounds (a scan block rounds it up to the "
             "block); the artifact lands as profile_NNN/ under the "
             "flight-recorder bundle dir, hard-capped by the obs "
             "config's profile_max_captures/profile_max_mb. POST "
             "/profile on the ops server arms later captures (0 = none "
             "armed at start)",
    )
    parser.add_argument(
        "--slo-mesh-imbalance-ratio", type=float, default=0.0, metavar="R",
        help="mesh_imbalance watchdog rule: worst/median attributed "
             "device step time above this ratio flips /healthz to 503 "
             "(needs the dp fleet plane's device rollup; >= 1.0, "
             "0 = rule off)",
    )
    _add_slo_flags(parser)


def _add_slo_flags(parser: argparse.ArgumentParser) -> None:
    """SLO v2: the history plane + error-budget engine ([slo] block)."""
    parser.add_argument(
        "--slo", action="store_true",
        help="enable the SLO v2 plane: sample selected registry families "
             "into the bounded history store each round/batch, account "
             "per-SLO error budgets, and page/ticket on multi-window "
             "burn rates (slo_fast_burn / slo_slow_burn watchdog rules, "
             "/slo and /query endpoints with --serve)",
    )
    parser.add_argument(
        "--slo-objective", type=float, default=None, metavar="FRAC",
        help="success-fraction objective every default SLO targets "
             "(default: the [slo] block's objective, 0.99 = 1%% budget)",
    )
    parser.add_argument(
        "--slo-latency-ms", type=float, default=None, metavar="MS",
        help="additionally compile a serving-latency SLO: requests over "
             "this end-to-end threshold burn budget (default: the [slo] "
             "block's latency_threshold_ms, 0 = off)",
    )
    parser.add_argument(
        "--slo-budget-window", type=int, default=None, metavar="TICKS",
        help="error-budget accounting window in ticks — rounds/batches, "
             "not wall time (default: the [slo] block's budget_window, "
             "512)",
    )
    parser.add_argument(
        "--slo-fast-window", type=int, default=None, metavar="TICKS",
        help="fast (page) burn window in ticks; an implicit 1/12 "
             "confirm window rides along (default: the [slo] block's "
             "fast_window, 48)",
    )
    parser.add_argument(
        "--slo-fast-burn", type=float, default=None, metavar="X",
        help="fast burn-rate threshold in budget multiples; both fast "
             "windows over it fire slo_fast_burn (default: the [slo] "
             "block's fast_burn, 14.4; 0 = rule off)",
    )
    parser.add_argument(
        "--slo-slow-window", type=int, default=None, metavar="TICKS",
        help="slow (ticket) burn window in ticks (default: the [slo] "
             "block's slow_window, 288)",
    )
    parser.add_argument(
        "--slo-slow-burn", type=float, default=None, metavar="X",
        help="slow burn-rate threshold; both slow windows over it fire "
             "slo_slow_burn (default: the [slo] block's slow_burn, 6.0; "
             "0 = rule off)",
    )
    parser.add_argument(
        "--slo-series-capacity", type=int, default=None, metavar="N",
        help="history-plane ring points per series (default: the [slo] "
             "block's series_capacity, 512)",
    )
    parser.add_argument(
        "--slo-max-series", type=int, default=None, metavar="N",
        help="history-plane hard global series budget; beyond it the "
             "least-recently-updated ring is evicted and counted "
             "(default: the [slo] block's max_series, 256)",
    )


def _slo_config(args):
    """The SloConfig a run command builds from its --slo* flags (None
    flags fall through to the frozen block's defaults)."""
    from kubernetes_rescheduling_tpu.config import SloConfig

    base = SloConfig(enabled=bool(getattr(args, "slo", False)))
    overrides = {
        k: v
        for k, v in (
            ("objective", getattr(args, "slo_objective", None)),
            ("latency_threshold_ms", getattr(args, "slo_latency_ms", None)),
            ("budget_window", getattr(args, "slo_budget_window", None)),
            ("fast_window", getattr(args, "slo_fast_window", None)),
            ("fast_burn", getattr(args, "slo_fast_burn", None)),
            ("slow_window", getattr(args, "slo_slow_window", None)),
            ("slow_burn", getattr(args, "slo_slow_burn", None)),
            ("series_capacity", getattr(args, "slo_series_capacity", None)),
            ("max_series", getattr(args, "slo_max_series", None)),
        )
        if v is not None
    }
    import dataclasses as _dc

    return _dc.replace(base, **overrides) if overrides else base


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="kubernetes_rescheduling_tpu",
        description="TPU-native communication-aware rescheduling",
    )
    sub = p.add_subparsers(dest="command", required=True)

    workmodel_help = (
        "path to a µBench workmodel JSON (e.g. workmodelC.json); "
        "overrides the scenario's builtin topology"
    )

    r = sub.add_parser("reschedule", help="run the rescheduling control loop")
    r.add_argument("--algorithm", default="communication",
                   help="spread|binpack|random|kubescheduling|communication|"
                        "car|global|proactive (proactive = CAR against the "
                        "forecast-predicted next-window state; --forecast-*)")
    r.add_argument("--backend", default="sim", choices=["sim", "k8s"])
    r.add_argument("--scenario", default="mubench",
                   choices=["mubench", "dense", "powerlaw", "large", "xlarge"])
    r.add_argument("--workmodel", default=None, help=workmodel_help)
    r.add_argument("--rounds", type=int, default=10)
    r.add_argument("--threshold", type=float, default=30.0)
    r.add_argument("--seed", type=int, default=0)
    r.add_argument("--imbalance", action="store_true",
                   help="inject the cordon-style imbalance before starting")
    r.add_argument("--moves-per-round", type=_moves_per_round, default=1,
                   help="deployments moved per round: a positive int "
                        "(1 = reference-faithful) or 'all' (global solve)")
    r.add_argument("--namespace", default="default")
    r.add_argument("--balance-weight", type=float, default=0.0,
                   help="λ: comm-cost edges traded per load-std point "
                        "(global algorithm)")
    r.add_argument("--capacity-frac", type=float, default=None,
                   help="enable capacity enforcement with this packing "
                        "budget (fraction of node capacity)")
    r.add_argument("--restarts", type=int, default=1,
                   help="best-of-N global solves per round over the mesh")
    r.add_argument("--tp", type=int, default=1,
                   help="node-axis devices per solve (SPMD sharded solver)")
    r.add_argument("--move-cost", type=float, default=0.0,
                   help="disruption pricing: comm-weight units per restarted "
                        "pod inside the global solve (0 = moves are free)")
    r.add_argument("--solver-backend", default="dense",
                   choices=["dense", "sparse"],
                   help="pair-weight storage for global rounds (sparse = "
                        "block-local form, breaks the dense memory wall)")
    r.add_argument("--global-moves-cap", type=_moves_per_round, default="all",
                   help="apply only the k highest-gain improving moves per "
                        "global round ('all' = uncapped)")
    r.add_argument("--placement-unit", default="service",
                   choices=["service", "pod"],
                   help="pod = every replica places independently (global "
                        "algorithm, sim backend)")
    r.add_argument("--fleet", type=int, default=0, metavar="N",
                   help="fleet mode: run N same-shaped tenants of the "
                        "scenario under the multiplexed controller — one "
                        "boundary + breaker per tenant, ONE batched device "
                        "solve per round (sim backend, greedy algorithms)")
    r.add_argument("--fleet-plane", default="vmap", choices=["vmap", "dp"],
                   help="device batching for --fleet: 'vmap' (leading "
                        "tenant axis, one program) or 'dp' (one tenant per "
                        "device over the mesh)")
    r.add_argument("--fleet-chaos-tenants", default="", metavar="I,J,...",
                   help="tenant indices the --chaos-profile wraps (empty = "
                        "all tenants) — the per-tenant fault-isolation knob")
    r.add_argument("--tenant-label-budget", type=int, default=None,
                   metavar="N",
                   help="fleet cardinality budget: fleets with more than N "
                        "tenants suppress the per-tenant labeled metric "
                        "series (counted) and observe through the bounded "
                        "device-side rollup families instead (default: the "
                        "obs config's tenant_label_budget, 64)")
    r.add_argument("--shadow", default=None, metavar="TRACE",
                   help="shadow mode: replay a recorded cluster trace (a "
                        "native ClusterTrace .jsonl file, or a directory "
                        "of Alibaba-style machines/containers CSVs or "
                        "Borg-style machine_events/task_usage CSVs), "
                        "recommend moves WITHOUT applying any, and score "
                        "our counterfactual placement against what the "
                        "trace's actual scheduler did (render the "
                        "head-to-head with `telemetry shadow rounds.jsonl`)")
    r.add_argument("--shadow-format", default="auto",
                   choices=["auto", "native", "alibaba", "borg"],
                   help="force the --shadow trace layout (auto detects "
                        "from the path's contents)")
    r.add_argument("--shadow-win-margin", type=float, default=0.0,
                   help="undercut a shadow round must achieve to count as "
                        "a win: counterfactual cost <= actual * (1 - "
                        "margin); 0 = ties count as wins")
    r.add_argument("--perf-ledger", default=None, metavar="PATH",
                   help="append this run's decisions/sec to the perf ledger "
                        "at PATH and judge it with the [perf] block's "
                        "rolling-window detector; a regression arms the "
                        "ops plane's perf_regression rule when --serve is "
                        "active (render trends with `telemetry perf PATH`)")
    _add_resilience_flags(r)
    _add_forecast_flags(r)
    _add_pipeline_flags(r)
    _add_telemetry_flags(r)
    _add_serve_flags(r)

    b = sub.add_parser("bench", help="run the experiment matrix")
    b.add_argument("--backend", default="sim", choices=["sim", "k8s"],
                   help="k8s runs the matrix against the live cluster, like "
                        "the reference's auto_full_pipeline_repeat.sh")
    b.add_argument("--namespace", default="default")
    b.add_argument("--scenario", default="mubench",
                   choices=["mubench", "dense", "powerlaw", "large", "xlarge"])
    b.add_argument("--workmodel", default=None, help=workmodel_help)
    b.add_argument("--algorithms", default="spread,binpack,random,kubescheduling,communication,global")
    b.add_argument("--repeats", type=int, default=5)
    b.add_argument("--rounds", type=int, default=10)
    b.add_argument("--out", default="result")
    b.add_argument("--session", default=None,
                   help="named session: re-running with the same name "
                        "resumes a crashed matrix instead of restarting")
    b.add_argument("--moves-per-round", type=_moves_per_round, default=1)
    b.add_argument("--move-cost", type=float, default=0.0,
                   help="disruption pricing in the global solve (see "
                        "reschedule --move-cost)")
    b.add_argument("--solver-backend", default="dense",
                   choices=["dense", "sparse"],
                   help="pair-weight storage for global rounds")
    b.add_argument("--global-moves-cap", type=_moves_per_round, default="all",
                   help="wave cap for global rounds: apply only the k "
                        "highest-gain moves per round ('all' = uncapped); "
                        "spreads disruption across rounds at most of the "
                        "comm-cost win")
    b.add_argument("--restarts", type=int, default=1,
                   help="best-of-N global solves per round (global algorithm)")
    b.add_argument("--tp", type=int, default=1,
                   help="node-axis devices per solve: each global solve runs "
                        "as the SPMD node-sharded solver over tp devices "
                        "(composes with --restarts as a dp×tp mesh)")
    b.add_argument("--capacity-frac", type=float, default=None,
                   help="enable capacity enforcement with this packing "
                        "budget (fraction of node capacity; global "
                        "algorithm only)")
    b.add_argument("--observe-weights", action="store_true",
                   help="estimate edge weights from the phase-r1 request "
                        "stream's traversal counts and solve on those "
                        "instead of the declared workmodel topology")
    b.add_argument("--placement-unit", default="service",
                   choices=["service", "pod"],
                   help="pod = every replica places independently (global "
                        "algorithm, sim backend)")
    b.add_argument("--seed", type=int, default=0)
    _add_resilience_flags(b)
    _add_forecast_flags(b)
    _add_pipeline_flags(b)
    _add_telemetry_flags(b)
    _add_serve_flags(b)

    t = sub.add_parser(
        "trace",
        help="streaming trace replay: online rescheduling as edge weights "
             "shift (external workmodel + trace stream, or the builtin "
             "Bookinfo canary rollout demo)",
    )
    t.add_argument("--workmodel", default=None,
                   help="external µBench workmodel JSON to replay over "
                        "(default: builtin Bookinfo)")
    t.add_argument("--trace", default=None,
                   help="external trace stream (JSONL, one step per line: "
                        '{"t": 1.0, "weights": [["a", "b", 0.9], ...]}); '
                        "default: the builtin canary schedule")
    t.add_argument("--steps", type=int, default=12,
                   help="builtin canary steps (ignored with --trace)")
    t.add_argument("--replicas", type=int, default=1,
                   help="replicas per service (builtin workmodel only)")
    t.add_argument("--nodes", type=int, default=3)
    t.add_argument("--sweeps", type=int, default=4)
    t.add_argument("--balance-weight", type=float, default=0.5)
    t.add_argument("--capacity-frac", type=float, default=None,
                   help="enable capacity enforcement with this packing "
                        "budget (fraction of node capacity)")
    t.add_argument("--restarts", type=int, default=1,
                   help="best-of-N solves per trace step over the mesh")
    t.add_argument("--seed", type=int, default=0)
    _add_telemetry_flags(t)

    s = sub.add_parser("solve", help="one-shot global solve")
    s.add_argument("--scenario", default="mubench",
                   choices=["mubench", "dense", "powerlaw", "large", "xlarge"])
    s.add_argument("--workmodel", default=None, help=workmodel_help)
    s.add_argument("--sweeps", type=int, default=9)
    s.add_argument("--balance-weight", type=float, default=0.0)
    s.add_argument("--capacity-frac", type=float, default=1.0,
                   help="packing budget as a fraction of node capacity "
                        "(solver feasibility + over-budget repulsion)")
    s.add_argument("--seed", type=int, default=0)
    s.add_argument("--restarts", type=int, default=1,
                   help="best-of-N independent solves, sharded over the "
                        "device mesh (1 = single solve)")
    s.add_argument("--tp", type=int, default=1,
                   help="node-axis devices per solve (SPMD node-sharded "
                        "solver; composes with --restarts as a dp×tp mesh)")
    s.add_argument("--move-cost", type=float, default=0.0,
                   help="disruption pricing: comm-weight units per restarted "
                        "pod (0 = moves are free)")
    s.add_argument("--sparse", action="store_true",
                   help="solve over the sparse block-local pair-weight form "
                        "(breaks the dense-W memory wall; single-solve only)")
    s.add_argument("--placement-unit", default="service",
                   choices=["service", "pod"],
                   help="'pod' places each replica independently (replicas "
                        "may split across nodes — the capability the "
                        "reference's whole-Deployment teardown cannot have)")
    s.add_argument("--latency-budget", type=float, default=None,
                   help="auto-tune the sweep count to fill this many ms of "
                        "device time per round (overrides --sweeps)")

    m = sub.add_parser(
        "telemetry",
        help="summarize telemetry artifacts (metrics JSONL, structured "
             "event logs, manifests, Chrome traces, flight-recorder "
             "bundles) as a readable report; 'telemetry explain <files>' "
             "renders decision explanations, 'telemetry bundle <file>' "
             "summarizes a flight-recorder bundle (incl. the "
             "explain-consistency verdict), 'telemetry topo <files>' "
             "renders cost attribution, the node-pair heatmap, and move "
             "provenance, 'telemetry dataset <rounds.jsonl...>' extracts "
             "forecast training windows from recorded soaks and scores "
             "the oracle ridge fit against the persistence baseline",
    )
    m.add_argument("paths", nargs="+",
                   help="artifact files (kind detected from record shape); "
                        "an optional leading mode word — 'report' "
                        "(default), 'explain', 'bundle', 'perf', 'topo', "
                        "'dataset', 'shadow', or 'fleet' — selects the "
                        "rendering; 'fleet' takes a fleet run's "
                        "structured-event JSONL (or flight-recorder "
                        "bundles) and renders the tenant-rollup quantile "
                        "trend plus the worst-offender table; "
                        "'shadow' takes rounds.jsonl files (or "
                        "flight-recorder bundles) from a --shadow run and "
                        "renders the head-to-head win-rate table against "
                        "the trace's actual scheduler; 'perf' takes "
                        "perf-ledger JSONL files and/or historical "
                        "BENCH_r*.json / MULTICHIP_r*.json snapshots and "
                        "renders the trend table with "
                        "improved/flat/regressed verdicts; 'topo' takes "
                        "rounds.jsonl files or flight-recorder bundles and "
                        "renders the cost-attribution table, node-pair "
                        "heatmap, and move-provenance trail with the "
                        "sum-consistency verdict; 'dataset' takes "
                        "rounds.jsonl files (or flight-recorder bundles) "
                        "and reports the extracted per-node load / "
                        "per-edge traffic training windows with the "
                        "oracle fit's skill vs persistence")
    m.add_argument("--perf-window", type=int, default=5,
                   help="perf mode: prior readings each series is judged "
                        "against")
    m.add_argument("--perf-threshold", type=float, default=0.2,
                   help="perf mode: fraction above baseline that counts as "
                        "a regression")
    m.add_argument("--perf-baseline", default="median",
                   choices=["median", "best"],
                   help="perf mode: judge against the window's median or "
                        "its best reading")
    m.add_argument("--dataset-lags", type=int, default=4,
                   help="dataset mode: lag-feature window length of the "
                        "extracted training windows")
    m.add_argument("--dataset-ridge", type=float, default=1e-3,
                   help="dataset mode: L2 term of the offline oracle fit "
                        "scored against the persistence baseline")
    return p


def _write_telemetry_artifacts(args) -> dict | None:
    """Flush the process registry/tracer to the paths the run asked for.
    Returns the manifest (also written next to the metrics dump) so the
    command's JSON output can reference what was recorded."""
    metrics_out = getattr(args, "metrics_out", None)
    trace_out = getattr(args, "trace_out", None)
    if not metrics_out and not trace_out:
        return None
    from kubernetes_rescheduling_tpu.telemetry import (
        get_registry,
        get_tracer,
        write_manifest,
    )

    if metrics_out:
        registry = get_registry()
        registry.dump_jsonl(metrics_out)
        registry.write_exposition(Path(metrics_out).with_suffix(".prom"))
    if trace_out:
        get_tracer().export_chrome(trace_out)
    anchor = Path(metrics_out if metrics_out else trace_out)
    config = {
        k: v for k, v in vars(args).items()
        if k != "command" and not callable(v)
    }
    config["command"] = args.command
    return write_manifest(anchor.with_suffix(".manifest.json"), config)


def cmd_telemetry(args) -> str:
    from kubernetes_rescheduling_tpu.telemetry.report import (
        report,
        report_bundle,
        report_explain,
        report_perf,
        report_topo,
    )  # report_shadow resolves below, with the mode word

    mode, paths = "report", list(args.paths)
    if paths and paths[0] in (
        "report", "explain", "bundle", "perf", "topo", "dataset", "shadow",
        "fleet", "slo",
    ):
        mode, paths = paths[0], paths[1:]
    if not paths:
        raise SystemExit(f"telemetry {mode}: no artifact paths given")
    if mode == "slo":
        from kubernetes_rescheduling_tpu.telemetry.report import report_slo

        return report_slo(paths)
    if mode == "shadow":
        from kubernetes_rescheduling_tpu.telemetry.report import report_shadow

        return report_shadow(paths)
    if mode == "fleet":
        from kubernetes_rescheduling_tpu.telemetry.report import report_fleet

        return report_fleet(paths)
    if mode == "dataset":
        # forecast training windows from recorded soaks — the numpy-only
        # dataset module + oracle fitter (the forecast package resolves
        # its jax halves lazily, so this mode never imports them)
        from kubernetes_rescheduling_tpu.forecast.dataset import report_dataset

        return report_dataset(
            paths, lags=args.dataset_lags, ridge=args.dataset_ridge
        )
    if mode == "explain":
        return report_explain(paths)
    if mode == "bundle":
        return report_bundle(paths)
    if mode == "topo":
        return report_topo(paths)
    if mode == "perf":
        return report_perf(
            paths,
            window=args.perf_window,
            threshold_frac=args.perf_threshold,
            baseline=args.perf_baseline,
        )
    return report(paths)


def _build_ops_plane(args, config):
    """The live ops plane for a run command (``--serve``); None when off.
    Returns (ops, logger): the logger feeds /events and decision events."""
    if args.serve is None:
        return None, None
    import dataclasses as _dc

    from kubernetes_rescheduling_tpu.telemetry import OpsPlane
    from kubernetes_rescheduling_tpu.utils.logging import get_logger

    obs = _dc.replace(config.obs, serve_port=args.serve)
    logger = get_logger()
    ops = OpsPlane.from_config(
        obs, slo=config.slo, logger=logger, bundle_dir=args.bundle_dir
    ).start()
    port = ops.server.port if ops.server is not None else None
    if port is not None:
        sys.stderr.write(
            f"ops plane: http://127.0.0.1:{port}/metrics /healthz /events\n"
        )
    return ops, logger


def _reschedule_perf(args, cfg, result, ops, algo) -> dict | None:
    """The ``[perf]``/``--perf-ledger`` consumer on the reschedule path:
    append this run's decisions/sec, judge every series with the block's
    knobs, arm the ops plane's perf_regression rule, and return the
    verdict statuses for the command's JSON output."""
    if not (cfg.perf.enabled and cfg.perf.ledger_path):
        return None
    import dataclasses as _dc

    import jax

    from kubernetes_rescheduling_tpu.telemetry import perf_ledger as pl

    ledger = pl.PerfLedger(cfg.perf.ledger_path)
    # seed excluded: repeated runs of the same setup form ONE series
    digest_src = {
        k: v for k, v in _dc.asdict(cfg).items() if k not in ("seed", "perf")
    }
    ledger.append(
        metric="decisions_per_sec",
        value=result.decisions_per_sec,
        unit="1/s",
        scenario=f"{getattr(args, 'scenario', 'k8s')}/{algo}",
        device_kind=jax.devices()[0].platform,
        config=digest_src,
        better="higher",
        seed=cfg.seed,
    )
    verdicts = pl.detect(
        ledger.entries(),
        window=cfg.perf.window,
        threshold_frac=cfg.perf.regression_frac,
        baseline=cfg.perf.baseline,
        min_history=cfg.perf.min_history,
    )
    if ops is not None:
        ops.observe_perf(verdicts)
    return {k: v["status"] for k, v in sorted(verdicts.items())}


def _parse_tenant_list(raw: str) -> tuple[int, ...]:
    try:
        return tuple(int(x) for x in raw.split(",") if x.strip())
    except ValueError:
        raise SystemExit(
            f"--fleet-chaos-tenants must be comma-separated ints, got {raw!r}"
        ) from None


def cmd_fleet_reschedule(args, algo: str) -> dict:
    """The ``reschedule --fleet N`` path: N tenants of the scenario under
    the multiplexed controller, reporting per-tenant round streams plus
    the amortized batched-solve cost."""
    import jax

    from kubernetes_rescheduling_tpu.backends.fleet import make_fleet
    from kubernetes_rescheduling_tpu.bench.fleet import run_fleet_controller
    from kubernetes_rescheduling_tpu.config import (
        ChaosConfig,
        ElasticConfig,
        FleetConfig,
        RescheduleConfig,
    )

    if args.backend != "sim":
        raise SystemExit(
            "--fleet requires the sim backend (one live cluster is one "
            "tenant; fleet mode multiplexes hermetic tenants)"
        )
    if args.perf_ledger:
        # fail loudly rather than silently dropping a documented flag —
        # the solo path's decisions/sec series has no fleet consumer yet
        raise SystemExit(
            "--perf-ledger is not supported with --fleet yet (the fleet "
            "headline rides the BENCH_SCENARIO=fleet cell's ledger "
            "append instead)"
        )
    # every solver-shaping flag flows into the config so the fleet
    # validation actually sees it: --fleet with --moves-per-round 3 or
    # --placement-unit pod must REJECT, not silently run something else
    cfg = RescheduleConfig(
        algorithm=algo,
        max_rounds=args.rounds,
        hazard_threshold_pct=args.threshold,
        sleep_after_action_s=0.0,
        moves_per_round=args.moves_per_round,
        global_moves_cap=args.global_moves_cap,
        balance_weight=args.balance_weight,
        move_cost=args.move_cost,
        solver_backend=args.solver_backend,
        placement_unit=args.placement_unit,
        solver_restarts=args.restarts,
        solver_tp=args.tp,
        seed=args.seed,
        chaos=ChaosConfig(profile=args.chaos_profile, seed=args.chaos_seed),
        elastic=ElasticConfig(
            profile=args.churn_profile, seed=args.churn_seed
        ),
        max_consecutive_failures=args.max_consecutive_failures,
        controller=_pipeline_config(args),
        reconcile=_reconcile_config(args),
        fleet=FleetConfig(
            tenants=args.fleet,
            plane=args.fleet_plane,
            chaos_tenants=_parse_tenant_list(args.fleet_chaos_tenants),
        ),
        obs=(
            _obs_config(args, tenant_label_budget=args.tenant_label_budget)
            if args.tenant_label_budget is not None
            else _obs_config(args)
        ),
        slo=_slo_config(args),
    )
    try:
        cfg.validate()
    except ValueError as e:
        # a clean CLI exit before any tenant backends are built
        raise SystemExit(f"--fleet: {e}") from None
    fleet = make_fleet(
        args.scenario, args.fleet, seed=args.seed,
        workmodel_path=args.workmodel,
    )
    if args.imbalance:
        fleet.inject_imbalance()
    ops, logger = _build_ops_plane(args, cfg)
    try:
        result = run_fleet_controller(
            fleet, cfg, key=jax.random.PRNGKey(args.seed),
            logger=logger, ops=ops,
        )
    finally:
        if ops is not None:
            ops.close()
    return {
        "algorithm": algo,
        "fleet": {"tenants": args.fleet, "plane": args.fleet_plane},
        "batched_solves": result.batched_solves,
        "amortized_solve_ms_per_tenant_round": round(
            result.amortized_solve_ms_per_tenant_round, 4
        ),
        "per_tenant": {
            name: {
                "rounds": len(r.rounds),
                "skipped_rounds": r.skipped_rounds,
                "degraded_rounds": r.degraded_rounds,
                "moves": r.moves,
                "boundary_failures": r.boundary_failures,
                "final_communication_cost": (
                    r.rounds[-1].communication_cost if r.rounds else None
                ),
                "final_load_std": (
                    r.rounds[-1].load_std if r.rounds else None
                ),
            }
            for name, r in result.results.items()
        },
    }


def cmd_reschedule(args) -> dict:
    import jax

    from kubernetes_rescheduling_tpu.bench.controller import run_controller
    from kubernetes_rescheduling_tpu.bench.harness import make_backend
    from kubernetes_rescheduling_tpu.config import (
        ChaosConfig,
        ElasticConfig,
        PerfConfig,
        RescheduleConfig,
        ShadowConfig,
    )

    algo = _norm_algo(args.algorithm)
    if args.shadow:
        # config.validate() rejects the same compositions; surface them
        # as clean CLI exits before any trace parsing
        for flag, why in (
            (args.fleet, "--fleet (no per-tenant counterfactual twin)"),
            (args.backend == "k8s", "--backend k8s (the trace IS the cluster)"),
            (args.churn_profile != "none",
             "--churn-profile (the trace replays recorded churn)"),
            (args.chaos_profile != "none",
             "--chaos-profile (corrupting the replayed trace poisons "
             "the head-to-head scores)"),
            (args.imbalance,
             "--imbalance (recorded state cannot be mutated)"),
            (args.placement_unit == "pod",
             "--placement-unit pod (shadow scoring is service-granular)"),
            (args.no_admission,
             "--no-admission (replayed snapshots must ride the guard)"),
        ):
            if flag:
                raise SystemExit(f"--shadow is incompatible with {why}")
    if args.place and args.fleet:
        raise SystemExit(
            "--place is a solo-loop plane: serving scores against ONE "
            "backend's snapshot (per-tenant serving is future work)"
        )
    if args.place and args.shadow:
        raise SystemExit(
            "--place is incompatible with --shadow: the replay backend's "
            "fresh-snapshot contract cannot feed a second consumer"
        )
    if args.fleet:
        return cmd_fleet_reschedule(args, algo)
    if args.backend == "k8s" and args.churn_profile != "none":
        # config.validate() raises the same rule; surface it as the
        # CLI's clean exit instead of a traceback
        raise SystemExit(
            "--churn-profile requires the sim backend: a live cluster "
            "churns itself"
        )
    if args.backend == "k8s" and args.placement_unit == "pod":
        # fail before any cluster work: K8sBackend rejects per-pod moves
        # (the Deployment mechanism cannot pin one replica), so the run
        # would otherwise crash mid-round after solving the pod graph
        raise SystemExit(
            "--placement-unit pod requires the sim backend: the k8s "
            "Deployment mechanism cannot pin a single replica"
        )
    if args.shadow:
        from kubernetes_rescheduling_tpu.backends.replay import ReplayBackend
        from kubernetes_rescheduling_tpu.traces.adapters import (
            load_shadow_trace,
        )

        backend = ReplayBackend(
            load_shadow_trace(args.shadow, fmt=args.shadow_format)
        )
    elif args.backend == "k8s":
        from kubernetes_rescheduling_tpu.backends.k8s import K8sBackend
        from kubernetes_rescheduling_tpu.core.workmodel import (
            Workmodel,
            mubench_workmodel_c,
        )

        wm = (
            Workmodel.from_file(args.workmodel)
            if args.workmodel
            else mubench_workmodel_c()
        )
        backend = K8sBackend(workmodel=wm, namespace=args.namespace)
    else:
        backend = make_backend(args.scenario, args.seed, workmodel_path=args.workmodel)
        if args.imbalance:
            backend.inject_imbalance(backend.node_names[0])
    cfg = RescheduleConfig(
        algorithm=algo,
        max_rounds=args.rounds,
        hazard_threshold_pct=args.threshold,
        sleep_after_action_s=0.0 if args.backend == "sim" else 15.0,
        moves_per_round=args.moves_per_round,
        global_moves_cap=args.global_moves_cap,
        balance_weight=args.balance_weight,
        move_cost=args.move_cost,
        solver_backend=args.solver_backend,
        placement_unit=args.placement_unit,
        enforce_capacity=args.capacity_frac is not None,
        capacity_frac=args.capacity_frac if args.capacity_frac is not None else 1.0,
        solver_restarts=args.restarts,
        solver_tp=args.tp,
        seed=args.seed,
        backend="replay" if args.shadow else args.backend,
        chaos=ChaosConfig(profile=args.chaos_profile, seed=args.chaos_seed),
        elastic=ElasticConfig(
            profile=args.churn_profile, seed=args.churn_seed
        ),
        max_consecutive_failures=args.max_consecutive_failures,
        forecast=_forecast_config(args),
        controller=_pipeline_config(args),
        reconcile=_reconcile_config(args),
        shadow=ShadowConfig(
            enabled=bool(args.shadow), win_margin=args.shadow_win_margin
        ),
        perf=PerfConfig(ledger_path=args.perf_ledger),
        obs=_obs_config(args),
        serving=_serving_config(args),
        slo=_slo_config(args),
    )
    ops, logger = _build_ops_plane(args, cfg)
    engine = None
    if args.place:
        # config.validate() rejects the same compositions; surface them
        # as clean CLI exits before any engine work
        if args.serve is None:
            raise SystemExit(
                "--place requires --serve PORT: the ops plane's HTTP "
                "server is the serving front (POST /place)"
            )
        from kubernetes_rescheduling_tpu.config import POLICIES
        from kubernetes_rescheduling_tpu.serving import ServingEngine

        if algo not in POLICIES:
            raise SystemExit(
                "--place requires a greedy algorithm (the serving plane "
                f"scores requests with the greedy machinery): got {algo!r}"
            )
        engine = ServingEngine(
            backend,
            config=cfg.serving,
            policy=algo,
            threshold=cfg.hazard_threshold_pct,
            seed=cfg.seed,
            top_k=cfg.obs.explain_top_k,
            ops=ops,
        ).start()
        ops.bind_serving(engine)
        sys.stderr.write(
            f"serving: POST http://127.0.0.1:{ops.server.port}/place "
            f"{{\"service\": <name>}}\n"
        )
    try:
        result = run_controller(
            backend, cfg, key=jax.random.PRNGKey(args.seed),
            logger=logger, ops=ops,
        )
        perf = _reschedule_perf(args, cfg, result, ops, algo)
    finally:
        if engine is not None:
            engine.stop()
        if ops is not None:
            ops.close()
    out = {
        "algorithm": algo,
        "rounds": [rec.as_dict() for rec in result.rounds],
        "moves": result.moves,
        "decisions_per_sec": result.decisions_per_sec,
        "skipped_rounds": result.skipped_rounds,
        "degraded_rounds": result.degraded_rounds,
        "boundary_failures": result.boundary_failures,
        "breaker_transitions": result.breaker_transitions,
    }
    if perf is not None:
        out["perf"] = perf
    if args.shadow:
        blocks = [r.shadow for r in result.rounds if r.shadow]
        deltas = [b["cost_delta"] for b in blocks]
        out["shadow"] = {
            "trace": args.shadow,
            "recommendations": len(backend.recommendations),
            "scored_rounds": len(blocks),
            "wins": sum(1 for b in blocks if b.get("win")),
            "win_rate": blocks[-1]["win_rate"] if blocks else None,
            "mean_cost_delta": (
                sum(deltas) / len(deltas) if deltas else None
            ),
        }
    return out


def cmd_bench(args) -> dict:
    from kubernetes_rescheduling_tpu.bench.harness import ExperimentConfig, run_experiment

    if args.backend == "k8s" and args.placement_unit == "pod":
        # ExperimentConfig would raise the same rule at construction;
        # surface it as the CLI's clean exit instead of a traceback
        raise SystemExit(
            "--placement-unit pod requires the sim backend: the k8s "
            "Deployment mechanism cannot pin a single replica"
        )
    cfg = ExperimentConfig(
        algorithms=tuple(_norm_algo(a) for a in args.algorithms.split(",") if a),
        repeats=args.repeats,
        rounds=args.rounds,
        scenario=args.scenario,
        backend=args.backend,
        namespace=args.namespace,
        workmodel=args.workmodel,
        out_dir=args.out,
        session_name=args.session,
        moves_per_round=args.moves_per_round,
        global_moves_cap=args.global_moves_cap,
        move_cost=args.move_cost,
        solver_backend=args.solver_backend,
        placement_unit=args.placement_unit,
        solver_restarts=args.restarts,
        solver_tp=args.tp,
        observe_weights=args.observe_weights,
        enforce_capacity=args.capacity_frac is not None,
        capacity_frac=args.capacity_frac if args.capacity_frac is not None else 1.0,
        seed=args.seed,
        chaos_profile=args.chaos_profile,
        chaos_seed=args.chaos_seed,
        max_consecutive_failures=args.max_consecutive_failures,
        churn_profile=args.churn_profile,
        churn_seed=args.churn_seed,
        forecast=_forecast_config(args),
        pipeline=args.pipeline,
        pipeline_depth=args.pipeline_depth,
        scan_block=args.scan_block,
        reconcile=_reconcile_config(args),
        serve_port=args.serve,
        bundle_dir=args.bundle_dir,
    )
    return run_experiment(cfg)


def cmd_trace(args) -> dict:
    import jax

    from kubernetes_rescheduling_tpu.bench.trace import (
        bookinfo_workmodel,
        canary_trace,
        load_trace,
        replay,
    )
    from kubernetes_rescheduling_tpu.core.topology import state_from_workmodel
    from kubernetes_rescheduling_tpu.core.workmodel import Workmodel
    from kubernetes_rescheduling_tpu.solver import GlobalSolverConfig

    wm = (
        Workmodel.from_file(args.workmodel)
        if args.workmodel
        else bookinfo_workmodel(replicas=args.replicas)
    )
    steps = (
        load_trace(args.trace) if args.trace else canary_trace(steps=args.steps)
    )
    state = state_from_workmodel(
        wm,
        node_names=[f"worker{i}" for i in range(args.nodes)],
        node_cpu_cap_m=20_000.0,
        seed=args.seed,
    )
    _, records = replay(
        state,
        wm.comm_graph(),
        steps,
        key=jax.random.PRNGKey(args.seed),
        config=GlobalSolverConfig(
            sweeps=args.sweeps,
            balance_weight=args.balance_weight,
            enforce_capacity=args.capacity_frac is not None,
            capacity_frac=(
                args.capacity_frac if args.capacity_frac is not None else 1.0
            ),
        ),
        restarts=args.restarts,
    )
    return {
        "workmodel": wm.source,
        "trace": args.trace or f"builtin:canary[{args.steps}]",
        "balance_weight": args.balance_weight,
        "restarts": args.restarts,
        "steps": [r.__dict__ for r in records],
        "total_moves": sum(r.moves for r in records),
        "final_cost": records[-1].cost_after_solve if records else None,
    }


def cmd_solve(args) -> dict:
    import jax

    from kubernetes_rescheduling_tpu.bench.harness import make_backend
    from kubernetes_rescheduling_tpu.objectives import communication_cost, load_std
    from kubernetes_rescheduling_tpu.parallel import solve_with_restarts
    from kubernetes_rescheduling_tpu.solver import GlobalSolverConfig

    backend = make_backend(args.scenario, args.seed, workmodel_path=args.workmodel)
    state = backend.monitor()
    graph = backend.comm_graph()
    cfg = GlobalSolverConfig(
        sweeps=args.sweeps,
        balance_weight=args.balance_weight,
        capacity_frac=args.capacity_frac,
        move_cost=args.move_cost,
    )
    # `solve_graph` is whatever pytree the chosen solver consumes as its
    # graph ARGUMENT — it must flow through call signatures, never a
    # closure: a closed-over sparse/pod graph would be baked into the
    # autotuner's jit as HLO constants (tens of MB → remote-compile 413)
    tune_info = None
    solve_graph = graph
    if args.placement_unit == "pod":
        from kubernetes_rescheduling_tpu.solver.pod_mode import (
            global_assign_pods,
            pod_level_graph,
        )

        solve_graph = pod_level_graph(state, graph)

        def solver(st, g, k, c):
            # the full production matrix: dp restarts, tp node-sharding,
            # and their composition all route through the pod graph
            return global_assign_pods(
                st, None, k, c, pod_graph=g,
                n_restarts=args.restarts, tp=args.tp,
            )

    elif args.sparse:
        from kubernetes_rescheduling_tpu.core import sparsegraph
        from kubernetes_rescheduling_tpu.solver import global_assign_sparse

        solve_graph = sparsegraph.from_comm_graph(graph)
        solver = global_assign_sparse
    else:
        from kubernetes_rescheduling_tpu.solver import global_assign as solver
    if args.latency_budget is not None:
        from kubernetes_rescheduling_tpu.solver.autotune import tune_sweeps

        # tune against the ACTUAL production path: with --restarts/--tp the
        # per-round program is the mesh solve, not the single-chip solver —
        # budgeting the wrong (slower) program would systematically
        # under-fill the latency budget
        if args.placement_unit == "pod":
            tune_solver = solver
        elif args.sparse:

            def tune_solver(st, g, k, c):
                return solve_with_restarts(
                    st, None, k, n_restarts=args.restarts, config=c,
                    tp=args.tp, sparse_graph=g,
                )

        else:

            def tune_solver(st, g, k, c):
                return solve_with_restarts(
                    st, g, k, n_restarts=args.restarts, config=c, tp=args.tp
                )

        cfg, tune_info = tune_sweeps(
            state, solve_graph, cfg, args.latency_budget, solver=tune_solver
        )
    if args.placement_unit == "pod":
        new_state, info = solver(
            state, solve_graph, jax.random.PRNGKey(args.seed), cfg
        )
        info = dict(info)
        info.setdefault("restarts", 1)
    else:
        new_state, info = solve_with_restarts(
            state,
            graph,
            jax.random.PRNGKey(args.seed),
            n_restarts=args.restarts,
            config=cfg,
            tp=args.tp,
            sparse_graph=solve_graph if args.sparse else None,
        )
    out = {
        "scenario": args.scenario,
        "restarts": int(info["restarts"]),
        "tp": int(info["tp"]) if "tp" in info else 1,
        "communication_cost_before": float(communication_cost(state, graph)),
        "communication_cost_after": float(communication_cost(new_state, graph)),
        "load_std_before": float(load_std(state)),
        "load_std_after": float(load_std(new_state)),
    }
    if "moves_per_sweep" in info:
        out["moves_per_sweep"] = [int(m) for m in info["moves_per_sweep"]]
    if "restart_objectives" in info:
        out["restart_objectives"] = [float(o) for o in info["restart_objectives"]]
    if args.move_cost > 0 and "move_penalty" in info:
        out["move_cost"] = args.move_cost
        out["move_penalty"] = float(info["move_penalty"])
    if args.sparse:
        out["sparse"] = True
    if args.placement_unit != "service":
        out["placement_unit"] = args.placement_unit
    if tune_info is not None:
        out["autotune"] = tune_info
        out["sweeps"] = tune_info["sweeps"]
    return out


def main(argv: list[str] | None = None) -> int:
    # Honor JAX_PLATFORMS even when a site hook pre-imported jax and pinned
    # an accelerator plugin (the env var only applies before first backend
    # init; the config update applies after). Lets operators run the CLI on
    # a forced-CPU mesh: XLA_FLAGS=--xla_force_host_platform_device_count=8
    # JAX_PLATFORMS=cpu python -m kubernetes_rescheduling_tpu solve --tp 2
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax

        try:
            jax.config.update("jax_platforms", plat)
        except Exception as e:
            # the run continues on whatever platform is pinned — say so
            # instead of silently ignoring the operator's explicit choice
            print(
                f"warning: could not apply JAX_PLATFORMS={plat!r} ({e}); "
                f"running on {jax.default_backend()}",
                file=sys.stderr,
            )
    args = build_parser().parse_args(argv)
    handler = {
        "reschedule": cmd_reschedule,
        "bench": cmd_bench,
        "solve": cmd_solve,
        "trace": cmd_trace,
        "telemetry": cmd_telemetry,
    }[args.command]
    out = handler(args)
    _write_telemetry_artifacts(args)
    if isinstance(out, str):  # the telemetry report is already human text
        print(out)
        return 0
    json.dump(out, sys.stdout, indent=2, default=float)
    print()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
