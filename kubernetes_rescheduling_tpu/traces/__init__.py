"""Trace corpus: recorded real-cluster data as a first-class input.

Everything the repo measured before this package came from its own
simulator. The corpus layer defines ONE normalized on-disk form — the
``ClusterTrace`` JSONL schema (``traces.corpus``) — plus adapters from
the public cluster-trace layouts (Alibaba cluster-trace-style and
Borg-ClusterData-style CSVs, ``traces.adapters``) and a converter from
our own recorded ``rounds.jsonl`` soaks. ``backends.replay.ReplayBackend``
serves a loaded trace through the standard ``Backend`` surface so the
unchanged control loop can run against recorded production data in
shadow mode (``bench.shadow``): recommend, never apply, score against
what the real scheduler actually did.

jax-free at module level (the corpus builds host-side numpy; states
convert at ``ClusterState.build``), like the telemetry package.
"""

from kubernetes_rescheduling_tpu.traces.corpus import (
    ClusterTrace,
    TraceWindow,
    dump_trace_jsonl,
    load_trace_jsonl,
    parse_records,
    window_state,
)
from kubernetes_rescheduling_tpu.traces.adapters import (
    load_alibaba_csv,
    load_borg_csv,
    load_shadow_trace,
    rounds_to_trace,
)

__all__ = [
    "ClusterTrace",
    "TraceWindow",
    "dump_trace_jsonl",
    "load_trace_jsonl",
    "parse_records",
    "window_state",
    "load_alibaba_csv",
    "load_borg_csv",
    "load_shadow_trace",
    "rounds_to_trace",
]
