"""The ``ClusterTrace`` JSONL schema and its loader/builder.

One record per line, four kinds, grouped into **windows** by timestamp
(records sharing a ``t`` form one snapshot window; timestamps must be
monotone non-decreasing — ``scripts/check_trace_schema.py`` enforces the
schema over checked-in fixtures):

``{"kind": "node", "t": 0.0, "node": "m1", "cpu_cap_m": 4000.0,
"mem_cap_b": 8.0e9, "cpu_used_m": 900.0, "mem_used_b": 1.0e9,
"alive": true}``
    Node capacity + measured usage. Node records CARRY FORWARD: a node
    described once keeps its latest capacity/alive status in every later
    window until a new record updates it (real traces emit machine
    events sparsely). ``cpu_used_m``/``mem_used_b`` are the node's total
    measured usage — the window's base (untracked) load is derived as
    ``max(used − Σ tracked pod usage, 0)``, the k8s adapter's rule.

``{"kind": "pod", "t": 0.0, "pod": "svc-a-0", "service": "svc-a",
"node": "m1", "cpu_m": 250.0, "mem_b": 2.0e8}``
    One tracked pod in this window. Pods are restated per window (a
    window's pod set IS its snapshot); ``node: null`` means unscheduled.

``{"kind": "edge", "t": 0.0, "a": "svc-a", "b": "svc-b", "w": 1.0}``
    Optional service↔service communication weight (symmetric; the
    latest record per unordered pair wins). Public cluster traces carry
    no call graph — a trace with no edge records gets the uniform
    complete graph over its services, documented as such, so the
    comm-cost objective rewards consolidation rather than silently
    reading zero.

``{"kind": "placement", "t": 30.0, "pod": "svc-a-0", "node": "m2"}``
    Informational: a placement decision the REAL scheduler made between
    windows (the next window's pod records already reflect it). The
    ``rounds_to_trace`` converter emits these from ``applied_moves``.

Malformed rows — broken JSON, unknown kinds, missing identity fields,
non-finite timestamps, pod references to nodes the trace never declares,
out-of-order timestamps (repaired by a stable re-sort) — are
**quarantined and counted** (``trace_rows_quarantined_total{reason}``),
never a crash: real traces are dirty by nature. Value-level poison
(NaN/Inf/negative/over-capacity usage readings) is deliberately KEPT in
the built snapshots — that is the PR-10 ``AdmissionGuard``'s job, and
routing it there keeps one quarantine discipline for live and replayed
data alike.
"""

from __future__ import annotations

import json
import math
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable

from kubernetes_rescheduling_tpu.telemetry.registry import get_registry

KIND_NODE = "node"
KIND_POD = "pod"
KIND_EDGE = "edge"
KIND_PLACEMENT = "placement"
KINDS = (KIND_NODE, KIND_POD, KIND_EDGE, KIND_PLACEMENT)

# identity fields a record cannot be used without (value fields may be
# absent or poisoned — admission handles values; these handle identity)
REQUIRED_FIELDS = {
    KIND_NODE: ("node",),
    KIND_POD: ("pod", "service"),
    KIND_EDGE: ("a", "b"),
    KIND_PLACEMENT: ("pod", "node"),
}

REASON_BAD_JSON = "bad_json"
REASON_NOT_OBJECT = "not_object"
REASON_UNKNOWN_KIND = "unknown_kind"
REASON_MISSING_FIELD = "missing_field"
REASON_BAD_TIMESTAMP = "bad_timestamp"
REASON_UNKNOWN_NODE_REF = "unknown_node_ref"
REASON_OUT_OF_ORDER = "out_of_order"


def _count_quarantine(registry, reason: str, n: int = 1) -> None:
    if n <= 0:
        return
    reg = registry if registry is not None else get_registry()
    reg.counter(
        "trace_rows_quarantined_total",
        "trace rows dropped or repaired by the corpus layer while "
        "loading a recorded cluster trace (broken JSON, unknown kinds, "
        "missing identity fields, phantom node references) — dirty "
        "real-world data is counted, never a crash",
        labelnames=("reason",),
    ).labels(reason=reason).inc(n)


def parse_records(
    lines: Iterable[str], *, registry=None, logger=None
) -> tuple[list[dict], dict[str, int]]:
    """JSONL lines → (clean records, quarantine counts by reason).

    Identity-level breakage quarantines the row; value-level poison
    passes through for the admission guard (module docstring).
    """
    records: list[dict] = []
    quarantined: dict[str, int] = {}

    def bad(reason: str, line_no: int) -> None:
        quarantined[reason] = quarantined.get(reason, 0) + 1
        _count_quarantine(registry, reason)
        if logger is not None:
            logger.warn("trace_row_quarantined", reason=reason, line=line_no)

    for i, line in enumerate(lines, start=1):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            bad(REASON_BAD_JSON, i)
            continue
        if not isinstance(rec, dict):
            bad(REASON_NOT_OBJECT, i)
            continue
        kind = rec.get("kind")
        if kind not in KINDS:
            bad(REASON_UNKNOWN_KIND, i)
            continue
        if any(
            rec.get(f) is None or rec.get(f) == ""
            for f in REQUIRED_FIELDS[kind]
        ):
            # identity fields only — a pod's node may be null
            # (unscheduled), but placement's node is identity (where the
            # real scheduler put it). Absent/empty, NOT falsy: integer-id
            # corpora legitimately use 0 as a machine or job id
            bad(REASON_MISSING_FIELD, i)
            continue
        try:
            t = float(rec.get("t", 0.0))
        except (TypeError, ValueError):
            bad(REASON_BAD_TIMESTAMP, i)
            continue
        if not math.isfinite(t):
            bad(REASON_BAD_TIMESTAMP, i)
            continue
        rec["t"] = t
        records.append(rec)
    # out-of-order rows are REPAIRED by a stable re-sort, and counted:
    # windows() groups consecutive equal-t runs, so a late row would
    # otherwise fragment its window and replay time backwards — silently
    # (the adapters sort their CSV output; the native path must be just
    # as safe against dirty user files)
    disorder = sum(
        1
        for prev, rec in zip(records, records[1:])
        if rec["t"] < prev["t"]
    )
    if disorder:
        quarantined[REASON_OUT_OF_ORDER] = disorder
        _count_quarantine(registry, REASON_OUT_OF_ORDER, disorder)
        if logger is not None:
            logger.warn("trace_rows_reordered", rows=disorder)
        records.sort(key=lambda r: r["t"])  # stable: intra-t order kept
    return records, quarantined


@dataclass
class TraceWindow:
    """One snapshot window: the records sharing a timestamp, with node
    state carried forward from every earlier window."""

    t: float
    # node name -> latest node record (carry-forward view at this t)
    nodes: dict[str, dict]
    # this window's pod records, in file order (restated per window)
    pods: list[dict]
    # placement events recorded at this t (informational)
    placements: list[dict] = field(default_factory=list)


@dataclass
class ClusterTrace:
    """A parsed trace: ordered records plus derived, trace-wide tables.

    Derived tables are fixed across the whole trace — node order, the
    service set, and the max per-window pod count — so every window
    builds a ``ClusterState`` at ONE static shape and the decision
    kernels trace once for the entire replay (the elastic plane's
    1-steady-state-trace contract, inherited for free).
    """

    records: list[dict]
    quarantined: dict[str, int] = field(default_factory=dict)
    source: str = "?"

    def __post_init__(self) -> None:
        self._windows: list[TraceWindow] | None = None
        node_names: list[str] = []
        service_names: list[str] = []
        seen_n: set[str] = set()
        seen_s: set[str] = set()
        for rec in self.records:
            kind = rec["kind"]
            if kind == KIND_NODE and rec["node"] not in seen_n:
                seen_n.add(rec["node"])
                node_names.append(rec["node"])
            elif kind == KIND_POD and rec["service"] not in seen_s:
                seen_s.add(rec["service"])
                service_names.append(rec["service"])
            elif kind == KIND_EDGE:
                for key in ("a", "b"):
                    if rec[key] not in seen_s:
                        seen_s.add(rec[key])
                        service_names.append(rec[key])
        self.node_names: tuple[str, ...] = tuple(node_names)
        self.service_names: tuple[str, ...] = tuple(service_names)

    # ---- derived views ----

    def windows(self) -> list[TraceWindow]:
        """Snapshot windows in timestamp order (consecutive runs of one
        ``t`` value), node state carried forward between them."""
        if self._windows is not None:
            return self._windows
        windows: list[TraceWindow] = []
        node_state: dict[str, dict] = {}
        cur: TraceWindow | None = None
        for rec in self.records:
            t = rec["t"]
            if cur is None or t != cur.t:
                if cur is not None:
                    cur.nodes = dict(node_state)
                cur = TraceWindow(t=t, nodes={}, pods=[])
                windows.append(cur)
            kind = rec["kind"]
            if kind == KIND_NODE:
                prev = node_state.get(rec["node"], {})
                node_state[rec["node"]] = {**prev, **rec}
            elif kind == KIND_POD:
                cur.pods.append(rec)
            elif kind == KIND_PLACEMENT:
                cur.placements.append(rec)
        if cur is not None:
            # windows see the carry-forward node view as of their close
            cur.nodes = dict(node_state)
        self._windows = windows
        return windows

    @property
    def max_window_pods(self) -> int:
        return max((len(w.pods) for w in self.windows()), default=0)

    def comm_graph(self):
        """The trace's service communication graph.

        Edge records win; with none, the uniform complete graph over the
        trace's services (weight 1.0 — consolidation-rewarding, and
        honest about carrying no recorded call-graph information).
        """
        import jax.numpy as jnp
        import numpy as np

        from kubernetes_rescheduling_tpu.core.state import CommGraph

        names = self.service_names
        s = len(names)
        index = {n: i for i, n in enumerate(names)}
        adj = np.zeros((s, s), dtype=np.float32)
        declared = False
        for rec in self.records:
            if rec["kind"] != KIND_EDGE:
                continue
            declared = True
            i, j = index[rec["a"]], index[rec["b"]]
            w = float(rec.get("w", 1.0))
            if i != j:
                adj[i, j] = w
                adj[j, i] = w
        if not declared and s > 1:
            adj[:] = 1.0
            np.fill_diagonal(adj, 0.0)
        valid = np.ones((s,), dtype=bool)
        return CommGraph(
            adj=jnp.asarray(adj), service_valid=jnp.asarray(valid),
            names=names,
        )


def window_state(
    trace: ClusterTrace,
    index: int,
    *,
    pod_capacity: int | None = None,
    registry=None,
    count_refs: bool = True,
):
    """Build the ``ClusterState`` snapshot of one window — the
    normalization into the existing snapshot path.

    Node order, capacities and padding are trace-wide (static shapes,
    see :class:`ClusterTrace`); a pod referencing a node the trace never
    declares is placed ``UNASSIGNED`` and counted
    (``trace_rows_quarantined_total{reason="unknown_node_ref"}``) — the
    phantom-reference repair that keeps a dirty trace replayable.
    ``count_refs=False`` suppresses that count for callers that rebuild
    windows repeatedly and count once up front (the replay backend —
    the metric is documented as load-time row counts, so a re-served
    clamped-tail window must not re-inflate it). Value-level poison
    (NaN/Inf/negative/over-capacity readings) passes through untouched
    for the admission guard.
    """
    from kubernetes_rescheduling_tpu.core.state import ClusterState, UNASSIGNED

    w = trace.windows()[index]
    node_names = trace.node_names
    node_index = {n: i for i, n in enumerate(node_names)}
    svc_index = {n: i for i, n in enumerate(trace.service_names)}

    cap_cpu, cap_mem, used_cpu, used_mem, alive = [], [], [], [], []
    for name in node_names:
        rec = w.nodes.get(name)
        if rec is None:
            # declared later in the trace: not part of this window's pool
            cap_cpu.append(0.0)
            cap_mem.append(0.0)
            used_cpu.append(0.0)
            used_mem.append(0.0)
            alive.append(False)
            continue
        cap_cpu.append(float(rec.get("cpu_cap_m", 0.0)))
        cap_mem.append(float(rec.get("mem_cap_b", 0.0)))
        used_cpu.append(float(rec.get("cpu_used_m", 0.0)))
        used_mem.append(float(rec.get("mem_used_b", 0.0)))
        alive.append(bool(rec.get("alive", True)))

    services, pod_nodes, pod_cpu, pod_mem, pod_names = [], [], [], [], []
    tracked_cpu = [0.0] * len(node_names)
    tracked_mem = [0.0] * len(node_names)
    unknown_refs = 0
    for rec in w.pods:
        node = rec.get("node")
        ni = node_index.get(node) if node is not None else None
        if node is not None and ni is None:
            unknown_refs += 1
            ni = None
        cpu = float(rec.get("cpu_m", 0.0))
        mem = float(rec.get("mem_b", 0.0))
        services.append(svc_index[rec["service"]])
        pod_nodes.append(ni if ni is not None else UNASSIGNED)
        pod_cpu.append(cpu)
        pod_mem.append(mem)
        pod_names.append(rec["pod"])
        if ni is not None:
            # independent finite guards: a NaN cpu reading must not
            # suppress the pod's FINITE mem contribution (base_mem would
            # silently inflate by a plausible wrong amount the admission
            # guard has no way to catch), and vice versa
            if math.isfinite(cpu):
                tracked_cpu[ni] += cpu
            if math.isfinite(mem):
                tracked_mem[ni] += mem
    if unknown_refs and count_refs:
        _count_quarantine(registry, REASON_UNKNOWN_NODE_REF, unknown_refs)

    # base load = measured node usage minus tracked pod usage (the k8s
    # adapter's derivation — system daemons and untracked tenants)
    base_cpu = [max(u - t, 0.0) for u, t in zip(used_cpu, tracked_cpu)]
    base_mem = [max(u - t, 0.0) for u, t in zip(used_mem, tracked_mem)]

    return ClusterState.build(
        node_names=node_names,
        node_cpu_cap=cap_cpu,
        node_mem_cap=cap_mem,
        node_alive=alive,
        node_base_cpu=base_cpu,
        node_base_mem=base_mem,
        pod_services=services,
        pod_nodes=pod_nodes,
        pod_cpu=pod_cpu,
        pod_mem=pod_mem,
        pod_names=pod_names,
        pod_capacity=pod_capacity or trace.max_window_pods,
    )


def load_trace_jsonl(
    path: str | Path, *, registry=None, logger=None
) -> ClusterTrace:
    """Load a native-format trace file (see module docstring)."""
    p = Path(path)
    records, quarantined = parse_records(
        p.read_text().splitlines(), registry=registry, logger=logger
    )
    return ClusterTrace(
        records=records, quarantined=quarantined, source=str(p)
    )


def dump_trace_jsonl(trace: ClusterTrace, path: str | Path) -> Path:
    """Write a trace in the native JSONL form (the adapters' round-trip
    target: ``load(dump(x)).records == x.records``)."""
    p = Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    with p.open("w") as f:
        for rec in trace.records:
            f.write(json.dumps(rec, default=float) + "\n")
    return p
