"""Adapters: external trace layouts → the native ``ClusterTrace`` form.

Two public-corpus styles plus our own telemetry:

- **Alibaba cluster-trace-style** (Lu et al., IEEE CAL'17; the
  cluster-trace-v2018 table shapes): a machine table and a container
  table, both timestamped CSVs. Expected headers::

      machines:   machine_id,time_stamp,cpu_num,mem_size,status
      containers: container_id,machine_id,time_stamp,app_du,cpu_request,
                  cpu_util_percent,mem_size

  Units follow the corpus conventions: ``cpu_num``/``cpu_request`` in
  cores, ``mem_size`` in GB, ``cpu_util_percent`` of the container's
  request. ``app_du`` (the deployment unit) is the service identity —
  exactly the co-located-workload grouping the trace was published to
  expose. ``status`` other than ``USING`` marks the machine dead.

- **Borg-ClusterData-style** (Verma et al., EuroSys'15; the Google
  clusterdata-2011 table shapes, headered): machine events plus task
  usage::

      machine_events: time,machine_id,event_type,cpus,memory
      task_usage:     start_time,end_time,job_id,task_index,machine_id,
                      cpu_rate,canonical_memory_usage

  Capacities and usage are NORMALIZED (the public trace's obfuscation);
  ``cpu_unit_m``/``mem_unit_b`` scale them into the corpus units.
  ``event_type`` 1 (REMOVE) marks the machine dead. Tasks group into
  windows by ``start_time``; pod = ``j<job>-<task_index>``, service =
  ``j<job>`` (a Borg job is the Deployment-like unit).

- **our own rounds.jsonl** (:func:`rounds_to_trace`): recorded soaks
  carry per-node traffic shares (the attribution plane's ingress+egress)
  and the applied moves — converted to node-usage records (traffic-share
  units, said out loud in the source tag) plus ``placement`` events, so
  the schema tooling and usage analysis consume our own telemetry as a
  trace. Replay needs pod records, which rounds.jsonl does not carry —
  the external adapters are the replay corpus.

Malformed CSV rows quarantine-and-count through the corpus counter
(``trace_rows_quarantined_total{reason}``), like native rows.
"""

from __future__ import annotations

import csv
from pathlib import Path
from typing import Iterable

from kubernetes_rescheduling_tpu.traces.corpus import (
    REASON_MISSING_FIELD,
    ClusterTrace,
    _count_quarantine,
    load_trace_jsonl,
)

GB = float(1024**3)


def _read_csv(path: str | Path) -> list[dict]:
    with Path(path).open(newline="") as f:
        return list(csv.DictReader(f))


def _f(row: dict, key: str) -> float:
    """Float field; raises KeyError/ValueError for the caller's
    quarantine accounting."""
    v = row.get(key)
    if v is None or v == "":
        raise KeyError(key)
    return float(v)


def _sorted_records(records: list[dict]) -> list[dict]:
    """Stable sort by timestamp — the schema's monotonicity contract;
    within a timestamp, node records precede pods (capacity before
    usage), preserving input order otherwise."""
    kind_rank = {"node": 0, "edge": 1, "pod": 2, "placement": 3}
    return sorted(
        records, key=lambda r: (r["t"], kind_rank.get(r["kind"], 9))
    )


def load_alibaba_csv(
    machines: str | Path,
    containers: str | Path,
    *,
    registry=None,
) -> ClusterTrace:
    """Alibaba cluster-trace-style CSVs → ``ClusterTrace``."""
    records: list[dict] = []
    quarantined: dict[str, int] = {}

    def bad() -> None:
        quarantined[REASON_MISSING_FIELD] = (
            quarantined.get(REASON_MISSING_FIELD, 0) + 1
        )
        _count_quarantine(registry, REASON_MISSING_FIELD)

    for row in _read_csv(machines):
        try:
            records.append(
                {
                    "kind": "node",
                    "t": _f(row, "time_stamp"),
                    "node": row["machine_id"],
                    "cpu_cap_m": _f(row, "cpu_num") * 1000.0,
                    "mem_cap_b": _f(row, "mem_size") * GB,
                    "alive": (row.get("status") or "USING") == "USING",
                }
            )
        except (KeyError, ValueError):
            bad()
    for row in _read_csv(containers):
        try:
            req_m = _f(row, "cpu_request") * 1000.0
            util = _f(row, "cpu_util_percent")
            records.append(
                {
                    "kind": "pod",
                    "t": _f(row, "time_stamp"),
                    "pod": row["container_id"],
                    "service": row["app_du"],
                    "node": row.get("machine_id") or None,
                    "cpu_m": req_m * util / 100.0,
                    "mem_b": _f(row, "mem_size") * GB,
                }
            )
        except (KeyError, ValueError):
            bad()
    return ClusterTrace(
        records=_sorted_records(records),
        quarantined=quarantined,
        source=f"alibaba:{machines}",
    )


def load_borg_csv(
    machine_events: str | Path,
    task_usage: str | Path,
    *,
    cpu_unit_m: float = 32_000.0,
    mem_unit_b: float = 64.0 * GB,
    registry=None,
) -> ClusterTrace:
    """Borg-ClusterData-style CSVs → ``ClusterTrace``. The normalized
    capacities/usages scale by ``cpu_unit_m``/``mem_unit_b`` (the
    biggest machine = 1.0 in the public trace)."""
    records: list[dict] = []
    quarantined: dict[str, int] = {}

    def bad() -> None:
        quarantined[REASON_MISSING_FIELD] = (
            quarantined.get(REASON_MISSING_FIELD, 0) + 1
        )
        _count_quarantine(registry, REASON_MISSING_FIELD)

    for row in _read_csv(machine_events):
        try:
            records.append(
                {
                    "kind": "node",
                    "t": _f(row, "time"),
                    "node": row["machine_id"],
                    "cpu_cap_m": _f(row, "cpus") * cpu_unit_m,
                    "mem_cap_b": _f(row, "memory") * mem_unit_b,
                    "alive": int(_f(row, "event_type")) != 1,  # 1 = REMOVE
                }
            )
        except (KeyError, ValueError):
            bad()
    for row in _read_csv(task_usage):
        try:
            job, task = row["job_id"], row["task_index"]
            if not job or task is None or task == "":
                raise KeyError("job_id/task_index")
            records.append(
                {
                    "kind": "pod",
                    "t": _f(row, "start_time"),
                    "pod": f"j{job}-{task}",
                    "service": f"j{job}",
                    "node": row.get("machine_id") or None,
                    "cpu_m": _f(row, "cpu_rate") * cpu_unit_m,
                    "mem_b": _f(row, "canonical_memory_usage") * mem_unit_b,
                }
            )
        except (KeyError, ValueError):
            bad()
    return ClusterTrace(
        records=_sorted_records(records),
        quarantined=quarantined,
        source=f"borg:{task_usage}",
    )


def rounds_to_trace(
    paths: Iterable[str | Path],
    *,
    node_cpu_cap_m: float = 0.0,
) -> ClusterTrace:
    """Recorded ``rounds.jsonl`` soaks → a usage+placement trace.

    Per attributed round: one ``node`` record per node carrying its
    traffic share (ingress + egress — comm-cost units, not millicores;
    the source tag says so), plus one ``placement`` event per applied
    move (service-granular — the pod field carries the service name the
    Deployment-unit move re-homed). ``node_cpu_cap_m`` > 0 stamps a
    uniform capacity so the trace also loads as a percent-scale series.
    """
    from kubernetes_rescheduling_tpu.forecast.dataset import load_rounds

    records: list[dict] = []
    for i, rec in enumerate(load_rounds(paths)):
        t = float(rec.get("round", i))
        attr = rec.get("attribution")
        if isinstance(attr, dict):
            ingress = attr.get("ingress") or {}
            egress = attr.get("egress") or {}
            for node in sorted(set(ingress) | set(egress)):
                records.append(
                    {
                        "kind": "node",
                        "t": t,
                        "node": node,
                        "cpu_cap_m": node_cpu_cap_m,
                        "mem_cap_b": 0.0,
                        "cpu_used_m": float(ingress.get(node, 0.0))
                        + float(egress.get(node, 0.0)),
                        "mem_used_b": 0.0,
                        "alive": True,
                    }
                )
        for mv in rec.get("applied_moves") or ():
            try:
                service, landed = mv[0], mv[1]
            except (TypeError, IndexError, KeyError):
                continue
            records.append(
                {
                    "kind": "placement",
                    "t": t,
                    "pod": str(service),
                    "node": str(landed),
                }
            )
    # sorted like the CSV adapters: multi-file input restarts round
    # numbers (the t axis) per file, and an unsorted ClusterTrace would
    # fragment windows and replay time backwards
    return ClusterTrace(
        records=_sorted_records(records),
        source="rounds.jsonl:traffic-share-units",
    )


def load_shadow_trace(
    path: str | Path, *, fmt: str = "auto", registry=None, logger=None
) -> ClusterTrace:
    """The CLI's one-stop loader: a native ``.jsonl`` file, or a
    directory holding one external-format table pair.

    ``fmt='auto'`` detects: a file → native JSONL; a directory → borg
    when ``machine_events*.csv`` + ``task_usage*.csv`` are present,
    alibaba when ``*machines*.csv`` + ``*containers*.csv`` are, native
    when a single ``*.jsonl`` is.
    """
    p = Path(path)
    if fmt not in ("auto", "native", "alibaba", "borg"):
        raise ValueError(f"unknown trace format {fmt!r}")
    if p.is_file():
        if fmt in ("auto", "native"):
            return load_trace_jsonl(p, registry=registry, logger=logger)
        raise ValueError(
            f"format {fmt!r} needs a directory with its CSV table pair, "
            f"got a file: {p}"
        )
    if not p.is_dir():
        raise FileNotFoundError(f"no such trace: {p}")

    def one(pattern: str) -> Path | None:
        hits = sorted(p.glob(pattern))
        return hits[0] if hits else None

    borg = (one("machine_events*.csv"), one("task_usage*.csv"))
    alibaba = (one("*machines*.csv"), one("*containers*.csv"))
    native = one("*.jsonl")
    if fmt == "borg" or (fmt == "auto" and all(borg)):
        if not all(borg):
            raise FileNotFoundError(
                f"borg-style trace needs machine_events*.csv + "
                f"task_usage*.csv under {p}"
            )
        return load_borg_csv(borg[0], borg[1], registry=registry)
    if fmt == "alibaba" or (fmt == "auto" and all(alibaba)):
        if not all(alibaba):
            raise FileNotFoundError(
                f"alibaba-style trace needs *machines*.csv + "
                f"*containers*.csv under {p}"
            )
        return load_alibaba_csv(alibaba[0], alibaba[1], registry=registry)
    if native is not None and fmt in ("auto", "native"):
        return load_trace_jsonl(native, registry=registry, logger=logger)
    raise FileNotFoundError(
        f"no recognizable trace under {p} (native *.jsonl, alibaba "
        f"*machines*/*containers* CSVs, or borg machine_events/"
        f"task_usage CSVs)"
    )
