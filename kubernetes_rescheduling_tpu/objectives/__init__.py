"""Pure, jit-able objective functions — the metrics the reference collects
offline (communicationcost.py, nodemonitor.py) recast as on-device reductions.

They serve double duty: test oracles for parity with the reference, and score
terms inside the batched solver.
"""

from kubernetes_rescheduling_tpu.objectives.metrics import (
    communication_cost,
    communication_cost_attribution,
    communication_cost_deployment,
    load_std,
    node_cpu_pct_rounded,
    node_pair_cost_matrix,
    capacity_violation,
    objective_summary,
)

__all__ = [
    "communication_cost",
    "communication_cost_attribution",
    "communication_cost_deployment",
    "load_std",
    "node_cpu_pct_rounded",
    "node_pair_cost_matrix",
    "capacity_violation",
    "objective_summary",
]
