"""Objective metrics as jit-able array reductions.

Reference parity (semantics, not code — see SURVEY.md §5.5, §6):

- ``communication_cost``: the reference walks every default-namespace pod,
  maps its Deployment to a node, then counts cross-node edges of the relation
  dict and halves the double count (reference communicationcost.py:40-45).
  Here the same quantity is a masked quadratic form over the service×node
  occupancy matrix — one matmul, MXU-friendly, and it generalizes cleanly to
  multi-replica deployments (the reference's dict collapses a Deployment to a
  single node, last pod wins — communicationcost.py:37).
- ``load_std``: population standard deviation of per-node CPU-usage percent
  over valid worker nodes (reference nodemonitor.py:37-46, ``numpy.std``).
- ``node_cpu_pct_rounded``: the monitor stores ``int(round(pct))`` (reference
  get_resource_usage.py:37) and hazard detection compares that rounded value
  against the threshold (reference harzard_detect.py:12) — so the rounded
  variant exists as its own function for exact detection parity.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from kubernetes_rescheduling_tpu.core.state import ClusterState, CommGraph


def communication_cost(state: ClusterState, graph: CommGraph) -> jax.Array:
    """Cross-node communicating pod pairs, weighted by the comm graph.

    cost = 1/2 · Σ_{i,j} adj[i,j] · (#cross-node pod pairs of services i,j)

    With one replica per service this equals the reference's
    cross-node-edges/2 (reference communicationcost.py:40-45): each edge
    contributes 1 iff its two services sit on different nodes.
    """
    num_s = graph.num_services
    occ = state.service_node_counts(num_s)          # f32[S, N]
    tot = occ.sum(axis=1)                           # f32[S]
    same_node_pairs = occ @ occ.T                   # f32[S, S]
    all_pairs = tot[:, None] * tot[None, :]
    cross = all_pairs - same_node_pairs
    adj = graph.adj * graph.service_valid[:, None] * graph.service_valid[None, :]
    return 0.5 * jnp.sum(adj * cross)


def comm_edge_list(graph: CommGraph):
    """Host-side: the masked adjacency's upper-triangle nonzero edges as
    ``(src i32[E], dst i32[E], w f32[E])`` device arrays — the static
    structure :func:`communication_cost_edges` contracts against.

    The dense quadratic form pays O(S²·N) FLOPs plus several S×S
    temporaries per evaluation; real service meshes are sparse (the
    powerlaw scenario carries ~4 edges per service), so the same scalar
    is O(E·N) off the edge list — the difference between the round-end
    metrics kernel dominating a CPU round and disappearing into it.
    Build once per (static) graph and reuse.

    E is padded up to the next power of two (floor 8 — the same
    quantization rule as ``elastic.buckets.bucket_capacity``, mirrored
    here so objectives stays import-light) with zero-weight self-edges:
    a churn event that adds or removes a few graph edges must land in
    the SAME compiled round-end signature, or every graph-changing
    churn round would silently retrace the kernel the 1-trace invariant
    pins (padding rows contribute exactly ``0·cross == 0``).
    """
    import numpy as np

    adj = np.asarray(graph.adj)
    valid = np.asarray(graph.service_valid)
    masked = adj * valid[:, None] * valid[None, :]
    src, dst = np.nonzero(np.triu(masked, k=1))
    w = masked[src, dst].astype(np.float32)
    cap = 8
    while cap < src.size:
        cap *= 2
    pad = cap - src.size
    src = np.concatenate([src, np.zeros(pad, np.int64)])
    dst = np.concatenate([dst, np.zeros(pad, np.int64)])
    w = np.concatenate([w, np.zeros(pad, np.float32)])
    return (
        jnp.asarray(src, jnp.int32),
        jnp.asarray(dst, jnp.int32),
        jnp.asarray(w, jnp.float32),
    )


def communication_cost_edges(
    state: ClusterState, num_services: int, edges
) -> jax.Array:
    """:func:`communication_cost` contracted over a precomputed edge
    list (:func:`comm_edge_list`): Σ_{i<j} w_ij·(tot_i·tot_j − occ_i·occ_j)
    — the same quantity as the dense quadratic form (each unordered pair
    once ≡ half the symmetric double sum), in O(E·N) instead of O(S²·N).
    f32 summation ORDER differs from the dense kernel, so the two are
    equal mathematically, not bit-for-bit — every consumer of a run must
    use one formulation throughout (the round-end protocol picks per
    run: edge list when attribution is off, dense — whose S×S work the
    attribution bundle needs anyway — when it is on)."""
    src, dst, w = edges
    occ = state.service_node_counts(num_services)        # f32[S, N]
    tot = occ.sum(axis=1)                                # f32[S]
    cross = tot[src] * tot[dst] - jnp.sum(occ[src] * occ[dst], axis=1)
    return jnp.sum(w * cross)


def communication_cost_deployment(state: ClusterState, graph: CommGraph) -> jax.Array:
    """Deployment-level cost, exactly the reference's accounting.

    The reference collapses each Deployment to ONE node — the node of
    whichever of its pods was listed last (communicationcost.py:22-37) — then
    counts cross-node relation edges / 2. Here: a service's node is the node
    of its highest-indexed valid pod.
    """
    num_s = graph.num_services
    p = state.num_pods
    # highest-indexed valid pod per service ("last pod wins")
    pod_idx = jnp.arange(p)
    svc = jnp.where(state.pod_valid, state.pod_service, num_s)
    last = (
        jnp.full((num_s + 1,), -1, jnp.int32)
        .at[svc]
        .max(jnp.where(state.pod_valid, pod_idx, -1).astype(jnp.int32))
    )[:num_s]
    has_pod = last >= 0
    svc_node = jnp.where(has_pod, state.pod_node[jnp.clip(last, 0, p - 1)], -1)
    diff = svc_node[:, None] != svc_node[None, :]
    present = has_pod[:, None] & has_pod[None, :]
    adj = graph.adj * graph.service_valid[:, None] * graph.service_valid[None, :]
    # reference counts an edge as cross-node also when the peer is absent
    # (inf.get(rel) is None != node — communicationcost.py:42-43)
    absent_peer = has_pod[:, None] & ~has_pod[None, :]
    return 0.5 * jnp.sum(adj * ((diff & present) | absent_peer))


def load_std(state: ClusterState) -> jax.Array:
    """Population std-dev of CPU-usage % over valid nodes with cap > 0
    (reference nodemonitor.py:37-46)."""
    pct = state.node_cpu_pct()
    mask = state.node_valid & (state.node_cpu_cap > 0)
    n = jnp.maximum(mask.sum(), 1)
    mean = jnp.sum(jnp.where(mask, pct, 0.0)) / n
    var = jnp.sum(jnp.where(mask, (pct - mean) ** 2, 0.0)) / n
    return jnp.sqrt(var)


def node_cpu_pct_rounded(state: ClusterState) -> jax.Array:
    """i32[N] — ``int(round(pct))`` per node, -1 for zero-capacity nodes
    (reference get_resource_usage.py:37). Hazard detection compares this."""
    pct = state.node_cpu_pct()
    # jnp.round is round-half-to-even like Python's round() on .5 — parity.
    rounded = jnp.round(pct).astype(jnp.int32)
    return jnp.where(state.node_valid & (state.node_cpu_cap > 0), rounded, -1)


def capacity_violation(state: ClusterState) -> jax.Array:
    """Total millicores of CPU over-subscription (0 when feasible).

    The reference never checks capacity (pods are pinned via nodeName even
    onto full nodes); the solver uses this as a feasibility term.
    """
    over = jnp.maximum(state.node_cpu_used() - state.node_cpu_cap, 0.0)
    return jnp.sum(jnp.where(state.node_valid, over, 0.0))


def _masked_adj(graph: CommGraph) -> jax.Array:
    return graph.adj * graph.service_valid[:, None] * graph.service_valid[None, :]


def node_pair_cost_matrix(state: ClusterState, graph: CommGraph) -> jax.Array:
    """f32[N, N] — the communication cost decomposed over node pairs.

    ``M[a, b]`` is the pair-weighted traffic between nodes ``a`` and ``b``
    (ordered; symmetric because ``adj`` is): Σ_{i,j} adj[i,j]·occ[i,a]·occ[j,b]
    with the diagonal zeroed (same-node pairs carry no cost). By
    construction ``0.5·ΣM == communication_cost`` — the matrix is an exact
    decomposition of the scalar objective, not a second estimate.
    """
    occ = state.service_node_counts(graph.num_services)  # f32[S, N]
    adj = _masked_adj(graph)
    m = occ.T @ adj @ occ                                # f32[N, N]
    n = state.num_nodes
    return m * (1.0 - jnp.eye(n, dtype=m.dtype))


def communication_cost_attribution(
    state: ClusterState, graph: CommGraph, *, top_k: int = 8
) -> jax.Array:
    """The on-device cost-decomposition kernel: everything the host needs
    to attribute ``communication_cost`` to service edges and node pairs,
    as ONE flat f32 bundle (pulled in a single transfer,
    ``site="attribution"`` — same discipline as ``decide_explain``).

    Layout (k = min(top_k, S·S), N = num_nodes)::

        [0]                total      — 0.5·ΣM == communication_cost
        [1]                tail       — total − Σ(top-k edge costs)
        [2 : 2+5k]         edge rows  — k×(src_service, dst_service,
                                       src_node, dst_node, cost); index
                                       slots are −1 on empty/padding rows
        [2+5k : 2+5k+N·N]  M          — the node-pair matrix, row-major

    Edges are unordered service pairs ranked by their cost contribution
    ``adj[i,j]·cross_pairs(i,j)`` (each pair counted ONCE, so the edge
    costs plus the tail sum to the scalar — the consistency invariant
    ``telemetry.attribution`` enforces). ``src_node``/``dst_node`` are the
    dominant cross-node placement of the pair: the (a≠b) node pair
    holding the most communicating replica pairs.
    """
    num_s = graph.num_services
    n = state.num_nodes
    occ = state.service_node_counts(num_s)               # f32[S, N]
    adj = _masked_adj(graph)
    tot = occ.sum(axis=1)                                # f32[S]
    same = occ @ occ.T
    cross = tot[:, None] * tot[None, :] - same
    contrib = adj * cross                                # f32[S, S], symmetric
    # ONE source of truth for the node-pair collapse (XLA CSEs the shared
    # occ/adj subexpressions — calling it costs nothing inside this jit)
    m = node_pair_cost_matrix(state, graph)
    total = 0.5 * jnp.sum(m)

    k = max(1, min(int(top_k), num_s * num_s))
    upper = jnp.triu(jnp.ones((num_s, num_s), dtype=bool), k=1)
    vals = jnp.where(upper, contrib, -jnp.inf)
    top_v, top_i = lax.top_k(vals.reshape(-1), k)
    src = top_i // num_s
    dst = top_i % num_s
    ok = jnp.isfinite(top_v) & (top_v > 0)

    def dominant_pair(i, j):
        pair = occ[i][:, None] * occ[j][None, :]
        pair = pair * (1.0 - jnp.eye(n, dtype=pair.dtype))
        flat = jnp.argmax(pair.reshape(-1))
        has = jnp.max(pair) > 0
        return (
            jnp.where(has, flat // n, -1),
            jnp.where(has, flat % n, -1),
        )

    a, b = jax.vmap(dominant_pair)(src, dst)
    rows = jnp.stack(
        [
            jnp.where(ok, src, -1).astype(jnp.float32),
            jnp.where(ok, dst, -1).astype(jnp.float32),
            jnp.where(ok, a, -1).astype(jnp.float32),
            jnp.where(ok, b, -1).astype(jnp.float32),
            jnp.where(ok, top_v, 0.0),
        ],
        axis=1,
    )                                                    # f32[k, 5]
    tail = total - jnp.sum(jnp.where(ok, top_v, 0.0))
    return jnp.concatenate(
        [jnp.stack([total, tail]), rows.reshape(-1), m.reshape(-1)]
    )


def objective_summary(state: ClusterState, graph: CommGraph) -> dict[str, jax.Array]:
    """All objectives at once (single fused evaluation for telemetry)."""
    return {
        "communication_cost": communication_cost(state, graph),
        "load_std": load_std(state),
        "capacity_violation": capacity_violation(state),
        "max_cpu_pct": jnp.max(
            jnp.where(state.node_valid, state.node_cpu_pct(), -jnp.inf)
        ),
    }
