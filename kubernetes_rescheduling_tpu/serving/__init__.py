"""The serving plane: request-grain placement as a query service.

The round loop answers "which pod should move" once per round; this
package answers "where should THIS pod go, now" at pod-creation rate —
the placements/sec + p99-latency axis of ROADMAP item 3. One solo kernel
(:func:`serving.kernel.place_one`) scores a single admitted request
against the device-resident cluster state with the existing greedy
machinery (one dispatch, no solve); a bounded batcher
(:class:`serving.engine.ServingEngine`) coalesces concurrent arrivals
into ONE vmapped dispatch (:func:`serving.kernel.place_batch`), with
per-request decisions bit-identical to the solo kernel. The ops plane's
``POST /place`` endpoint (``telemetry.server``) is the HTTP front.
"""

from kubernetes_rescheduling_tpu.serving.engine import (
    OUTCOME_NO_CANDIDATE,
    OUTCOME_PLACED,
    OUTCOME_SHED,
    OUTCOME_TIMEOUT,
    PlaceResult,
    ServingEngine,
)
from kubernetes_rescheduling_tpu.serving.kernel import place_batch, place_one

__all__ = [
    "OUTCOME_NO_CANDIDATE",
    "OUTCOME_PLACED",
    "OUTCOME_SHED",
    "OUTCOME_TIMEOUT",
    "PlaceResult",
    "ServingEngine",
    "place_batch",
    "place_one",
]
