"""The serving plane's device kernels: score ONE requested service.

:func:`place_one` is the request-grain sibling of
``solver.round_loop.decide_explain``: the same finite guard, the same
hazard detection, the same ``policy_scores`` rows and masked
lexicographic argmax, and the same f32[6, k] explain bundle — but the
service being placed comes from the REQUEST, not from victim selection,
and nothing is removed from the snapshot (the pod does not exist yet;
serving places NEW work, the round loop moves existing work). Because
the scoring half is literally ``policies.scoring.choose_node``'s rows,
the served decision is test-pinned bit-identical to the round kernel's
placement on the same state.

:func:`place_batch` is the vmapped twin (the fleet kernels'
``stack → vmap → one dispatch`` template, ``solver.fleet``): B coalesced
requests score against ONE shared snapshot under one
``instrument_jit``-counted dispatch. The batch shape is padded static by
the engine (``jax_traces_total{fn="serving_place"} == 1`` in steady
state — the trace-count invariant the soak pins), padded slots compute
inert garbage the host discards, and each row is bit-identical to
:func:`place_one` on the same ``(svc, key)`` — the serve-vs-solo parity
pin.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from kubernetes_rescheduling_tpu.core.state import ClusterState, CommGraph
from kubernetes_rescheduling_tpu.objectives.metrics import node_cpu_pct_rounded
from kubernetes_rescheduling_tpu.policies.hazard import detect_hazard
from kubernetes_rescheduling_tpu.policies.scoring import (
    lex_argmax,
    policy_scores,
)
from kubernetes_rescheduling_tpu.solver.round_loop import finite_guard
from kubernetes_rescheduling_tpu.telemetry.accounting import instrument_jit


def _place_core(
    state: ClusterState,
    graph: CommGraph,
    policy_id: jax.Array,
    threshold: jax.Array,
    svc: jax.Array,
    key: jax.Array,
    top_k: int,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Shared trace body of the solo and vmapped kernels (one definition,
    so the parity pin cannot drift). Returns ``(most_hazard, target,
    bundle)`` — target is -1 when every valid node is hazardous, and the
    bundle is ``decide_explain``'s f32[6, k] layout so
    ``telemetry.explain.greedy_explanation`` decodes it unchanged."""
    state = finite_guard(state)
    most, hazard_mask = detect_hazard(state, threshold)
    k1, k2, cand = policy_scores(
        policy_id, state, graph, svc, hazard_mask, key
    )
    target = lex_argmax([k1, k2], cand)

    k = min(int(top_k), state.num_nodes)
    pct = node_cpu_pct_rounded(state).astype(jnp.float32)
    hz_v, hz_i = lax.top_k(jnp.where(state.node_valid, pct, -jnp.inf), k)
    c_v, c_i = lax.top_k(jnp.where(cand, k1, -jnp.inf), k)
    # top-k by k1 alone can exclude the lex winner when >k nodes tie on
    # the primary key — force the chosen node into the last slot so the
    # recorded candidates always contain the argmax (the
    # explain-consistency invariant, same as decide_explain)
    missing = (target >= 0) & ~jnp.any(c_i == target)
    c_i = c_i.at[-1].set(jnp.where(missing, target, c_i[-1]))
    bundle = jnp.stack(
        [
            hz_i.astype(jnp.float32),
            hz_v,
            c_i.astype(jnp.float32),
            k1[c_i],
            k2[c_i],
            cand[c_i].astype(jnp.float32),
        ]
    )
    return most, target, bundle


@partial(instrument_jit, name="serving_place_one", static_argnames=("top_k",))
def place_one(
    state: ClusterState,
    graph: CommGraph,
    policy_id: jax.Array,
    threshold: jax.Array,
    svc: jax.Array,
    key: jax.Array,
    *,
    top_k: int = 3,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Place one requested service (i32 scalar ``svc``) against the
    current state: ``(most_hazard, target, bundle)``."""
    return _place_core(state, graph, policy_id, threshold, svc, key, top_k)


@partial(instrument_jit, name="serving_place", static_argnames=("top_k",))
def place_batch(
    state: ClusterState,
    graph: CommGraph,
    policy_id: jax.Array,
    threshold: jax.Array,
    svcs: jax.Array,
    keys: jax.Array,
    *,
    top_k: int = 3,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """B coalesced requests against ONE shared snapshot: ``svcs`` is
    i32[B], ``keys`` the per-request PRNG keys [B, ...]. Returns
    ``(most_hazard[B], target[B], bundle[B, 6, k])``, each row
    bit-identical to :func:`place_one` on that row's inputs."""

    def one(svc, key):
        return _place_core(state, graph, policy_id, threshold, svc, key, top_k)

    return jax.vmap(one)(svcs, keys)
