"""The serving engine: bounded queue → batcher → one vmapped dispatch.

Host-side request plumbing around :mod:`serving.kernel`, instrumented
end to end from day one:

- **Bounded admission**: ``place()`` sheds immediately (counted
  ``serving_shed_total{reason="queue_full"}``) when the queue is at
  ``queue_depth``, and rejects unknown services with ``ValueError``
  before a request object exists — the HTTP front maps that to 400.
- **Coalescing batcher**: one daemon thread collects up to ``max_batch``
  requests within ``batch_window_ms`` of the first dequeue and issues
  ONE vmapped ``place_batch`` dispatch, padded to the static
  ``max_batch`` shape so steady state holds exactly one compiled trace
  (``jax_traces_total{fn="serving_place"} == 1`` — the soak's pin).
- **Per-request deadline**: a request whose deadline passed by dequeue
  time is completed ``timeout`` without occupying a batch slot.
- **Exact accounting**: outcomes are single-owner — ``place()`` decides
  sheds at admission, the batcher decides everything it dequeued — so
  ``placed + no_candidate + shed + timed_out == submitted`` holds under
  any interleaving (the seeded concurrency soak asserts it).
- **Stage spans**: every completed request carries queue-wait /
  batch-formation / device-dispatch / decode / total, published to the
  micro-bucket ``serving_request_seconds{stage}`` families
  (``registry.MICRO_BUCKETS`` — request latencies live orders of
  magnitude below the round-scale default buckets).
- **Snapshot admission**: cluster state enters ONLY through
  :meth:`ServingEngine._admitted_snapshot` — ``backend.monitor()``
  routed through the admission guard, statically enforced by
  ``scripts/check_snapshot_admission.py`` like the controller's monitor
  path.

The rolling summary (rate, p50/p95/p99 over the last ``window``
requests, batch-size distribution, shed counts) feeds
``OpsPlane.observe_serving`` after every dispatched batch: the
``serving`` stanza on ``/healthz``, the ``serving_p99`` watchdog rule,
and — on rule entry — a flight-recorder bundle carrying the bounded
recent-request ring.
"""

from __future__ import annotations

import collections
import threading
import time
from dataclasses import dataclass, field
from typing import Any

import numpy as np

from kubernetes_rescheduling_tpu.policies.scoring import POLICY_IDS
from kubernetes_rescheduling_tpu.serving.kernel import place_batch, place_one
from kubernetes_rescheduling_tpu.telemetry.explain import greedy_explanation
from kubernetes_rescheduling_tpu.telemetry.registry import (
    MICRO_BUCKETS,
    MetricsRegistry,
    get_registry,
)

OUTCOME_PLACED = "placed"
OUTCOME_NO_CANDIDATE = "no_candidate"
OUTCOME_SHED = "shed"
OUTCOME_TIMEOUT = "timeout"

SHED_QUEUE_FULL = "queue_full"
SHED_SHUTDOWN = "shutdown"
SHED_DEADLINE = "deadline"

STAGES = (
    "queue_wait", "batch_formation", "device_dispatch", "decode", "total",
)

# batch-size buckets: powers of two up to the largest supported max_batch
# — the distribution /healthz renders and the bench cell reads
_BATCH_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0)


@dataclass(frozen=True)
class PlaceResult:
    """One request's outcome, JSON-safe via :meth:`as_dict` (the
    ``POST /place`` response body)."""

    request_id: int
    service: str
    outcome: str                       # placed|no_candidate|shed|timeout
    node: str | None = None
    node_index: int = -1
    shed_reason: str | None = None
    batch_size: int = 0
    timings_ms: dict[str, float] = field(default_factory=dict)
    explain: dict[str, Any] | None = None

    def as_dict(self) -> dict[str, Any]:
        return {
            "request_id": self.request_id,
            "service": self.service,
            "outcome": self.outcome,
            "node": self.node,
            "node_index": self.node_index,
            **(
                {"shed_reason": self.shed_reason}
                if self.shed_reason is not None
                else {}
            ),
            "batch_size": self.batch_size,
            "timings_ms": dict(self.timings_ms),
            **({"explain": self.explain} if self.explain is not None else {}),
        }


class _Request:
    """Internal queue item; ``done`` gates the submitting thread."""

    __slots__ = (
        "seq", "service", "svc_idx", "deadline", "t_submit", "t_dequeue",
        "result", "done", "ring_entry",
    )

    def __init__(self, seq, service, svc_idx, deadline, ring_entry):
        self.seq = seq
        self.service = service
        self.svc_idx = svc_idx
        self.deadline = deadline          # absolute perf_counter, or None
        self.t_submit = time.perf_counter()
        self.t_dequeue: float | None = None
        self.result: PlaceResult | None = None
        self.done = threading.Event()
        self.ring_entry = ring_entry


class ServingEngine:
    """Request-grain placement over one backend's admitted snapshots.

    ``policy`` is a greedy policy name (``policies.scoring.POLICY_IDS``);
    decisions use the snapshot captured at construction (or the latest
    :meth:`refresh_snapshot`) — serving scores against device-resident
    state, it does not monitor per request. Call :meth:`start` before
    submitting and :meth:`stop` when done (``with engine:`` does both).
    """

    def __init__(
        self,
        backend,
        *,
        config=None,
        policy: str = "communication",
        threshold: float = 30.0,
        seed: int = 0,
        top_k: int = 3,
        registry: MetricsRegistry | None = None,
        ops=None,
        guard=None,
    ) -> None:
        import jax
        import jax.numpy as jnp

        from kubernetes_rescheduling_tpu.config import ServingConfig

        self.config = (config or ServingConfig()).validate()
        if policy not in POLICY_IDS:
            raise ValueError(
                f"unknown serving policy {policy!r}; expected one of "
                f"{sorted(POLICY_IDS)}"
            )
        self.policy = policy
        self.registry = registry
        self.ops = ops
        self._backend = backend
        if guard is None:
            from kubernetes_rescheduling_tpu.bench.admission import (
                AdmissionGuard,
            )
            from kubernetes_rescheduling_tpu.config import ReconcileConfig

            guard = AdmissionGuard(ReconcileConfig(), registry=registry)
        self._guard = guard
        self._policy_id = jnp.asarray(POLICY_IDS[policy], jnp.int32)
        self._threshold = jnp.asarray(threshold, jnp.float32)
        self._base_key = jax.random.PRNGKey(seed)
        self._top_k = int(top_k)
        self.graph = backend.comm_graph()
        self._svc_index = {n: i for i, n in enumerate(self.graph.names)}
        self.state = self._admitted_snapshot(backend)
        self._node_names = list(self.state.node_names)

        self._cond = threading.Condition()
        self._queue: collections.deque[_Request] = collections.deque()
        self._running = False
        self._thread: threading.Thread | None = None
        self._seq = 0
        self._inflight = 0                 # queued + in the current batch
        # exact-accounting counters (single-owner writes under _cond)
        self.submitted = 0
        self.outcomes: dict[str, int] = {}
        self.shed_reasons: dict[str, int] = {}
        self.dispatches = 0
        self._batch_sizes: dict[int, int] = {}
        # rolling window of completed-request totals (seconds) — the
        # p50/p95/p99 the /healthz stanza and the serving_p99 rule judge
        self._recent: collections.deque[float] = collections.deque(
            maxlen=self.config.window
        )
        # bounded recent-request ring (newest last): entries are written
        # at submit and mutated in place at completion, so an in-flight
        # request shows outcome "inflight" — the flight-recorder payload
        self._ring: collections.deque[dict[str, Any]] = collections.deque(
            maxlen=self.config.ring
        )
        self._started_mono = time.perf_counter()
        self._completed = 0
        # makes {compute summary → publish to ops} atomic: downstream
        # state is last-write-wins, so without this a request thread's
        # stale summary could overwrite the batcher's fresher one (e.g.
        # /healthz losing the final deadline sheds after traffic stops).
        # Ordering only — watchdog THREAD-SAFETY lives in OpsPlane's own
        # lock, which also covers the round-grain feeds racing these
        self._feed_lock = threading.Lock()

    # ---- snapshot admission ----

    def _admitted_snapshot(self, backend):
        """The serving plane's ONLY cluster-state ingest: a fresh monitor
        snapshot routed through the admission guard
        (``check_snapshot_admission.py`` statically enforces that no
        other ``.monitor()`` call exists under ``serving/``). A rejected
        snapshot keeps serving on the last admitted state; rejection at
        construction (no last-good yet) raises."""
        admitted = self._guard.admit(backend.monitor())
        if admitted is None:
            if getattr(self, "state", None) is None:
                raise RuntimeError(
                    "serving: the first monitor snapshot was rejected by "
                    "the admission guard — no admitted state to serve from"
                )
            return self.state
        return admitted

    def refresh_snapshot(self) -> None:
        """Re-pull an admitted snapshot (between soak phases; the engine
        never monitors per request)."""
        self.state = self._admitted_snapshot(self._backend)
        self._node_names = list(self.state.node_names)

    # ---- lifecycle ----

    def start(self) -> "ServingEngine":
        if self._thread is not None:
            return self
        self._running = True
        self._thread = threading.Thread(
            target=self._run, name="krt-serving-batcher", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        with self._cond:
            self._running = False
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None

    def __enter__(self) -> "ServingEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ---- metrics plumbing ----

    def _reg(self) -> MetricsRegistry:
        return self.registry if self.registry is not None else get_registry()

    def _count_outcome(self, outcome: str) -> None:
        self._reg().counter(
            "serving_placements_total",
            "serving requests completed, by outcome",
            labelnames=("outcome",),
        ).labels(outcome=outcome).inc()

    def _count_shed(self, reason: str) -> None:
        self._reg().counter(
            "serving_shed_total",
            "serving requests shed under overload, by reason",
            labelnames=("reason",),
        ).labels(reason=reason).inc()

    def _observe_stage(self, stage: str, seconds: float) -> None:
        self._reg().histogram(
            "serving_request_seconds",
            "per-request serving latency decomposed by stage "
            "(queue_wait/batch_formation/device_dispatch/decode/total)",
            labelnames=("stage",),
            buckets=MICRO_BUCKETS,
        ).labels(stage=stage).observe(max(seconds, 0.0))

    def _set_inflight(self, n: int) -> None:
        self._inflight = n
        self._reg().gauge(
            "serving_inflight",
            "serving requests currently queued or in the forming batch",
        ).set(n)

    # ---- submission ----

    def place(
        self, service: str, *, deadline_ms: float | None = None
    ) -> PlaceResult:
        """Submit one request and block until its outcome. Raises
        ``ValueError`` for an unknown service (nothing is submitted —
        the HTTP front's 400 path); every submitted request resolves to
        exactly one counted outcome."""
        svc_idx = self._svc_index.get(service)
        if svc_idx is None:
            raise ValueError(
                f"unknown service {service!r} (not in the snapshot's "
                f"communication graph)"
            )
        if deadline_ms is None:
            deadline_ms = self.config.deadline_ms
        deadline = (
            time.perf_counter() + float(deadline_ms) / 1e3
            if deadline_ms and deadline_ms > 0
            else None
        )
        shed: PlaceResult | None = None
        with self._cond:
            self.submitted += 1
            seq = self._seq
            self._seq += 1
            ring_entry = {
                "request_id": seq,
                "service": service,
                "outcome": "inflight",
                "submitted_ts": time.time(),
            }
            self._ring.append(ring_entry)
            req = _Request(seq, service, svc_idx, deadline, ring_entry)
            if not self._running:
                shed = self._shed_locked(req, SHED_SHUTDOWN)
            elif len(self._queue) >= self.config.queue_depth:
                shed = self._shed_locked(req, SHED_QUEUE_FULL)
            else:
                self._queue.append(req)
                self._set_inflight(self._inflight + 1)
                self._cond.notify()
        if shed is not None:
            # feed ops only AFTER _cond is released: _feed_ops re-enters
            # _cond via summary()/ring(), and the batcher calls it without
            # holding _cond — feeding while holding _cond would invert the
            # lock order against the batcher's path (ABBA deadlock)
            self._feed_ops()
            return shed
        req.done.wait()
        assert req.result is not None
        return req.result

    def _shed_locked(self, req: _Request, reason: str) -> PlaceResult:
        """Complete a request as shed at admission. Caller holds _cond and
        must call :meth:`_feed_ops` after releasing it — never under it."""
        now = time.perf_counter()
        timings = {
            "queue_wait": 0.0,
            "batch_formation": 0.0,
            "device_dispatch": 0.0,
            "decode": 0.0,
            "total": (now - req.t_submit) * 1e3,
        }
        result = PlaceResult(
            request_id=req.seq,
            service=req.service,
            outcome=OUTCOME_SHED,
            shed_reason=reason,
            timings_ms=timings,
        )
        self.outcomes[OUTCOME_SHED] = self.outcomes.get(OUTCOME_SHED, 0) + 1
        self.shed_reasons[reason] = self.shed_reasons.get(reason, 0) + 1
        self._count_outcome(OUTCOME_SHED)
        self._count_shed(reason)
        req.ring_entry.update(outcome=OUTCOME_SHED, shed_reason=reason)
        req.result = result
        req.done.set()
        return result

    # ---- the batcher ----

    def _run(self) -> None:
        while True:
            with self._cond:
                while self._running and not self._queue:
                    self._cond.wait()
                if not self._queue:
                    break  # stopped and drained
                batch = [self._queue.popleft()]
                batch[0].t_dequeue = time.perf_counter()
                window_end = batch[0].t_dequeue + (
                    self.config.batch_window_ms / 1e3
                )
                while len(batch) < self.config.max_batch:
                    if self._queue:
                        req = self._queue.popleft()
                        req.t_dequeue = time.perf_counter()
                        batch.append(req)
                        continue
                    remaining = window_end - time.perf_counter()
                    if remaining <= 0 or not self._running:
                        break
                    self._cond.wait(remaining)
            self._process_batch(batch)
            with self._cond:
                self._set_inflight(len(self._queue))

    def _process_batch(self, batch: list[_Request]) -> None:
        import jax
        import jax.numpy as jnp

        t_closed = time.perf_counter()
        live: list[_Request] = []
        for req in batch:
            if req.deadline is not None and t_closed > req.deadline:
                self._complete_timeout(req, t_closed)
            else:
                live.append(req)
        if not live:
            return
        # pad to the static max_batch shape: ONE compiled signature for
        # every batch size (the 1-steady-state-trace invariant); padded
        # slots score service 0 under a folded key and are discarded
        B = self.config.max_batch
        svcs = np.zeros(B, dtype=np.int32)
        seqs = np.zeros(B, dtype=np.int64)
        for i, req in enumerate(live):
            svcs[i] = req.svc_idx
            seqs[i] = req.seq
        keys = jnp.stack(
            [
                jax.random.fold_in(self._base_key, int(s))
                for s in seqs
            ]
        )
        t0 = time.perf_counter()
        most, target, bundle = place_batch(
            self.state,
            self.graph,
            self._policy_id,
            self._threshold,
            jnp.asarray(svcs),
            keys,
            top_k=self._top_k,
        )
        jax.block_until_ready(target)
        t1 = time.perf_counter()
        most_h, target_h, bundle_h = jax.device_get((most, target, bundle))
        with self._cond:
            self.dispatches += 1
            n = len(live)
            self._batch_sizes[n] = self._batch_sizes.get(n, 0) + 1
        self._reg().histogram(
            "serving_batch_size",
            "live requests per coalesced serving dispatch",
            buckets=_BATCH_BUCKETS,
        ).observe(len(live))
        for i, req in enumerate(live):
            self._complete_placed(
                req,
                int(target_h[i]),
                int(most_h[i]),
                bundle_h[i],
                batch_size=len(live),
                t_closed=t_closed,
                t_dispatch=(t0, t1),
            )
        self._feed_ops()

    def _complete_timeout(self, req: _Request, now: float) -> None:
        timings = {
            "queue_wait": ((req.t_dequeue or now) - req.t_submit) * 1e3,
            "batch_formation": 0.0,
            "device_dispatch": 0.0,
            "decode": 0.0,
            "total": (now - req.t_submit) * 1e3,
        }
        result = PlaceResult(
            request_id=req.seq,
            service=req.service,
            outcome=OUTCOME_TIMEOUT,
            shed_reason=SHED_DEADLINE,
            timings_ms=timings,
        )
        # a timeout counts BOTH as outcome `timeout` and shed reason
        # `deadline`, in the metric AND the summary/healthz/ring views —
        # the two views must agree (OBSERVABILITY.md pins this)
        with self._cond:
            self.outcomes[OUTCOME_TIMEOUT] = (
                self.outcomes.get(OUTCOME_TIMEOUT, 0) + 1
            )
            self.shed_reasons[SHED_DEADLINE] = (
                self.shed_reasons.get(SHED_DEADLINE, 0) + 1
            )
        self._count_outcome(OUTCOME_TIMEOUT)
        self._count_shed(SHED_DEADLINE)
        req.ring_entry.update(shed_reason=SHED_DEADLINE)
        self._finish(req, result, timings)

    def _complete_placed(
        self,
        req: _Request,
        target: int,
        most: int,
        bundle,
        *,
        batch_size: int,
        t_closed: float,
        t_dispatch: tuple[float, float],
    ) -> None:
        t0, t1 = t_dispatch
        outcome = OUTCOME_PLACED if target >= 0 else OUTCOME_NO_CANDIDATE
        node = (
            self._node_names[target]
            if 0 <= target < len(self._node_names)
            else None
        )
        hazard = (
            self._node_names[most]
            if 0 <= most < len(self._node_names)
            else None
        )
        explain = greedy_explanation(
            bundle,
            self._node_names,
            round=0,
            seq=req.seq,
            policy=self.policy,
            service=req.service,
            hazard_node=hazard,
            chosen=node,
        )
        now = time.perf_counter()
        timings = {
            "queue_wait": ((req.t_dequeue or t_closed) - req.t_submit) * 1e3,
            "batch_formation": (t_closed - (req.t_dequeue or t_closed)) * 1e3,
            "device_dispatch": (t1 - t0) * 1e3,
            "decode": (now - t1) * 1e3,
            "total": (now - req.t_submit) * 1e3,
        }
        result = PlaceResult(
            request_id=req.seq,
            service=req.service,
            outcome=outcome,
            node=node,
            node_index=target,
            batch_size=batch_size,
            timings_ms=timings,
            explain=explain,
        )
        with self._cond:
            self.outcomes[outcome] = self.outcomes.get(outcome, 0) + 1
        self._count_outcome(outcome)
        req.ring_entry.update(node=node, batch_size=batch_size)
        self._finish(req, result, timings)

    def _finish(
        self, req: _Request, result: PlaceResult, timings: dict[str, float]
    ) -> None:
        for stage in STAGES:
            self._observe_stage(stage, timings.get(stage, 0.0) / 1e3)
        with self._cond:
            self._recent.append(timings["total"] / 1e3)
            self._completed += 1
        req.ring_entry.update(
            outcome=result.outcome, total_ms=timings["total"]
        )
        req.result = result
        req.done.set()

    # ---- observability feeds ----

    def summary(self) -> dict[str, Any]:
        """The rolling serving summary: /healthz's ``serving`` stanza and
        the ``serving_p99`` watchdog rule's input."""
        with self._cond:
            recent = list(self._recent)
            outcomes = dict(self.outcomes)
            sheds = dict(self.shed_reasons)
            batch_sizes = {str(k): v for k, v in sorted(self._batch_sizes.items())}
            submitted = self.submitted
            completed = self._completed
            dispatches = self.dispatches
            inflight = self._inflight
        uptime = max(time.perf_counter() - self._started_mono, 1e-9)
        q = (
            np.percentile(np.asarray(recent) * 1e3, [50, 95, 99])
            if recent
            else (0.0, 0.0, 0.0)
        )
        return {
            "submitted": submitted,
            "completed": completed,
            "count": len(recent),
            "rate_rps": completed / uptime,
            "p50_ms": float(q[0]),
            "p95_ms": float(q[1]),
            "p99_ms": float(q[2]),
            "batch_sizes": batch_sizes,
            "dispatches": dispatches,
            "outcomes": outcomes,
            "shed": sheds,
            "inflight": inflight,
        }

    def ring(self) -> list[dict[str, Any]]:
        """The bounded recent-request ring (newest last) — the payload
        breaker-open and serving_p99 flight-recorder bundles ship."""
        with self._cond:
            return [dict(e) for e in self._ring]

    def _feed_ops(self) -> None:
        if self.ops is None:
            return
        # never called while holding _cond — summary()/ring() re-enter it
        # briefly, and OpsPlane takes its own watchdog lock inside, so
        # the only legal order is _feed_lock → _cond / _feed_lock →
        # plane lock (the batcher and the admission-shed path both come
        # through here lock-free, which is what buries the old ABBA)
        with self._feed_lock:
            self.ops.observe_serving(self.summary(), requests=self.ring())
