"""Fleet mode, dp-mesh plane: one tenant per device.

The vmap plane (``solver.fleet``) batches tenants into one program on
one device — the right shape when the per-tenant kernel is small and
fixed cost dominates. On a multi-chip mesh the same tenant axis can
instead shard over ``dp``, exactly the way the sharded-restart machinery
(``parallel.sharded._run_shard``) shards independent solves: each dp
slice owns a contiguous block of tenants and runs the SAME vmapped
decision kernel over its block, so the two planes are decision-identical
by construction (the shard body IS ``solver.fleet._fleet_decide`` —
parity is structural, and test-pinned).

Like ``_run_shard``, the jitted shard_map is cached per mesh so the
multiplexed controller's per-round dispatch hits the compile cache, and
instrumented (``fn="fleet_solve_dp"``) under the usual 1-trace
steady-state invariant.
"""

from __future__ import annotations

from functools import partial

import jax
from jax.sharding import Mesh, PartitionSpec as P

from kubernetes_rescheduling_tpu.parallel.compat import shard_map
from kubernetes_rescheduling_tpu.solver.fleet import _fleet_decide
from kubernetes_rescheduling_tpu.telemetry.accounting import instrument_jit

# jitted shard-mapped fleet kernels keyed by mesh — the dp twin of
# parallel.sharded._RUN_SHARD_CACHE (same reuse rationale: the
# controller re-dispatches every round and must not retrace a fresh
# closure each time)
_FLEET_SHARD_CACHE: dict = {}


def _fleet_shard(mesh: Mesh):
    fn = _FLEET_SHARD_CACHE.get(mesh)
    if fn is None:

        @partial(
            shard_map,
            mesh=mesh,
            in_specs=(P("dp"), P("dp"), P(), P(), P("dp"), P("dp")),
            out_specs=(P("dp"), P("dp")),
            check_vma=False,
        )
        def run_shard(states, graphs, policy_id, threshold, keys, mask):
            # each shard's tenant block runs the SAME batched kernel the
            # vmap plane runs over the whole fleet — no collectives: the
            # tenants are independent clusters
            return _fleet_decide(
                states, graphs, policy_id, threshold, keys, mask
            )

        fn = instrument_jit(run_shard, name="fleet_solve_dp")
        _FLEET_SHARD_CACHE[mesh] = fn
    return fn


def fleet_solve_dp(
    states,
    graphs,
    policy_id: jax.Array,
    threshold: jax.Array,
    keys: jax.Array,
    tenant_mask: jax.Array,
    *,
    mesh: Mesh | None = None,
):
    """:func:`solver.fleet.fleet_solve` with the tenant axis sharded over
    the mesh's ``dp`` dimension — one (block of) tenant(s) per device.

    ``states``/``graphs`` are the stacked tenant pytrees
    (:func:`solver.fleet.stack_tenants`); the tenant count must divide by
    the mesh's dp extent. With no mesh given one is auto-shaped over the
    largest dp that divides the tenant count — on a single chip that
    degenerates to the vmap plane's single-device program, so the same
    call works from laptop CPU to a pod slice.
    """
    t = int(tenant_mask.shape[0])
    if mesh is None:
        from kubernetes_rescheduling_tpu.parallel.mesh import make_mesh
        from kubernetes_rescheduling_tpu.parallel.sharded import (
            _largest_divisor,
        )

        dp = _largest_divisor(t, len(jax.devices()))
        mesh = make_mesh(dp, shape=(dp, 1))
    dp = mesh.shape["dp"]
    if t % dp:
        raise ValueError(f"tenant count {t} must be a multiple of dp={dp}")
    return _fleet_shard(mesh)(
        states, graphs, policy_id, threshold, keys, tenant_mask
    )
