"""Fleet mode, dp-mesh plane: one tenant (group) per device.

The vmap plane (``solver.fleet`` / ``solver.fleet_global``) batches
tenants into one program on one device — the right shape when the
per-tenant kernel is small and fixed cost dominates. On a multi-chip
mesh the same tenant axis can instead shard over ``dp``, exactly the way
the sharded-restart machinery (``parallel.sharded._run_shard``) shards
independent solves: each dp slice owns a contiguous block of tenants and
runs the SAME batched kernel over its block, so the two planes are
decision-identical by construction (the shard bodies ARE
``solver.fleet._fleet_decide`` / ``_fleet_decide_proactive`` /
``solver.fleet_global._fleet_global_solve`` — parity is structural, and
test-pinned).

Three dp kernels, one per batched decision plane:

- :func:`fleet_solve_dp` — the greedy decide (PR 6);
- :func:`fleet_solve_proactive_dp` — the proactive decide against each
  tenant's predicted state (the forecast RLS state itself stays a
  single-device ``lax.map`` program in ``forecast.fleet`` — its per-round
  deltas shard here with the states);
- :func:`fleet_global_solve_dp` — the batched global solve, one tenant
  group's full re-placement (restart fan-out included) per device. This
  is the MULTICHIP fleet-matrix configuration: ~1k tenants × 2k services
  sharded one-group-per-chip with per-tenant decisions bit-exact vs the
  solo kernels.

Like ``_run_shard``, each jitted shard_map is cached per mesh (and, for
the global solve, per static config) so the multiplexed controller's
per-round dispatch hits the compile cache, and instrumented under the
usual 1-trace steady-state invariant.

Parity boundary (global solve): the shard bodies are the vmap plane's
functions, so parity is structural — and bitwise on every objective
term that is EXACT in f32 (comm cut mass and the disruption bill:
integer-valued pair weights times replica counts). The sqrt-balance
term is irrational, and a differently-partitioned executable (one
tenant group per device vs one batch on one device) may reduce it in a
different order — enough to flip a near-tie admission and land on a
DIFFERENT never-worse optimum of the same quality (measured on the
8-device CPU mesh; test-pinned as never-worse, with bitwise parity
pinned on the balance-free configuration). This is the same
ulps-not-bitwise contract ``input_comm_cost`` documents for its two
branches — cross-executable float reduction order is not part of any
kernel's contract.
"""

from __future__ import annotations

from functools import partial

import jax
from jax.sharding import Mesh, PartitionSpec as P

from kubernetes_rescheduling_tpu.parallel.compat import shard_map
from kubernetes_rescheduling_tpu.solver.fleet import (
    _fleet_decide,
    _fleet_decide_proactive,
)
from kubernetes_rescheduling_tpu.solver.fleet_global import (
    _fleet_global_solve,
)
from kubernetes_rescheduling_tpu.telemetry.accounting import instrument_jit

# jitted shard-mapped fleet kernels keyed by mesh — the dp twin of
# parallel.sharded._RUN_SHARD_CACHE (same reuse rationale: the
# controller re-dispatches every round and must not retrace a fresh
# closure each time)
_FLEET_SHARD_CACHE: dict = {}


def _fleet_shard(mesh: Mesh):
    fn = _FLEET_SHARD_CACHE.get(mesh)
    if fn is None:

        @partial(
            shard_map,
            mesh=mesh,
            in_specs=(P("dp"), P("dp"), P(), P(), P("dp"), P("dp")),
            out_specs=(P("dp"), P("dp")),
            check_vma=False,
        )
        def run_shard(states, graphs, policy_id, threshold, keys, mask):
            # each shard's tenant block runs the SAME batched kernel the
            # vmap plane runs over the whole fleet — no collectives: the
            # tenants are independent clusters
            return _fleet_decide(
                states, graphs, policy_id, threshold, keys, mask
            )

        fn = instrument_jit(run_shard, name="fleet_solve_dp")
        _FLEET_SHARD_CACHE[mesh] = fn
    return fn


def fleet_solve_dp(
    states,
    graphs,
    policy_id: jax.Array,
    threshold: jax.Array,
    keys: jax.Array,
    tenant_mask: jax.Array,
    *,
    mesh: Mesh | None = None,
):
    """:func:`solver.fleet.fleet_solve` with the tenant axis sharded over
    the mesh's ``dp`` dimension — one (block of) tenant(s) per device.

    ``states``/``graphs`` are the stacked tenant pytrees
    (:func:`solver.fleet.stack_tenants`); the tenant count must divide by
    the mesh's dp extent. With no mesh given one is auto-shaped over the
    largest dp that divides the tenant count — on a single chip that
    degenerates to the vmap plane's single-device program, so the same
    call works from laptop CPU to a pod slice.
    """
    mesh = _fleet_mesh(int(tenant_mask.shape[0]), mesh)
    return _fleet_shard(mesh)(
        states, graphs, policy_id, threshold, keys, tenant_mask
    )


def _fleet_mesh(t: int, mesh: Mesh | None) -> Mesh:
    """Resolve (or auto-shape) the fleet dp mesh and validate that the
    tenant count divides its dp extent — ONE rule for all three dp
    kernels."""
    if mesh is None:
        from kubernetes_rescheduling_tpu.parallel.mesh import make_mesh
        from kubernetes_rescheduling_tpu.parallel.sharded import (
            _largest_divisor,
        )

        dp = _largest_divisor(t, len(jax.devices()))
        mesh = make_mesh(dp, shape=(dp, 1))
    dp = mesh.shape["dp"]
    if t % dp:
        raise ValueError(f"tenant count {t} must be a multiple of dp={dp}")
    return mesh


def dp_device_names(
    mesh: Mesh | None = None, *, tenants: int | None = None
) -> tuple[str, ...]:
    """Device *names* along the fleet dp axis, in dp order — what the
    telemetry mesh plane labels its per-device readings with. Resolves
    the mesh exactly the way the dp kernels do (:func:`_fleet_mesh`
    auto-shaping when none is given), so name ``i`` is always the
    device that runs tenant block ``i``. Names are event/endpoint data
    only — the cardinality checker bans ``device`` as a raw metric
    label outside the budget-gated families."""
    from kubernetes_rescheduling_tpu.parallel.sharded import dp_devices

    if mesh is None:
        if tenants is None:
            raise ValueError("need a mesh or a tenant count to shape one")
        mesh = _fleet_mesh(int(tenants), None)
    return tuple(str(d) for d in dp_devices(mesh))


# dp twins of the proactive decide and the batched global solve — cached
# like _FLEET_SHARD_CACHE (the controller re-dispatches per round and
# must not retrace a fresh closure each time)
_FLEET_PROACTIVE_SHARD_CACHE: dict = {}
_FLEET_GLOBAL_SHARD_CACHE: dict = {}


def _fleet_proactive_shard(mesh: Mesh):
    fn = _FLEET_PROACTIVE_SHARD_CACHE.get(mesh)
    if fn is None:

        @partial(
            shard_map,
            mesh=mesh,
            in_specs=(P("dp"), P("dp"), P(), P(), P("dp"), P("dp"), P("dp")),
            out_specs=(P("dp"), P("dp")),
            check_vma=False,
        )
        def run_shard(states, graphs, policy_id, threshold, keys, mask,
                      deltas):
            # the shard body IS the vmap plane's batched proactive kernel
            return _fleet_decide_proactive(
                states, graphs, policy_id, threshold, keys, mask, deltas
            )

        fn = instrument_jit(run_shard, name="fleet_solve_proactive_dp")
        _FLEET_PROACTIVE_SHARD_CACHE[mesh] = fn
    return fn


def fleet_solve_proactive_dp(
    states,
    graphs,
    policy_id: jax.Array,
    threshold: jax.Array,
    keys: jax.Array,
    tenant_mask: jax.Array,
    deltas: jax.Array,
    *,
    mesh: Mesh | None = None,
):
    """:func:`solver.fleet.fleet_solve_proactive` with the tenant axis
    (states, keys, mask, AND the per-tenant forecast deltas) sharded
    over the mesh's ``dp`` dimension — the proactive twin of
    :func:`fleet_solve_dp`."""
    mesh = _fleet_mesh(int(tenant_mask.shape[0]), mesh)
    return _fleet_proactive_shard(mesh)(
        states, graphs, policy_id, threshold, keys, tenant_mask, deltas
    )


def _fleet_global_shard(mesh: Mesh, config, n_restarts: int):
    cache_key = (mesh, config, n_restarts)
    fn = _FLEET_GLOBAL_SHARD_CACHE.get(cache_key)
    if fn is None:

        @partial(
            shard_map,
            mesh=mesh,
            in_specs=(P("dp"), P("dp"), P("dp"), P("dp")),
            out_specs=P("dp"),
            check_vma=False,
        )
        def run_shard(states, graphs, keys, mask):
            # the shard body IS the vmap plane's batched global solve —
            # its flat bundle concatenates over the tenant axis shard
            return _fleet_global_solve(
                states, graphs, keys, mask,
                config=config, n_restarts=n_restarts,
            )

        fn = instrument_jit(run_shard, name="fleet_global_solve_dp")
        _FLEET_GLOBAL_SHARD_CACHE[cache_key] = fn
    return fn


def fleet_global_solve_dp(
    states,
    graphs,
    keys: jax.Array,
    tenant_mask: jax.Array,
    *,
    config,
    n_restarts: int = 1,
    mesh: Mesh | None = None,
):
    """:func:`solver.fleet_global.fleet_global_solve` with the tenant
    axis sharded over the mesh's ``dp`` dimension — one tenant group's
    global re-placement per device, the fleet-matrix MULTICHIP shape.

    The flat per-shard bundles concatenate along dp into the SAME layout
    the vmap plane emits, so ``decode_fleet_global`` serves both planes
    unchanged — but note the concatenation is per-shard-blockwise: each
    shard's ``[svc_target, first_pod, obj]`` triple is contiguous.
    :func:`decode_fleet_global_dp` re-interleaves to the vmap layout."""
    t = int(tenant_mask.shape[0])
    mesh = _fleet_mesh(t, mesh)
    return _fleet_global_shard(mesh, config, n_restarts)(
        states, graphs, keys, tenant_mask
    )


def decode_fleet_global_dp(flat, *, tenants: int, num_services: int, dp: int):
    """Decode the dp plane's bundle: each dp shard emitted the vmap
    layout over ITS tenant block, concatenated — re-split per shard and
    merge the per-tenant move lists/objective rows in tenant order."""
    import numpy as np

    from kubernetes_rescheduling_tpu.solver.fleet_global import (
        decode_fleet_global,
    )

    flat = np.asarray(flat)
    if tenants % dp:
        raise ValueError(f"tenants {tenants} not divisible by dp={dp}")
    per = tenants // dp
    block = flat.reshape(dp, -1)
    moves, objs = [], []
    for d in range(dp):
        m, o = decode_fleet_global(
            block[d], tenants=per, num_services=num_services
        )
        moves.extend(m)
        objs.extend(o)
    return moves, objs
