"""Device-mesh parallelism.

The reference's "distributed backend" is the Kubernetes REST API plus
pod-to-pod REST over Calico (SURVEY.md §5.8) — there is nothing to port.
TPU-natively, the solver's collectives ride ICI via XLA:

- ``make_mesh`` — build a ``jax.sharding.Mesh`` over available devices
  (dp = restarts/services, tp = nodes).
- ``parallel_restarts`` — data-parallel multi-restart global solve: R
  restarts sharded over dp, best result selected on device.
- ``solve_with_restarts`` — the production wrapper: best-of-N with an
  auto-built mesh, degenerating to a batched single-device solve.
- ``sharded_choose_node`` — the policy kernel with the node axis sharded
  over tp: per-shard lexicographic maxima combined with all-gather.
- ``sharded_global_assign`` — the flagship solver with the NODE axis
  sharded over tp: per-shard scoring, all_gather'd argmax, psum'd
  current-score/slack contributions — O(C) scalars over ICI per step.
- ``sharded_solve_with_restarts`` — dp restarts *of* tp-sharded solves:
  the two axes composed on one mesh, best-of-N selected on device.
- ``fleet_solve_dp`` — fleet mode's dp plane: the multi-tenant decision
  batch (``solver.fleet``) with the tenant axis sharded one-per-device
  over ``dp``, via the same cached shard_map pattern as the restarts.
"""

from kubernetes_rescheduling_tpu.parallel.mesh import make_mesh
from kubernetes_rescheduling_tpu.parallel.sharded import (
    parallel_restarts,
    sharded_choose_node,
    solve_with_restarts,
)
from kubernetes_rescheduling_tpu.parallel.sharded_solver import (
    sharded_global_assign,
    sharded_solve_with_restarts,
)
from kubernetes_rescheduling_tpu.parallel.sharded_sparse import (
    sharded_sparse_assign,
)
from kubernetes_rescheduling_tpu.parallel.fleet import fleet_solve_dp

__all__ = [
    "make_mesh",
    "parallel_restarts",
    "sharded_choose_node",
    "sharded_global_assign",
    "sharded_sparse_assign",
    "sharded_solve_with_restarts",
    "solve_with_restarts",
    "fleet_solve_dp",
]
