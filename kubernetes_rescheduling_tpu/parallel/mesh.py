"""Mesh construction helpers."""

from __future__ import annotations

import math

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(
    n_devices: int | None = None,
    axis_names: tuple[str, ...] = ("dp", "tp"),
    shape: tuple[int, ...] | None = None,
) -> Mesh:
    """Build a mesh over the first ``n_devices`` devices.

    Default shape puts everything on ``dp`` (restart/data parallelism) with
    ``tp`` (node-axis sharding) of 1; pass ``shape`` for a custom split.
    On a single chip this degenerates to a 1×1 mesh, so the same pjit'd
    program runs anywhere.
    """
    devices = jax.devices()
    n = n_devices or len(devices)
    if n > len(devices):
        raise ValueError(f"requested {n} devices, only {len(devices)} available")
    if shape is None:
        shape = (n,) + (1,) * (len(axis_names) - 1)
    if math.prod(shape) != n:
        raise ValueError(f"mesh shape {shape} != {n} devices")
    return Mesh(np.asarray(devices[:n]).reshape(shape), axis_names)
