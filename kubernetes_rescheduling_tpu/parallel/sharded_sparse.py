"""Node-axis-sharded SPARSE solver: the block-local form as SPMD.

The sparse single-chip solver (solver/sparse_solver.py) breaks the dense
SP² weight wall; this module shards its NODE axis over the mesh's ``tp``
dimension the same way the dense ``sharded_global_assign`` does:

- sharded: per-node loads/capacities; each shard computes the chunk's
  neighbor mass for ITS node columns only (the block-local matmul twins
  take a ``col_offset`` — contraction work divides by tp).
- replicated: the block-local weights (``w_local`` is small — that is the
  whole point of the sparse form: 388 MB at 50k services, so replication
  is cheap where the dense form could not even be allocated), neighbor
  ids, service vectors, the assignment, and the COO edge list.
- collectives per chunk step: the SHARED ``sharded_place``
  (parallel/sharded_solver.py) — all_gather of per-shard top-1, psum of
  cur-score and landing slack. The decision math cannot fork from the
  dense sharded solver because it IS the same function.

Sweep structure mirrors the single-chip sparse solver exactly (hub groups
first with the same key stream, then randomized regular chunks over the
same composition), so with annealing noise off and balance_weight 0 the
sharded solve makes bit-identical decisions (parity-tested at tp=4).

Plain shard_map + XLA, like the dense sharded solver — the Pallas kernels
optimize single-chip launch count; here the structure exists to scale
FLOPs across chips.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from kubernetes_rescheduling_tpu.parallel.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from kubernetes_rescheduling_tpu.core.sparsegraph import (
    BLOCK_R,
    SparseCommGraph,
    rv_weighted_edge_w,
)
from kubernetes_rescheduling_tpu.core.state import ClusterState
from kubernetes_rescheduling_tpu.objectives.metrics import load_std
from kubernetes_rescheduling_tpu.ops.sparse_mass import (
    chunk_local_slabs,
    reference_hub_mass,
    reference_sparse_mass,
)
from kubernetes_rescheduling_tpu.parallel.sharded_solver import (
    sharded_place,
    sharded_swap,
)
from kubernetes_rescheduling_tpu.solver.global_solver import (
    GlobalSolverConfig,
    auto_chunk,
    pod_restart_bill,
)
from kubernetes_rescheduling_tpu.solver.swap import scan_sweeps, swap_flags
from kubernetes_rescheduling_tpu.solver.sparse_solver import (
    hub_slab,
    sorted_problem_arrays,
    sparse_pod_comm_cost,
)

_SOLVE_CACHE: dict = {}


def _geometry(sgraph: SparseCommGraph, config: GlobalSolverConfig):
    S = sgraph.num_services
    C = min(auto_chunk(S, config.chunk_size), S)
    KB = max(1, C // BLOCK_R)
    NBR = len(sgraph.regular_blocks)
    n_chunks = max(1, -(-NBR // KB)) if NBR else 0
    ndummy = n_chunks * KB - NBR
    SPX = sgraph.sp + ndummy * BLOCK_R
    hub_groups = [
        tuple(sgraph.hub_blocks[g : g + KB])
        for g in range(0, len(sgraph.hub_blocks), KB)
    ]
    return C, KB, n_chunks, ndummy, SPX, hub_groups


def _solve_factory(
    config: GlobalSolverConfig, sgraph_meta, S: int, N: int, tp: int
):
    """Shard-local sparse solve body. ``sgraph_meta`` carries only STATIC
    graph structure (block offsets/widths, hub groups) — all arrays arrive
    as shard_map arguments."""
    (
        C, KB, n_chunks, ndummy, SPX, hub_groups,
        block_toff, block_ntiles, bu, reg_tiles,
    ) = sgraph_meta
    Nl = N // tp
    ow = config.overload_weight if config.enforce_capacity else 0.0
    # numpy, NOT jnp — see sharded_solver._solve_factory: the factory can
    # run inside an outer trace and the cached closure must not capture a
    # tracer
    temps = config.noise_temp * (
        1.0
        - np.arange(config.sweeps, dtype=np.float32)
        / max(config.sweeps - 1, 1)
    )
    # per-sweep swap-phase flags (numpy — same trace-agnostic reasoning);
    # hub groups sit the swap phase out, mirroring the single-chip sparse
    # solver
    swf = swap_flags(config.sweeps, config.swap_every)
    C_eff = KB * BLOCK_R
    use_swaps = config.swap_every > 0
    # static slab boundaries for the hub groups' concatenated columns
    group_widths = [
        sum(block_ntiles[b] * bu for b in g) for g in hub_groups
    ]
    group_lo = np.concatenate([[0], np.cumsum(group_widths)]).astype(int)

    class _Meta:  # duck-typed sgraph for reference_hub_mass (static fields)
        pass

    meta = _Meta()
    meta.block_toff = block_toff
    meta.block_ntiles = block_ntiles
    meta.bu = bu
    meta.hub_blocks = tuple(b for g in hub_groups for b in g)

    def solve_one(
        assign_init, w_mm, u_ids, rvu, rv_s, svc_valid, svc_cpu, svc_mem,
        toff_ext, reg_ext, hub_ids_all, u_hub_all, rvu_hub_all,
        e_src, e_dst, e_rvw,
        cap_l, mem_cap_l, base_cpu_l, base_mem_l, valid_l, keys_r,
    ):
        shard = lax.axis_index("tp")
        col0 = shard * Nl
        gcol = col0 + lax.broadcasted_iota(jnp.int32, (1, Nl), 1)
        nvalid = jnp.maximum(lax.psum(jnp.sum(valid_l), "tp"), 1)

        def local_loads(assign):
            owned = (assign[:, None] == gcol) & svc_valid[:, None]
            of = owned.astype(jnp.float32)
            return base_cpu_l + svc_cpu @ of, base_mem_l + svc_mem @ of

        def _balance_terms(cpu_l):
            pct = jnp.where(valid_l, cpu_l / cap_l * 100.0, 0.0)
            s1 = lax.psum(jnp.sum(pct), "tp")
            s2 = lax.psum(jnp.sum(pct * pct), "tp")
            mean = s1 / nvalid
            var = jnp.maximum(s2 / nvalid - mean * mean, 0.0)
            over = lax.psum(jnp.sum(jnp.maximum(pct - 100.0, 0.0)), "tp")
            return config.balance_weight * jnp.sqrt(var) + ow * over

        # ``e_rvw`` arrives PRECOMPUTED (``_prep`` calls the canonical
        # core.sparsegraph.rv_weighted_edge_w outside the shard_map body,
        # replicated like the rest of the edge list): rv is fixed across
        # sweeps, so the per-sweep objective gathers only the two assign
        # columns instead of four (measured ~2.4 of the 2.6 ms/sweep
        # objective cost at 50k) — and the single-chip and sharded solvers
        # now share ONE product grouping by construction, so the tp
        # bit-parity contract cannot drift through a hand-copied formula.

        def objective(assign, cpu_l):
            """EXACT sparse cut-sum (replicated — every shard computes the
            same value from the replicated edge list) + psum'd balance."""
            cut = (assign[e_src] != assign[e_dst]).astype(jnp.float32)
            comm = 0.5 * jnp.sum(e_rvw * cut)
            return comm + _balance_terms(cpu_l)

        # disruption pricing: penalized per-sweep ranking, raw exact return
        # (mirrors the single-chip sparse solver)
        mc_on = config.move_cost > 0
        pen_vec = config.move_cost * rv_s if mc_on else None

        def move_penalty(assign):
            return config.move_cost * jnp.sum(
                jnp.where(svc_valid & (assign != assign_init), rv_s, 0.0)
            )

        def objective_rank(assign, cpu_l):
            obj = objective(assign, cpu_l)
            return obj + move_penalty(assign) if mc_on else obj

        def place(inner, ids, M, chunk_key, temp):
            assign, cpu_l, mem_l = inner
            valid_c = svc_valid[ids]
            c_cpu = svc_cpu[ids]
            c_mem = svc_mem[ids]
            cur = assign[ids]
            new_node, admitted, _, d_cpu, d_mem = sharded_place(
                M, cur, valid_c, c_cpu, c_mem, cpu_l, mem_l,
                cap_l, mem_cap_l, valid_l, gcol, N, config, ow,
                chunk_key, temp, shard,
                home=assign_init[ids] if mc_on else None,
                move_pen=pen_vec[ids] if mc_on else None,
            )
            return (
                (assign.at[ids].set(new_node), cpu_l + d_cpu, mem_l + d_mem),
                admitted,
            )

        def chunk_slabs(blocks):
            starts = toff_ext[blocks] * bu
            return chunk_local_slabs(u_ids, rvu, starts, reg_tiles * bu)

        def chunk_mass(tgt_c, rvu_c, blocks, ids, nn, off):
            """Mass of the chunk's rows against targets ``tgt_c`` over
            ``nn`` columns from ``off`` — the shard's node columns for M
            (nn=Nl, off=col0), chunk position for the swap phase's
            replicated Wc (nn=C_eff, off=0)."""
            raw = reference_sparse_mass(
                w_mm, tgt_c, rvu_c, blocks, toff_ext,
                num_nodes=nn, bu=bu, reg_tiles=reg_tiles, col_offset=off,
            )
            return raw * rv_s[ids][:, None]

        def make_sweep(do_swap: bool):
            return partial(sweep, do_swap=do_swap)

        def sweep(carry, xs, do_swap: bool = False):
            sweep_key, temp = xs
            assign, cpu_l, mem_l, best_assign, best_obj = carry
            perm_key, noise_key = jax.random.split(sweep_key)
            hub_moves = jnp.int32(0)
            if hub_groups:
                keys = jax.random.split(noise_key, n_chunks + len(hub_groups))
                chunk_keys = keys[:n_chunks]
                inner = (assign, cpu_l, mem_l)
                hub_cursor = 0
                for g, blocks_g in enumerate(hub_groups):
                    assign = inner[0]
                    lo, hi = int(group_lo[g]), int(group_lo[g + 1])
                    u_g = u_hub_all[lo:hi]
                    rvu_g = rvu_hub_all[lo:hi]
                    tgt_g = assign[jnp.clip(u_g, 0, SPX - 1)]
                    ids_g = lax.dynamic_slice(
                        hub_ids_all,
                        (hub_cursor,),
                        (len(blocks_g) * BLOCK_R,),
                    )
                    raw = reference_hub_mass(
                        meta, w_mm, tgt_g, rvu_g,
                        num_nodes=Nl, blocks=blocks_g, col_offset=col0,
                    )
                    M = raw * rv_s[ids_g][:, None]
                    inner, g_adm = place(
                        inner, ids_g, M, keys[n_chunks + g], temp
                    )
                    hub_moves = hub_moves + jnp.sum(g_adm)
                    hub_cursor += len(blocks_g) * BLOCK_R
                assign, cpu_l, mem_l = inner
            else:
                chunk_keys = jax.random.split(noise_key, n_chunks)
            bp = jax.random.permutation(perm_key, n_chunks * KB)
            chunk_blocks = reg_ext[bp].reshape(n_chunks, KB)
            chunk_ids = (
                chunk_blocks[:, :, None] * BLOCK_R
                + jnp.arange(BLOCK_R, dtype=jnp.int32)[None, None, :]
            ).reshape(n_chunks, KB * BLOCK_R)

            def chunk_step(inner, xs_c):
                blocks, ids, chunk_key = xs_c
                assign = inner[0]
                u_c, rvu_c = chunk_slabs(blocks)
                M = chunk_mass(
                    assign[jnp.clip(u_c, 0, SPX - 1)], rvu_c, blocks, ids,
                    Nl, col0,
                )
                inner, admitted = place(inner, ids, M, chunk_key, temp)
                n_moves = jnp.sum(admitted)
                if not (use_swaps and do_swap):  # STATIC (scan_sweeps)
                    return inner, (n_moves, jnp.int32(0))

                assign2, cpu2, mem2 = inner
                cur2 = assign2[ids]
                pos = (
                    jnp.full((SPX,), C_eff, jnp.int32)
                    .at[ids]
                    .set(jnp.arange(C_eff, dtype=jnp.int32))
                )
                # replicated Wc (chunk position as the "node" axis) —
                # every shard computes the same full [C_eff, C_eff]
                Wc = chunk_mass(
                    pos[jnp.clip(u_c, 0, SPX - 1)], rvu_c, blocks,
                    ids, C_eff, 0,
                )
                new2, swapped, n_sw, d_c, d_m = sharded_swap(
                    M, Wc, cur2,
                    svc_valid[ids] & ~admitted,
                    svc_cpu[ids], svc_mem[ids],
                    cpu2, mem2, cap_l, mem_cap_l, valid_l, gcol,
                    config, ow, col0=col0,
                    home=assign_init[ids] if mc_on else None,
                    move_pen=pen_vec[ids] if mc_on else None,
                )
                return (
                    assign2.at[ids].set(new2), cpu2 + d_c, mem2 + d_m
                ), (n_moves, n_sw)

            # chunk_step closes over the sweep's STATIC do_swap
            (assign, _, _), (moves, _) = lax.scan(
                chunk_step, (assign, cpu_l, mem_l),
                (chunk_blocks, chunk_ids, chunk_keys),
            )
            cpu_fresh, mem_fresh = local_loads(assign)
            obj = objective_rank(assign, cpu_fresh)
            better = obj < best_obj
            best_assign = jnp.where(better, assign, best_assign)
            best_obj = jnp.where(better, obj, best_obj)
            return (
                (assign, cpu_fresh, mem_fresh, best_assign, best_obj),
                jnp.sum(moves) + hub_moves,
            )

        cpu0, mem0 = local_loads(assign_init)
        obj0 = objective_rank(assign_init, cpu0)
        (_, _, _, best_assign, best_obj), _ = scan_sweeps(
            make_sweep, (assign_init, cpu0, mem0, assign_init, obj0),
            keys_r, temps, swf,
        )
        # the scan ranked with the penalized objective; return the RAW
        # exact value — the entry's adopt gate re-prices with the exact
        # pod-level restart bill
        if mc_on:
            best_obj = objective(best_assign, local_loads(best_assign)[0])
        return best_assign, best_obj

    return solve_one


_IN_SPECS = (
    # replicated problem data
    P(), P(), P(), P(), P(), P(), P(), P(),
    P(), P(), P(), P(), P(),
    P(), P(), P(),
    # node-axis-sharded per-node vectors
    P("tp"), P("tp"), P("tp"), P("tp"), P("tp"),
    # keys (replicated)
    P(),
)


def _build_solve(mesh, config, sgraph_meta, S, N):
    # the FULL meta (incl. per-block offsets/widths) keys the cache: the
    # factory bakes group_lo slab boundaries and the chunk slab width into
    # the compiled closure, so two graphs agreeing only on counts must not
    # share a solver
    cache_key = (mesh, config, sgraph_meta, S, N)
    fn = _SOLVE_CACHE.get(cache_key)
    if fn is not None:
        return fn
    solve_one = _solve_factory(config, sgraph_meta, S, N, mesh.shape["tp"])
    fn = jax.jit(
        partial(
            shard_map,
            mesh=mesh,
            in_specs=_IN_SPECS,
            out_specs=(P(), P()),
            check_vma=False,
        )(solve_one)
    )
    _SOLVE_CACHE[cache_key] = fn
    return fn


def _build_solve_restarts(mesh, config, sgraph_meta, S, N, r_local):
    """dp restarts of tp-sharded SPARSE solves — the sparse twin of
    ``sharded_solver._build_solve_restarts`` (same selection semantics:
    each dp slice scans its restarts sequentially, the winner is picked
    by the GATED PENALIZED value min(raw + exact pod restart bill,
    input objective) in global restart order)."""
    from kubernetes_rescheduling_tpu.solver.global_solver import (
        restart_bill_from_arrays,
    )

    cache_key = (mesh, config, sgraph_meta, S, N, r_local)
    fn = _SOLVE_CACHE.get(cache_key)
    if fn is not None:
        return fn
    solve_one = _solve_factory(config, sgraph_meta, S, N, mesh.shape["tp"])

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(*_IN_SPECS[:-1], P(), P(), P(), P(), P("dp")),
        out_specs=(P(), P(), P()),
        check_vma=False,
    )
    def solve_r(
        assign_init, w_mm, u_ids, rvu, rv_s, svc_valid, svc_cpu, svc_mem,
        toff_ext, reg_ext, hub_ids_all, u_hub_all, rvu_hub_all,
        e_src, e_dst, e_rvw,
        cap_l, mem_cap_l, base_cpu_l, base_mem_l, valid_l,
        pod_slot, pod_node0, pod_mask, obj_true0, keys_block,
    ):
        def body(carry, keys_r):
            ba, bo = solve_one(
                assign_init, w_mm, u_ids, rvu, rv_s, svc_valid, svc_cpu,
                svc_mem, toff_ext, reg_ext, hub_ids_all, u_hub_all,
                rvu_hub_all, e_src, e_dst, e_rvw,
                cap_l, mem_cap_l, base_cpu_l, base_mem_l, valid_l, keys_r,
            )
            return carry, (ba, bo)

        _, (assigns, objs) = lax.scan(body, 0, keys_block)
        tgts = assigns[:, pod_slot]                               # [r, P]
        bills = jax.vmap(
            lambda t: restart_bill_from_arrays(
                pod_mask, pod_node0, t, config.move_cost
            )
        )(tgts)
        gated = jnp.minimum(objs + bills, obj_true0)
        all_gated = lax.all_gather(gated, "dp", tiled=True)       # [R]
        all_objs = lax.all_gather(objs, "dp", tiled=True)         # [R]
        all_assigns = lax.all_gather(assigns, "dp", tiled=True)   # [R, SPX]
        best = jnp.argmin(all_gated)
        return all_assigns[best], all_objs[best], all_gated

    fn = jax.jit(solve_r)
    _SOLVE_CACHE[cache_key] = fn
    return fn


def _validate(state, sgraph, config, mesh):
    if not config.capacity_frac > 0:
        raise ValueError(f"capacity_frac must be > 0, got {config.capacity_frac}")
    if sgraph.num_blocks <= 1:
        raise ValueError(
            "single-block sparse graphs delegate to the dense solver; use "
            "global_assign_sparse (or sharded_global_assign) instead"
        )
    if sgraph.weight_bytes() > config.max_weight_bytes:
        # same sizing contract as the single-chip sparse solver — w_local
        # is REPLICATED per shard, so the budget matters at least as much
        raise ValueError(
            f"sparse pair weights need {sgraph.weight_bytes() / 2**30:.2f} "
            f"GiB — over max_weight_bytes; the graph is too dense for the "
            "sparse form (use the dense solver)."
        )
    tp = mesh.shape["tp"]
    N = state.num_nodes
    if N % tp:
        raise ValueError(f"num_nodes {N} must be a multiple of tp={tp}")
    return tp, sgraph.num_services, N


def _prep(state, sgraph, config, N):
    """Problem arrays in the shard_map argument order (minus keys) plus
    ``(sgraph_meta, cap, SPX)`` — ONE preamble for the single-restart and
    dp-restarts entries (the decision parity between them depends on it).
    """
    C, KB, n_chunks, ndummy, SPX, hub_groups = _geometry(sgraph, config)
    sgraph_meta = (
        C, KB, n_chunks, ndummy, SPX, tuple(hub_groups),
        sgraph.block_toff, sgraph.block_ntiles, sgraph.bu, sgraph.reg_tiles,
    )

    # ---- sorted-space arrays: THE single-chip sparse solver's preamble
    # (one definition — the tp=4/8 bit-parity test pins the two paths) ----
    svc_valid, svc_cpu_s, svc_mem_s, cur_s, rv_s, rvu = sorted_problem_arrays(
        state, sgraph, SPX
    )
    w_mm = sgraph.w_local.astype(jnp.dtype(config.matmul_dtype))
    assign0 = jnp.where(svc_valid, jnp.clip(cur_s, 0, N - 1), 0)

    toff_ext = jnp.asarray(
        np.asarray(
            list(sgraph.block_toff) + [sgraph.zero_toff] * ndummy,
            dtype=np.int32,
        )
    )
    NB = sgraph.num_blocks
    reg_ext = jnp.asarray(
        np.asarray(
            list(sgraph.regular_blocks) + [NB + d for d in range(ndummy)],
            dtype=np.int32,
        )
    )
    flat_hubs = [b for g in hub_groups for b in g]
    if flat_hubs:
        hub_ids_all = jnp.asarray(
            np.concatenate(
                [np.arange(BLOCK_R, dtype=np.int32) + b * BLOCK_R for b in flat_hubs]
            )
        )
        u_hub_all, rvu_hub_all = hub_slab(sgraph, flat_hubs, rv_s, SPX)
    else:
        hub_ids_all = jnp.zeros((0,), jnp.int32)
        u_hub_all = jnp.zeros((0,), jnp.int32)
        rvu_hub_all = jnp.zeros((0,), jnp.float32)

    cpu_cap = jnp.where(state.node_valid, state.node_cpu_cap, 0.0)
    mem_cap_raw = jnp.where(state.node_valid, state.node_mem_cap, 0.0)
    mem_cap = (
        jnp.where(mem_cap_raw > 0, mem_cap_raw, jnp.inf) * config.capacity_frac
    )
    cap = jnp.where(cpu_cap > 0, cpu_cap, 1.0) * config.capacity_frac

    # per-edge rv-weighted weight through the ONE canonical helper, built
    # here (outside the shard_map body) and replicated like the rest of
    # the edge list — the solver bodies consume it directly instead of
    # re-deriving the product by hand (the three-site bit-parity hazard)
    e_rvw = rv_weighted_edge_w(sgraph, rv_s)
    args = (
        assign0, w_mm, sgraph.u_ids, rvu, rv_s, svc_valid, svc_cpu_s,
        svc_mem_s, toff_ext, reg_ext, hub_ids_all, u_hub_all, rvu_hub_all,
        sgraph.edges_src, sgraph.edges_dst, e_rvw,
        cap, mem_cap, state.node_base_cpu, state.node_base_mem,
        state.node_valid,
    )
    return sgraph_meta, args, cap, SPX


def _true_objective(state, sgraph, config, cap):
    ow = config.overload_weight if config.enforce_capacity else 0.0
    pct0 = jnp.where(state.node_valid, state.node_cpu_used() / cap * 100.0, 0.0)
    return (
        sparse_pod_comm_cost(state, sgraph)
        + config.balance_weight * (load_std(state) / config.capacity_frac)
        + ow * jnp.sum(jnp.maximum(pct0 - 100.0, 0.0))
    )


def _finalize(state, sgraph, config, best_assign, best_obj, SPX, obj_true0):
    """Never-worse gate vs the TRUE input placement + pod scatter. Under
    disruption pricing the gate re-prices with the EXACT pod-level
    restart bill (the scans rank with the service-level form; best_obj
    comes back RAW)."""
    S = sgraph.num_services
    pod_slot = jnp.clip(
        sgraph.inv[jnp.clip(state.pod_service, 0, S - 1)], 0, SPX - 1
    )
    tgt = best_assign[pod_slot]
    bill = (
        pod_restart_bill(state, tgt, config.move_cost)
        if config.move_cost > 0
        else jnp.float32(0.0)
    )
    improved = best_obj + bill < obj_true0
    new_pod_node = jnp.where(improved & state.pod_valid, tgt, state.pod_node)
    info = {
        "objective_before": obj_true0,
        "objective_after": jnp.where(improved, best_obj, obj_true0),
        "improved": improved,
        "move_penalty": jnp.where(improved, bill, 0.0),
    }
    return state.replace(pod_node=new_pod_node), info


def sharded_sparse_assign(
    state: ClusterState,
    sgraph: SparseCommGraph,
    key: jax.Array,
    mesh: Mesh,
    config: GlobalSolverConfig = GlobalSolverConfig(),
) -> tuple[ClusterState, dict[str, jax.Array]]:
    """``global_assign_sparse`` with the node axis sharded over ``mesh``'s
    ``tp``. Requires ``num_nodes % tp == 0`` and ≥ 2 blocks (single-block
    graphs belong to the dense solver — same rule as the single-chip
    sparse path). Never worse than the input placement."""
    tp, S, N = _validate(state, sgraph, config, mesh)
    sgraph_meta, args, cap, SPX = _prep(state, sgraph, config, N)
    keys = jax.random.split(key, config.sweeps)
    best_assign, best_obj = _build_solve(mesh, config, sgraph_meta, S, N)(
        *args, keys
    )
    obj_true0 = _true_objective(state, sgraph, config, cap)
    new_state, info = _finalize(
        state, sgraph, config, best_assign, best_obj, SPX, obj_true0
    )
    info["tp"] = jnp.asarray(tp)
    return new_state, info


def sharded_sparse_solve_with_restarts(
    state: ClusterState,
    sgraph: SparseCommGraph,
    key: jax.Array,
    mesh: Mesh,
    *,
    n_restarts: int = 1,
    config: GlobalSolverConfig = GlobalSolverConfig(),
) -> tuple[ClusterState, dict[str, jax.Array]]:
    """dp restarts *of* tp-sharded SPARSE solves — completes the
    (solver, dp, tp) production matrix (the dense twin is
    ``sharded_solver.sharded_solve_with_restarts``). ``n_restarts`` must
    be a multiple of the mesh's ``dp``; per-restart keys match
    ``parallel_restarts`` (``split(key, n_restarts)``, each split into
    per-sweep keys), so with annealing noise off each restart makes the
    same decisions as the single-chip sparse solver and the best-of-N
    selection (gated penalized value, first minimum in global restart
    order) matches the dp-only path."""
    tp, S, N = _validate(state, sgraph, config, mesh)
    dp = mesh.shape.get("dp", 1)
    if n_restarts % dp:
        raise ValueError(f"n_restarts {n_restarts} must be a multiple of dp={dp}")
    r_local = n_restarts // dp
    sgraph_meta, args, cap, SPX = _prep(state, sgraph, config, N)
    obj_true0 = _true_objective(state, sgraph, config, cap)
    pod_slot = jnp.clip(
        sgraph.inv[jnp.clip(state.pod_service, 0, S - 1)], 0, SPX - 1
    )
    pod_mask = state.pod_valid & (state.pod_node >= 0)
    keys_all = jax.random.split(key, n_restarts)                    # [R, 2]
    keys_block = jax.vmap(
        lambda k: jax.random.split(k, config.sweeps)
    )(keys_all)                                                     # [R, sweeps, 2]
    best_assign, best_raw, all_gated = _build_solve_restarts(
        mesh, config, sgraph_meta, S, N, r_local
    )(*args, pod_slot, state.pod_node, pod_mask, obj_true0, keys_block)
    new_state, info = _finalize(
        state, sgraph, config, best_assign, best_raw, SPX, obj_true0
    )
    info.update(
        restart_objectives=all_gated,
        best_restart=jnp.argmin(all_gated),
        tp=jnp.asarray(tp),
    )
    return new_state, info
