"""JAX version compatibility shims for the parallel layer.

``jax.shard_map`` (with its ``check_vma`` flag) is the stable API on
recent jax; older releases only ship ``jax.experimental.shard_map`` whose
equivalent flag is ``check_rep``. One import site so every sharded solver
works on both — the call sites keep the modern spelling.
"""

from __future__ import annotations

import functools

try:
    from jax import shard_map  # jax >= 0.6: stable API
except ImportError:  # pragma: no cover - depends on jax version
    from jax.experimental.shard_map import shard_map as _shard_map_exp

    def shard_map(f=None, /, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        if f is None:
            return functools.partial(shard_map, **kwargs)
        return _shard_map_exp(f, **kwargs)


__all__ = ["shard_map"]
