"""Sharded solver entry points.

Two parallelism axes, mapped to the domain:

- **dp (restarts)**: local search is embarrassingly parallel across random
  restarts; ``parallel_restarts`` shards R independent ``global_assign``
  solves over dp and argmin-selects the best objective on device.
- **tp (nodes)**: at 1k+ nodes the per-(service, node) score matrix shards
  cleanly along the node axis; ``sharded_choose_node`` runs the policy
  kernel under ``shard_map`` with per-shard lexicographic maxima combined
  by all-gather — the collective rides ICI, never the host.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from kubernetes_rescheduling_tpu.parallel.compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubernetes_rescheduling_tpu.core.state import ClusterState, CommGraph
from kubernetes_rescheduling_tpu.telemetry.accounting import instrument_jit
from kubernetes_rescheduling_tpu.policies.scoring import (
    node_features,
    policy_key_table,
)
from kubernetes_rescheduling_tpu.solver.global_solver import (
    GlobalSolverConfig,
    global_assign,
)


def parallel_restarts(
    state: ClusterState,
    graph,
    key: jax.Array,
    mesh: Mesh,
    *,
    n_restarts: int | None = None,
    config: GlobalSolverConfig = GlobalSolverConfig(),
    solver=global_assign,
    solver_tag: str = "dense",
) -> tuple[ClusterState, dict[str, jax.Array]]:
    """Run ``n_restarts`` independent global solves sharded over the mesh's
    ``dp`` axis and return the best (lowest-objective) result.

    Each restart differs only by PRNG key (random per-sweep chunk
    composition), so results are bitwise-reproducible for a fixed key and
    mesh. Defaults to one restart per dp slice.

    Within a shard, restarts run *sequentially* (``lax.scan``), not vmapped:
    batching the solver multiplies its working set (the S×S weight matrix
    alone is 400 MB at 10k services) and vmapping its scatter updates
    produces variadic-scatter HLO the TPU backend cannot emit. dp is the
    parallel axis; the scan is the batch axis.
    """
    dp = mesh.shape["dp"]
    r = n_restarts or dp
    if r % dp:
        raise ValueError(f"n_restarts {r} must be a multiple of dp={dp}")
    keys = jax.random.split(key, r)  # [r, 2]

    pod_nodes, objs, pens = _run_shard(mesh, config, solver, solver_tag)(
        state, graph, keys
    )
    # selection ranks the GATED PENALIZED value: objective_after is the
    # raw objective when a restart improved (else the input objective) and
    # move_penalty its restart bill — so under disruption pricing a
    # cheap-but-heavily-disruptive restart cannot mask a net-better one.
    # With move_cost=0 the penalties are all zero (historical behavior).
    best = jnp.argmin(objs + pens)
    best_state = state.replace(pod_node=pod_nodes[best])
    info = {
        "objective_after": objs[best],
        "move_penalty": pens[best],
        # the RANKED values (gated + bill) — identical semantics to the
        # dp×tp path's report, so the named best restart is the adopted
        # one on both paths; with move_cost=0 these are the historical
        # gated objectives
        "restart_objectives": objs + pens,
        "best_restart": best,
    }
    return best_state, info


# jitted shard-mapped solvers keyed by (mesh, config) so repeated calls —
# e.g. the controller's per-round global solve — hit the compile cache
# instead of retracing a fresh closure every round
_RUN_SHARD_CACHE: dict = {}


def _run_shard(mesh: Mesh, config: GlobalSolverConfig, solver=global_assign,
               solver_tag: str = "dense"):
    # the tag AND the solver object key the cache: the sparse and dense
    # round functions are distinct compiled programs, and a future caller
    # reusing a tag with a different solver must not silently hit the
    # other solver's compiled shard_map (module-level solver functions are
    # hashable with stable identity, so the controller's repeated calls
    # still hit the cache)
    cache_key = (mesh, config, solver_tag, solver)
    fn = _RUN_SHARD_CACHE.get(cache_key)
    if fn is None:

        @partial(
            shard_map,
            mesh=mesh,
            in_specs=(P(), P(), P("dp")),
            out_specs=(P("dp"), P("dp"), P("dp")),
            check_vma=False,
        )
        def run_shard(st, g, keys_block):
            def body(carry, k):
                new_state, info = solver(st, g, k, config)
                return carry, (
                    new_state.pod_node,
                    info["objective_after"],
                    info["move_penalty"],
                )

            _, (pods, objs, pens) = jax.lax.scan(body, 0, keys_block)
            return pods, objs, pens

        # instrumented: the controller's restart rounds dispatch this once
        # per round — retraces become visible, and the compiled program's
        # cost/HBM snapshot lands under fn="sharded_restarts_<tag>"
        fn = instrument_jit(run_shard, name=f"sharded_restarts_{solver_tag}")
        _RUN_SHARD_CACHE[cache_key] = fn
    return fn


def _largest_divisor(r: int, cap: int) -> int:
    """Largest divisor of ``r`` that is <= ``cap`` — the dp extent used by
    mesh auto-shaping (one heuristic, shared by the tp and non-tp paths)."""
    return max(d for d in range(1, min(cap, r) + 1) if r % d == 0)


def dp_devices(mesh: Mesh) -> tuple:
    """The devices along the mesh's ``dp`` axis, in dp order — the
    device axis every dp-sharded output block maps onto (block ``i`` of
    a ``P("dp")`` output lives on ``dp_devices(mesh)[i]``). The
    telemetry mesh plane keys its per-device attribution on exactly
    this ordering, so rollup index ``i`` always names the device that
    ran tenant block ``i``."""
    # index [:, 0]: the fleet meshes are (dp, 1)-shaped (make_mesh), and
    # for a general (dp, tp) mesh the dp axis is the leading one
    arr = mesh.devices
    return tuple(arr[:, 0]) if arr.ndim == 2 else tuple(arr.ravel())


def solve_with_restarts(
    state: ClusterState,
    graph: CommGraph,
    key: jax.Array,
    *,
    n_restarts: int = 1,
    config: GlobalSolverConfig = GlobalSolverConfig(),
    mesh: Mesh | None = None,
    tp: int = 1,
    sparse_graph=None,
    donate: bool = False,
) -> tuple[ClusterState, dict[str, jax.Array]]:
    """Production best-of-N global solve — the mesh-parallel path with
    graceful degradation.

    ``sparse_graph`` (a SparseCommGraph) switches every solve to the
    block-local sparse form, with the same (dp, tp) composition matrix
    as dense: tp>1 single-restart is one node-sharded sparse solve,
    tp>1 with restarts runs dp restarts OF tp-sharded sparse solves,
    and tp=1 with restarts runs dp restarts of single-chip sparse
    solves.

    ``tp > 1`` shards the NODE axis of every solve over the mesh's ``tp``
    dimension (``sharded_solver``): with ``n_restarts <= 1`` that is one
    node-sharded solve; otherwise dp restarts compose *of* tp-sharded
    solves on a (dp, tp) mesh. With ``tp == 1``: ``n_restarts <= 1`` is a
    plain single-device solve, and otherwise restarts parallelize over the
    mesh's ``dp`` axis and run *sequentially* (scan) within each shard.

    With no mesh given one is auto-shaped: ``tp`` devices per solve, and
    the dp extent the largest divisor of ``n_restarts`` that fits the
    remaining devices — on a single chip that degenerates to a 1×1 mesh
    running all N solves back to back (N× wall clock, flat memory), so the
    same call works from laptop CPU to a pod slice. ``info["restarts"]``
    records N for benchmark provenance; ``info["tp"]`` is present when the
    node axis was sharded.

    ``donate=True`` (the controller's donated-carry dispatch) hands the
    state's device buffers to the solver on the ONE path with a
    top-level donatable jit — the single-restart, unsharded dense solve
    (``global_assign_donated``: output placement aliases the input). The
    sharded/scan/sparse paths trace the solver inline, where a nested
    donation would be dropped anyway, so they ignore the flag. The
    caller must treat ``state`` as consumed when it sets this.
    """
    if mesh is not None:
        mesh_tp = mesh.shape.get("tp", 1)
        if tp != 1 and mesh_tp != tp:
            raise ValueError(
                f"tp={tp} conflicts with the explicit mesh's tp={mesh_tp}; "
                "pass one or the other"
            )
        tp = mesh_tp
    if tp > 1:
        from kubernetes_rescheduling_tpu.parallel.mesh import make_mesh
        from kubernetes_rescheduling_tpu.parallel.sharded_solver import (
            sharded_global_assign,
            sharded_solve_with_restarts,
        )

        if mesh is None:
            n_dev = len(jax.devices())
            if n_dev % tp:
                raise ValueError(
                    f"tp={tp} does not divide the {n_dev} available devices"
                )
            dp = _largest_divisor(max(n_restarts, 1), max(n_dev // tp, 1))
            mesh = make_mesh(dp * tp, shape=(dp, tp))
        if sparse_graph is not None:
            from kubernetes_rescheduling_tpu.parallel.sharded_sparse import (
                sharded_sparse_assign,
                sharded_sparse_solve_with_restarts,
            )

            if n_restarts > 1:
                new_state, info = sharded_sparse_solve_with_restarts(
                    state, sparse_graph, key, mesh,
                    n_restarts=n_restarts, config=config,
                )
            else:
                new_state, info = sharded_sparse_assign(
                    state, sparse_graph, key, mesh, config
                )
        elif n_restarts <= 1:
            new_state, info = sharded_global_assign(state, graph, key, mesh, config)
        else:
            new_state, info = sharded_solve_with_restarts(
                state, graph, key, mesh, n_restarts=n_restarts, config=config
            )
        info = dict(info)
        info["restarts"] = jnp.asarray(max(n_restarts, 1))
        return new_state, info
    if sparse_graph is not None:
        from kubernetes_rescheduling_tpu.solver.sparse_solver import (
            global_assign_sparse,
        )

        solver, solve_graph, tag = global_assign_sparse, sparse_graph, "sparse"
    else:
        solver, solve_graph, tag = global_assign, graph, "dense"
    if n_restarts <= 1:
        donated = donate and tag == "dense"
        if donated:
            from kubernetes_rescheduling_tpu.solver.global_solver import (
                global_assign_donated,
            )

            solver = global_assign_donated
        new_state, info = solver(state, solve_graph, key, config)
        info = dict(info)
        info["restarts"] = jnp.asarray(1)
        if donated:
            # host flag (never a jax array): tells the caller its input
            # buffers were actually consumed on THIS path — the
            # sharded/scan/sparse paths above never donate, so a caller
            # that must rebuild its carry keys off this, not off the
            # flag it passed
            info["donated"] = True
        return new_state, info
    if mesh is None:
        from kubernetes_rescheduling_tpu.parallel.mesh import make_mesh

        dp = _largest_divisor(n_restarts, len(jax.devices()))
        mesh = make_mesh(dp, shape=(dp, 1))
    best_state, info = parallel_restarts(
        state, solve_graph, key, mesh, n_restarts=n_restarts, config=config,
        solver=solver, solver_tag=tag,
    )
    info = dict(info)
    info["restarts"] = jnp.asarray(n_restarts)
    return best_state, info


def sharded_choose_node(
    policy_id: jax.Array,
    state: ClusterState,
    graph: CommGraph,
    service_idx: jax.Array,
    hazard_mask: jax.Array,
    key: jax.Array,
    mesh: Mesh,
) -> jax.Array:
    """`policies.choose_node` with the node axis sharded over ``tp``.

    Each shard computes its local feature block and lexicographic key tuple;
    a global argmax over (keys..., -index) is taken after an all-gather of
    one scalar tuple per shard — O(tp) bytes over ICI, independent of N.
    """
    tp = mesh.shape["tp"]
    n = state.num_nodes
    if n % tp:
        raise ValueError(f"num_nodes {n} must be a multiple of tp={tp}")

    f = node_features(state, graph, service_idx)
    keys_by_policy = _policy_keys(policy_id, f, state, key)
    cand = state.node_valid & ~hazard_mask

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(None, "tp"), P("tp")),
        out_specs=P(),
        # outputs are replicated by construction (post-all_gather reduction);
        # the static VMA check can't see that through the loop
        check_vma=False,
    )
    def pick(keys_block, cand_block):
        # local lexicographic winner within this shard
        winners = cand_block
        for i in range(keys_block.shape[0]):
            k = keys_block[i]
            best = jnp.max(jnp.where(winners, k, -jnp.inf))
            winners = winners & (k == best)
        local_idx = jnp.argmax(winners).astype(jnp.int32)
        shard = jax.lax.axis_index("tp")
        global_idx = shard * cand_block.shape[0] + local_idx
        local_keys = jnp.where(
            jnp.any(winners), keys_block[:, local_idx], -jnp.inf
        )
        # gather one (keys, idx) tuple per shard, reduce lexicographically
        all_keys = jax.lax.all_gather(local_keys, "tp")      # [tp, K]
        all_idx = jax.lax.all_gather(global_idx, "tp")       # [tp]
        winners2 = jnp.ones((all_keys.shape[0],), bool)
        for i in range(all_keys.shape[1]):
            k = all_keys[:, i]
            best = jnp.max(jnp.where(winners2, k, -jnp.inf))
            winners2 = winners2 & (k == best)
        # lowest global index among tied shards (first-max parity)
        tie_idx = jnp.where(winners2, all_idx, jnp.iinfo(jnp.int32).max)
        chosen = jnp.min(tie_idx)
        any_cand = jnp.any(all_keys[:, 0] > -jnp.inf)
        return jnp.where(any_cand, chosen, -1)

    keys_stack = jnp.stack(keys_by_policy)  # [K, N]
    return jax.jit(pick)(keys_stack, cand)


def _policy_keys(policy_id, f, state, key):
    """Traced-policy key selection from the ONE table
    (``policies.scoring.policy_key_table``) the single-device path also
    uses — a policy edit there propagates here by construction."""
    k1, k2 = policy_key_table(f, state, key)
    pid = jnp.clip(policy_id, 0, k1.shape[0] - 1)
    return [k1[pid], k2[pid]]
