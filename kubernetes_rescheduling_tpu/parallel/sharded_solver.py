"""Node-axis-sharded global solver: the flagship solve as an SPMD program.

``global_assign`` holds the whole problem on one chip; fine to ~10k×1k
(X is 20 MB, W 400 MB). Beyond that — or to put a whole pod slice on one
solve — the node axis shards over the mesh's ``tp`` dimension:

- sharded: the occupancy matrix ``X [SP, N/tp]``, per-node loads and
  capacities. Each shard scores its own node columns.
- replicated: the pair weights (``adj``/``rv``/``W_mm`` — the f32 W matrix
  is never materialized), service vectors, and the assignment (global node
  ids) — every shard agrees on every decision.
- collectives per chunk step, all O(C) scalars over ICI:
  ``all_gather`` of each shard's local top-1 (score, global index) and
  ``psum`` of the current-node score / landing-slack contributions (only
  the owning shard's term is nonzero). The pairwise admission race then
  runs replicated on the gathered vectors — bit-identical on all shards.

Decision math mirrors ``global_assign``'s XLA path term for term, so with
annealing noise off the sharded solve makes the same moves (objective
sums associate differently across shards, so best-seen selection can in
principle differ on exact ulp ties).

This is deliberately plain shard_map + XLA (no Pallas): the single-chip
fused path optimizes launch count, while here the structure exists to
scale memory and FLOPs across chips — profile before fusing.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from kubernetes_rescheduling_tpu.parallel.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from kubernetes_rescheduling_tpu.core.state import ClusterState, CommGraph
from kubernetes_rescheduling_tpu.objectives.metrics import communication_cost, load_std
from kubernetes_rescheduling_tpu.ops.fused_admission import pairwise_admission
from kubernetes_rescheduling_tpu.solver.global_solver import (
    GlobalSolverConfig,
    _pad_to,
    _service_aggregates,
    auto_chunk,
    build_pair_weights,
    check_weight_budget,
    exact_comm_cost,
    pod_restart_bill,
    restart_bill_from_arrays,
    sweep_composition,
    total_pair_weight,
)
from kubernetes_rescheduling_tpu.solver.swap import (
    BIG_CAP,
    cols_at,
    scan_sweeps,
    swap_decisions,
    swap_desire,
    swap_flags,
    swap_subset,
)

_NEG_INF = float("-inf")


def sharded_swap(
    M, Wc, cur, eligible, c_cpu, c_mem, cpu_l, mem_l, cap_l, mem_cap_l,
    valid_l, gcol, config, ow, col0, home=None, move_pen=None,
):
    """The swap phase under a mesh with a ``tp`` axis — shard-local
    reductions feeding the SAME replicated core (solver/swap.py) the
    single-chip ``chunk_swap`` runs, including the desire-ranked top-k
    candidate subset, so the decisions cannot fork. Per-node inputs are
    owned by exactly one shard; the psum'd one-hot contractions reproduce
    the single-chip f32 values bit-exactly (one nonzero term each).
    Shared by the dense and sparse node-sharded solvers (``Wc`` is the
    only input whose computation differs). Returns ``(new_node, swapped,
    n_swaps, d_cpu_l, d_mem_l)``."""
    C = cur.shape[0]
    is_cur = gcol == cur[:, None]                       # (C, Nl)
    m_cur = lax.psum(jnp.sum(jnp.where(is_cur, M, 0.0), axis=1), "tp")

    def at_cur_of(is_at, v):
        return lax.psum(
            jnp.sum(jnp.where(is_at, v[None, :], 0.0), axis=1), "tp"
        )

    mem_cap_s = jnp.where(jnp.isinf(mem_cap_l), BIG_CAP, mem_cap_l)
    eligible = eligible & (at_cur_of(is_cur, valid_l.astype(jnp.float32)) > 0)

    pen_home = (
        move_pen * (cur == home).astype(jnp.float32)
        if move_pen is not None
        else 0.0
    )
    k = min(config.swap_k, C)
    if k < C:
        # replicated desire (local max pmax'd over shards) → the SHARED
        # subset step: every shard selects the same candidates the
        # single-chip solver would
        desire = swap_desire(
            lax.pmax(jnp.max(M, axis=1), "tp"), m_cur, pen_home
        )
        sel, M_k, Wc_k, sub = swap_subset(desire, eligible, M, Wc, k)
    else:
        sel = jnp.arange(C, dtype=jnp.int32)
        M_k, Wc_k = M, Wc
        sub = lambda v: v
    cur_k = sub(cur)
    is_cur_k = gcol == cur_k[:, None]
    M_cur_k = lax.psum(cols_at(M_k, cur_k, col0=col0), "tp")  # (k, k)
    new_k, swapped_k, n_sw = swap_decisions(
        M_cur_k, sub(m_cur), Wc_k, cur_k, sub(eligible),
        sub(c_cpu), sub(c_mem),
        at_cur_of(is_cur_k, cpu_l), at_cur_of(is_cur_k, mem_l),
        at_cur_of(is_cur_k, cap_l), at_cur_of(is_cur_k, mem_cap_s),
        config.balance_weight, ow,
        pen=sub(move_pen) if move_pen is not None else None,
        home=sub(home) if home is not None else None,
        enforce_capacity=config.enforce_capacity,
    )
    new_node = cur.at[sel].set(new_k)
    swapped = jnp.zeros((C,), bool).at[sel].set(swapped_k)
    is_new_k = gcol == new_k[:, None]
    sw_c = jnp.where(swapped_k, sub(c_cpu), 0.0)
    sw_m = jnp.where(swapped_k, sub(c_mem), 0.0)
    d_cpu = jnp.sum(
        jnp.where(is_new_k, sw_c[:, None], 0.0)
        - jnp.where(is_cur_k, sw_c[:, None], 0.0),
        axis=0,
    )
    d_mem = jnp.sum(
        jnp.where(is_new_k, sw_m[:, None], 0.0)
        - jnp.where(is_cur_k, sw_m[:, None], 0.0),
        axis=0,
    )
    return new_node, swapped, n_sw, d_cpu, d_mem


def sharded_place(
    M, cur, valid_c, c_cpu, c_mem, cpu_l, mem_l, cap_l, mem_cap_l,
    valid_l, gcol, N, config, ow, chunk_key, temp, shard,
    home=None, move_pen=None,
):
    """Shard-local score → global first-max → admission → per-node load
    deltas for one chunk, under a mesh with a ``tp`` axis.

    ``M`` is the chunk's neighbor mass for THIS shard's node columns —
    the only input whose computation differs between the dense
    (materialized-X matmul) and sparse (block-local slab) node-sharded
    solvers; everything downstream is THIS one function, so the decision
    math cannot fork between them. Collectives: ``all_gather`` of each
    shard's top-1 (score, global index), ``psum`` of the current-node
    score and the landing slack (only the owning shard's term is
    nonzero). Returns ``(new_node, admitted, is_new, d_cpu, d_mem)``.
    """
    is_cur = gcol == cur[:, None]                     # (C, Nl)
    proj_cpu = cpu_l[None, :] + jnp.where(is_cur, 0.0, c_cpu[:, None])
    proj_pct = proj_cpu / cap_l[None, :] * 100.0
    score = (
        M
        - config.balance_weight * proj_pct
        - ow * jnp.maximum(proj_pct - 100.0, 0.0)
    )
    if move_pen is not None:
        # disruption pricing: residency anywhere but the round-start node
        # costs the restart bill (same term as the single-chip score
        # kernels — global node ids, so the shard owning `home` exempts it)
        score = score - jnp.where(
            gcol == home[:, None], 0.0, move_pen[:, None]
        )
    if config.noise_temp > 0:
        # keys are replicated; fold in the shard so each node column
        # block draws its own stream (matches nothing — annealing
        # noise carries no parity requirement)
        noise_key = jax.random.fold_in(chunk_key, shard)
        score = score + temp * jax.random.gumbel(noise_key, score.shape)

    if config.enforce_capacity:
        proj_mem = mem_l[None, :] + jnp.where(is_cur, 0.0, c_mem[:, None])
        fits = (proj_cpu <= cap_l[None, :]) & (proj_mem <= mem_cap_l[None, :])
        feasible = (fits | is_cur) & valid_l[None, :]
    else:
        feasible = jnp.broadcast_to(valid_l[None, :], score.shape)

    masked = jnp.where(feasible, score, _NEG_INF)
    loc_val = jnp.max(masked, axis=1)                 # (C,)
    at_max = masked == loc_val[:, None]
    loc_idx = jnp.min(jnp.where(at_max, gcol, N), axis=1)
    cur_score = lax.psum(
        jnp.sum(jnp.where(is_cur, score, 0.0), axis=1), "tp"
    )

    # global first-max: gather each shard's top-1, then among the
    # shards achieving the max score take the lowest global index
    all_val = lax.all_gather(loc_val, "tp")           # (tp, C)
    all_idx = lax.all_gather(loc_idx, "tp")           # (tp, C)
    best_val = jnp.max(all_val, axis=0)
    prop = jnp.min(
        jnp.where(all_val == best_val[None, :], all_idx, N), axis=0
    ).astype(jnp.int32)
    prop = jnp.minimum(prop, N - 1)
    gain = best_val - cur_score
    wants = valid_c & (gain > 0) & (prop != cur)

    # landing slack lives on the owning shard; psum the masked term
    is_prop = gcol == prop[:, None]                   # (C, Nl)
    slack_cpu = lax.psum(
        jnp.sum(jnp.where(is_prop, cap_l[None, :] - cpu_l[None, :], 0.0), axis=1),
        "tp",
    ) - c_cpu
    slack_mem = lax.psum(
        jnp.sum(
            jnp.where(
                is_prop,
                jnp.where(
                    jnp.isinf(mem_cap_l), 3.4e38, mem_cap_l
                )[None, :]
                - mem_l[None, :],
                0.0,
            ),
            axis=1,
        ),
        "tp",
    ) - c_mem

    if config.enforce_capacity:
        # replicated vectors -> the shared race, bit-identical to
        # the single-device reference path
        admitted = pairwise_admission(
            gain, prop, wants, c_cpu, c_mem, slack_cpu, slack_mem
        )
    else:
        admitted = wants

    new_node = jnp.where(admitted, prop, cur)
    is_new = gcol == new_node[:, None]
    a_cpu = jnp.where(admitted, c_cpu, 0.0)
    a_mem = jnp.where(admitted, c_mem, 0.0)
    d_cpu = jnp.sum(
        jnp.where(is_new, a_cpu[:, None], 0.0)
        - jnp.where(is_cur, a_cpu[:, None], 0.0),
        axis=0,
    )
    d_mem = jnp.sum(
        jnp.where(is_new, a_mem[:, None], 0.0)
        - jnp.where(is_cur, a_mem[:, None], 0.0),
        axis=0,
    )
    return new_node, admitted, is_new, d_cpu, d_mem


def _dims(config: GlobalSolverConfig, S: int, N: int, tp: int):
    C = min(auto_chunk(S, config.chunk_size), S)
    n_chunks = -(-S // C)
    return C, n_chunks, n_chunks * C, N // tp


# compiled SPMD solvers keyed by (mesh, config, S, N[, r_local]): repeated
# calls — e.g. one solve per control-loop round — hit the jit cache instead
# of retracing a fresh shard_map closure every time (same pattern as
# parallel.sharded._RUN_SHARD_CACHE)
_SOLVE_CACHE: dict = {}

# shard_map argument layout shared by the single-restart and dp×tp wrappers:
# replicated problem data (assign0, adj, rv, W_mm, service vectors),
# node-axis-sharded per-node vectors, then keys. adj/W_mm and service
# vectors are replicated ARGUMENTS, not closures: a closed-over array
# becomes an HLO constant, and a 10k×10k weight matrix baked into the
# program overflows compile-request limits.
_IN_SPECS_COMMON = (
    P(), P(), P(), P(), P(), P(), P(),
    P("tp"), P("tp"), P("tp"), P("tp"), P("tp"),
)


def _solve_factory(config: GlobalSolverConfig, S: int, N: int, tp: int):
    """The shard-local solve body (collectives over the mesh's ``tp`` axis).

    Returns ``solve_one(assign_init, adj, rv, W_mm, svc_valid, svc_cpu,
    svc_mem, cap_l, mem_cap_l, base_cpu_l, base_mem_l, valid_l, keys_r) ->
    (best_assign, best_obj)``; must run under ``shard_map`` on a mesh with a
    ``tp`` axis. Both the single-restart and the dp-restarts-of-tp-solves
    wrappers are thin shard_map shells around this one body, so the decision
    math cannot fork between the two production paths.
    """
    C, n_chunks, SP, Nl = _dims(config, S, N, tp)
    ow = config.overload_weight if config.enforce_capacity else 0.0
    # numpy, NOT jnp: the factory can run inside an outer trace (e.g. the
    # latency-budget tuner jits around the whole solve) and a jnp value
    # computed here would be a tracer captured by the CACHED closure —
    # escaping its trace. A numpy constant is trace-agnostic.
    import numpy as _np

    temps = config.noise_temp * (
        1.0 - _np.arange(config.sweeps, dtype=_np.float32) / max(config.sweeps - 1, 1)
    )
    # per-sweep swap-phase flags (numpy — same trace-agnostic reasoning)
    swf = swap_flags(config.sweeps, config.swap_every)
    use_swaps = config.swap_every > 0 and C >= 2

    def solve_one(
        assign_init, adj, rv, W_mm, svc_valid, svc_cpu, svc_mem,
        cap_l, mem_cap_l, base_cpu_l, base_mem_l, valid_l, keys_r,
    ):
        shard = lax.axis_index("tp")
        gcol = shard * Nl + lax.broadcasted_iota(jnp.int32, (1, Nl), 1)  # (1, Nl)
        nvalid = jnp.maximum(lax.psum(jnp.sum(valid_l), "tp"), 1)

        def local_loads(assign):
            owned = (assign[:, None] == gcol) & svc_valid[:, None]  # (SP, Nl)
            of = owned.astype(jnp.float32)
            return (
                base_cpu_l + svc_cpu @ of,
                base_mem_l + svc_mem @ of,
            )

        def _balance_terms(cpu_l):
            pct = jnp.where(valid_l, cpu_l / cap_l * 100.0, 0.0)
            s1 = lax.psum(jnp.sum(pct), "tp")
            s2 = lax.psum(jnp.sum(pct * pct), "tp")
            mean = s1 / nvalid
            var = jnp.maximum(s2 / nvalid - mean * mean, 0.0)
            over = lax.psum(jnp.sum(jnp.maximum(pct - 100.0, 0.0)), "tp")
            return config.balance_weight * jnp.sqrt(var) + ow * over

        # THE shared pair-weight helpers (global_solver) — one definition,
        # so the exact gate cannot fork between the two solvers
        w_total = total_pair_weight(adj, rv)

        # disruption pricing (config.move_cost): restart bill per service,
        # anchored at the round-start placement (mirrors global_assign)
        mc_on = config.move_cost > 0
        rv_sp = _pad_to(rv, SP)
        pen_vec = config.move_cost * rv_sp if mc_on else None

        def move_penalty(assign):
            return config.move_cost * jnp.sum(
                jnp.where(svc_valid & (assign != assign_init), rv_sp, 0.0)
            )

        def objective(assign, cpu_l):
            """EXACT (direct cut-sum via exact_comm_cost) — the final
            adopted/reported value."""
            return exact_comm_cost(adj, rv, assign) + _balance_terms(cpu_l)

        # per-sweep selection on the bf16 kept-mass form — same trade and
        # same expression as global_solver.objective_fast (exact for
        # integer weights; exact f32 re-evaluation after the scan)

        def objective_fast(assign, cpu_l):
            same = assign[:, None] == assign[None, :]
            kept = jnp.einsum(
                "ij,ij->", W_mm, same.astype(W_mm.dtype),
                preferred_element_type=jnp.float32,
            )
            obj = 0.5 * (w_total - kept) + _balance_terms(cpu_l)
            # penalized ranking under disruption pricing (see global_solver)
            return obj + move_penalty(assign) if mc_on else obj

        def chunk_step(inner, xs_c, do_swap: bool = False):
            ids, chunk_key, temp = xs_c
            assign, X_l, cpu_l, mem_l = inner
            valid_c = svc_valid[ids]
            c_cpu = svc_cpu[ids]
            c_mem = svc_mem[ids]
            cur = assign[ids]

            Wr = W_mm[ids]
            M = jnp.matmul(Wr, X_l, preferred_element_type=jnp.float32)
            # everything after M is the SHARED shard-local placement (also
            # used by the sparse node-sharded solver)
            new_node, admitted, is_new, d_cpu, d_mem = sharded_place(
                M, cur, valid_c, c_cpu, c_mem, cpu_l, mem_l,
                cap_l, mem_cap_l, valid_l, gcol, N, config, ow,
                chunk_key, temp, shard,
                home=assign_init[ids] if mc_on else None,
                move_pen=pen_vec[ids] if mc_on else None,
            )
            inner = (
                assign.at[ids].set(new_node),
                X_l.at[ids].set((is_new & valid_c[:, None]).astype(X_l.dtype)),
                cpu_l + d_cpu,
                mem_l + d_mem,
            )
            n_moves = jnp.sum(admitted)
            if not (use_swaps and do_swap):  # STATIC branch (scan_sweeps)
                return inner, (n_moves, jnp.int32(0))

            assign2, X2, cpu2, mem2 = inner
            cur2 = assign2[ids]
            # replicated chunk-local pair weights: one-hot contraction
            # of the already-gathered W rows (HIGHEST keeps the values
            # bit-equal to the single-chip column take)
            pos = (
                jnp.full((SP,), C, jnp.int32)
                .at[ids]
                .set(jnp.arange(C, dtype=jnp.int32))
            )
            E = (
                pos[:, None] == jnp.arange(C, dtype=jnp.int32)[None, :]
            ).astype(Wr.dtype)
            Wc = jnp.dot(
                Wr, E,
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST,
            )
            new2, swapped, n_sw, d_c, d_m = sharded_swap(
                M, Wc, cur2, valid_c & ~admitted, c_cpu, c_mem,
                cpu2, mem2, cap_l, mem_cap_l, valid_l, gcol, config, ow,
                col0=shard * Nl,
                home=assign_init[ids] if mc_on else None,
                move_pen=pen_vec[ids] if mc_on else None,
            )
            assign2 = assign2.at[ids].set(new2)
            X2 = X2.at[ids].set(
                ((gcol == new2[:, None]) & valid_c[:, None]).astype(
                    X2.dtype
                )
            )
            return (assign2, X2, cpu2 + d_c, mem2 + d_m), (n_moves, n_sw)

        def make_sweep(do_swap: bool):
            return partial(sweep, do_swap=do_swap)

        def sweep(carry, xs, do_swap: bool = False):
            sweep_key, temp = xs
            assign, best_assign, best_obj = carry
            perm_key, noise_key = jax.random.split(sweep_key)
            chunk_ids, _ = sweep_composition(perm_key, SP, C, n_chunks)
            chunk_keys = jax.random.split(noise_key, n_chunks)
            chunk_temps = jnp.full((n_chunks,), temp)
            X0 = (
                (assign[:, None] == gcol) & svc_valid[:, None]
            ).astype(jnp.dtype(config.matmul_dtype))
            cpu_l, mem_l = local_loads(assign)
            (assign, _, _, _), (moves, _) = lax.scan(
                partial(chunk_step, do_swap=do_swap),
                (assign, X0, cpu_l, mem_l),
                (chunk_ids, chunk_keys, chunk_temps),
            )
            # best-seen selection uses loads recomputed from the assignment,
            # not the incrementally-carried cpu_l: accumulated f32 drift in
            # the carry could flip near-tie selections away from the
            # single-chip solver, whose objective() also rebuilds loads
            cpu_fresh, _ = local_loads(assign)
            obj = objective_fast(assign, cpu_fresh)
            better = obj < best_obj
            best_assign = jnp.where(better, assign, best_assign)
            best_obj = jnp.where(better, obj, best_obj)
            return (assign, best_assign, best_obj), jnp.sum(moves)

        cpu0, _ = local_loads(assign_init)
        obj0 = objective_fast(assign_init, cpu0)
        (_, best_assign, _), _ = scan_sweeps(
            make_sweep, (assign_init, assign_init, obj0), keys_r, temps, swf
        )
        # exact f32 re-evaluation of the adopted placement (same reason as
        # global_solver: the fast objective only ranks sweeps)
        cpu_best, _ = local_loads(best_assign)
        return best_assign, objective(best_assign, cpu_best)

    return solve_one


def _build_solve(mesh: Mesh, config: GlobalSolverConfig, S: int, N: int):
    """Single tp-sharded solve (one restart; keys replicated)."""
    cache_key = (mesh, config, S, N)
    fn = _SOLVE_CACHE.get(cache_key)
    if fn is not None:
        return fn
    solve_one = _solve_factory(config, S, N, mesh.shape["tp"])
    fn = jax.jit(
        partial(
            shard_map,
            mesh=mesh,
            in_specs=(*_IN_SPECS_COMMON, P()),
            out_specs=(P(), P()),
            check_vma=False,
        )(solve_one)
    )
    _SOLVE_CACHE[cache_key] = fn
    return fn


def _build_solve_restarts(
    mesh: Mesh, config: GlobalSolverConfig, S: int, N: int, r_local: int
):
    """dp restarts of tp-sharded solves, best-of-N selected on device.

    Each dp slice runs ``r_local`` restarts *sequentially* (lax.scan — the
    same reasoning as ``parallel_restarts``: vmapping the solver multiplies
    its working set and produces variadic-scatter HLO the TPU backend
    rejects), with the node axis of every solve sharded over ``tp``. The
    final all_gather over dp moves one ``[r_local, SP]`` assignment block
    and ``r_local`` objectives per slice — O(R·S) ints over ICI, once.
    """
    cache_key = (mesh, config, S, N, r_local)
    fn = _SOLVE_CACHE.get(cache_key)
    if fn is not None:
        return fn
    solve_one = _solve_factory(config, S, N, mesh.shape["tp"])

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(*_IN_SPECS_COMMON, P(), P(), P(), P(), P("dp")),
        out_specs=(P(), P(), P()),
        check_vma=False,
    )
    def solve_r(
        assign_init, adj, rv, W_mm, svc_valid, svc_cpu, svc_mem,
        cap_l, mem_cap_l, base_cpu_l, base_mem_l, valid_l,
        pod_slot, pod_node0, pod_mask, obj_true0, keys_block,
    ):
        def body(carry, keys_r):
            ba, bo = solve_one(
                assign_init, adj, rv, W_mm, svc_valid, svc_cpu, svc_mem,
                cap_l, mem_cap_l, base_cpu_l, base_mem_l, valid_l, keys_r,
            )
            return carry, (ba, bo)

        _, (assigns, objs) = lax.scan(body, 0, keys_block)
        # selection ranks the GATED PENALIZED value — min(raw + exact pod
        # restart bill, input objective) — matching what the dp-only
        # parallel_restarts path ranks (each of its restarts is internally
        # gated, and its selection adds move_penalty). Without this, a
        # cheap-but-heavily-disruptive restart could mask a net-better one
        # under disruption pricing. move_cost=0 → bills are 0 and the
        # minimum reduces to min(raw, true0): the historical ranking.
        tgts = assigns[:, pod_slot]                               # [r, P]
        bills = jax.vmap(
            lambda t: restart_bill_from_arrays(
                pod_mask, pod_node0, t, config.move_cost
            )
        )(tgts)
        gated = jnp.minimum(objs + bills, obj_true0)
        # global restart order = dp-shard-major (shard d owns restarts
        # [d·r_local, (d+1)·r_local)), matching how the caller split the
        # keys — so argmin tie-breaking (first minimum) agrees with the
        # dp-only parallel_restarts path
        all_gated = lax.all_gather(gated, "dp", tiled=True)       # [R]
        all_objs = lax.all_gather(objs, "dp", tiled=True)         # [R]
        all_assigns = lax.all_gather(assigns, "dp", tiled=True)   # [R, SP]
        best = jnp.argmin(all_gated)
        # winner by GATED value; its RAW objective goes to the adopt gate
        # (which re-adds the exact bill itself); the gated per-restart
        # values are reported — they are what selection ranked (and what
        # the dp-only path's objective_after+move_penalty equals), so the
        # named best restart is always the adopted one
        return all_assigns[best], all_objs[best], all_gated

    fn = jax.jit(solve_r)
    _SOLVE_CACHE[cache_key] = fn
    return fn


def _check_and_dims(state, graph, config, mesh):
    if not config.capacity_frac > 0:
        raise ValueError(f"capacity_frac must be > 0, got {config.capacity_frac}")
    tp = mesh.shape["tp"]
    S = graph.num_services
    N = state.num_nodes
    if N % tp:
        raise ValueError(f"num_nodes {N} must be a multiple of tp={tp}")
    _, _, SP, _ = _dims(config, S, N, tp)
    check_weight_budget(SP, config)  # W is REPLICATED under tp
    return tp, S, N, SP


def _prep(state, graph, config, S, N, SP):
    """Problem arrays in the shard_map argument order (minus keys)."""
    replicas, svc_cpu, svc_mem, cur_node, has_pods = _service_aggregates(state, S)
    svc_valid = _pad_to(graph.service_valid & has_pods, SP, False)
    svc_cpu = _pad_to(svc_cpu, SP)
    svc_mem = _pad_to(svc_mem, SP)
    replicas = _pad_to(replicas, SP)
    cur_node = _pad_to(cur_node, SP, -1)

    # f32 W is never materialized: the shared jitted builder fuses
    # multiply+pad+convert into one mm-dtype write (an eager op-by-op
    # build here would transiently allocate the full f32 SP² product)
    rv = (replicas * svc_valid)[:S]
    W_mm = build_pair_weights(graph.adj, rv, SP=SP, dtype=config.matmul_dtype)

    cpu_cap = jnp.where(state.node_valid, state.node_cpu_cap, 0.0)
    mem_cap_raw = jnp.where(state.node_valid, state.node_mem_cap, 0.0)
    mem_cap = jnp.where(mem_cap_raw > 0, mem_cap_raw, jnp.inf) * config.capacity_frac
    cap = jnp.where(cpu_cap > 0, cpu_cap, 1.0) * config.capacity_frac

    assign0 = jnp.where(svc_valid, jnp.clip(cur_node, 0, N - 1), 0)
    return (
        assign0, graph.adj, rv, W_mm, svc_valid, svc_cpu, svc_mem,
        cap, mem_cap, state.node_base_cpu, state.node_base_mem, state.node_valid,
    )


def _true_objective(state, graph, config, cap):
    """The TRUE input objective (the adopt gate's reference point) —
    computed once and shared between the gate and the restart-selection
    ranking so the two cannot disagree."""
    ow = config.overload_weight if config.enforce_capacity else 0.0
    pct0 = jnp.where(state.node_valid, state.node_cpu_used() / cap * 100.0, 0.0)
    return (
        communication_cost(state, graph)
        + config.balance_weight * (load_std(state) / config.capacity_frac)
        + ow * jnp.sum(jnp.maximum(pct0 - 100.0, 0.0))
    )


def _finalize(state, graph, config, best_assign, best_obj, SP, cap,
              obj_true0=None):
    """Best-seen gating against the TRUE input objective + pod scatter —
    identical to the single-chip solver's epilogue (global_solver.py)."""
    if obj_true0 is None:
        obj_true0 = _true_objective(state, graph, config, cap)
    # under disruption pricing the adopt gate re-prices with the EXACT
    # pod-level restart bill (same contract as the single-chip solvers)
    tgt = best_assign[jnp.clip(state.pod_service, 0, SP - 1)]
    bill = (
        pod_restart_bill(state, tgt, config.move_cost)
        if config.move_cost > 0
        else jnp.float32(0.0)
    )
    improved = best_obj + bill < obj_true0
    new_pod_node = jnp.where(improved & state.pod_valid, tgt, state.pod_node)
    info = {
        "objective_before": obj_true0,
        "objective_after": jnp.where(improved, best_obj, obj_true0),
        "move_penalty": jnp.where(improved, bill, 0.0),
    }
    return state.replace(pod_node=new_pod_node), info


def sharded_global_assign(
    state: ClusterState,
    graph: CommGraph,
    key: jax.Array,
    mesh: Mesh,
    config: GlobalSolverConfig = GlobalSolverConfig(),
) -> tuple[ClusterState, dict[str, jax.Array]]:
    """``global_assign`` with the node axis sharded over ``mesh``'s ``tp``.

    Requires ``num_nodes % tp == 0``. Never worse than the input placement
    (same best-seen gating as the single-chip solver).
    """
    tp, S, N, SP = _check_and_dims(state, graph, config, mesh)
    args = _prep(state, graph, config, S, N, SP)
    cap = args[7]  # the budget-scaled CPU capacities (see _prep's order)
    keys = jax.random.split(key, config.sweeps)
    best_assign, best_obj = _build_solve(mesh, config, S, N)(*args, keys)
    new_state, info = _finalize(state, graph, config, best_assign, best_obj, SP, cap)
    info["tp"] = jnp.asarray(tp)
    return new_state, info


def sharded_solve_with_restarts(
    state: ClusterState,
    graph: CommGraph,
    key: jax.Array,
    mesh: Mesh,
    *,
    n_restarts: int = 1,
    config: GlobalSolverConfig = GlobalSolverConfig(),
) -> tuple[ClusterState, dict[str, jax.Array]]:
    """dp restarts *of* tp-sharded solves — the full-mesh production solve.

    ``n_restarts`` must be a multiple of the mesh's ``dp``; each dp slice
    scans its share of restarts sequentially while every solve shards the
    node axis over ``tp``. Per-restart keys match ``parallel_restarts``
    (``split(key, n_restarts)``, each split into per-sweep keys the way
    ``global_assign`` does), so with annealing noise off the composed path
    makes the same per-restart decisions as the single-device solver and
    the same best-of-N selection (first minimum in global restart order) as
    the dp-only path.
    """
    tp, S, N, SP = _check_and_dims(state, graph, config, mesh)
    dp = mesh.shape.get("dp", 1)
    if n_restarts % dp:
        raise ValueError(f"n_restarts {n_restarts} must be a multiple of dp={dp}")
    r_local = n_restarts // dp
    args = _prep(state, graph, config, S, N, SP)
    cap = args[7]  # the budget-scaled CPU capacities (see _prep's order)
    obj_true0 = _true_objective(state, graph, config, cap)
    pod_slot = jnp.clip(state.pod_service, 0, SP - 1)
    pod_mask = state.pod_valid & (state.pod_node >= 0)
    keys_all = jax.random.split(key, n_restarts)                    # [R, 2]
    keys_block = jax.vmap(
        lambda k: jax.random.split(k, config.sweeps)
    )(keys_all)                                                     # [R, sweeps, 2]
    best_assign, best_raw, all_gated = _build_solve_restarts(
        mesh, config, S, N, r_local
    )(*args, pod_slot, state.pod_node, pod_mask, obj_true0, keys_block)
    new_state, info = _finalize(
        state, graph, config, best_assign, best_raw, SP, cap,
        obj_true0=obj_true0,
    )
    info.update(
        restart_objectives=all_gated,
        best_restart=jnp.argmin(all_gated),
        tp=jnp.asarray(tp),
    )
    return new_state, info
