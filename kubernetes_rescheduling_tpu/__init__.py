"""kubernetes_rescheduling_tpu — a TPU-native communication-aware rescheduling framework.

A brand-new framework with the capabilities of ye0nj00/Kubernetes-Rescheduling
(CAR — Communication-Aware Rescheduling — plus the spread/binpack/random/
kube-scheduling baselines, hazard detection, victim selection, and the
communication-cost / load-deviation evaluation harness), re-designed TPU-first:

- cluster snapshots are fixed-capacity padded JAX arrays (``core.state``),
- the objectives are jit-able reductions (``objectives``),
- all five placement policies are one vmapped scoring kernel (``policies``),
- the multi-round control loop is a ``lax.scan`` (``solver.round_loop``),
- a batched global assignment solver replaces the one-pod-per-round greedy
  (``solver.global_solver``), sharding over a device mesh (``parallel``),
- live-cluster I/O lives in a thin host-side adapter (``backends.k8s``),
  with a hermetic in-memory simulator (``backends.sim``) for tests.

Reference parity citations use ``file:line`` of the reference repo
(e.g. ``rescheduling.py:174-218``); see SURVEY.md at the repo root.
"""

from kubernetes_rescheduling_tpu.core.state import ClusterState, CommGraph
from kubernetes_rescheduling_tpu.core.quantities import (
    cpu_to_millicores,
    mem_to_bytes,
    format_millicores,
    format_bytes_as_mi,
)
from kubernetes_rescheduling_tpu.config import RescheduleConfig

__version__ = "0.1.0"

__all__ = [
    "ClusterState",
    "CommGraph",
    "RescheduleConfig",
    "cpu_to_millicores",
    "mem_to_bytes",
    "format_millicores",
    "format_bytes_as_mi",
    "__version__",
]
