from kubernetes_rescheduling_tpu.cli import main

raise SystemExit(main())
