"""Typed configuration for the whole framework.

Replaces the reference's scattered hardcoded constants with one dataclass
(SURVEY.md §5.6): hazard threshold 30 (reference harzard_detect.py:7), 15 s
pacing and 10 rounds (reference main.py:27-28), policy name (reference
main.py:118-125), plus the knobs the reference never had (backend, scale,
capacity enforcement, solver iterations). Loadable from TOML.
"""

from __future__ import annotations

import dataclasses

try:
    import tomllib  # Python >= 3.11
except ModuleNotFoundError:  # pragma: no cover - depends on interpreter
    import tomli as tomllib  # type: ignore[no-redef]
from dataclasses import dataclass, field
from pathlib import Path
from typing import Literal

from kubernetes_rescheduling_tpu.utils.retry import RetryPolicy

PolicyName = Literal[
    "spread", "binpack", "random", "kubescheduling", "communication", "global"
]

POLICIES: tuple[str, ...] = (
    "spread",
    "binpack",
    "random",
    "kubescheduling",
    "communication",
)


# greedy policies whose placement mechanism PINS the landing node
# (nodeName/nodeSelector): the device-resident round scan can replay
# their moves knowing where they land. Mirrored from
# backends.k8s.PlacementMechanism so config validation stays
# import-light — tests/test_scan.py asserts the two registries agree.
# kubescheduling is deliberately absent: its affinityOnly mechanism
# delegates the landing to the (simulated) scheduler, and a scanned
# block must not bet K future decisions on an f32 twin of an f64 choice.
SCAN_POLICIES: tuple[str, ...] = (
    "spread",
    "binpack",
    "random",
    "communication",
)


# the named churn profiles elastic/events.py implements (mirrored here so
# config validation stays jax/numpy-free — the elastic package asserts the
# two registries agree)
ELASTIC_PROFILES: tuple[str, ...] = (
    "steady",
    "diurnal-autoscale",
    "deploy-waves",
    "node-flap",
)


@dataclass(frozen=True)
class ElasticConfig:
    """Elastic-topology block (``[elastic]`` in TOML): which seeded churn
    profile mutates the cluster between rounds (``"none"`` = static
    topology, the historical behavior), and how live S×N×P counts are
    padded into quantized shape buckets so churn within a bucket never
    retraces a kernel (``elastic.buckets``). jax-free, like the other
    blocks, so config import stays light.

    ``tenants`` applies only under fleet mode: the tenant indices the
    churn profile mutates (empty = every tenant) — the per-tenant churn
    domain the fleet isolation tests pin, mirroring
    ``FleetConfig.chaos_tenants``."""

    profile: str = "none"
    seed: int = 0
    bucket_floor: int = 8
    tenants: tuple[int, ...] = ()

    def validate(self) -> "ElasticConfig":
        valid = ("none",) + ELASTIC_PROFILES
        if self.profile not in valid:
            raise ValueError(
                f"unknown churn profile {self.profile!r}; expected one of "
                f"{sorted(valid)}"
            )
        if self.bucket_floor < 1:
            raise ValueError(
                f"bucket_floor must be >= 1, got {self.bucket_floor}"
            )
        for t in self.tenants:
            if not (isinstance(t, int) and t >= 0):
                raise ValueError(
                    f"elastic tenants must be non-negative ints, got {t!r}"
                )
        return self


@dataclass(frozen=True)
class ForecastConfig:
    """Forecast-plane block (``[forecast]`` in TOML): the online
    lag-feature ridge forecaster behind the ``proactive`` algorithm
    (``forecast/``). jax-free, like the other blocks, so config import
    stays light.

    ``lags`` is the feature window length; ``ridge`` the L2 term that
    keeps every per-node solve well-posed; ``min_history`` how many
    observations a node needs before its model prediction is trusted
    (until then the prediction IS persistence — proactive rounds are
    bit-identical to reactive ones); ``min_skill`` the device-side
    degrade gate: when ``forecast_skill = 1 − mae_model/mae_persistence``
    drops below it, the applied delta zeroes and the round falls back to
    reactive CAR (the shadow model keeps scoring so it can recover).
    ``decay`` is the exponential weight of the skill window (per scored
    round): ~1/(1−decay) recent rounds dominate, so a model that starts
    badly and then learns re-earns the gate instead of dragging its
    cold-start errors forever (1.0 = cumulative, never forgets).
    ``fit_decay`` is the separate recursive-least-squares forgetting of
    the ridge statistics — deliberately LONGER than the skill window
    (the noise mean-reversion the model exploits is stationary and
    rewards memory; the skill verdict must react fast).
    ``base_policy`` is the greedy policy the proactive rounds score
    with — the forecast moves the STATE the policy sees, not the policy
    itself."""

    lags: int = 2
    ridge: float = 1e-3
    min_history: int = 12
    min_skill: float = 0.0
    decay: float = 0.85
    fit_decay: float = 0.97
    base_policy: str = "communication"

    def validate(self) -> "ForecastConfig":
        if self.lags < 1:
            raise ValueError(f"forecast lags must be >= 1, got {self.lags}")
        if self.ridge <= 0:
            raise ValueError(
                f"forecast ridge must be > 0 (it keeps cold solves "
                f"well-posed), got {self.ridge}"
            )
        if self.min_history < self.lags + 2:
            raise ValueError(
                f"forecast min_history must be >= lags + 2 (a node needs "
                f"a full feature window plus targets before its fit "
                f"means anything), got {self.min_history} with lags="
                f"{self.lags}"
            )
        if not (0.0 < self.decay <= 1.0):
            raise ValueError(
                f"forecast decay must be in (0, 1] (1 = cumulative skill "
                f"window), got {self.decay}"
            )
        if not (0.0 < self.fit_decay <= 1.0):
            raise ValueError(
                f"forecast fit_decay must be in (0, 1] (1 = infinite "
                f"fit memory), got {self.fit_decay}"
            )
        if self.base_policy not in POLICIES:
            raise ValueError(
                f"forecast base_policy must be a greedy policy "
                f"{sorted(POLICIES)}, got {self.base_policy!r}"
            )
        return self


@dataclass(frozen=True)
class ControllerConfig:
    """Control-loop execution block (``[controller]`` in TOML): how the
    live round loop schedules its work. jax-free, like the other blocks,
    so config import stays light.

    ``pipeline`` turns on the software-pipelined round loop: the
    post-move ``monitor`` is issued asynchronously through the boundary
    (retry/breaker/degraded semantics unchanged — the call ORDER the
    backend sees is exactly the sequential loop's, so decisions are
    bit-identical on the sim backend), decision kernels dispatch
    asynchronously, the host fences device work only at the apply
    boundary, and the previous round's single round-end bundle pull +
    record finalization overlap the current round's device compute.
    Rounds that cannot pipeline — an open/half-open breaker, pending
    churn, a streaming (callable) decision graph — drain the pipeline
    and run the sequential path for that round.

    ``depth`` is the snapshot double-buffer depth: how many rounds may
    be in flight at once. Only 2 — one round closing while the next
    decides — is implemented (the monitor→decide data dependency admits
    no more without speculation), and validation REJECTS anything else
    so the ``pipeline_depth`` gauge and ``RoundRecord.pipeline`` can
    never report a schedule that did not run; the knob reserves the
    config surface for speculative deeper variants.

    ``scan_block`` selects the third schedule — the device-resident
    round scan (``bench/scan.py``): K > 0 fuses K steady-state rounds
    (decide → sim-twin apply → monitor → round-end metrics) into ONE
    compiled ``lax.scan`` dispatch and ONE counted ``round_end``
    transfer per block, draining to the per-round path on anything the
    scan cannot honor (churn, breaker events, checkpoints, incompatible
    backends — counted ``scan_drains_total{reason}``). Mutually
    exclusive with ``pipeline`` (they are different schedules of the
    same loop), and only meaningful for pinning greedy algorithms with
    ``moves_per_round=1`` on the hermetic sim backend — validation in
    ``RescheduleConfig`` enforces the config-level half; the loop
    drains at runtime on the rest. 0 = off.

    ``donate_carry`` gates donation of the GLOBAL SOLVER's snapshot
    carry (``global_assign_donated`` — the output placement aliases the
    input instead of holding both; visible in the ``jax_hbm_*``
    cost-model gauges), applied only when nothing outside the loop can
    touch the pre-solve snapshot (no checkpoint manager, ``on_round``,
    or ops plane). It does NOT govern the forecast plane's
    recursive-least-squares carry: that state is private to the plane
    and consumed every round by construction, so it is ALWAYS donated
    (``forecast/plane.py``). The greedy decide kernels are deliberately
    never donated: none of their outputs (index scalars, a bool hazard
    mask) can alias the f32/i32 snapshot buffers, so XLA would warn and
    reuse nothing."""

    pipeline: bool = False
    depth: int = 2
    donate_carry: bool = True
    scan_block: int = 0

    def validate(self) -> "ControllerConfig":
        if self.depth != 2:
            raise ValueError(
                f"controller pipeline depth must be 2 (the only "
                f"implemented schedule: one round closing while the next "
                f"decides), got {self.depth}"
            )
        if self.scan_block < 0:
            raise ValueError(
                f"controller scan_block must be >= 0 (0 = scanned "
                f"schedule off), got {self.scan_block}"
            )
        if self.scan_block and self.pipeline:
            raise ValueError(
                "controller scan_block and pipeline are mutually "
                "exclusive schedules of the same loop: the scan already "
                "amortizes dispatch and transfer over K rounds, so there "
                "is no per-round tail left to overlap"
            )
        return self


@dataclass(frozen=True)
class ReconcileConfig:
    """Reconciliation & admission block (``[reconcile]`` in TOML): the
    controller's trust boundary on its own INPUTS and ACTIONS. jax-free,
    like the other blocks, so config import stays light.

    ``admission`` gates the snapshot admission guard
    (``bench/admission.py``): every ``boundary.monitor()`` result is
    classified before it can touch device state — non-finite/negative
    loads are quarantined per entry (last-good value reused, counted
    ``admission_quarantined_total{field,reason}``), impossibly-large
    loads are clamped to capacity, and structurally-broken snapshots
    (duplicate pods, unknown node references, a mostly-garbage metrics
    wave) are REJECTED, which charges the boundary like any other
    failure (the PR-2 degraded-round/breaker machinery).

    ``enabled`` gates the intent ledger (``bench/reconcile.py``): after
    each round's applies the controller records where everything SHOULD
    be; each admitted snapshot is diffed against that intent, divergences
    are classified (``wrong_node``/``lost_move``/``external_drift``/
    ``phantom_pod``/``missing_pod`` — churn events are consumed first so
    legitimate topology changes never read as drift) and counted
    (``reconcile_divergences_total{kind}``), and up to
    ``repair_budget_per_round`` corrective moves per round are issued
    through the normal boundary/breaker budget until observed state
    converges back to intent (0 = detect and count only, never repair).

    ``max_quarantine_frac``: a snapshot needing more than this fraction
    of its valid pods quarantined is rejected outright — repairing a
    mostly-fabricated metrics wave entry-by-entry would launder garbage
    into 'last good'."""

    admission: bool = True
    enabled: bool = True
    repair_budget_per_round: int = 2
    max_quarantine_frac: float = 0.5

    def validate(self) -> "ReconcileConfig":
        if self.repair_budget_per_round < 0:
            raise ValueError(
                f"reconcile repair_budget_per_round must be >= 0 "
                f"(0 = detect only), got {self.repair_budget_per_round}"
            )
        if not (0.0 < self.max_quarantine_frac <= 1.0):
            raise ValueError(
                f"reconcile max_quarantine_frac must be in (0, 1], got "
                f"{self.max_quarantine_frac}"
            )
        return self


@dataclass(frozen=True)
class ShadowConfig:
    """Shadow-mode block (``[shadow]`` in TOML): replay a recorded
    real-cluster trace, recommend moves without applying any, and score
    our counterfactual placement against what the trace's actual
    scheduler did (``backends.replay`` + ``bench.shadow``). jax-free,
    like the other blocks, so config import stays light.

    ``enabled`` turns the plane on; the run must use the replay backend
    (the CLI's ``--shadow TRACE`` builds both together). ``win_margin``
    is the undercut a round must achieve to count as a win: our
    counterfactual comm cost must be at or below
    ``actual · (1 − win_margin)`` — 0 means ties count (matching the
    production scheduler at zero risk is a win)."""

    enabled: bool = False
    win_margin: float = 0.0

    def validate(self) -> "ShadowConfig":
        if not (0.0 <= self.win_margin < 1.0):
            raise ValueError(
                f"shadow win_margin must be in [0, 1) (a fraction of the "
                f"actual cost to undercut), got {self.win_margin}"
            )
        return self


@dataclass(frozen=True)
class ChaosConfig:
    """Fault-injection block: which named ``backends.chaos`` profile wraps
    the loop's backend (``"none"`` = no wrapper), under which fault seed.
    Profile names are validated by ``backends.chaos.with_chaos`` at wrap
    time — this block stays jax-free so config import stays light."""

    profile: str = "none"
    seed: int = 0


@dataclass(frozen=True)
class FleetConfig:
    """Fleet-mode block (``[fleet]`` in TOML): N tenants solved by ONE
    batched device program per round under the multiplexed controller
    (``bench.fleet``). jax-free, like the other blocks, so config import
    stays light.

    ``tenants == 0`` means fleet mode is off (the historical
    one-backend-one-loop controller). ``plane`` selects the device
    batching: ``"vmap"`` (one program, leading tenant axis —
    ``solver.fleet`` / ``solver.fleet_global`` / ``forecast.fleet``) or
    ``"dp"`` (one tenant group per device over the mesh's dp axis —
    ``parallel.fleet``). ``chaos_tenants`` wraps ONLY those tenant
    indices in the run's chaos profile — the per-tenant fault domain the
    isolation tests pin.

    Which decision planes batch (fleet v2): the greedy kernel
    (``moves_per_round=1``), the ``proactive`` kernel (per-tenant
    recursive-least-squares forecast state stacked ``[T, N, ...]``, the
    skill gate judged per tenant), and the dense global solver
    (``algorithm='global'`` / ``moves_per_round='all'`` — swap phases
    and ``solver_restarts`` fan out inside the one batched dispatch).
    Still rejected, with the reason in the error: ``placement_unit=
    'pod'`` (host-built per-tenant pod graphs), ``solver_backend=
    'sparse'`` (per-tenant static block layout forks the compiled
    signature), an integer ``global_moves_cap`` (sequential host-side
    wave-cap re-scoring — use ``move_cost``), and ``solver_tp`` (the
    fleet dp axis owns the mesh). Tenants may have HETEROGENEOUS shapes:
    the multiplexed loop aligns every tenant to shared power-of-two
    shape buckets at startup (``elastic.buckets``), pads snapshots to
    the bucket, and the mask-native kernels keep padded slots inert —
    per-tenant decisions stay bit-exact with an unpadded solo run (the
    mask-twin pin)."""

    tenants: int = 0
    plane: str = "vmap"                  # "vmap" | "dp"
    chaos_tenants: tuple[int, ...] = ()  # tenant indices the chaos profile hits

    def validate(self) -> "FleetConfig":
        if self.tenants < 0:
            raise ValueError(f"fleet tenants must be >= 0, got {self.tenants}")
        if self.plane not in ("vmap", "dp"):
            raise ValueError(
                f"fleet plane must be 'vmap' or 'dp', got {self.plane!r}"
            )
        for t in self.chaos_tenants:
            if not (isinstance(t, int) and t >= 0):
                raise ValueError(
                    f"chaos_tenants must be non-negative ints, got {t!r}"
                )
            if self.tenants and t >= self.tenants:
                raise ValueError(
                    f"chaos tenant {t} out of range for {self.tenants} tenants"
                )
        return self


@dataclass(frozen=True)
class ObsConfig:
    """Live ops plane block: the in-process HTTP endpoint
    (``telemetry.server``), decision explainability, the flight recorder,
    and the SLO watchdog. jax-free, like :class:`ChaosConfig`, so config
    import stays light; ``OpsPlane.from_config`` consumes it."""

    serve_port: int | None = None        # None = no HTTP server; 0 = ephemeral
    explain: bool = True                 # record DecisionExplanations when a
                                         # logger or ops plane is attached
    explain_top_k: int = 3               # candidates/hazard nodes per decision
    attribution: bool = True             # per-round cost attribution (edge/
                                         # node-pair decomposition + move
                                         # provenance) when a logger or ops
                                         # plane is attached
    attribution_top_k: int = 8           # service edges / node pairs recorded
    attribution_drift_frac: float = 0.0  # attribution_drift SLO rule: top-1
                                         # edge share of total cost (0 = off)
    # fleet observability (telemetry.fleet_rollup): the cardinality
    # budget — fleets with at most this many tenants keep the legacy
    # per-tenant labeled families (fleet_rounds_total{tenant}, cost/load
    # gauges, per-tenant /healthz rows) bit-identically; larger fleets
    # suppress them (counted tenant_series_suppressed_total{family}) and
    # observe through the bounded rollup families instead
    tenant_label_budget: int = 64
    fleet_rollup: bool = True            # device-side tenant rollups riding
                                         # the fleet round-end bundle
    fleet_rollup_top_k: int = 3          # worst tenants recorded per rollup
                                         # dimension (rank-labeled, bounded)
    slo_fleet_tail_frac: float = 0.0     # fleet_tail_cost SLO rule: the p99
                                         # cost rollup rising more than this
                                         # fraction above the rolling
                                         # window's best is a violation
                                         # (0 = off; the window rebases with
                                         # the run, like the cost rule)
    flight_recorder_rounds: int = 16     # ring capacity (rounds)
    bundle_dir: str = "flight_recorder"  # where trigger dumps land
    max_round_age_s: float = 0.0         # /healthz staleness rule (0 = off)
    slo_window: int = 20                 # rolling-window rounds
    slo_min_samples: int = 5
    slo_latency_p95_s: float = 0.0       # 0 disables the latency rule
    slo_cost_regression_frac: float = 0.0  # 0 disables the cost rule
    slo_max_retraces: int = 1            # 0 disables the retrace rule
    slo_forecast_min_skill: float = 0.0  # forecast_skill SLO rule: a trained
                                         # forecaster whose skill drops below
                                         # this is in violation (only judges
                                         # rounds that carry forecast data,
                                         # so reactive runs never trip it)
    slo_pipeline_min_overlap: float = 0.0  # pipeline_overlap SLO rule: the
                                           # rolling mean overlap_ratio of
                                           # pipelined rounds collapsing
                                           # below this means the pipeline
                                           # has degenerated to sequential
                                           # round-trips (0 = off; only
                                           # judges rounds that carry
                                           # pipeline telemetry)
    slo_reconcile_drift_pods: int = 0      # reconcile_divergence SLO rule:
                                           # a round whose reconcile block
                                           # reports at least this many
                                           # pods still diverged from the
                                           # controller's intent is in
                                           # violation (0 = off; 1 = any
                                           # persistent drift; only rounds
                                           # carrying reconcile data are
                                           # judged)
    slo_shadow_min_win_rate: float = 0.0   # shadow_win_rate SLO rule: a
                                           # shadow run whose running
                                           # win-rate against the trace's
                                           # actual scheduler sits below
                                           # this is in violation (0 =
                                           # off; only rounds carrying
                                           # shadow data are judged, so
                                           # live runs never trip it)
    # in-block tripwires (telemetry.tripwire): device-side health
    # predicates inside the scanned schedules' lax.scan body — a trip
    # latches the rest of the block to no-move identity rounds in-trace
    # and drains the block (reason "tripwire")
    scan_tripwires: bool = True          # the plane itself; the always-armed
                                         # non_finite rule never fires on a
                                         # healthy sim, so on-by-default
                                         # keeps trip-free runs bit-identical
    tripwire_cost_frac: float = 0.0      # cost_regression rule: comm cost
                                         # rising more than this fraction
                                         # above the block-start baseline
                                         # trips (0 = rule off)
    tripwire_load_factor: float = 0.0    # load_std_spike rule: load std
                                         # exceeding this factor of the
                                         # block-start baseline trips
                                         # (0 = rule off)
    tripwire_hazard_streak: int = 0      # hazard_streak rule: the same node
                                         # most-hazardous this many rounds
                                         # in a row trips (0 = rule off)
    slo_scan_tripwire: bool = True       # scan_tripwire SLO rule: a tripped
                                         # block flips /healthz until a
                                         # clean block lands (only scan runs
                                         # carry the data, so the per-round
                                         # path never trips it)
    slo_serving_p99_ms: float = 0.0      # serving_p99 SLO rule: the serving
                                         # plane's rolling-window p99
                                         # request latency exceeding this
                                         # many ms is a violation (0 = off;
                                         # only judged once the window holds
                                         # slo_min_samples completed
                                         # requests, so idle serving never
                                         # trips it)
    # mesh & device plane (telemetry.mesh): the tenant rollup's sibling
    # on the DEVICE axis — per-device attributed step-time/transfer
    # rollups over the dp fleet planes, budget-gated per-device series,
    # and on-demand jax.profiler capture
    device_rollup: bool = True           # the device plane itself (its
                                         # inputs are host-resident already,
                                         # so on-by-default costs zero new
                                         # device transfers)
    device_label_budget: int = 64        # per-DEVICE series cardinality
                                         # budget, the device-axis twin of
                                         # tenant_label_budget: over it the
                                         # mesh_device_* families suppress
                                         # (counted) and the bounded mesh_*
                                         # rollup families carry the plane
    slo_mesh_imbalance_ratio: float = 0.0  # mesh_imbalance SLO rule: the
                                           # worst/median attributed device
                                           # step-time ratio exceeding this
                                           # is a violation (0 = off; only
                                           # meshes with >= 2 devices are
                                           # judged, so single-chip runs
                                           # never trip it)
    profile_rounds: int = 0              # arm one on-demand jax.profiler
                                         # capture spanning this many fleet
                                         # rounds (or one scan block) at run
                                         # start (0 = off; POST /profile
                                         # arms the same gate mid-run)
    profile_max_captures: int = 4        # hard per-process capture cap —
                                         # POST /profile answers 409 once
                                         # spent
    profile_max_mb: float = 256.0        # hard per-artifact size cap: a
                                         # capture larger than this is
                                         # DELETED (counted status=oversize)
                                         # so a runaway trace can never fill
                                         # the bundle dir

    def validate(self) -> "ObsConfig":
        if self.serve_port is not None and not (0 <= self.serve_port <= 65535):
            raise ValueError(f"serve_port must be in [0, 65535], got {self.serve_port}")
        if self.explain_top_k < 1:
            raise ValueError("explain_top_k must be >= 1")
        if self.attribution_top_k < 1:
            raise ValueError("attribution_top_k must be >= 1")
        if not (0.0 <= self.attribution_drift_frac <= 1.0):
            raise ValueError("attribution_drift_frac must be in [0, 1]")
        if self.tenant_label_budget < 0:
            raise ValueError(
                "tenant_label_budget must be >= 0 (0 = per-tenant series "
                "always suppressed in fleet mode)"
            )
        if self.fleet_rollup_top_k < 1:
            raise ValueError("fleet_rollup_top_k must be >= 1")
        if self.slo_fleet_tail_frac < 0:
            raise ValueError(
                "slo_fleet_tail_frac must be >= 0 (0 disables the "
                "fleet_tail_cost rule)"
            )
        if self.flight_recorder_rounds < 1:
            raise ValueError("flight_recorder_rounds must be >= 1")
        if self.max_round_age_s < 0:
            raise ValueError("max_round_age_s must be >= 0")
        if self.slo_window < 2:
            raise ValueError("slo_window must be >= 2")
        if self.slo_min_samples < 1:
            raise ValueError("slo_min_samples must be >= 1")
        if self.slo_latency_p95_s < 0 or self.slo_cost_regression_frac < 0:
            raise ValueError("SLO thresholds must be >= 0")
        if self.slo_max_retraces < 0:
            raise ValueError("slo_max_retraces must be >= 0")
        if self.slo_forecast_min_skill > 1.0:
            raise ValueError(
                "slo_forecast_min_skill must be <= 1.0 (skill is bounded "
                "above by 1, so a larger threshold would always violate)"
            )
        if not (0.0 <= self.slo_pipeline_min_overlap <= 1.0):
            raise ValueError(
                "slo_pipeline_min_overlap must be in [0, 1] (overlap_ratio "
                "is a fraction of background boundary time hidden)"
            )
        if self.slo_reconcile_drift_pods < 0:
            raise ValueError(
                "slo_reconcile_drift_pods must be >= 0 (0 disables the "
                "reconcile_divergence rule)"
            )
        if not (0.0 <= self.slo_shadow_min_win_rate <= 1.0):
            raise ValueError(
                "slo_shadow_min_win_rate must be in [0, 1] (a win-rate "
                "fraction; 0 disables the shadow_win_rate rule)"
            )
        if self.tripwire_cost_frac < 0:
            raise ValueError(
                "tripwire_cost_frac must be >= 0 (0 disables the "
                "cost_regression tripwire rule)"
            )
        if self.tripwire_load_factor < 0:
            raise ValueError(
                "tripwire_load_factor must be >= 0 (0 disables the "
                "load_std_spike tripwire rule)"
            )
        if self.tripwire_hazard_streak < 0:
            raise ValueError(
                "tripwire_hazard_streak must be >= 0 (0 disables the "
                "hazard_streak tripwire rule)"
            )
        if self.slo_serving_p99_ms < 0:
            raise ValueError(
                "slo_serving_p99_ms must be >= 0 (0 disables the "
                "serving_p99 rule)"
            )
        if self.device_label_budget < 0:
            raise ValueError(
                "device_label_budget must be >= 0 (0 = per-device series "
                "always suppressed; the bounded mesh rollups still emit)"
            )
        if self.slo_mesh_imbalance_ratio != 0.0 and (
            self.slo_mesh_imbalance_ratio < 1.0
        ):
            raise ValueError(
                "slo_mesh_imbalance_ratio must be 0 (rule off) or >= 1 "
                "(worst/median device step time can never sit below 1)"
            )
        if self.profile_rounds < 0:
            raise ValueError(
                "profile_rounds must be >= 0 (0 = no capture armed at "
                "run start)"
            )
        if self.profile_max_captures < 1:
            raise ValueError("profile_max_captures must be >= 1")
        if self.profile_max_mb <= 0:
            raise ValueError(
                "profile_max_mb must be > 0 (the per-artifact size cap)"
            )
        return self


@dataclass(frozen=True)
class PerfConfig:
    """Performance-ledger block (``[perf]`` in TOML): where the
    append-only perf ledger lives and how its rolling-window regression
    detector judges. jax-free; ``telemetry.perf_ledger`` consumes it.

    ``ledger_path = None`` means the consumer picks a default (the bench
    harness writes ``<session>/perf_ledger.jsonl``); ``enabled = False``
    turns ledger writes and detection off entirely."""

    enabled: bool = True
    ledger_path: str | None = None
    window: int = 5                  # prior readings judged against
    regression_frac: float = 0.2     # threshold above baseline = regressed
    baseline: str = "median"         # "median" | "best" of the window
    min_history: int = 2             # readings before a series is judged

    def validate(self) -> "PerfConfig":
        if self.window < 1:
            raise ValueError("perf window must be >= 1")
        if self.regression_frac < 0:
            raise ValueError("perf regression_frac must be >= 0")
        if self.baseline not in ("median", "best"):
            raise ValueError(
                f"perf baseline must be 'median' or 'best', got {self.baseline!r}"
            )
        if self.min_history < 1:
            raise ValueError("perf min_history must be >= 1")
        return self


@dataclass(frozen=True)
class ServingConfig:
    """Serving-plane block (``[serving]`` in TOML): the request-grain
    placement service (``serving/``) behind ``POST /place``. jax-free,
    like the other blocks, so config import stays light.

    ``enabled`` turns the plane on under the CLI (the engine itself can
    always be built programmatically). ``max_batch`` is the static batch
    shape every coalesced dispatch pads to — the one-compiled-trace
    invariant; ``batch_window_ms`` how long the batcher holds the first
    dequeued request open for company; ``queue_depth`` the bounded
    admission queue (arrivals beyond it shed immediately, counted
    ``serving_shed_total{reason="queue_full"}``); ``deadline_ms`` the
    default per-request deadline (requests still queued past it complete
    ``timeout`` without occupying a batch slot; 0 = no deadline);
    ``window`` the rolling completed-request window behind the /healthz
    percentiles and the ``serving_p99`` watchdog rule; ``ring`` the
    bounded recent-request ring flight-recorder bundles capture."""

    enabled: bool = False
    max_batch: int = 8
    batch_window_ms: float = 2.0
    queue_depth: int = 64
    deadline_ms: float = 250.0
    window: int = 256
    ring: int = 32

    def validate(self) -> "ServingConfig":
        if self.max_batch < 1:
            raise ValueError(
                f"serving max_batch must be >= 1, got {self.max_batch}"
            )
        if self.batch_window_ms < 0:
            raise ValueError(
                f"serving batch_window_ms must be >= 0 (0 = dispatch "
                f"whatever is queued immediately), got {self.batch_window_ms}"
            )
        if self.queue_depth < 1:
            raise ValueError(
                f"serving queue_depth must be >= 1, got {self.queue_depth}"
            )
        if self.deadline_ms < 0:
            raise ValueError(
                f"serving deadline_ms must be >= 0 (0 = no deadline), "
                f"got {self.deadline_ms}"
            )
        if self.window < 2:
            raise ValueError(
                f"serving window must be >= 2 (percentiles over one "
                f"sample judge nothing), got {self.window}"
            )
        if self.ring < 1:
            raise ValueError(f"serving ring must be >= 1, got {self.ring}")
        return self


@dataclass(frozen=True)
class SloConfig:
    """SLO v2 block (``[slo]`` in TOML): error budgets and multi-window
    burn-rate alerting over the in-process history plane
    (``telemetry/timeseries.py`` + ``telemetry/slo.py``). jax-free.

    ``enabled`` turns the plane on (off by default: disabled runs must
    stay bit-identical to pre-SLO output). ``objective`` is the success
    fraction every default SLO targets (0.99 = 1% error budget);
    ``latency_threshold_ms`` additionally compiles a serving-latency SLO
    over the ``serving_request_seconds{stage="total"}`` histogram (0
    disables it). All windows are in *ticks* (rounds/batches — the sim
    clock is not wall time): ``budget_window`` is the long accounting
    window behind ``slo_budget_remaining_frac``; the
    ``fast_window``/``fast_burn`` pair is the page
    (``slo_fast_burn``, the 5m-of-1h analogue with a 14.4x default
    threshold), ``slow_window``/``slow_burn`` the ticket
    (``slo_slow_burn``, 6x); each long window carries an implicit 1/12
    confirm window, and a burn of 0 disables that rule.
    ``series_capacity``/``max_series`` bound the history plane: points
    per ring and the hard global series budget (LRU-evicted, counted
    ``timeseries_evictions_total``)."""

    enabled: bool = False
    objective: float = 0.99
    latency_threshold_ms: float = 0.0
    budget_window: int = 512
    fast_window: int = 48
    fast_burn: float = 14.4
    slow_window: int = 288
    slow_burn: float = 6.0
    series_capacity: int = 512
    max_series: int = 256

    def validate(self) -> "SloConfig":
        if not 0.0 < self.objective < 1.0:
            raise ValueError(
                f"slo objective must be in (0, 1), got {self.objective}"
            )
        if self.latency_threshold_ms < 0:
            raise ValueError(
                f"slo latency_threshold_ms must be >= 0 (0 disables the "
                f"latency SLO), got {self.latency_threshold_ms}"
            )
        for name in ("budget_window", "fast_window", "slow_window"):
            if getattr(self, name) < 2:
                raise ValueError(
                    f"slo {name} must be >= 2, got {getattr(self, name)}"
                )
        if self.fast_window >= self.slow_window:
            raise ValueError(
                f"slo fast_window ({self.fast_window}) must be shorter "
                f"than slow_window ({self.slow_window})"
            )
        if self.budget_window < self.slow_window:
            raise ValueError(
                f"slo budget_window ({self.budget_window}) must cover "
                f"slow_window ({self.slow_window})"
            )
        if self.fast_burn < 0 or self.slow_burn < 0:
            raise ValueError(
                "slo burn thresholds must be >= 0 (0 disables the rule)"
            )
        if self.series_capacity < 2:
            raise ValueError(
                f"slo series_capacity must be >= 2, got {self.series_capacity}"
            )
        if self.max_series < 1:
            raise ValueError(
                f"slo max_series must be >= 1, got {self.max_series}"
            )
        return self


@dataclass(frozen=True)
class RescheduleConfig:
    """One config object for a rescheduling run."""

    # Policy & loop — reference semantics
    algorithm: str = "communication"       # reference main.py:118-125 (CLI arg)
    hazard_threshold_pct: float = 30.0     # reference harzard_detect.py:7
    max_rounds: int = 10                   # reference main.py:28
    sleep_after_action_s: float = 15.0     # reference main.py:27 (live backend only)
    # Deployments moved per greedy round. 1 = reference-faithful (one
    # victim, delete_replaced_pod.py:154); k = up to k victims drained from
    # the hazard node (stopping early once no hazard remains); "all" = the
    # SURVEY §7 greedy→global bridge, routing the round through the batched
    # global solver regardless of algorithm.
    moves_per_round: int | str = 1
    # Wave cap for GLOBAL rounds: the solver re-places every service, but
    # only the k highest-gain strictly-improving moves are applied per
    # round ("all" = unlimited, the historical behavior). Each Deployment
    # move tears down and recreates all its replicas, and requests that
    # traverse the service during that window fail (measured by the
    # request-level load generator: uncapped global fails ~36% of
    # in-flight requests on the µBench matrix vs ~17% at k=2 — RESULTS.md);
    # capping spreads the wave across rounds while the per-round re-solve
    # keeps pursuing the full optimum.
    global_moves_cap: int | str = "all"

    # New capabilities
    backend: str = "sim"                   # "sim" | "k8s"
    enforce_capacity: bool = False         # reference never checks capacity
    capacity_frac: float = 1.0             # packing budget as a fraction of capacity
    global_solver_iters: int = 9           # best-response sweeps per solve
    balance_weight: float = 0.0            # λ for load-balance term in global solver
    # Disruption pricing inside the global solve: comm-weight units per
    # restarted pod (0 = moves are free). The principled alternative to
    # global_moves_cap — the solver itself stops proposing moves that do
    # not pay for their restarts, so the move budget is emergent.
    move_cost: float = 0.0
    solver_restarts: int = 1               # best-of-N solves over the device mesh
    solver_tp: int = 1                     # node-axis sharding of each solve (devices per solve)
    # "dense" (default) | "sparse": pair-weight storage for global rounds.
    # sparse = the block-local form (memory O(S·Ū), breaks the ~46k dense
    # wall); composes with dp restarts, tp node-sharding, and both at once
    # (dp restarts OF tp-sharded sparse solves).
    solver_backend: str = "dense"
    # "service" (default): whole Deployments move as units (the reference
    # mechanism). "pod": every replica places independently (the expanded
    # sparse pod graph; global algorithm + sim backend — the k8s
    # Deployment mechanism cannot pin a single replica).
    placement_unit: str = "service"
    seed: int = 0

    # Scale (array capacities; 0 = size to the scenario)
    node_capacity: int = 0
    pod_capacity: int = 0

    # Live adapter
    namespace: str = "default"             # reference main.py:68
    delete_timeout_s: float = 180.0        # reference delete_replaced_pod.py:8
    delete_poll_interval_s: float = 1.5    # reference delete_replaced_pod.py:8

    # Resilience: every controller→backend call goes through the retry
    # boundary (utils.retry + bench.boundary); the breaker opens into safe
    # mode after this many CONSECUTIVE boundary failures (0 disables the
    # state machine — retries only), stays open `breaker_cooldown_rounds`
    # rounds (each a counted skip), then half-open probes its way closed.
    # `failure_budget_per_round` freezes a round's remaining MOVES once it
    # has burned that many failures (0 = unlimited).
    retry: RetryPolicy = field(default_factory=RetryPolicy)
    chaos: ChaosConfig = field(default_factory=ChaosConfig)
    max_consecutive_failures: int = 5
    breaker_cooldown_rounds: int = 2
    failure_budget_per_round: int = 0

    # Reconciliation & admission: snapshot admission guard + intent
    # ledger with rate-limited corrective moves — see ReconcileConfig.
    reconcile: ReconcileConfig = field(default_factory=ReconcileConfig)

    # Shadow mode: replay a recorded real-cluster trace, recommend
    # without applying, score against the trace's actual scheduler —
    # see ShadowConfig.
    shadow: ShadowConfig = field(default_factory=ShadowConfig)

    # Fleet mode: N tenants multiplexed over one device plane — see
    # FleetConfig. With tenants > 0 the `chaos` block above applies only
    # to the tenant indices in fleet.chaos_tenants.
    fleet: FleetConfig = field(default_factory=FleetConfig)

    # Elastic topologies: seeded churn events (service deploy/teardown
    # waves, replica autoscaling, node drain/add, spot preemption)
    # applied between rounds, absorbed by shape buckets — see
    # ElasticConfig.
    elastic: ElasticConfig = field(default_factory=ElasticConfig)

    # Forecast plane: the online forecaster behind the `proactive`
    # algorithm (lag window, ridge term, warm-up, skill degrade gate) —
    # see ForecastConfig.
    forecast: ForecastConfig = field(default_factory=ForecastConfig)

    # Control-loop execution: the software-pipelined round loop and
    # device-carry donation — see ControllerConfig.
    controller: ControllerConfig = field(default_factory=ControllerConfig)

    # Observability: the live ops plane (HTTP endpoint, decision
    # explainability, flight recorder, SLO watchdog) — see ObsConfig.
    obs: ObsConfig = field(default_factory=ObsConfig)
    # Performance ledger: append-only perf history + rolling-window
    # regression detection — see PerfConfig.
    perf: PerfConfig = field(default_factory=PerfConfig)
    # Serving plane: the request-grain placement service behind
    # POST /place (bounded batcher, per-request deadlines, stage-span
    # telemetry) — see ServingConfig.
    serving: ServingConfig = field(default_factory=ServingConfig)
    # SLO v2: error budgets + multi-window burn-rate alerting over the
    # in-process history plane — see SloConfig.
    slo: SloConfig = field(default_factory=SloConfig)

    def validate(self) -> "RescheduleConfig":
        valid = set(POLICIES) | {"global", "proactive"}
        if self.algorithm not in valid:
            raise ValueError(
                f"unknown algorithm {self.algorithm!r}; expected one of {sorted(valid)}"
            )
        if self.max_rounds < 0:
            raise ValueError("max_rounds must be >= 0")
        mpr = self.moves_per_round
        if not (mpr == "all" or (isinstance(mpr, int) and mpr >= 1)):
            raise ValueError(
                f"moves_per_round must be a positive int or 'all', got {mpr!r}"
            )
        gmc = self.global_moves_cap
        if not (gmc == "all" or (isinstance(gmc, int) and gmc >= 1)):
            raise ValueError(
                f"global_moves_cap must be a positive int or 'all', got {gmc!r}"
            )
        if self.solver_backend not in ("dense", "sparse"):
            raise ValueError(
                f"solver_backend must be 'dense' or 'sparse', got "
                f"{self.solver_backend!r}"
            )
        if self.placement_unit not in ("service", "pod"):
            raise ValueError(
                f"placement_unit must be 'service' or 'pod', got "
                f"{self.placement_unit!r}"
            )
        if self.placement_unit == "pod":
            if self.algorithm != "global":
                raise ValueError(
                    "placement_unit='pod' requires algorithm='global' "
                    "(the greedy policies score whole services)"
                )
            if isinstance(self.global_moves_cap, int):
                raise ValueError(
                    "placement_unit='pod' does not support global_moves_cap "
                    "(use move_cost — disruption pricing measures strictly "
                    "better than wave capping, RESULTS.md round 4)"
                )
        self.retry.validate()
        self.forecast.validate()
        if self.algorithm == "proactive":
            # proactive is the greedy machinery against the predicted
            # state — the global/pod solvers never consume the forecast
            # delta, so routing a proactive round through them would
            # silently decide reactively under a predictive label
            if self.moves_per_round == "all":
                raise ValueError(
                    "algorithm='proactive' requires integer "
                    "moves_per_round: 'all' routes the round through the "
                    "global solver, which does not consume the forecast"
                )
            if self.placement_unit != "service":
                raise ValueError(
                    "algorithm='proactive' requires placement_unit="
                    "'service' (the forecast-aware kernels are the greedy "
                    "deployment movers)"
                )
        self.elastic.validate()
        if self.elastic.profile != "none" and self.backend == "k8s":
            raise ValueError(
                "churn injection requires the hermetic sim backend: a live "
                "cluster churns itself (watch-driven snapshots are ROADMAP "
                "item 3)"
            )
        self.controller.validate()
        if self.controller.scan_block:
            # two tiers of incompatibility: configurations whose
            # DECISIONS are made outside the scan body (global/pod
            # solvers, the forecast plane, affinityOnly landings, a live
            # cluster, shadow replay) can never scan and are REJECTED
            # here; environmental planes (chaos, elastic churn,
            # checkpoints, load hooks) are legal and DRAIN per round at
            # runtime instead — visibly, via scan_drains_total{reason}
            # — because drain-heavy runs are a supported shape (the
            # chaos-drain soaks are test-pinned) and churn/checkpoints
            # can also arrive through run_controller arguments no
            # config validation can see
            if self.algorithm not in SCAN_POLICIES:
                raise ValueError(
                    f"controller scan_block requires a pinning greedy "
                    f"algorithm {sorted(SCAN_POLICIES)} (got "
                    f"{self.algorithm!r}: global/pod solvers and the "
                    f"forecast plane decide outside the scan body, and "
                    f"kubescheduling's affinityOnly landing belongs to "
                    f"the scheduler, not the twin)"
                )
            if self.moves_per_round != 1:
                raise ValueError(
                    "controller scan_block requires moves_per_round=1 "
                    "(the scan body is the reference-faithful "
                    "one-decision round)"
                )
            if self.backend != "sim":
                raise ValueError(
                    "controller scan_block requires the hermetic sim "
                    "backend: the device twin IS the simulator's "
                    "steady-state update, and a live cluster has no twin"
                )
            if self.shadow.enabled:
                raise ValueError(
                    "controller scan_block cannot compose with shadow "
                    "mode: replayed trace windows drive every round, so "
                    "there is no steady state for the twin to scan"
                )
        self.obs.validate()
        self.perf.validate()
        self.serving.validate()
        self.slo.validate()
        if self.serving.enabled and self.algorithm not in POLICIES:
            raise ValueError(
                "the serving plane scores requests with the greedy "
                f"machinery: serving.enabled requires a greedy algorithm "
                f"{sorted(POLICIES)}, got {self.algorithm!r}"
            )
        self.reconcile.validate()
        self.shadow.validate()
        if self.shadow.enabled:
            # shadow is the solo greedy/global loop over replayed real
            # snapshots — the planes it cannot compose with must reject
            # loudly rather than silently score nonsense
            if self.fleet.tenants > 0:
                raise ValueError(
                    "shadow mode is a solo-loop plane: fleet multiplexing "
                    "has no per-tenant counterfactual twin yet"
                )
            if self.elastic.profile != "none":
                raise ValueError(
                    "shadow mode replays RECORDED churn: the synthetic "
                    "churn engine cannot compose with a trace-driven "
                    "cluster"
                )
            if self.chaos.profile != "none":
                raise ValueError(
                    "shadow mode cannot compose with chaos injection: "
                    "corrupting the replayed trace poisons the very "
                    "head-to-head scores the plane exists to produce "
                    "(and stale re-serves break the replay backend's "
                    "fresh-snapshot contract)"
                )
            if self.placement_unit != "service":
                raise ValueError(
                    "shadow scoring re-homes whole services "
                    "(applied_moves is service-granular); "
                    "placement_unit='pod' is not supported in shadow mode"
                )
            if not self.reconcile.admission:
                raise ValueError(
                    "shadow mode requires the admission guard: replayed "
                    "real-world snapshots are exactly the untrusted "
                    "input it quarantines (and the shadow plane reuses "
                    "its pulled host arrays)"
                )
        self.fleet.validate()
        if self.fleet.tenants > 0:
            # fleet v2: three batched decision planes — the greedy kernel,
            # the proactive (forecast-steered) kernel with per-tenant RLS
            # state, and the global solver (dense, swap phases and restart
            # fan-out included) — each one device program per round over a
            # leading tenant axis. Combinations whose decisions are made
            # host-side per tenant (pod graphs, wave-cap selection) or
            # whose compiled signature forks per tenant (sparse block
            # structure) still reject, loudly, below.
            if self.placement_unit != "service":
                raise ValueError(
                    "fleet mode requires placement_unit='service': the "
                    "expanded per-pod graph is built host-side per tenant, "
                    "which the batched device plane cannot amortize"
                )
            greedy_family = (
                self.algorithm in POLICIES or self.algorithm == "proactive"
            ) and self.moves_per_round == 1
            global_family = (
                self.algorithm == "global" or self.moves_per_round == "all"
            )
            if not (greedy_family or global_family):
                raise ValueError(
                    "fleet mode batches whole decision planes: it requires "
                    "a greedy/proactive algorithm with moves_per_round=1, "
                    "or a global round (algorithm='global' / "
                    "moves_per_round='all') "
                    f"(got algorithm={self.algorithm!r}, "
                    f"moves_per_round={self.moves_per_round!r})"
                )
            if global_family:
                if self.solver_backend == "sparse":
                    raise ValueError(
                        "fleet mode cannot batch solver_backend='sparse': "
                        "the sparse form's degree-sorted block layout is "
                        "static per-tenant metadata, so every tenant would "
                        "fork the compiled signature the batching exists "
                        "to share (the dense solver batches; sparse stays "
                        "solo)"
                    )
                if self.global_moves_cap != "all":
                    raise ValueError(
                        "fleet mode does not support an integer "
                        "global_moves_cap: wave-cap selection is a "
                        "sequential host-side re-scoring loop per tenant, "
                        "which defeats the batched dispatch (use move_cost "
                        "— disruption pricing is the in-solver lever and "
                        "batches for free)"
                    )
                if self.solver_tp != 1:
                    raise ValueError(
                        "fleet mode does not compose with solver_tp yet: "
                        "the mesh's dp axis is the tenant axis "
                        "(fleet.plane='dp'); node-axis sharding of each "
                        "tenant's solve would need a dp×tp fleet mesh"
                    )
        if self.max_consecutive_failures < 0:
            raise ValueError("max_consecutive_failures must be >= 0")
        if self.breaker_cooldown_rounds < 1:
            raise ValueError("breaker_cooldown_rounds must be >= 1")
        if self.failure_budget_per_round < 0:
            raise ValueError("failure_budget_per_round must be >= 0")
        return self

    @classmethod
    def from_toml(cls, path: str | Path) -> "RescheduleConfig":
        data = tomllib.loads(Path(path).read_text())
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(data) - known
        if unknown:
            raise ValueError(f"unknown config keys: {sorted(unknown)}")
        # nested blocks arrive as TOML tables — rehydrate the dataclasses
        if isinstance(data.get("retry"), dict):
            data["retry"] = RetryPolicy(**data["retry"])
        if isinstance(data.get("chaos"), dict):
            data["chaos"] = ChaosConfig(**data["chaos"])
        if isinstance(data.get("reconcile"), dict):
            data["reconcile"] = ReconcileConfig(**data["reconcile"])
        if isinstance(data.get("shadow"), dict):
            data["shadow"] = ShadowConfig(**data["shadow"])
        if isinstance(data.get("fleet"), dict):
            fl = dict(data["fleet"])
            if isinstance(fl.get("chaos_tenants"), list):
                fl["chaos_tenants"] = tuple(fl["chaos_tenants"])
            data["fleet"] = FleetConfig(**fl)
        if isinstance(data.get("elastic"), dict):
            el = dict(data["elastic"])
            if isinstance(el.get("tenants"), list):
                el["tenants"] = tuple(el["tenants"])
            data["elastic"] = ElasticConfig(**el)
        if isinstance(data.get("forecast"), dict):
            data["forecast"] = ForecastConfig(**data["forecast"])
        if isinstance(data.get("controller"), dict):
            data["controller"] = ControllerConfig(**data["controller"])
        if isinstance(data.get("obs"), dict):
            data["obs"] = ObsConfig(**data["obs"])
        if isinstance(data.get("perf"), dict):
            data["perf"] = PerfConfig(**data["perf"])
        if isinstance(data.get("serving"), dict):
            data["serving"] = ServingConfig(**data["serving"])
        if isinstance(data.get("slo"), dict):
            data["slo"] = SloConfig(**data["slo"])
        return cls(**data).validate()
