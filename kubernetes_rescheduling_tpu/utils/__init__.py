"""Cross-cutting utilities: structured logging, retry, profiling, checkpointing.

The reference logs with bare ``print`` (SURVEY.md §5.5), has no profiler, and
persists nothing but append-only CSVs (§5.4) — a crashed experiment restarts
from round 1. Here: JSONL structured logs, a shared boundary retry policy,
per-round decision-latency histograms + a ``jax.profiler`` wrapper, and
array-native checkpoint/resume.

``checkpoint`` imports ``jax.numpy`` at module load, so its names are
resolved lazily (PEP 562): ``utils`` itself adds no jax dependency for
consumers that only want ``logging``/``retry`` (``backends/k8s.py``,
``config.py``). Note this is module-level hygiene only — the top-level
package ``__init__`` currently imports jax anyway.
"""

from kubernetes_rescheduling_tpu.utils.logging import StructuredLogger, get_logger
from kubernetes_rescheduling_tpu.utils.retry import (
    RetryPolicy,
    call_with_retry,
    is_transient,
)
from kubernetes_rescheduling_tpu.utils.profiling import (
    LatencyHistogram,
    Timer,
    trace_to,
)

_LAZY = {
    "load_state": "checkpoint",
    "save_state": "checkpoint",
    "CheckpointManager": "checkpoint",
}


def __getattr__(name: str):
    if name in _LAZY:
        import importlib

        mod = importlib.import_module(
            f"kubernetes_rescheduling_tpu.utils.{_LAZY[name]}"
        )
        return getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "StructuredLogger",
    "get_logger",
    "RetryPolicy",
    "call_with_retry",
    "is_transient",
    "LatencyHistogram",
    "Timer",
    "trace_to",
    "load_state",
    "save_state",
    "CheckpointManager",
]
