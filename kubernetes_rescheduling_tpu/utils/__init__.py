"""Cross-cutting utilities: structured logging, profiling, checkpointing.

The reference logs with bare ``print`` (SURVEY.md §5.5), has no profiler, and
persists nothing but append-only CSVs (§5.4) — a crashed experiment restarts
from round 1. Here: JSONL structured logs, per-round decision-latency
histograms + a ``jax.profiler`` wrapper, and array-native checkpoint/resume.
"""

from kubernetes_rescheduling_tpu.utils.logging import StructuredLogger, get_logger
from kubernetes_rescheduling_tpu.utils.profiling import (
    LatencyHistogram,
    Timer,
    trace_to,
)
from kubernetes_rescheduling_tpu.utils.checkpoint import (
    load_state,
    save_state,
    CheckpointManager,
)

__all__ = [
    "StructuredLogger",
    "get_logger",
    "LatencyHistogram",
    "Timer",
    "trace_to",
    "load_state",
    "save_state",
    "CheckpointManager",
]
