"""Profiling: wall-clock timers, decision-latency histograms, device traces.

The north-star metric is rescheduling decisions/sec (BASELINE.md); the
reference measures only whole-run wall time (main.py:126-135). Here every
decision gets a latency sample and the distribution is inspectable; for
device-level analysis ``trace_to`` wraps ``jax.profiler.trace`` so a block
can be profiled under TensorBoard.
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field

import numpy as np


@dataclass
class Timer:
    """``with Timer() as t: ...; t.elapsed_s``"""

    elapsed_s: float = 0.0

    def __enter__(self) -> "Timer":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self.elapsed_s = time.perf_counter() - self._t0


@dataclass
class LatencyHistogram:
    """Streaming latency stats for decision rounds."""

    samples_s: list[float] = field(default_factory=list)

    def add(self, seconds: float) -> None:
        self.samples_s.append(seconds)

    def summary(self) -> dict[str, float]:
        if not self.samples_s:
            return {"count": 0}
        a = np.asarray(self.samples_s)
        return {
            "count": int(a.size),
            "mean_ms": float(a.mean() * 1e3),
            "p50_ms": float(np.percentile(a, 50) * 1e3),
            "p90_ms": float(np.percentile(a, 90) * 1e3),
            "p99_ms": float(np.percentile(a, 99) * 1e3),
            "max_ms": float(a.max() * 1e3),
            "decisions_per_sec": float(1.0 / a.mean()),
        }


@contextlib.contextmanager
def trace_to(log_dir: str | None):
    """``jax.profiler.trace`` when a directory is given, no-op otherwise."""
    if log_dir is None:
        yield
        return
    import jax

    with jax.profiler.trace(log_dir):
        yield
