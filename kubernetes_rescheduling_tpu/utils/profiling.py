"""Profiling: wall-clock timers, decision-latency histograms, device traces.

The north-star metric is rescheduling decisions/sec (BASELINE.md); the
reference measures only whole-run wall time (main.py:126-135). Here every
decision gets a latency sample and the distribution is inspectable; for
device-level analysis ``trace_to`` wraps ``jax.profiler.trace`` so a block
can be profiled under TensorBoard.
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass

from kubernetes_rescheduling_tpu.telemetry.registry import Histogram


@dataclass
class Timer:
    """``with Timer() as t: ...; t.elapsed_s``"""

    elapsed_s: float = 0.0

    def __enter__(self) -> "Timer":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self.elapsed_s = time.perf_counter() - self._t0


class LatencyHistogram(Histogram):
    """Streaming latency stats for decision rounds.

    Now a fixed-bucket streaming histogram (``telemetry.registry.
    Histogram``) instead of an unbounded sample list: memory is
    O(buckets) however long the run, count/mean/max stay exact, and the
    percentiles are bucket-interpolated estimates (error bounded by the
    bucket width). ``add``/``summary`` keep the historical API."""

    def __init__(self) -> None:
        super().__init__("latency_seconds")

    def add(self, seconds: float) -> None:
        self.observe(seconds)


@contextlib.contextmanager
def trace_to(log_dir: str | None):
    """``jax.profiler.trace`` when a directory is given, no-op otherwise."""
    if log_dir is None:
        yield
        return
    import jax

    with jax.profiler.trace(log_dir):
        yield
