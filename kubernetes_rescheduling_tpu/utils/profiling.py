"""Profiling: wall-clock timers and decision-latency histograms.

The north-star metric is rescheduling decisions/sec (BASELINE.md); the
reference measures only whole-run wall time (main.py:126-135). Here every
decision gets a latency sample and the distribution is inspectable. The
device-profiler integration (``trace_to``) lives in
``telemetry.spans`` now; the re-export below is a deprecation shim.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

from kubernetes_rescheduling_tpu.telemetry.registry import Histogram

# Deprecated re-export: trace_to moved to telemetry.spans (the module
# that already owned the rest of the profiler integration). Import it
# from there; this name stays ONLY so existing call sites keep working,
# and it is pinned to be the SAME object (tests enforce identity).
from kubernetes_rescheduling_tpu.telemetry.spans import trace_to  # noqa: F401


@dataclass
class Timer:
    """``with Timer() as t: ...; t.elapsed_s``"""

    elapsed_s: float = 0.0

    def __enter__(self) -> "Timer":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self.elapsed_s = time.perf_counter() - self._t0


class LatencyHistogram(Histogram):
    """Streaming latency stats for decision rounds.

    Now a fixed-bucket streaming histogram (``telemetry.registry.
    Histogram``) instead of an unbounded sample list: memory is
    O(buckets) however long the run, count/mean/max stay exact, and the
    percentiles are bucket-interpolated estimates (error bounded by the
    bucket width). ``add``/``summary`` keep the historical API."""

    def __init__(self) -> None:
        super().__init__("latency_seconds")

    def add(self, seconds: float) -> None:
        self.observe(seconds)
