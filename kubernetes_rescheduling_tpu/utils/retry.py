"""Retry policy for cluster-boundary calls: exponential backoff + jitter.

The reference's control loop treats every boundary failure the same way —
a failed delete "skips the round" (reference delete_replaced_pod.py:178-180)
— and our port inherited that. This module gives the boundary one shared
retry discipline instead: bounded attempts, exponential backoff with
deterministic seeded jitter, a per-call wall-clock deadline, and an
injectable sleeper (matching the ``delete_timeout_s`` poll pattern in
``backends/k8s.py``: a fake/sim sleeper makes retried paths hermetic and
instant while a live cluster really waits).

No jax usage anywhere in this module (the telemetry registry's
convention): the never-traced k8s adapter routes its API calls through
:func:`call_with_retry`, and this module adds no device dependency of
its own. (The PACKAGE ``__init__`` currently imports jax regardless —
the contract here is module-level hygiene, not process-level
jax-freeness.)

Telemetry (through the jax-free registry):

- ``boundary_retries_total{call=...}``  — backoff sleeps performed;
- ``boundary_failures_total{call=...}`` — calls that exhausted their
  attempts or deadline.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass
from typing import Any, Callable

from kubernetes_rescheduling_tpu.telemetry.registry import (
    MetricsRegistry,
    get_registry,
)


# one seeded stream for default jitter: see call_with_retry
_default_jitter_rng = random.Random(0)


@dataclass(frozen=True)
class RetryPolicy:
    """How a boundary call retries.

    ``max_attempts=1`` means no retries (the call runs once); backoff for
    attempt ``k`` (1-based) is ``base_delay_s * multiplier**(k-1)`` capped
    at ``max_delay_s``, scaled by a seeded jitter factor in
    ``[1-jitter_frac, 1+jitter_frac]``. ``deadline_s`` bounds the whole
    call wall-clock: no retry starts if the budget (including its own
    backoff) would be exceeded. ``retry_none=True`` additionally treats a
    ``None`` return as a transient failure (the Backend protocol's
    "move failed, skip the round" signal).
    """

    max_attempts: int = 3
    base_delay_s: float = 0.1
    max_delay_s: float = 10.0
    multiplier: float = 2.0
    jitter_frac: float = 0.1
    deadline_s: float | None = 60.0
    retry_none: bool = False

    def validate(self) -> "RetryPolicy":
        if self.max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if self.base_delay_s < 0 or self.max_delay_s < 0:
            raise ValueError("delays must be >= 0")
        if self.multiplier < 1.0:
            raise ValueError("multiplier must be >= 1")
        if not (0.0 <= self.jitter_frac < 1.0):
            raise ValueError("jitter_frac must be in [0, 1)")
        if self.deadline_s is not None and self.deadline_s <= 0:
            raise ValueError("deadline_s must be positive (or None)")
        return self

    def backoff_s(self, attempt: int, rng: random.Random) -> float:
        """Sleep before retry number ``attempt`` (1-based)."""
        delay = min(
            self.base_delay_s * self.multiplier ** (attempt - 1),
            self.max_delay_s,
        )
        if self.jitter_frac > 0:
            delay *= 1.0 + rng.uniform(-self.jitter_frac, self.jitter_frac)
        return max(delay, 0.0)


# API statuses worth another attempt (throttling / server-side); a
# definitive answer (404, 403, 422, …) never is.
TRANSIENT_STATUSES: tuple[int, ...] = (429, 500, 502, 503, 504)

# OSError subclasses that are definitive local answers, not transport
# blips — a missing kubeconfig or unreadable CA bundle must fail fast
# with the actionable error, never burn a retry budget.
_NON_TRANSIENT_OS: tuple[type[BaseException], ...] = (
    FileNotFoundError,
    PermissionError,
    IsADirectoryError,
    NotADirectoryError,
)


def is_transient(e: BaseException) -> bool:
    """The shared transient-failure predicate: transport-level errors
    (``OSError`` covers ``ConnectionError``/``TimeoutError`` too, minus
    the definitive local subclasses above), or an API exception carrying
    a throttling/server-side ``status`` (the kubernetes client's
    ``ApiException`` shape). One definition, used by both the controller
    boundary (``bench/boundary.py``) and the k8s adapter — they must
    never disagree on what retries."""
    if isinstance(e, _NON_TRANSIENT_OS):
        return False
    return isinstance(e, OSError) or (
        getattr(e, "status", None) in TRANSIENT_STATUSES
    )


def call_with_retry(
    fn: Callable[[], Any],
    *,
    policy: RetryPolicy,
    label: str = "call",
    retryable: Callable[[BaseException], bool] | None = None,
    sleeper: Callable[[float], None] = time.sleep,
    clock: Callable[[], float] = time.monotonic,
    rng: random.Random | None = None,
    registry: MetricsRegistry | None = None,
    on_retry: Callable[[int, BaseException | None], None] | None = None,
) -> Any:
    """Run ``fn()`` under ``policy``.

    ``retryable(exc)`` decides whether an exception is transient (default:
    every ``Exception``); a non-retryable exception re-raises immediately.
    On exhaustion the LAST exception re-raises (its type intact — callers
    keep matching on it); when the policy retried only ``None`` returns,
    ``None`` comes back after the final attempt. ``sleeper`` receives each
    backoff (inject the sim clock or a no-op for hermetic tests), ``rng``
    drives the jitter (default: seeded per call for determinism).
    """
    policy = policy.validate()
    reg = registry if registry is not None else get_registry()
    # default jitter draws from ONE seeded module-level stream: sequential
    # calls in a process desynchronize (the point of jitter) while a whole
    # run stays bit-reproducible (the repo's hermeticity contract); tests
    # wanting fixed delays inject their own rng or zero jitter_frac
    rng = rng if rng is not None else _default_jitter_rng
    t0 = clock()
    last_exc: BaseException | None = None
    for attempt in range(1, policy.max_attempts + 1):
        try:
            out = fn()
        except Exception as e:  # noqa: BLE001 — filtered by `retryable`
            if retryable is not None and not retryable(e):
                raise
            last_exc = e
            out = None
        else:
            if out is not None or not policy.retry_none:
                return out
            last_exc = None
        if attempt >= policy.max_attempts:
            break
        delay = policy.backoff_s(attempt, rng)
        if (
            policy.deadline_s is not None
            and clock() - t0 + delay > policy.deadline_s
        ):
            break  # the retry would overrun the call's wall budget
        reg.counter(
            "boundary_retries_total",
            "boundary-call retries (backoff sleeps performed)",
            labelnames=("call",),
        ).labels(call=label).inc()
        if on_retry is not None:
            on_retry(attempt, last_exc)
        sleeper(delay)
    reg.counter(
        "boundary_failures_total",
        "boundary calls that exhausted retries or deadline",
        labelnames=("call",),
    ).labels(call=label).inc()
    if last_exc is not None:
        raise last_exc
    return None  # retry_none path: every attempt returned None
