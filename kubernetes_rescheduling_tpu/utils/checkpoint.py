"""Checkpoint/resume for multi-round experiments.

The reference persists nothing but CSVs — a crashed run restarts from
round 1 (SURVEY.md §5.4). A ``ClusterState`` is a handful of flat arrays, so
a checkpoint is one ``.npz`` plus a JSON sidecar for the static name tuples;
``CheckpointManager`` keeps per-round checkpoints and resumes from the
latest one.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from pathlib import Path

import jax.numpy as jnp
import numpy as np

from kubernetes_rescheduling_tpu.core.state import ClusterState

_ARRAY_FIELDS = (
    "node_cpu_cap",
    "node_mem_cap",
    "node_base_cpu",
    "node_base_mem",
    "node_valid",
    "node_lex_rank",
    "pod_node",
    "pod_service",
    "pod_cpu",
    "pod_mem",
    "pod_valid",
)


def save_state(state: ClusterState, path: str | Path, extra: dict | None = None) -> None:
    """Write ``<path>.npz`` (arrays) + ``<path>.json`` (names, extra).

    Extensions are appended, not substituted: a checkpoint named
    ``ckpt.v2`` writes ``ckpt.v2.npz``, never colliding with ``ckpt``.
    """
    p = Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    # write-to-temp then os.replace, .json before .npz: latest() discovers
    # checkpoints by .npz, so a kill at any point leaves either no round_k
    # entry or a complete one — never a truncated file that poisons every
    # later resume. os.replace (not rename) is atomic AND overwrites, so a
    # round replayed after a crash-resume cleanly supersedes its torn
    # predecessor on every platform; each temp is fsynced before the
    # replace so the swap never publishes data the kernel hasn't flushed.
    tmp_npz = Path(f"{p}.tmp.npz")  # numpy insists on the .npz extension
    with open(tmp_npz, "wb") as f:
        np.savez_compressed(
            f,
            **{a: np.asarray(getattr(state, a)) for a in _ARRAY_FIELDS},
        )
        f.flush()
        os.fsync(f.fileno())
    meta = {
        "node_names": list(state.node_names),
        "pod_names": list(state.pod_names),
        "extra": extra or {},
    }
    tmp_json = Path(f"{p}.json.tmp")
    with open(tmp_json, "w") as f:
        f.write(json.dumps(meta, default=float))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp_json, f"{p}.json")
    os.replace(tmp_npz, f"{p}.npz")


def load_state(path: str | Path) -> tuple[ClusterState, dict]:
    """Inverse of :func:`save_state`; returns ``(state, extra)``."""
    p = Path(path)
    arrays = np.load(f"{p}.npz")
    meta = json.loads(Path(f"{p}.json").read_text())
    state = ClusterState(
        **{f: jnp.asarray(arrays[f]) for f in _ARRAY_FIELDS},
        node_names=tuple(meta["node_names"]),
        pod_names=tuple(meta["pod_names"]),
    )
    return state, meta.get("extra", {})


@dataclass
class CheckpointManager:
    """Per-round checkpoints with latest-resume."""

    directory: str | Path
    keep: int = 5

    def save(self, round_num: int, state: ClusterState, extra: dict | None = None) -> Path:
        """Crash-safe: temp-file + fsync + atomic ``os.replace`` (see
        :func:`save_state`) — a kill mid-save can never leave a torn
        latest checkpoint for resume to load."""
        d = Path(self.directory)
        d.mkdir(parents=True, exist_ok=True)
        path = d / f"round_{round_num:06d}"
        save_state(state, path, extra={"round": round_num, **(extra or {})})
        self._gc()
        return path

    def latest(self) -> tuple[int, ClusterState, dict] | None:
        """Most recent *loadable* checkpoint, or None (start from round 1).

        A checkpoint a previous crash left unreadable is skipped (falling
        back to the one before it) rather than poisoning every resume."""
        for r in reversed(self._rounds()):
            try:
                state, extra = load_state(Path(self.directory) / f"round_{r:06d}")
                return r, state, extra
            except Exception:
                continue
        return None

    def _rounds(self) -> list[int]:
        d = Path(self.directory)
        if not d.is_dir():
            return []
        return sorted(
            int(f.stem.split("_")[1])
            for f in d.glob("round_*.npz")
            if not f.stem.endswith(".tmp")  # half-written leftovers
        )

    def _gc(self) -> None:
        d = Path(self.directory)
        rounds = self._rounds()
        for r in rounds[: -self.keep] if self.keep > 0 else []:
            for suffix in (".npz", ".json"):
                (d / f"round_{r:06d}{suffix}").unlink(missing_ok=True)
        # a crash between savez and the renames leaves *.tmp.npz /
        # *.json.tmp (and possibly a .json with no matching .npz) that
        # _rounds() skips but would otherwise accumulate forever
        for tmp in (*d.glob("round_*.tmp.npz"), *d.glob("round_*.json.tmp")):
            tmp.unlink(missing_ok=True)
        live = {f"round_{r:06d}" for r in rounds}
        for meta in d.glob("round_*.json"):
            if meta.stem not in live:
                meta.unlink(missing_ok=True)
