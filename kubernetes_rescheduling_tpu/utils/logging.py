"""Structured logging (replaces the reference's ad-hoc prints,
e.g. main.py:54-115, rescheduling.py:65-68)."""

from __future__ import annotations

import collections
import json
import sys
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, IO

_LEVELS = {"debug": 10, "info": 20, "warn": 30, "error": 40}


@dataclass
class StructuredLogger:
    """JSONL event logger with optional human-readable echo.

    In-memory retention is a RING buffer of the newest ``max_records``
    events (a long-running controller logs one event per round forever;
    an unbounded list was a slow leak). The file/stream sinks still see
    every event — only the in-process ``records`` view is capped.

    Fleet mode shares ONE ring across tenants, and a plain ring is
    unfair: one chatty tenant (a chaos soak's fault storm) silently
    evicts every other tenant's events, making a quiet tenant
    indistinguishable from an evicted one. Two fixes, both bounded:

    - every eviction of a TENANT-tagged event is counted
      (``fleet_events_dropped_total{reason}`` in the metrics registry
      plus the in-process :attr:`dropped_by_tenant` tally), so silence
      and eviction are distinguishable;
    - ``max_records_per_tenant`` (0 = off; the fleet loop sets a fair
      share) caps any one tenant's in-ring events — a tenant at its cap
      evicts its OWN oldest event (reason ``tenant_cap``), never
      another tenant's.

    File/stream sinks are unaffected — fairness governs only the
    in-memory ring the live ``/events`` endpoint serves.
    """

    name: str = "krt"
    path: str | Path | None = None
    stream: IO | None = None
    level: str = "info"
    echo: bool = False
    max_records: int = 4096
    max_records_per_tenant: int = 0
    registry: Any = None  # metric sink for drop counts (default registry
                          # when None — resolved lazily, import stays light)

    # the ring is an OrderedDict keyed by a monotone sequence id, with a
    # per-tenant deque of live seq ids: both eviction paths (global ring
    # capacity, per-tenant fair share) find and unlink their victim in
    # O(1) — a chatty tenant's fault storm must not turn the hot logging
    # path into a linear ring scan per event
    _records: "collections.OrderedDict" = field(default=None, repr=False)  # type: ignore[assignment]
    _seq: int = field(default=0, repr=False)
    _tenant_seqs: dict = field(default=None, repr=False)  # type: ignore[assignment]
    dropped_by_tenant: collections.Counter = field(
        default=None, repr=False  # type: ignore[assignment]
    )
    _lock: threading.Lock = field(default=None, repr=False)  # type: ignore[assignment]

    # distinct tenants the drop tally remembers before halving to its
    # top counts — tenant churn must not grow the process-lifetime
    # cached logger without bound (the watchdog/ring discipline)
    _DROP_TALLY_CAP = 1024

    def __post_init__(self) -> None:
        self._records = collections.OrderedDict()
        self._tenant_seqs = {}
        self.dropped_by_tenant = collections.Counter()
        # the multi-step ring mutation must be atomic: pipelined fleet
        # mode logs from ThreadPoolExecutor workers (the old bare
        # deque.append was GIL-atomic; this bookkeeping is not)
        self._lock = threading.Lock()

    def _count_drop(self, tenant: str, reason: str) -> None:
        self.dropped_by_tenant[tenant] += 1
        if len(self.dropped_by_tenant) > self._DROP_TALLY_CAP:
            self.dropped_by_tenant = collections.Counter(
                dict(
                    self.dropped_by_tenant.most_common(
                        self._DROP_TALLY_CAP // 2
                    )
                )
            )
        reg = self.registry
        if reg is None:
            from kubernetes_rescheduling_tpu.telemetry.registry import (
                get_registry,
            )

            reg = get_registry()
        reg.counter(
            "fleet_events_dropped_total",
            "tenant-tagged events dropped from the shared in-memory "
            "event ring, by reason (ring_full = displaced at capacity; "
            "tenant_cap = the tenant hit its fair ring share and "
            "displaced its own oldest event) — tenant identity rides "
            "the logger's dropped_by_tenant tally, not a label key",
            labelnames=("reason",),
        ).labels(reason=reason).inc()

    def _remember(self, rec: dict) -> None:
        if self.max_records <= 0:
            # the historical deque(maxlen=0) contract: an in-memory
            # ring of zero keeps nothing (sinks still see every event)
            return
        tenant = rec.get("tenant")
        with self._lock:
            cap = self.max_records_per_tenant
            if tenant is not None and cap > 0:
                seqs = self._tenant_seqs.get(tenant)
                if seqs is not None and len(seqs) >= cap:
                    # fairness: a tenant at its ring share displaces its
                    # OWN oldest event, never another tenant's
                    self._records.pop(seqs.popleft(), None)
                    if not seqs:
                        del self._tenant_seqs[tenant]
                    self._count_drop(tenant, "tenant_cap")
            if len(self._records) >= self.max_records:
                old_seq, evicted = self._records.popitem(last=False)
                ev_tenant = evicted.get("tenant")
                if ev_tenant is not None:
                    seqs = self._tenant_seqs.get(ev_tenant)
                    # seq ids are globally monotone, so the ring's
                    # oldest entry is also its tenant's oldest live seq
                    if seqs and seqs[0] == old_seq:
                        seqs.popleft()
                        if not seqs:  # churn-proof: no residue deques
                            del self._tenant_seqs[ev_tenant]
                    self._count_drop(ev_tenant, "ring_full")
            self._seq += 1
            self._records[self._seq] = rec
            if tenant is not None:
                self._tenant_seqs.setdefault(
                    tenant, collections.deque()
                ).append(self._seq)

    def log(self, level: str, event: str, **fields: Any) -> None:
        if _LEVELS.get(level, 20) < _LEVELS.get(self.level, 20):
            return
        rec = {
            "ts": time.time(),
            "level": level,
            "logger": self.name,
            "event": event,
            **fields,
        }
        self._remember(rec)
        line = json.dumps(rec, default=float)
        if self.path is not None:
            p = Path(self.path)
            p.parent.mkdir(parents=True, exist_ok=True)
            with p.open("a") as f:
                f.write(line + "\n")
        out = self.stream or (sys.stderr if self.echo else None)
        if out is not None:
            out.write(line + "\n")

    def debug(self, event: str, **fields: Any) -> None:
        self.log("debug", event, **fields)

    def info(self, event: str, **fields: Any) -> None:
        self.log("info", event, **fields)

    def warn(self, event: str, **fields: Any) -> None:
        self.log("warn", event, **fields)

    def error(self, event: str, **fields: Any) -> None:
        self.log("error", event, **fields)

    @property
    def records(self) -> list[dict]:
        with self._lock:
            return list(self._records.values())


_loggers: dict[str, StructuredLogger] = {}


def get_logger(name: str = "krt", **kwargs: Any) -> StructuredLogger:
    if name not in _loggers:
        _loggers[name] = StructuredLogger(name=name, **kwargs)
    return _loggers[name]
