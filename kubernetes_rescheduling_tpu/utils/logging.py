"""Structured logging (replaces the reference's ad-hoc prints,
e.g. main.py:54-115, rescheduling.py:65-68)."""

from __future__ import annotations

import collections
import json
import sys
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, IO

_LEVELS = {"debug": 10, "info": 20, "warn": 30, "error": 40}


@dataclass
class StructuredLogger:
    """JSONL event logger with optional human-readable echo.

    In-memory retention is a RING buffer of the newest ``max_records``
    events (a long-running controller logs one event per round forever;
    an unbounded list was a slow leak). The file/stream sinks still see
    every event — only the in-process ``records`` view is capped.
    """

    name: str = "krt"
    path: str | Path | None = None
    stream: IO | None = None
    level: str = "info"
    echo: bool = False
    max_records: int = 4096

    _records: collections.deque = field(default=None, repr=False)  # type: ignore[assignment]

    def __post_init__(self) -> None:
        self._records = collections.deque(maxlen=self.max_records)

    def log(self, level: str, event: str, **fields: Any) -> None:
        if _LEVELS.get(level, 20) < _LEVELS.get(self.level, 20):
            return
        rec = {
            "ts": time.time(),
            "level": level,
            "logger": self.name,
            "event": event,
            **fields,
        }
        self._records.append(rec)
        line = json.dumps(rec, default=float)
        if self.path is not None:
            p = Path(self.path)
            p.parent.mkdir(parents=True, exist_ok=True)
            with p.open("a") as f:
                f.write(line + "\n")
        out = self.stream or (sys.stderr if self.echo else None)
        if out is not None:
            out.write(line + "\n")

    def debug(self, event: str, **fields: Any) -> None:
        self.log("debug", event, **fields)

    def info(self, event: str, **fields: Any) -> None:
        self.log("info", event, **fields)

    def warn(self, event: str, **fields: Any) -> None:
        self.log("warn", event, **fields)

    def error(self, event: str, **fields: Any) -> None:
        self.log("error", event, **fields)

    @property
    def records(self) -> list[dict]:
        return list(self._records)


_loggers: dict[str, StructuredLogger] = {}


def get_logger(name: str = "krt", **kwargs: Any) -> StructuredLogger:
    if name not in _loggers:
        _loggers[name] = StructuredLogger(name=name, **kwargs)
    return _loggers[name]
