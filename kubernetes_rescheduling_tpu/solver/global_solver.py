"""Batched global assignment solver — the north-star capability.

The reference moves ONE deployment per round, chosen greedily
(delete_replaced_pod.py:154 + rescheduling.py:174-218). This solver instead
optimizes the placement of EVERY service at once:

    minimize  0.5 · Σ_{i,j} W[i,j] · [node(i) != node(j)]
              + λ · load-imbalance
    s.t.      per-node CPU and memory capacity

where ``W = adj · replicas_i · replicas_j`` is the pairwise communication
weight (cross-node pod pairs — the generalization of the reference's
cross-node-edges/2 objective, communicationcost.py:40-45). Services are the
decision unit because a Deployment's replicas always move together
(foreground cascade delete + pinned re-create, delete_replaced_pod.py:173,
rescheduling.py:216).

Method: **chunked synchronous best-response** — TPU-shaped local search.
Each sweep:
  1. neighbor-mass matmul ``M = W[chunk] @ X`` (C×S · S×N — MXU work),
  2. score each (service, node): kept-local comm weight − λ·projected load%,
  3. every service in the chunk proposes its argmax feasible node,
  4. within-chunk capacity races resolve by gain order (sort-free
     pairwise-priority admission — a [C, C] MXU matmul against the
     per-service move masses), improving moves commit, loads update
     incrementally,
then scan to the next chunk. On TPU the whole step runs as three Pallas
kernels (``ops.fused_admission``): the neighbor-mass matmul gathers W
row-blocks by id and regenerates one-hot occupancy tiles in VMEM (the
occupancy matrix never exists in HBM — ``assign`` is the only state between
chunks), then score→argmax and sort-free admission; elsewhere the
term-for-term XLA twin runs against a materialized occupancy matrix. The
best state seen across all sweeps is returned (ranked by a bf16 kept-mass
objective, re-evaluated exactly in f32 before adoption), so oscillation can
never make the answer worse than the initial placement. Everything is
static-shaped — service arrays are padded to a chunk multiple, so one
compilation serves every round at a given (S, N) capacity.

Round-3 measurement (10k services × 1k nodes, v5e-1, 9 sweeps): 28.9 ms
device-side per round at comm cost 12115 — vs round 2's 41.5 ms @ 12180
(8 sweeps, materialized X, f32 objective): both faster and better.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from flax import struct
from jax import lax

from kubernetes_rescheduling_tpu.core.state import ClusterState, CommGraph
from kubernetes_rescheduling_tpu.objectives.metrics import communication_cost, load_std
from kubernetes_rescheduling_tpu.telemetry.accounting import instrument_jit
from kubernetes_rescheduling_tpu.ops.fused_admission import (
    fused_neighbor_mass,
    fused_score_admission,
    reference_score_admission,
)
from kubernetes_rescheduling_tpu.solver.swap import (
    BIG_CAP,
    chunk_swap,
    scan_sweeps,
    swap_flags,
)


@struct.dataclass
class GlobalSolverConfig:
    # 9 sweeps: with the round-3 inline-mass path a sweep costs ~2.9 ms at
    # 10k×1k (v5e-1), so one more sweep than the historical 8 both lands
    # under the <100 ms target with margin (28.9 ms) AND beats the round-2
    # objective (12115 vs 12180 comm cost) — quality per millisecond went
    # up, so spend one extra sweep of it.
    sweeps: int = struct.field(pytree_node=False, default=9)
    # 0 = auto: ~S/10, clamped to [1, 1024], rounded up to a multiple of 256
    # past that size (see auto_chunk — the rounding is what lets the
    # inline-mass Pallas path tile). Small chunks make the sweep more
    # Gauss-Seidel (each chunk sees the previous chunks' moves), which local
    # search needs to converge; large chunks amortize per-step work and feed
    # the MXU. Measured at 10k×1k on v5e-1 (round 3): C=1024 → 28.9 ms
    # @ cost 12115 (9 sweeps); C=2048 → 43 ms @ 12300 (the [C, C] admission
    # race grows quadratically and gets more conservative) — ~1k is the
    # sweet spot.
    chunk_size: int = struct.field(pytree_node=False, default=0)
    balance_weight: float = struct.field(pytree_node=False, default=0.0)
    enforce_capacity: bool = struct.field(pytree_node=False, default=True)
    # Utilization headroom: feasibility uses capacity_frac·capacity, the
    # operator's packing budget (k8s clusters are not packed to 100%). On
    # dense meshes the comm objective genuinely prefers total colocation —
    # a finite budget is what forces the pile-up apart while comm cost is
    # minimized within it; queueing (response time) is convex in
    # utilization, so the budget is also the response-time lever.
    capacity_frac: float = struct.field(pytree_node=False, default=1.0)
    # Repulsion from over-budget nodes (active only with enforce_capacity —
    # the no-budget mode keeps the reference's capacity-blind semantics):
    # feasibility alone only vetoes moves that would newly exceed the
    # budget — a node already past it (e.g. the cordon pile-up) is every
    # resident's "current node" and so always feasible to stay on. This
    # term charges comm-weight units per % of load beyond the budget,
    # making over-budget residency score (and count in the objective)
    # worse than relocating, so saturated nodes drain.
    overload_weight: float = struct.field(pytree_node=False, default=10.0)
    # Annealing: Gumbel noise added to move scores, linearly decayed to zero
    # over the sweeps. Lets the search climb out of local optima of the
    # partition objective; the best-seen tracking below means noise can only
    # ever improve the returned solution. Units = comm-weight (pod pairs).
    noise_temp: float = struct.field(pytree_node=False, default=1.0)
    # Disruption cost INSIDE the objective: comm-weight units charged per
    # restarted pod (a service's move restarts all its replicas — the
    # reference's restart metric, release1.sh:101-102). The score charges
    # it at every node except the service's ROUND-START node, so staying
    # moved keeps paying and moving back recovers it — a relocation must
    # beat home by more than its restart bill, and the move budget is
    # emergent instead of a post-hoc wave cap. 0 (default) = moves are
    # free, the historical objective.
    move_cost: float = struct.field(pytree_node=False, default=0.0)
    # Pairwise-exchange phase (solver/swap.py): every swap_every-th sweep,
    # each chunk step follows single-move admission with capacity-feasible
    # mutual-best swaps — the escape hatch for capacity deadlocks, where
    # every improving single move is infeasible until another service
    # vacates (the measured 15-25% optimality-gap regime of round 4).
    # 3 = sweeps 2, 5, 8 under the default 9 (the polish sweeps, where
    # annealing noise has decayed and deadlocks have formed) — the extra
    # per-chunk cost (one more mass-sized contraction for the chunk-local
    # pair weights + [C, C] vector math) is paid on a third of the sweeps.
    # 1 = every sweep; 0 = off (the historical single-move-only search).
    swap_every: int = struct.field(pytree_node=False, default=3)
    # Swap-candidate subset size: each swap phase considers the top-k
    # services of the chunk by exchange desire (best kept mass anywhere −
    # kept mass at the current node). A chunk rarely holds more than a
    # handful of genuinely deadlocked services, and the [k, k]
    # gain/interaction math at 256 is ~15× cheaper than at the full
    # 1024-wide chunk (the phase would otherwise cost ~0.45 ms VPU per
    # chunk). k ≥ chunk width = consider everyone (all small instances).
    swap_k: int = struct.field(pytree_node=False, default=256)
    # dtype of the neighbor-mass matmul. bfloat16 feeds the MXU at full
    # rate with f32 accumulation (a modest win — the round is launch-bound,
    # see chunk_size above; measured 69→66 ms at 10k×1k). W weights and
    # one-hot X are small ints, so error is bounded to hub rows, mis-ranking
    # only near-tie candidates — and the f32 best-seen objective gating
    # means the result can never get worse than the input. Set "float32"
    # for bit-identical scoring.
    matmul_dtype: str = struct.field(pytree_node=False, default="bfloat16")
    # Fused Pallas epilogue (ops.fused_admission): score → argmax →
    # pairwise admission in two kernels instead of XLA's ~15-op chain.
    # "auto" = on for TPU backends at kernel-worthy sizes (C, N ≥ 128),
    # off elsewhere (parity-tested in interpret mode; annealing noise uses
    # the TPU core PRNG, a different stream than jax.random).
    fused_epilogue: str = struct.field(pytree_node=False, default="auto")
    # The dense pair weights are this solver's scale wall: the mm-dtype
    # matmul copy (the f32 W product itself is never materialized — exact
    # objectives contract the input adj directly) PLUS the f32 input
    # adjacency, both live per device and REPLICATED even under tp
    # node-sharding (tp shards nodes, not services). The budget counts
    # both (6 bytes/pair at bf16): 12 GiB ≈ the comfortable budget on a
    # 16 GB v5e chip — 0.59 GiB at 10k services, 2.3 GiB at 20k, ~46k at
    # the budget. Past it the solver raises a clear sizing error instead
    # of OOM-crashing mid-compile; raise it on larger-HBM parts.
    max_weight_bytes: int = struct.field(
        pytree_node=False, default=12 * 1024**3
    )


def _service_aggregates(state: ClusterState, num_services: int):
    """Per-service totals: replica count, CPU, memory; and a current node
    (the node of the service's first valid pod; -1 if absent)."""
    p = state.num_pods
    svc = jnp.where(state.pod_valid, state.pod_service, num_services)
    ones = jnp.where(state.pod_valid, 1.0, 0.0)
    replicas = jnp.zeros((num_services + 1,), jnp.float32).at[svc].add(ones)[:num_services]
    cpu = (
        jnp.zeros((num_services + 1,), jnp.float32)
        .at[svc]
        .add(jnp.where(state.pod_valid, state.pod_cpu, 0.0))[:num_services]
    )
    mem = (
        jnp.zeros((num_services + 1,), jnp.float32)
        .at[svc]
        .add(jnp.where(state.pod_valid, state.pod_mem, 0.0))[:num_services]
    )
    first = (
        jnp.full((num_services + 1,), p, jnp.int32)
        .at[svc]
        .min(jnp.where(state.pod_valid, jnp.arange(p), p).astype(jnp.int32))[:num_services]
    )
    has = first < p
    cur_node = jnp.where(has, state.pod_node[jnp.clip(first, 0, p - 1)], -1)
    return replicas, cpu, mem, cur_node, has


def _pad_to(x: jax.Array, size: int, fill=0):
    pad = size - x.shape[0]
    return jnp.pad(x, [(0, pad)] + [(0, 0)] * (x.ndim - 1), constant_values=fill)


COMPOSITION_BLOCK = 256


def sweep_composition(
    perm_key: jax.Array, SP: int, C: int, n_chunks: int, block: int = 1
):
    """Random per-sweep chunk composition: which services move together.

    Returns ``(chunk_ids [n_chunks, C], block_rows [n_chunks, C // B])``
    where B is the composition granularity. Callers request ``block`` > 1
    ONLY where a kernel constraint demands it: the inline-mass Pallas path
    gathers W row-blocks by id (scalar prefetch), which is what makes
    randomized composition free there — so it passes B=256 and accepts the
    coarser neighborhood structure (services in the same fixed 256-id
    block always co-chunk; objective parity measured at 10k×1k, round 3).
    The XLA/materialized fallback and the node-sharded solver have no such
    constraint and keep the historical full permutation (B=1,
    `jax.random.permutation(key, SP)` — same key stream), preserving full
    neighborhood diversity on the paths where it costs nothing.
    """
    B = block if block > 1 and C % block == 0 and SP % block == 0 else 1
    NB = SP // B
    bp = jax.random.permutation(perm_key, NB)
    if B == 1:
        return bp.reshape(n_chunks, C), bp.reshape(n_chunks, C)
    ids = bp[:, None] * B + jnp.arange(B, dtype=jnp.int32)[None, :]
    return ids.reshape(n_chunks, C), bp.reshape(n_chunks, C // B)


def pct_balance_terms(
    loads, cap, node_valid, balance_weight, overload_weight, xp=jnp
):
    """The objective's balance + over-budget terms — ONE definition.

    ``cap`` must already be ``capacity_frac``-scaled (the packing budget):
    ``balance_weight·std(pct-of-budget) + overload_weight·Σ relu(pct−100)``.
    ``xp`` selects the array namespace: the solver traces it with jnp; the
    controller's wave-cap ranking evaluates the SAME expression host-side
    with numpy (per-candidate device dispatches through the tunnel would
    cost more than the solve) — so a future objective edit cannot
    desynchronize the cap's gain ranking from what the solver optimizes.
    The node-sharded solver's psum'd form in parallel/sharded_solver.py
    mirrors this distributively (parity-tested)."""
    pct = xp.where(node_valid, loads / cap * 100.0, 0.0)
    n = xp.maximum(xp.sum(node_valid), 1)
    mean = xp.sum(pct) / n
    var = xp.sum(xp.where(node_valid, (pct - mean) ** 2, 0.0)) / n
    over = xp.sum(xp.maximum(pct - 100.0, 0.0))
    return balance_weight * xp.sqrt(var) + overload_weight * over


def check_weight_budget(SP: int, config: "GlobalSolverConfig") -> None:
    """Fail with a SIZING error — not a mid-compile OOM — when the dense
    pair-weight residency exceeds ``config.max_weight_bytes``. Counts what
    is actually LIVE per device during a solve: the mm-dtype matmul copy
    AND the f32 input adjacency it is built from (both replicated under
    tp) — admitting only the copy would pass sizes that then OOM
    mid-compile, the exact failure this check exists to prevent."""
    mm_bytes = jnp.dtype(config.matmul_dtype).itemsize
    need = SP * SP * (mm_bytes + 4)
    if need > config.max_weight_bytes:
        raise ValueError(
            f"dense pair weights need {need / 2**30:.2f} GiB "
            f"({SP} padded services: {config.matmul_dtype} matmul copy + "
            f"f32 adjacency) — over "
            f"max_weight_bytes={config.max_weight_bytes / 2**30:.2f} GiB. "
            "The dense-W formulation is the documented scale wall (README "
            "scaling notes); tp node-sharding does NOT shard it. Raise "
            "max_weight_bytes on larger-HBM devices or reduce the service "
            "count."
        )


def build_pair_weights(adj, rv, *, SP: int, dtype):
    """The mm-dtype pair-weight matrix ``pad(adj·rv·rvᵀ)`` as ONE fused
    multiply+pad+convert (jitted): no f32 SP×SP product ever materializes
    — only the final SP²·itemsize write. Shared by both solvers."""
    return _build_pair_weights(adj, rv, SP=SP, dtype=jnp.dtype(dtype).name)


@partial(jax.jit, static_argnames=("SP", "dtype"))
def _build_pair_weights(adj, rv, *, SP, dtype):
    S = adj.shape[0]
    return jnp.pad(
        adj * rv[:, None] * rv[None, :], ((0, SP - S), (0, SP - S))
    ).astype(dtype)


def total_pair_weight(adj, rv):
    """ΣW as one fused pass over the input adjacency."""
    return jnp.einsum(
        "st,s,t->", adj, rv, rv, preferred_element_type=jnp.float32
    )


def exact_comm_cost(adj, rv, assign):
    """0.5·Σ adj·rv·rvᵀ over CUT pairs — a DIRECT sum (error ~ eps·cut),
    deliberately not the ``(ΣW − kept)/2`` subtraction form whose error
    scales with ulp(ΣW) and could understate a near-colocated result
    enough to flip the never-worse adopt gate. One definition for the
    single-chip and node-sharded exact objectives."""
    S = adj.shape[0]
    cut = (assign[:S, None] != assign[None, :S]).astype(jnp.float32)
    return 0.5 * jnp.einsum(
        "st,s,t,st->", adj, rv, rv, cut, preferred_element_type=jnp.float32
    )


def collapsed_placement(idx, node, counted, size: int, n):
    """Collapse detection over ``size`` groups of pods: returns
    ``(nmin, rv_eff, collapsed)`` where ``nmin`` is each group's lowest
    counted node (``n`` when empty), ``rv_eff`` its counted-pod count,
    and ``collapsed`` whether every nonempty group sits on ONE node.
    ONE definition shared by the dense (:func:`input_comm_cost`) and
    sparse (``sparse_solver.sparse_pod_comm_cost``) fast-path
    predicates — their cond routing must stay semantically identical
    to each twin's slow branch, so the masking lives here, never in
    one caller alone. ``counted`` must already exclude pods outside
    ``[0, n)`` and ``idx`` must be in ``[0, size)`` wherever counted."""
    idx_c = jnp.where(counted, idx, size)
    node_c = jnp.where(counted, node, n).astype(jnp.int32)
    nmin = jnp.full((size + 1,), n, jnp.int32).at[idx_c].min(node_c)[:size]
    nmax = (
        jnp.full((size + 1,), -1, jnp.int32)
        .at[idx_c]
        .max(jnp.where(counted, node_c, -1))[:size]
    )
    rv_eff = (
        jnp.zeros((size + 1,), jnp.float32)
        .at[idx_c]
        .add(jnp.where(counted, 1.0, 0.0))[:size]
    )
    return nmin, rv_eff, jnp.all((rv_eff == 0) | (nmin == nmax))


def comm_cost_collapse(state, graph):
    """The ``(nmin, rv_eff, collapsed)`` routing inputs of
    :func:`input_comm_cost`, exposed so the predicate itself is testable
    (the regression the ADVICE-round-5 fix pins: a split INVALID service
    must not defeat the collapsed fast path).

    Per-pod SERVICE validity joins the counted predicate: an invalid
    service contributes zero to BOTH branches (adj is masked on both
    axes / its rv factor is zeroed), so its pods must not be able to
    flip ``collapsed`` — one split invalid service would otherwise route
    every chained solve to the ~4 ms quadratic form."""
    num_s = graph.num_services
    n = state.num_nodes
    svc = jnp.where(state.pod_valid, state.pod_service, num_s)
    node = jnp.clip(jnp.where(state.pod_valid, state.pod_node, n), -1, n)
    svc_ok = (svc < num_s) & graph.service_valid[jnp.clip(svc, 0, num_s - 1)]
    counted = state.pod_valid & (node >= 0) & (node < n) & svc_ok
    return collapsed_placement(svc, node, counted, num_s, n)


def input_comm_cost(state, graph):
    """``objectives.metrics.communication_cost`` with a collapsed fast
    path (round 5): the occ@occᵀ quadratic form costs ~4 ms at 10k×1k
    (a 200-GFLOP f32 matmul), but it is only NEEDED when some service's
    replicas are split across nodes — every solver output colocates
    them, so chained production solves always present a collapsed
    placement. Three pod scatters detect that case
    (:func:`comm_cost_collapse` — ``service_node_counts``' pod masking
    plus per-pod service validity) and ``lax.cond``
    routes it to the direct cut-sum; split inputs keep the general
    quadratic form. The two branches compute the same mathematical
    quantity (cross pairs = rv_s·rv_t·[a_s≠a_t] when collapsed); f32
    summation order differs, so agreement is to ulps, not bitwise —
    same contract as the sparse twin's fast path."""
    nmin, rv_eff, collapsed = comm_cost_collapse(state, graph)

    def fast(_):
        # valid-service masking via the rv factors (communication_cost
        # masks adj on both axes; a zero rv on either side is equivalent)
        return exact_comm_cost(
            graph.adj, rv_eff * graph.service_valid, nmin
        )

    def slow(_):
        return communication_cost(state, graph)

    return lax.cond(collapsed, fast, slow, None)


def restart_bill_from_arrays(pod_mask, pod_node, tgt, move_cost):
    """Array-level core of :func:`pod_restart_bill` — also used inside
    shard_map bodies, where only the raw pod arrays are in scope."""
    return move_cost * jnp.sum(
        jnp.where(pod_mask & (pod_node != tgt), 1.0, 0.0)
    )


def pod_restart_bill(state, tgt, move_cost):
    """EXACT restart bill of adopting per-pod target nodes ``tgt``: every
    already-placed pod whose node would change (including split replicas
    being consolidated) pays ``move_cost``. Unplaced pods are creations,
    not restarts. ONE definition — the adopt gates of the single-chip and
    node-sharded solvers (dense and sparse) and the restart-selection
    ranking all price with this function, so the gate semantics cannot
    fork between them."""
    return restart_bill_from_arrays(
        state.pod_valid & (state.pod_node >= 0), state.pod_node, tgt, move_cost
    )


def auto_chunk(S: int, chunk_size: int = 0) -> int:
    """Resolve the chunk size: explicit, or ~S/10 in [1, 1024] (see
    GlobalSolverConfig.chunk_size). Auto sizes >= 256 round UP to a
    multiple of 256 so the padded service count tiles cleanly for the
    Pallas kernels (256 | C and 512 | SP) — e.g. 10k services: S/10 =
    1000 -> 1024, without which the inline-mass path would fall back to
    the materialized-X scheme. Shared by the single-chip and node-sharded
    solvers so their chunk composition (and hence decisions) stay equal.
    """
    if chunk_size:
        return chunk_size
    C = max(1, min(1024, S // 10))
    if C >= 256:
        C = min(1024, -(-C // 256) * 256)
    return C


def prepare_weights(
    state: ClusterState,
    graph: CommGraph,
    config: GlobalSolverConfig = GlobalSolverConfig(),
) -> jax.Array:
    """Build the mm-dtype pair-weight matrix ONCE for reuse across
    controller rounds via ``global_assign(..., w_mm=...)``.

    Valid as long as the service set and replica counts are unchanged —
    exactly the controller-round case, where only ``pod_node`` moves
    (a pod churn event invalidates it; rebuild then). Saves the ~2-3 ms
    per-round pad+multiply+convert of the SP² matrix (round-3 profile)."""
    S = graph.num_services
    C = min(auto_chunk(S, config.chunk_size), S)
    SP = -(-S // C) * C
    check_weight_budget(SP, config)  # clear sizing error, not a mid-compile OOM
    replicas, _, _, _, has_pods = _service_aggregates(state, S)
    svc_valid = _pad_to(graph.service_valid & has_pods, SP, False)
    rv = (_pad_to(replicas, SP) * svc_valid)[:S]
    return build_pair_weights(graph.adj, rv, SP=SP, dtype=config.matmul_dtype)


# instrument_jit instead of bare jax.jit: the controller's global rounds
# dispatch this kernel once per round, so the same 1-trace steady-state
# invariant (and the compiled-cost/HBM capture at first compile) applies
# to the batched solver as to the greedy decision kernel
@partial(instrument_jit, name="global_assign", static_argnames=("config",))
def global_assign(
    state: ClusterState,
    graph: CommGraph,
    key: jax.Array,
    config: GlobalSolverConfig = GlobalSolverConfig(),
    w_mm: jax.Array | None = None,
) -> tuple[ClusterState, dict[str, jax.Array]]:
    """Re-place every service; returns the new state and solve info.

    The initial point is the CURRENT placement, and only configurations that
    improve the true objective are ever adopted — the result is never worse
    than the input. ``w_mm`` optionally injects a prebuilt pair-weight
    matrix (:func:`prepare_weights`) to amortize its construction across
    rounds with an unchanged service set.
    """
    if not config.capacity_frac > 0:
        raise ValueError(
            f"capacity_frac must be > 0, got {config.capacity_frac}"
        )
    # over-budget repulsion only exists alongside budget enforcement
    ow = config.overload_weight if config.enforce_capacity else 0.0
    S = graph.num_services
    N = state.num_nodes
    C = min(auto_chunk(S, config.chunk_size), S)
    n_chunks = -(-S // C)
    SP = n_chunks * C  # padded service count
    check_weight_budget(SP, config)

    replicas, svc_cpu, svc_mem, cur_node, has_pods = _service_aggregates(state, S)
    svc_valid = graph.service_valid & has_pods

    # All service-level arrays padded to SP so chunk ids never alias.
    svc_valid = _pad_to(svc_valid, SP, False)
    svc_cpu = _pad_to(svc_cpu, SP)
    svc_mem = _pad_to(svc_mem, SP)
    replicas = _pad_to(replicas, SP)
    cur_node = _pad_to(cur_node, SP, -1)

    mm_dtype = jnp.dtype(config.matmul_dtype)
    # rv = replica count per service, zeroed for invalid services — the
    # pair weight is W[s,t] = adj[s,t]·rv[s]·rv[t]. The f32 W matrix is
    # NEVER materialized: the chunk matmuls read the persistent mm_dtype
    # copy below (built in one fused pad+multiply+convert pass), and the
    # exact objective contracts adj directly (einsum — one pass over the
    # input graph). Saves SP²·4 bytes of HBM (~400 MB at 10k services)
    # plus a full build pass per solve.
    rv = (replicas * svc_valid)[:S]
    W_mm = (
        w_mm
        if w_mm is not None
        else build_pair_weights(graph.adj, rv, SP=SP, dtype=mm_dtype)
    )

    cpu_cap = jnp.where(state.node_valid, state.node_cpu_cap, 0.0)
    mem_cap_raw = jnp.where(state.node_valid, state.node_mem_cap, 0.0)
    # capacity_frac shrinks the budget everywhere — feasibility checks and
    # the load-% denominators alike (inf·frac stays inf), so "load %" means
    # percent of the operator's packing budget throughout
    mem_cap = jnp.where(mem_cap_raw > 0, mem_cap_raw, jnp.inf) * config.capacity_frac
    cap = jnp.where(cpu_cap > 0, cpu_cap, 1.0) * config.capacity_frac
    base_cpu = state.node_base_cpu
    base_mem = state.node_base_mem

    assign0 = jnp.where(svc_valid, jnp.clip(cur_node, 0, N - 1), 0)
    # disruption pricing (config.move_cost): per-service restart bill =
    # cost × replica count, anchored at the ROUND-START placement
    mc_on = config.move_cost > 0
    pen_vec = config.move_cost * replicas * svc_valid if mc_on else None

    def move_penalty(assign):
        """Service-level restart bill vs the assign0 collapse — the cheap
        per-sweep RANKING form. It undercounts when the input has a
        service's replicas split across nodes (consolidating them to
        assign0 restarts pods this cannot see), so the adopt gate uses
        the exact pod-level bill below instead."""
        return config.move_cost * jnp.sum(
            jnp.where(svc_valid & (assign != assign0), replicas, 0.0)
        )

    def _pod_bill(assign):
        """The shared exact pod-level bill for this assignment (see
        module-level :func:`pod_restart_bill`)."""
        tgt = assign[jnp.clip(state.pod_service, 0, SP - 1)]
        return pod_restart_bill(state, tgt, config.move_cost)

    def loads(assign):
        oh = jax.nn.one_hot(assign, N, dtype=jnp.float32) * svc_valid[:, None]
        return base_cpu + svc_cpu @ oh, base_mem + svc_mem @ oh

    def _balance_terms(cpu_load):
        return pct_balance_terms(
            cpu_load, cap, state.node_valid, config.balance_weight, ow
        )

    w_total = total_pair_weight(graph.adj, rv)

    # EXACT objective (direct cut-sum over adj, fresh loads) is evaluated
    # once in the epilogue — see `best_comm`/`best_obj` there.

    # per-sweep best-seen selection uses the kept-mass form on the bf16 W
    # copy: comm = (ΣW − Σ W·[same])/2 reads 200 MB instead of 400+. The
    # bf16 entries are exact only for integer pair weights ≤ 256
    # (adj·rv_s·rv_t — replica-weighted hubs can exceed that) and the SP²
    # contraction accumulates in f32, so per-sweep best-seen ranking can
    # drift near ties; adoption stays safe because the returned objective
    # is re-evaluated with the exact f32 form after the scan, so the
    # never-worse gate cannot drift.

    def objective_fast(assign, cpu_load):
        same = assign[:, None] == assign[None, :]
        kept = jnp.einsum(
            "ij,ij->", W_mm, same.astype(mm_dtype),
            preferred_element_type=jnp.float32,
        )
        comm = 0.5 * (w_total - kept)
        obj = comm + _balance_terms(cpu_load)
        # with disruption pricing, per-sweep best-seen ranks the PENALIZED
        # objective — a sweep that wins on comm but spends more restarts
        # than the win is worth must not be selected
        return obj + move_penalty(assign) if mc_on else obj

    # fused Pallas epilogue: on for real TPU at kernel-worthy sizes;
    # "interpret" runs the same kernels through the interpreter (tests)
    fused_interpret = config.fused_epilogue == "interpret"
    use_fused = (
        config.fused_epilogue in ("on", "interpret")
        or (
            config.fused_epilogue == "auto"
            and jax.default_backend() == "tpu"
            and C >= 128
            and N >= 128
        )
    )
    # inline-mass variant of the fused path: the chunk matmul gathers W
    # row-blocks by id (scalar prefetch over the canonical W — no per-sweep
    # permute) and regenerates one-hot occupancy tiles from `assign` in VMEM
    # (ops.fused_neighbor_mass) — the [SP, N] occupancy matrix is never
    # built, carried, or scattered, and the chunk step's only state coupling
    # is the assign vector. Engages when the composition is block-granular
    # (256 | C and 256 | SP — every auto-chunked large instance); otherwise
    # the fused path keeps the materialized-X scheme below.
    mass_bj = next((b for b in (1024, 512, 256) if SP % b == 0), None)
    inline_mass = (
        use_fused
        and C % COMPOSITION_BLOCK == 0
        and SP % COMPOSITION_BLOCK == 0
        and mass_bj is not None
    )

    # pairwise-exchange phase (solver/swap.py): per chunk, after single-
    # move admission, on the sweeps flagged by config.swap_every — the
    # capacity-deadlock escape. Noise-free scores; protected end to end by
    # the exact-objective best-seen selection and the adopt gate.
    use_swaps = config.swap_every > 0 and C >= 2
    sw_flags = swap_flags(config.sweeps, config.swap_every)  # static numpy
    mem_cap_sw = jnp.where(jnp.isinf(mem_cap), BIG_CAP, mem_cap)

    def _swap_phase(ids, M, Wc, assign, cpu_load, mem_load, admitted):
        """Apply the chunk's swap phase to the post-singles state. ``M``
        is the chunk-start neighbor mass — rows of services the single
        phase just moved are stale, so those services sit out (they
        already improved; swaps exist for the stuck ones)."""
        cur = assign[ids]
        valid_c = svc_valid[ids]
        eligible = valid_c & ~admitted & state.node_valid[cur]
        c_cpu = svc_cpu[ids]
        c_mem = svc_mem[ids]
        new_node, swapped, n_sw = chunk_swap(
            M, Wc, cur, eligible, c_cpu, c_mem,
            cpu_load, mem_load, cap, mem_cap_sw,
            config.balance_weight, ow,
            pen_vec[ids] if mc_on else None,
            assign0[ids] if mc_on else None,
            min(config.swap_k, C),
            enforce_capacity=config.enforce_capacity,
        )
        d_c = jnp.where(swapped, c_cpu, 0.0)
        d_m = jnp.where(swapped, c_mem, 0.0)
        cpu_load = cpu_load.at[new_node].add(d_c).at[cur].add(-d_c)
        mem_load = mem_load.at[new_node].add(d_m).at[cur].add(-d_m)
        return assign.at[ids].set(new_node), cpu_load, mem_load, n_sw

    def _commit(inner, ids, valid_c, c_cpu, c_mem, cur, new_node, admitted):
        """Apply a chunk's admitted moves to the sweep state (XLA path only;
        the fused epilogue computes the equivalent occupancy rows and load
        deltas inside its admission kernel and commits inline — keep the
        two in lockstep when changing either)."""
        assign, X, cpu_load, mem_load = inner
        new_assign = assign.at[ids].set(new_node)
        # incremental occupancy update: only the chunk's rows change
        X = X.at[ids].set(
            jax.nn.one_hot(new_node, N, dtype=mm_dtype) * valid_c[:, None]
        )
        d_cpu = jnp.where(admitted, c_cpu, 0.0)
        d_mem = jnp.where(admitted, c_mem, 0.0)
        cpu_load = cpu_load.at[new_node].add(d_cpu).at[cur].add(-d_cpu)
        mem_load = mem_load.at[new_node].add(d_mem).at[cur].add(-d_mem)
        return (new_assign, X, cpu_load, mem_load), jnp.sum(admitted)

    def make_sweep(do_swap: bool):
        return partial(sweep, do_swap=do_swap)

    def sweep(carry, xs, do_swap: bool = False):
        sweep_key, temp = xs
        assign, best_assign, best_obj = carry
        # Random chunk composition per sweep: which services get to move
        # together varies, so repeated sweeps (and parallel restarts with
        # different keys) explore different neighborhoods of the search space.
        perm_key, noise_key = jax.random.split(sweep_key)
        # B=1: the materialized-X paths gather W rows by arbitrary id, so
        # the full permutation costs nothing and keeps neighborhood
        # diversity (block granularity is an inline-mass-kernel constraint)
        chunk_ids, _ = sweep_composition(perm_key, SP, C, n_chunks)
        chunk_keys = jax.random.split(noise_key, n_chunks)
        # one threefry draw covers every chunk's fused-kernel seed (the
        # per-chunk randint chatter measured ~15 µs/call on TPU); DCE'd
        # on the XLA lowering, which keeps gumbel on chunk_keys
        seeds = jax.random.randint(
            jax.random.fold_in(noise_key, 7), (n_chunks,), 0, 2**31 - 1
        )

        def chunk_step(inner, xs_c):
            ids, chunk_key, seed = xs_c
            assign, X, cpu_load, mem_load = inner
            valid_c = svc_valid[ids]

            # MXU matmul in mm_dtype (one-hot X is exact there), f32 accum
            Wr = W_mm[ids]
            M = jnp.matmul(
                Wr, X, preferred_element_type=jnp.float32
            )                                                 # f32[C, N] kept-local mass
            c_cpu = svc_cpu[ids]
            c_mem = svc_mem[ids]
            cur = assign[ids]

            # Score → argmax → sort-free pairwise admission. One shared
            # implementation, two lowerings: the fused Pallas epilogue
            # (ops.fused_admission, two kernels — the [C, N] score block
            # never leaves VMEM) on TPU, and its plain-XLA twin
            # reference_score_admission elsewhere. Admission semantics in
            # both: a proposal lands only if the target's free capacity
            # covers every higher-priority (greater gain, ties → lower
            # index) same-target arrival plus itself — deliberately
            # conservative: room freed by same-chunk departures is ignored,
            # so a feasible move may be deferred to a later sweep but an
            # infeasible one can never be admitted.
            if use_fused:
                new_node, admitted, x_rows, d_cpu, d_mem = fused_score_admission(
                    M, cur, c_cpu, c_mem, valid_c,
                    cpu_load, mem_load, cap, mem_cap, state.node_valid,
                    config.balance_weight, temp, seed,
                    overload_weight=ow,
                    home=assign0[ids] if mc_on else None,
                    move_pen=pen_vec[ids] if mc_on else None,
                    enforce_capacity=config.enforce_capacity,
                    # the TPU core PRNG has no interpret-mode lowering
                    use_noise=config.noise_temp > 0 and not fused_interpret,
                    interpret=fused_interpret,
                    x_dtype=mm_dtype,
                )
                inner = (
                    assign.at[ids].set(new_node),
                    X.at[ids].set(x_rows),
                    cpu_load + d_cpu,
                    mem_load + d_mem,
                )
            else:
                noise = (
                    temp * jax.random.gumbel(chunk_key, M.shape)
                    if config.noise_temp > 0
                    else None
                )
                new_node, admitted = reference_score_admission(
                    M, cur, c_cpu, c_mem, valid_c,
                    cpu_load, mem_load, cap, mem_cap, state.node_valid,
                    config.balance_weight, noise,
                    overload_weight=ow,
                    home=assign0[ids] if mc_on else None,
                    move_pen=pen_vec[ids] if mc_on else None,
                    enforce_capacity=config.enforce_capacity,
                )
                inner, _ = _commit(inner, ids, valid_c, c_cpu, c_mem, cur,
                                   new_node, admitted)
            n_moves = jnp.sum(admitted)
            if not (use_swaps and do_swap):  # STATIC branch (scan_sweeps)
                return inner, (n_moves, jnp.int32(0))

            assign2, X2, cpu2, mem2 = inner
            # chunk-local pair weights: W rows are already gathered for
            # the mass matmul; a [C, C] column take is fine on the
            # materialized-X lowerings (tests + CPU production)
            Wc = jnp.take(Wr, ids, axis=1).astype(jnp.float32)
            assign2, cpu2, mem2, n_sw = _swap_phase(
                ids, M, Wc, assign2, cpu2, mem2, admitted
            )
            X2 = X2.at[ids].set(
                jax.nn.one_hot(assign2[ids], N, dtype=mm_dtype)
                * valid_c[:, None]
            )
            return (assign2, X2, cpu2, mem2), (n_moves, n_sw)

        X0 = jax.nn.one_hot(assign, N, dtype=mm_dtype) * svc_valid[:, None]
        cpu_load, mem_load = loads(assign)
        (assign, _, _, _), (moves, sws) = lax.scan(
            chunk_step, (assign, X0, cpu_load, mem_load),
            (chunk_ids, chunk_keys, seeds),
            unroll=2,
        )
        obj = objective_fast(assign, loads(assign)[0])
        better = obj < best_obj
        best_assign = jnp.where(better, assign, best_assign)
        best_obj = jnp.where(better, obj, best_obj)
        return (assign, best_assign, best_obj), (jnp.sum(moves), jnp.sum(sws))

    def make_sweep_inline(do_swap: bool):
        return partial(sweep_inline, do_swap=do_swap)

    def sweep_inline(carry, xs, do_swap: bool = False):
        """The TPU inline-mass sweep: same decisions as `sweep` (same chunk
        composition / chunk keys / kernel math; M values are exact for
        integer weights), but the occupancy matrix never exists — the mass
        kernel gathers the chunk's W row-blocks by id (scalar prefetch,
        canonical W, no per-sweep permute) and regenerates occupancy tiles
        from `assign` in VMEM; per-node loads are carried through the chunk
        scan and refreshed from the assignment at each sweep boundary."""
        sweep_key, temp = xs
        assign, cpu_load, mem_load, best_assign, best_obj = carry
        perm_key, noise_key = jax.random.split(sweep_key)
        chunk_ids, block_rows = sweep_composition(
            perm_key, SP, C, n_chunks, block=COMPOSITION_BLOCK
        )
        chunk_keys = jax.random.split(noise_key, n_chunks)
        # one threefry draw for all chunks' kernel seeds (see `sweep`)
        seeds = jax.random.randint(
            jax.random.fold_in(noise_key, 7), (n_chunks,), 0, 2**31 - 1
        )

        def chunk_step(inner, xs_c):
            ids, blocks, chunk_key, seed = xs_c
            del chunk_key  # inline-mass is fused-only; gumbel unused
            assign, cpu_load, mem_load = inner
            valid_c = svc_valid[ids]
            c_cpu = svc_cpu[ids]
            c_mem = svc_mem[ids]
            cur = assign[ids]
            M = fused_neighbor_mass(
                W_mm, assign, svc_valid, blocks,
                num_nodes=N, block_b=COMPOSITION_BLOCK, block_j=mass_bj,
                interpret=fused_interpret,
            )
            new_node, admitted, d_cpu, d_mem = fused_score_admission(
                M, cur, c_cpu, c_mem, valid_c,
                cpu_load, mem_load, cap, mem_cap, state.node_valid,
                config.balance_weight, temp, seed,
                overload_weight=ow,
                home=assign0[ids] if mc_on else None,
                move_pen=pen_vec[ids] if mc_on else None,
                enforce_capacity=config.enforce_capacity,
                use_noise=config.noise_temp > 0 and not fused_interpret,
                interpret=fused_interpret,
                emit_x_rows=False,
            )
            inner = (
                assign.at[ids].set(new_node),
                cpu_load + d_cpu,
                mem_load + d_mem,
            )
            n_moves = jnp.sum(admitted)
            if not (use_swaps and do_swap):  # STATIC branch (scan_sweeps)
                return inner, (n_moves, jnp.int32(0))

            assign2, cpu2, mem2 = inner
            # chunk-local pair weights WITHOUT any contraction: the
            # inline composition is block-granular, so W[ids][:, ids]
            # is exactly KB×KB contiguous 256×256 tiles of the
            # canonical W — a ~2 MB slice assembly (a mass-kernel pass
            # with "node"=position computes the same values but re-reads
            # the chunk's full [C, SP] row blocks)
            kb = C // COMPOSITION_BLOCK
            Wc = jnp.concatenate(
                [
                    jnp.concatenate(
                        [
                            lax.dynamic_slice(
                                W_mm,
                                (
                                    blocks[i] * COMPOSITION_BLOCK,
                                    blocks[j] * COMPOSITION_BLOCK,
                                ),
                                (COMPOSITION_BLOCK, COMPOSITION_BLOCK),
                            )
                            for j in range(kb)
                        ],
                        axis=1,
                    )
                    for i in range(kb)
                ],
                axis=0,
            ).astype(jnp.float32)
            assign2, cpu2, mem2, n_sw = _swap_phase(
                ids, M, Wc, assign2, cpu2, mem2, admitted
            )
            return (assign2, cpu2, mem2), (n_moves, n_sw)

        (assign, _, _), (moves, sws) = lax.scan(
            chunk_step, (assign, cpu_load, mem_load),
            (chunk_ids, block_rows, chunk_keys, seeds),
            unroll=2,
        )
        # refresh the carried loads from the assignment each sweep (the
        # objective needs fresh loads anyway): incremental-delta f32 drift
        # is bounded to one sweep, matching the materialized-X and sharded
        # sweeps — carried drift could otherwise flip a feasibility check
        # on a node sitting exactly at its budget
        cpu_fresh, mem_fresh = loads(assign)
        obj = objective_fast(assign, cpu_fresh)
        better = obj < best_obj
        best_assign = jnp.where(better, assign, best_assign)
        best_obj = jnp.where(better, obj, best_obj)
        return (
            (assign, cpu_fresh, mem_fresh, best_assign, best_obj),
            (jnp.sum(moves), jnp.sum(sws)),
        )

    # True objective of the INPUT placement (which may have a service's
    # replicas split across nodes — not representable as a service-level
    # assignment). The solver's result only replaces the input when it beats
    # this, so "never worse than the input" holds even though assign0
    # (first-pod's-node collapse) may itself be worse than the input.
    # load_std measures % of raw capacity; the solver's objective measures
    # % of the packing budget — same units once divided by capacity_frac
    pct_true0 = jnp.where(
        state.node_valid, state.node_cpu_used() / cap * 100.0, 0.0
    )
    comm_true0 = input_comm_cost(state, graph)
    obj_true0 = (
        comm_true0
        + config.balance_weight * (load_std(state) / config.capacity_frac)
        + ow * jnp.sum(jnp.maximum(pct_true0 - 100.0, 0.0))
    )
    cpu0, mem0 = loads(assign0)
    obj0 = objective_fast(assign0, cpu0)
    keys = jax.random.split(key, config.sweeps)
    # linear decay to zero: the last sweeps polish greedily
    temps = config.noise_temp * (
        1.0 - jnp.arange(config.sweeps, dtype=jnp.float32) / max(config.sweeps - 1, 1)
    )
    if inline_mass:
        (_, _, _, best_assign, _), (moves_per_sweep, swaps_per_sweep) = (
            scan_sweeps(
                make_sweep_inline, (assign0, cpu0, mem0, assign0, obj0),
                keys, temps, sw_flags,
            )
        )
    else:
        (_, best_assign, _), (moves_per_sweep, swaps_per_sweep) = scan_sweeps(
            make_sweep, (assign0, assign0, obj0), keys, temps, sw_flags
        )
    # best-seen selection above ranks sweeps with the fast objective; the
    # adopted value is re-evaluated EXACTLY so the never-worse gate and the
    # reported objective carry no bf16 rounding (same term order as the
    # old `objective(best_assign)` — the comm term is kept separate so the
    # reported communication_cost can reuse it via the collapse identity)
    best_comm = exact_comm_cost(graph.adj, rv, best_assign)
    best_obj = best_comm + _balance_terms(loads(best_assign)[0])
    best_pen = _pod_bill(best_assign) if mc_on else jnp.float32(0.0)

    # scatter service assignment back to pods — but only when the solve
    # strictly beats the true input placement; otherwise keep the input
    # (prevents pointless cluster churn when no improvement was found).
    # Under disruption pricing the improvement must also cover the
    # restart bill (raw objective never-worse still follows a fortiori).
    improved = best_obj + best_pen < obj_true0
    new_pod_node = jnp.where(
        improved & state.pod_valid,
        best_assign[jnp.clip(state.pod_service, 0, SP - 1)],
        state.pod_node,
    )
    new_state = state.replace(pod_node=new_pod_node)
    info = {
        "objective_before": obj_true0,
        "objective_after": jnp.where(improved, best_obj, obj_true0),
        "improved": improved,
        "moves_per_sweep": moves_per_sweep,
        "swaps_per_sweep": swaps_per_sweep,
        "move_penalty": jnp.where(improved, best_pen, 0.0),
        # collapse identity: an adopted placement colocates every
        # service's replicas, so its pod-level cost equals the exact
        # service-level cut of best_assign; unadopted keeps the input's
        # already-computed true cost — the occ@occᵀ quadratic form
        # (~4 ms at 10k×1k) is never paid twice
        "communication_cost": jnp.where(improved, best_comm, comm_true0),
        "load_std": load_std(new_state),
        # which epilogue lowering ran (static): tests assert the inline
        # path actually engaged rather than silently falling back
        "inline_mass": jnp.asarray(inline_mass),
    }
    return new_state, info


# The DONATED twin of the solver jit (same traced body, same
# ``global_assign`` fn label so trace/cost accounting stays one series):
# the state carry is surrendered to XLA (``donate_argnums``), so the
# output placement — every leaf of which has exactly the input's shape —
# aliases the input buffers instead of holding both resident. This is
# the controller's steady-state dispatch under
# ``[controller] donate_carry``: the loop consumes a snapshot per round
# and replaces it with the post-move monitor, so the input is genuinely
# dead after the call. Callers MUST host-read anything they need from
# the input snapshot BEFORE dispatching (``bench.controller._global_round``
# does), and must never pass a snapshot that outlives the round — the
# un-donated ``global_assign`` stays the default for every other caller
# (tests, harness one-shots, nested sparse/trace/restart uses, where the
# inner jit would drop the donation anyway).
global_assign_donated = instrument_jit(
    global_assign.__wrapped__,
    name="global_assign",
    static_argnames=("config",),
    donate_argnums=(0,),
)
