"""Per-replica placement: the decision unit drops from service to pod.

The service-level solvers move whole Deployments because the REFERENCE
does (foreground cascade delete + pinned re-create,
delete_replaced_pod.py:173, rescheduling.py:216) — a mechanism
constraint, not an objective one. The TPU solver has no such constraint:
splitting a service's replicas across nodes is often strictly better
(a 4-replica service too big for any single node's budget can straddle
two nodes next to its peers instead of being exiled wholesale).

Mode of operation: each pod becomes its own pseudo-service in an expanded
sparse graph — the service edge (s, t, w) fans out to all (pod-of-s,
pod-of-t) pairs at weight w, exactly the pair-weight semantics the
service-level objective already encodes (W[s,t] = adj·rv_s·rv_t counts
pod pairs; here each pair is its own decision). Capacity packs per pod.
The sparse block-local form is what makes this affordable: the expanded
graph has Σ_e rv_s·rv_t edges (~rv²·E), never an SP² matrix.

`--placement-unit pod` on the solve CLI routes here.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from kubernetes_rescheduling_tpu.core import sparsegraph
from kubernetes_rescheduling_tpu.core.sparsegraph import SparseCommGraph
from kubernetes_rescheduling_tpu.core.state import ClusterState, CommGraph
from kubernetes_rescheduling_tpu.solver.global_solver import GlobalSolverConfig
from kubernetes_rescheduling_tpu.solver.sparse_solver import global_assign_sparse


def pod_level_graph(state: ClusterState, graph: CommGraph) -> SparseCommGraph:
    """Expand a service-level CommGraph to a pod-level SparseCommGraph:
    one pseudo-service per valid pod; every service edge fans out to the
    pods' cross product. Pseudo-service ids == pod indices (padding pods
    included as invalid isolated services, so ids need no remapping)."""
    P = state.num_pods
    svc = np.asarray(state.pod_service)
    valid = np.asarray(state.pod_valid)
    adj = np.asarray(graph.adj)
    S = graph.num_services
    pods_of: dict[int, np.ndarray] = {}
    for s in range(S):
        pods_of[s] = np.flatnonzero(valid & (svc == s))
    iu, ju = np.nonzero(np.triu(adj[:S, :S], k=1))
    srcs, dsts, ws = [], [], []
    for s, t in zip(iu, ju):
        ps, pt = pods_of[int(s)], pods_of[int(t)]
        if len(ps) == 0 or len(pt) == 0:
            continue
        grid = np.meshgrid(ps, pt, indexing="ij")
        srcs.append(grid[0].ravel())
        dsts.append(grid[1].ravel())
        ws.append(np.full(len(ps) * len(pt), float(adj[s, t])))
    if srcs:
        src = np.concatenate(srcs)
        dst = np.concatenate(dsts)
        w = np.concatenate(ws)
    else:
        src = dst = np.zeros((0,), np.int64)
        w = np.zeros((0,))
    return sparsegraph.from_edges(
        src, dst, w, P,
        names=tuple(state.pod_names) if state.pod_names else (),
    )


def global_assign_pods(
    state: ClusterState,
    graph: CommGraph,
    key: jax.Array,
    config: GlobalSolverConfig = GlobalSolverConfig(),
    *,
    pod_graph: SparseCommGraph | None = None,
) -> tuple[ClusterState, dict[str, jax.Array]]:
    """Re-place every POD independently. Same contract as the service
    solvers: never worse than the input (the gate compares pod-level comm
    + balance). Pass a prebuilt ``pod_graph`` (from
    :func:`pod_level_graph`) to amortize the host-side expansion across
    controller rounds with an unchanged pod set."""
    if pod_graph is None:
        pod_graph = pod_level_graph(state, graph)
    # each pod is its own pseudo-service; the sparse solver's aggregates
    # then see rv=1, the pod's own cpu/mem, and its current node
    view = state.replace(
        pod_service=jnp.arange(state.num_pods, dtype=jnp.int32)
    )
    new_view, info = global_assign_sparse(view, pod_graph, key, config)
    return state.replace(pod_node=new_view.pod_node), info
