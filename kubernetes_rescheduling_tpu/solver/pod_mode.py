"""Per-replica placement: the decision unit drops from service to pod.

The service-level solvers move whole Deployments because the REFERENCE
does (foreground cascade delete + pinned re-create,
delete_replaced_pod.py:173, rescheduling.py:216) — a mechanism
constraint, not an objective one. The TPU solver has no such constraint:
splitting a service's replicas across nodes is often strictly better
(a 4-replica service too big for any single node's budget can straddle
two nodes next to its peers instead of being exiled wholesale).

Mode of operation: each pod becomes its own pseudo-service in an expanded
sparse graph — the service edge (s, t, w) fans out to all (pod-of-s,
pod-of-t) pairs at weight w, exactly the pair-weight semantics the
service-level objective already encodes (W[s,t] = adj·rv_s·rv_t counts
pod pairs; here each pair is its own decision). Capacity packs per pod.
The sparse block-local form is what makes this affordable: the expanded
graph has Σ_e rv_s·rv_t edges (~rv²·E), never an SP² matrix.

The expansion is fully vectorized and **sparse-direct**: it consumes
either a dense ``CommGraph`` or a ``SparseCommGraph``'s COO edge list —
at 50k services the dense adjacency cannot exist, and the pod graph is
built straight from the sparse edges (no [S, S] array anywhere,
host-side or device-side).

Production routing: ``--placement-unit pod`` on the solve CLI and
``RescheduleConfig.placement_unit='pod'`` on the controller/harness route
here; restarts and tp shard exactly like the service-level sparse path
(``parallel.solve_with_restarts(sparse_graph=pod_graph)``).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from kubernetes_rescheduling_tpu.core import sparsegraph
from kubernetes_rescheduling_tpu.core.sparsegraph import SparseCommGraph
from kubernetes_rescheduling_tpu.core.state import ClusterState, CommGraph
from kubernetes_rescheduling_tpu.solver.global_solver import GlobalSolverConfig


def _pods_by_service(state: ClusterState, S: int):
    """Valid pod ids grouped by service: ``(pid, starts, counts)`` where
    service s's pods are ``pid[starts[s] : starts[s] + counts[s]]``."""
    svc = np.asarray(state.pod_service)
    valid = np.asarray(state.pod_valid)
    pid = np.flatnonzero(valid & (svc >= 0) & (svc < S))
    order = np.argsort(svc[pid], kind="stable")
    pid = pid[order]
    svs = svc[pid]
    starts = np.searchsorted(svs, np.arange(S))
    counts = np.searchsorted(svs, np.arange(S), side="right") - starts
    return pid, starts, counts


def pod_level_graph(
    state: ClusterState, graph: CommGraph | SparseCommGraph
) -> SparseCommGraph:
    """Expand a service-level graph to a pod-level SparseCommGraph: one
    pseudo-service per valid pod; every service edge fans out to the
    pods' cross product (vectorized — no per-edge Python loop). Accepts
    the dense ``CommGraph`` or, at scales where no dense adjacency can
    exist, a ``SparseCommGraph`` (the COO list is consumed directly).
    Pseudo-service ids == pod indices (padding pods are invalid isolated
    services, so ids need no remapping)."""
    P = state.num_pods
    if isinstance(graph, SparseCommGraph):
        S = graph.num_services
        src_s = np.asarray(graph.edges_src)
        dst_s = np.asarray(graph.edges_dst)
        wts = np.asarray(graph.edges_w)
        perm = np.asarray(graph.perm)
        # canonical undirected edges (each edge is stored twice)
        und = src_s < dst_s
        iu = perm[src_s[und]]
        ju = perm[dst_s[und]]
        w = wts[und].astype(np.float64)
    else:
        S = graph.num_services
        adj = np.asarray(graph.adj)
        iu, ju = np.nonzero(np.triu(adj[:S, :S], k=1))
        w = adj[iu, ju].astype(np.float64)

    pid, starts, counts = _pods_by_service(state, S)
    ca = counts[iu]
    cb = counts[ju]
    m = ca * cb
    keep = m > 0
    iu, ju, w, ca, cb, m = (x[keep] for x in (iu, ju, w, ca, cb, m))
    off = np.concatenate([[0], np.cumsum(m)])
    total = int(off[-1])
    # pair r of edge e is (pod r // cb of s, pod r % cb of t)
    eidx = np.repeat(np.arange(len(m)), m)
    r = np.arange(total) - off[eidx]
    src = pid[starts[iu][eidx] + r // cb[eidx]]
    dst = pid[starts[ju][eidx] + r % cb[eidx]]
    return sparsegraph.from_edges(
        src, dst, w[eidx], P,
        names=tuple(state.pod_names) if state.pod_names else (),
    )


def global_assign_pods(
    state: ClusterState,
    graph: CommGraph | SparseCommGraph | None,
    key: jax.Array,
    config: GlobalSolverConfig = GlobalSolverConfig(),
    *,
    pod_graph: SparseCommGraph | None = None,
    n_restarts: int = 1,
    tp: int = 1,
    mesh=None,
) -> tuple[ClusterState, dict[str, jax.Array]]:
    """Re-place every POD independently. Same contract as the service
    solvers: never worse than the input (the gate compares pod-level comm
    + balance). Pass a prebuilt ``pod_graph`` (from
    :func:`pod_level_graph`) to amortize the host-side expansion across
    controller rounds with an unchanged pod set.

    ``n_restarts``/``tp``/``mesh`` route through the SAME production
    entry as the service-level solvers
    (``parallel.solve_with_restarts(sparse_graph=...)``): dp restarts,
    node-axis tp sharding, and their composition all work on the pod
    graph — per-replica placement is a production path, not a demo.
    """
    from kubernetes_rescheduling_tpu.parallel.sharded import solve_with_restarts

    if pod_graph is None:
        pod_graph = pod_level_graph(state, graph)
    # each pod is its own pseudo-service; the sparse solver's aggregates
    # then see rv=1, the pod's own cpu/mem, and its current node
    view = state.replace(
        pod_service=jnp.arange(state.num_pods, dtype=jnp.int32)
    )
    new_view, info = solve_with_restarts(
        view, None, key,
        n_restarts=n_restarts, config=config, mesh=mesh, tp=tp,
        sparse_graph=pod_graph,
    )
    return state.replace(pod_node=new_view.pod_node), info
