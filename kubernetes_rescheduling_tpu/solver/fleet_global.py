"""Fleet mode, global-solver plane: the batched global solve over tenants.

PR 6 batched the greedy decision kernel; this module lifts the same
tenant axis over the DENSE global solver — the quality family that wins
the RESULTS.md round-5 gap table (global/sparse/swap ≤ 8.5% of optimum)
— so a fleet round's re-placement of every service in every tenant is
ONE device program instead of N sequential solves. RESULTS.md round 5
measured per-solve FIXED cost + dispatch as the dominant term at every
scale; the global solver pays a much larger fixed cost than the greedy
kernel (pair-weight build, chunk scans, sweep epilogues), so the
amortization win is correspondingly larger (the ``BENCH_SCENARIO=fleet``
``fleet_global`` reading measures it).

Composition mirrors the solo path exactly, which is what makes the
parity pin possible:

- ``n_restarts <= 1``: the per-tenant body IS ``global_assign`` under
  the original key (the solo ``solve_with_restarts`` single-restart
  path);
- ``n_restarts > 1``: per tenant, a ``lax.scan`` over
  ``jax.random.split(key, R)`` with device-side
  ``argmin(objective + penalty)`` selection — term-for-term
  ``parallel.sharded.parallel_restarts``'s shard body, so the batched
  restart fan-out selects the same restart the solo dp path selects
  (bit-exact, test-pinned). Like the solo restart path, only
  ``objective_after``/``move_penalty`` are reported (``objective_before``
  and ``improved`` ride as NaN and decode to None — the
  ``_defer_solver_objectives`` absent-key contract).

The swap phases (``config.swap_every``) and disruption pricing
(``config.move_cost``) live inside ``global_assign`` and batch for free.
``solver_backend='sparse'`` does NOT batch: the sparse form's
degree-sorted block layout is static per-tenant pytree metadata, so each
tenant would fork the compiled signature — config validation rejects the
combination with that reason.

The whole fleet's round comes home in ONE flat f32 bundle
(:func:`decode_fleet_global`): per-tenant service targets, the
first-moved-pod index per service (the solo host loop discovers moves in
pod-index order — the decode preserves that order so applied-move
streams are bit-identical), and the solver objective row. Padded tenant
slots (``tenant_mask`` False) never emit moves.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from kubernetes_rescheduling_tpu.core.state import ClusterState, CommGraph
from kubernetes_rescheduling_tpu.solver.global_solver import (
    GlobalSolverConfig,
    global_assign,
)
from kubernetes_rescheduling_tpu.telemetry.accounting import instrument_jit

# objective row layout (per tenant, appended after the two [T, S] planes):
# NaN in OBJ_BEFORE/OBJ_IMPROVED means "absent" (the restart fan-out
# reports only the selected restart's after/penalty, like the solo path)
OBJ_BEFORE, OBJ_AFTER, OBJ_IMPROVED, OBJ_PENALTY, OBJ_ROWS = range(5)


def _solve_one(
    state: ClusterState,
    graph: CommGraph,
    key: jax.Array,
    config: GlobalSolverConfig,
    n_restarts: int,
):
    """One tenant's global round: solve (with the solo restart
    composition), then collapse the pod-level move set to the service
    level — the device twin of the solo ``_global_round`` host loop."""
    if n_restarts <= 1:
        new_state, info = global_assign.__wrapped__(state, graph, key, config)
        obj = jnp.stack(
            [
                jnp.asarray(info["objective_before"], jnp.float32),
                jnp.asarray(info["objective_after"], jnp.float32),
                jnp.asarray(info["improved"], jnp.float32),
                jnp.asarray(info["move_penalty"], jnp.float32),
            ]
        )
    else:
        keys = jax.random.split(key, n_restarts)

        def body(carry, k):
            ns, info = global_assign.__wrapped__(state, graph, k, config)
            return carry, (
                ns.pod_node,
                info["objective_after"],
                info["move_penalty"],
            )

        _, (pods, objs, pens) = lax.scan(body, 0, keys)
        # gated penalized selection — parallel_restarts' rule verbatim
        best = jnp.argmin(objs + pens)
        new_state = state.replace(pod_node=pods[best])
        nan = jnp.float32(jnp.nan)
        obj = jnp.stack(
            [nan, jnp.asarray(objs[best], jnp.float32), nan,
             jnp.asarray(pens[best], jnp.float32)]
        )

    S = graph.num_services
    P = state.num_pods
    moved = state.pod_valid & (new_state.pod_node != state.pod_node)
    svc = jnp.where(
        moved, jnp.clip(state.pod_service, 0, S - 1), S
    ).astype(jnp.int32)
    # first moved pod per service: the solo loop walks pods in index
    # order and takes each changed service at its first changed pod —
    # the decode sorts by this so the applied-move ORDER is preserved
    first_pod = (
        jnp.full((S + 1,), P, jnp.int32)
        .at[svc]
        .min(jnp.where(moved, jnp.arange(P), P).astype(jnp.int32))[:S]
    )
    # all moved pods of a service share one solver target (the adopted
    # assignment is service-granular) — max over the service's moved pods
    svc_target = (
        jnp.full((S + 1,), -1, jnp.int32)
        .at[svc]
        .max(jnp.where(moved, new_state.pod_node, -1).astype(jnp.int32))[:S]
    )
    return svc_target, first_pod, obj


def _fleet_global_solve(
    states: ClusterState,
    graphs: CommGraph,
    keys: jax.Array,
    tenant_mask: jax.Array,
    *,
    config: GlobalSolverConfig,
    n_restarts: int = 1,
):
    """The batched fleet global round: ``_solve_one`` mapped over the
    leading tenant axis, masked so padded slots never emit moves, packed
    into ONE flat f32 bundle for the fleet loop's single counted pull.

    ``lax.map`` (a device-side scan over tenants), deliberately NOT
    ``vmap`` — for exactly the reasons ``parallel_restarts`` scans its
    restarts instead of vmapping them: batching the solver multiplies
    its working set (one occupancy matrix and one set of gathered W row
    blocks PER TENANT resident at once), vmapping its scatter updates
    produces variadic-scatter HLO the TPU backend cannot emit, and the
    batch-width-dependent matmul tiling drifts near-tie admissions at
    the ulp level — which would break the bit-exactness pin against the
    solo kernel AND between the vmap and dp planes (a dp shard sees a
    narrower tenant block; measured). The map body is the solo solver
    traced at solo shapes, so parity is structural; the amortization win
    — fixed cost + dispatch paid once per FLEET round instead of per
    tenant — is a property of the single dispatch, not of instruction-
    level batching.

    Layout: ``[svc_target (T·S), first_pod (T·S), obj rows (T·OBJ_ROWS)]``
    — small integers are exact in f32, and one concatenated vector means
    one transfer, the fleet transfer discipline."""
    svc_target, first_pod, obj = lax.map(
        lambda args: _solve_one(
            *args, config=config, n_restarts=n_restarts
        ),
        (states, graphs, keys),
    )
    m = tenant_mask
    P = states.pod_node.shape[1]
    svc_target = jnp.where(m[:, None], svc_target, jnp.int32(-1))
    first_pod = jnp.where(m[:, None], first_pod, jnp.int32(P))
    obj = jnp.where(m[:, None], obj, jnp.float32(0.0))
    return jnp.concatenate(
        [
            jnp.ravel(svc_target).astype(jnp.float32),
            jnp.ravel(first_pod).astype(jnp.float32),
            jnp.ravel(obj),
        ]
    )


# ONE device program for the whole fleet's global round — the same
# 1-steady-state-trace invariant as fleet_solve (test-pinned); a retrace
# means a tenant axis went shape-polymorphic and every round re-pays the
# (large) solver compile the batching exists to amortize.
fleet_global_solve = instrument_jit(
    _fleet_global_solve,
    name="fleet_global_solve",
    static_argnames=("config", "n_restarts"),
)


def decode_fleet_global(flat, *, tenants: int, num_services: int):
    """Decode the batched bundle into per-tenant move lists + objectives.

    Returns ``(moves, objs)``: ``moves[t]`` is ``[(service, target), …]``
    in the solo loop's first-moved-pod order; ``objs[t]`` is
    ``(objective_before, objective_after, improved, move_penalty)`` with
    None where the kernel reported NaN (the restart fan-out's
    absent-keys contract)."""
    flat = np.asarray(flat)
    ts = tenants * num_services
    svc_target = flat[:ts].reshape(tenants, num_services).astype(np.int64)
    first_pod = flat[ts: 2 * ts].reshape(tenants, num_services)
    obj = flat[2 * ts:].reshape(tenants, OBJ_ROWS)
    moves: list[list[tuple[int, int]]] = []
    objs: list[tuple] = []
    for t in range(tenants):
        changed = np.flatnonzero(svc_target[t] >= 0)
        order = changed[np.argsort(first_pod[t][changed], kind="stable")]
        moves.append([(int(s), int(svc_target[t, s])) for s in order])
        before, after, improved, pen = obj[t]
        objs.append(
            (
                None if np.isnan(before) else float(before),
                float(after),
                None if np.isnan(improved) else bool(improved),
                float(pen),
            )
        )
    return moves, objs
