"""Fleet mode, device half: vmap-batched multi-tenant decision solving.

RESULTS.md's round-5 conclusion is that per-solve FIXED cost and op
dispatch — not kernel launches — dominate at every scale. A scheduling
*service* (ROADMAP north star) therefore wants a leading ``tenant``
dimension: N same-shaped clusters solved by ONE device program per
round, so the fixed cost amortizes across the fleet instead of being
paid N times by a sequential loop.

This module is that batch axis:

- :func:`stack_tenants` — stack N same-shaped tenant pytrees
  (``ClusterState`` + ``CommGraph``) along a new leading tenant axis.
  Tenants must already be padded to a common capacity (``ClusterState.
  build(node_capacity=..., pod_capacity=...)``); mismatched shapes raise
  a sizing error, never a silent broadcast.
- :func:`fleet_solve` — ``vmap`` of the per-round decision kernel
  (:func:`solver.round_loop.decide`) over the tenant axis, under ONE
  ``instrument_jit`` (``fn="fleet_solve"``, the usual 1-trace
  steady-state invariant). Decisions are BIT-EXACT with the solo kernel
  per tenant under the same keys (test-pinned, including the
  threefry-partitionable ``random`` policy) — fleet mode changes the
  dispatch shape, never the answer.
- :func:`fleet_metrics` — the per-round reporting pair
  (``communication_cost``, ``load_std``) batched the same way, so the
  multiplexed controller's round epilogue is one transfer for the whole
  fleet instead of 2·N scalar pulls.

Padded tenant slots (``tenant_mask`` False — a fleet below its
configured capacity, or a tenant whose breaker froze the round) never
emit moves: their ``most``/``victim``/``target`` come back -1 and their
hazard mask all-False, exactly the per-tenant no-op path of the solo
loop. The dp-mesh alternative (one tenant per device through the
sharded-restart machinery) lives in ``parallel.fleet``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from kubernetes_rescheduling_tpu.core.state import ClusterState, CommGraph
from kubernetes_rescheduling_tpu.objectives.metrics import (
    communication_cost,
    load_std,
)
from kubernetes_rescheduling_tpu.solver.round_loop import (
    decide,
    decide_with_forecast,
)
from kubernetes_rescheduling_tpu.telemetry.accounting import instrument_jit


def stack_tenants(trees):
    """Stack N same-shaped tenant pytrees along a new leading tenant axis.

    Static (non-pytree) metadata — name tuples — is taken from tenant 0:
    it is host-side bookkeeping the device kernels never read, and fleet
    callers index back into each tenant's OWN names with the per-tenant
    rows of the batched result. Array shapes must match exactly across
    tenants; a mismatch raises a sizing error naming the offending
    tenant (pad every tenant to a common capacity first — the
    ``node_capacity``/``pod_capacity`` knobs exist for this).
    """
    if not trees:
        raise ValueError("stack_tenants needs at least one tenant")
    leaves0, treedef = jax.tree_util.tree_flatten(trees[0])
    cols = [leaves0]
    for t, tree in enumerate(trees[1:], start=1):
        leaves = jax.tree_util.tree_leaves(tree)
        if len(leaves) != len(leaves0):
            raise ValueError(
                f"tenant {t} has a different pytree structure than tenant 0"
            )
        for a, b in zip(leaves0, leaves):
            if jnp.shape(a) != jnp.shape(b):
                raise ValueError(
                    f"tenant {t} shape {jnp.shape(b)} != tenant 0 shape "
                    f"{jnp.shape(a)}: fleet tenants must be padded to a "
                    "common capacity (node_capacity/pod_capacity) before "
                    "stacking"
                )
        cols.append(leaves)
    stacked = [
        jnp.stack([col[i] for col in cols]) for i in range(len(leaves0))
    ]
    return jax.tree_util.tree_unflatten(treedef, stacked)


# rows of the per-tenant decision bundle (axis 1 of the i32[T, 4] the
# batched kernel returns): the solo kernel's scalar outputs, packed so
# the whole fleet's decisions come home in ONE counted transfer
ROW_MOST, ROW_VICTIM, ROW_SERVICE, ROW_TARGET = range(4)


def _fleet_decide(
    states: ClusterState,
    graphs: CommGraph,
    policy_id: jax.Array,
    threshold: jax.Array,
    keys: jax.Array,
    tenant_mask: jax.Array,
):
    """The batched decision: ``decide`` vmapped over the leading tenant
    axis of ``states``/``graphs``/``keys``, masked so padded slots are
    no-ops. Returns ``(decisions, hazard_mask)``: ``decisions`` is
    i32[T, 4] — per tenant ``(most, victim, service, target)``, the solo
    kernel's scalars packed tenant-leading (see ``ROW_*``) so the host
    pulls the fleet's round in one transfer — and ``hazard_mask`` is
    bool[T, N]."""
    most, hazard_mask, victim, svc, target = jax.vmap(
        decide, in_axes=(0, 0, None, None, 0)
    )(states, graphs, policy_id, threshold, keys)
    neg = jnp.int32(-1)
    m = tenant_mask
    decisions = jnp.stack(
        [
            jnp.where(m, most, neg),
            jnp.where(m, victim, neg),
            jnp.where(m, svc, jnp.int32(0)),
            jnp.where(m, target, neg),
        ],
        axis=1,
    )
    return decisions, hazard_mask & m[:, None]


# ONE device program for the whole fleet's round: the instrumented jit
# the multiplexed controller dispatches once per round. Steady state must
# show jax_traces_total{fn="fleet_solve"} == 1 — a second trace means a
# tenant axis went shape-polymorphic and every round re-pays the compile
# the batching exists to amortize (test-pinned, like controller_decide).
fleet_solve = instrument_jit(_fleet_decide, name="fleet_solve")


def _fleet_decide_proactive(
    states: ClusterState,
    graphs: CommGraph,
    policy_id: jax.Array,
    threshold: jax.Array,
    keys: jax.Array,
    tenant_mask: jax.Array,
    deltas: jax.Array,
):
    """The batched PROACTIVE decision: ``decide_with_forecast`` vmapped
    over the leading tenant axis — the same packed ``(decisions,
    hazard_mask)`` contract as :func:`_fleet_decide`, with each tenant's
    forecast ``delta`` (f32[T, N], from ``forecast.fleet``) folded into
    its predicted state inside the trace. A zero delta row reproduces
    that tenant's reactive decisions bit-for-bit (the
    reactive-equivalence contract, fleet-shaped); masked slots never
    emit moves."""
    most, hazard_mask, victim, svc, target = jax.vmap(
        decide_with_forecast, in_axes=(0, 0, None, None, 0, 0)
    )(states, graphs, policy_id, threshold, keys, deltas)
    neg = jnp.int32(-1)
    m = tenant_mask
    decisions = jnp.stack(
        [
            jnp.where(m, most, neg),
            jnp.where(m, victim, neg),
            jnp.where(m, svc, jnp.int32(0)),
            jnp.where(m, target, neg),
        ],
        axis=1,
    )
    return decisions, hazard_mask & m[:, None]


# the proactive fleet program: one dispatch decides for every tenant
# against its own predicted next-window state. Same 1-steady-state-trace
# invariant as fleet_solve, own fn label.
fleet_solve_proactive = instrument_jit(
    _fleet_decide_proactive, name="fleet_solve_proactive"
)


def _fleet_metrics(states: ClusterState, graphs: CommGraph):
    """Per-tenant round metrics: f32[T, 2] — ``(communication_cost,
    load_std)`` per tenant, tenant-leading like the decision bundle."""

    def one(state, graph):
        return jnp.stack([communication_cost(state, graph), load_std(state)])

    return jax.vmap(one)(states, graphs)


# the round epilogue's reporting pair, batched: 2 values × N tenants in
# one dispatch + one bundled transfer (site="fleet_metrics" at the pull).
fleet_metrics = instrument_jit(_fleet_metrics, name="fleet_metrics")
