"""Rescheduling solvers.

- ``round_loop``: the reference's monitor→detect→delete→place control loop
  (reference main.py:56-112) as a single ``lax.scan`` — one compiled program
  runs all rounds on device.
- ``global_solver``: the new capability — batched iterated best-response
  assignment over the full service×node score matrix, of which the greedy
  one-deployment-per-round loop is a special case.
"""

from kubernetes_rescheduling_tpu.solver.round_loop import (
    RoundTelemetry,
    round_step,
    run_rounds,
)
from kubernetes_rescheduling_tpu.solver.global_solver import (
    GlobalSolverConfig,
    global_assign,
)
from kubernetes_rescheduling_tpu.solver.sparse_solver import (
    global_assign_sparse,
    sparse_pod_comm_cost,
)
from kubernetes_rescheduling_tpu.solver.fleet import (
    fleet_metrics,
    fleet_solve,
    stack_tenants,
)

__all__ = [
    "RoundTelemetry",
    "round_step",
    "run_rounds",
    "GlobalSolverConfig",
    "global_assign",
    "global_assign_sparse",
    "sparse_pod_comm_cost",
    "fleet_metrics",
    "fleet_solve",
    "stack_tenants",
]
