"""The multi-round rescheduling control loop as a ``lax.scan``.

Reference semantics (main.py:56-112), per round:
monitor → hazard detection → pick the max-CPU pod on the most-hazardous node
→ delete its Deployment (all replicas) → choose a target node with the active
policy → re-create the Deployment there. Rounds with no hazard, no movable
pod, or no candidate node are no-ops (reference main.py:103-112 skips;
rescheduling.py:98-99 raises and main.py:97-98 swallows).

Deliberate fixes over the reference (SURVEY.md §2 quirks):
- the deleted Deployment's pods are actually removed from the snapshot before
  scoring (quirk 1: reference edit_cluster's ``is not`` comparison usually
  removes nothing, main.py:14);
- a skipped round can never crash the loop (quirk 2: reference pod_delete
  returns a bare None that the caller unpacks, delete_replaced_pod.py:157-160);
- when every node is hazardous the move is skipped and the Deployment is kept
  (the reference deletes first and only then fails to re-create —
  rescheduling.py:98-99 — losing the workload).

Host-side pacing (the reference's 15 s sleep, main.py:27) and live-cluster
reconciliation live in the backends, never in traced code.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from flax import struct
from jax import lax

from kubernetes_rescheduling_tpu.core.state import UNASSIGNED, ClusterState, CommGraph
from kubernetes_rescheduling_tpu.objectives.metrics import (
    communication_cost,
    load_std,
    node_cpu_pct_rounded,
)
from kubernetes_rescheduling_tpu.policies.hazard import detect_hazard
from kubernetes_rescheduling_tpu.policies.scoring import (
    choose_node,
    lex_argmax,
    policy_scores,
)
from kubernetes_rescheduling_tpu.policies.victim import deployment_group, pick_victim
from kubernetes_rescheduling_tpu.telemetry.accounting import instrument_jit


@struct.dataclass
class RoundTelemetry:
    """Per-round record (arrays have a leading rounds axis after the scan)."""

    moved: jax.Array            # bool — did a deployment move this round
    most_hazard: jax.Array      # i32 node index, -1 = cluster stable
    victim: jax.Array           # i32 pod index, -1 = none
    service: jax.Array          # i32 service index of the moved deployment
    target: jax.Array           # i32 target node index, -1 = none
    communication_cost: jax.Array  # f32, after the round
    load_std: jax.Array            # f32, after the round


def finite_guard(state: ClusterState) -> ClusterState:
    """Device-side finite guard on the solver's load inputs — the
    decision kernels' mirror of the forecast plane's never-NaN
    discipline. The HOST admission guard (``bench/admission.py``) is the
    real trust boundary; this is the last-resort in-trace guard for
    callers that bypass it (bare loops, tests, the scanned replay):
    a non-finite or negative pod load collapses to 0 instead of
    poisoning every score, argmax, and objective downstream (NaN
    compares false everywhere — a poisoned round silently freezes).

    Bit-identity contract: on clean inputs every ``where`` selects the
    original value, so guarded kernels are bit-identical to the
    historical ones (golden-pinned). ``node_base_cpu`` is only guarded
    for finiteness, NOT non-negativity — the proactive path folds a
    (legitimately negative) forecast delta into it before this guard
    runs (``decide_with_forecast``)."""
    def nn(x):
        return jnp.where(jnp.isfinite(x) & (x >= 0.0), x, 0.0)

    def fin(x):
        return jnp.where(jnp.isfinite(x), x, 0.0)

    return state.replace(
        pod_cpu=nn(state.pod_cpu),
        pod_mem=nn(state.pod_mem),
        node_base_cpu=fin(state.node_base_cpu),
        node_base_mem=fin(state.node_base_mem),
    )


def decide(
    state: ClusterState,
    graph: CommGraph,
    policy_id: jax.Array,
    threshold: jax.Array,
    key: jax.Array,
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array, jax.Array]:
    """The per-round decision kernel, shared by the scanned loop and the
    backend-driven controller: hazard detection → victim → policy choice.

    Returns ``(most_hazard, hazard_mask, victim, service, target)``; the
    scalars are -1 on the corresponding no-op path. Scoring runs on the
    snapshot with the victim Deployment's pods removed (the foreground
    cascade delete completes before placement runs, reference
    delete_replaced_pod.py:173-177).
    """
    state = finite_guard(state)
    most, hazard_mask = detect_hazard(state, threshold)
    victim = jnp.where(most >= 0, pick_victim(state, most), -1)
    group = deployment_group(state, victim)
    svc = state.pod_service[jnp.clip(victim, 0, state.num_pods - 1)]
    removed = state.replace(pod_node=jnp.where(group, UNASSIGNED, state.pod_node))
    target = choose_node(policy_id, removed, graph, svc, hazard_mask, key)
    target = jnp.where(victim >= 0, target, -1)
    return most, hazard_mask, victim, svc, target


def decide_explain(
    state: ClusterState,
    graph: CommGraph,
    policy_id: jax.Array,
    threshold: jax.Array,
    key: jax.Array,
    *,
    top_k: int = 3,
) -> tuple[jax.Array, ...]:
    """:func:`decide` plus a compact explanation bundle, in one compiled
    program — the device half of decision explainability.

    The decision itself is bit-identical to :func:`decide` (same
    ``policy_scores`` rows, same masked lex argmax, same key), so the
    controller can swap kernels without changing behavior. The extra
    output is one f32[6, k] array (k = min(top_k, num_nodes)) the host
    pulls in a SINGLE transfer:

    - rows 0-1: top-k hazard — node index, CPU percent (−inf-padded when
      fewer valid nodes exist);
    - rows 2-4: top-k candidate targets by primary score — node index,
      primary score ``k1``, tie-break ``k2``;
    - row 5: candidate validity (1.0 where the slot is a real candidate).

    The CHOSEN node is guaranteed to be among the recorded candidates
    (the last slot is overwritten when top-k by ``k1`` alone would miss a
    tie-break winner), so re-deriving the argmax over the recorded rows
    must reproduce the decision — the explain-consistency invariant the
    flight-recorder bundle check pins.
    """
    state = finite_guard(state)
    most, hazard_mask = detect_hazard(state, threshold)
    victim = jnp.where(most >= 0, pick_victim(state, most), -1)
    group = deployment_group(state, victim)
    svc = state.pod_service[jnp.clip(victim, 0, state.num_pods - 1)]
    removed = state.replace(pod_node=jnp.where(group, UNASSIGNED, state.pod_node))
    k1, k2, cand = policy_scores(
        policy_id, removed, graph, svc, hazard_mask, key
    )
    target = lex_argmax([k1, k2], cand)
    target = jnp.where(victim >= 0, target, -1)

    k = min(int(top_k), state.num_nodes)
    pct = node_cpu_pct_rounded(state).astype(jnp.float32)
    hz_v, hz_i = lax.top_k(jnp.where(state.node_valid, pct, -jnp.inf), k)
    c_v, c_i = lax.top_k(jnp.where(cand, k1, -jnp.inf), k)
    # top-k by k1 alone can exclude the lex winner when >k nodes tie on
    # the primary key — force the chosen node into the last slot so the
    # recorded candidates always contain the argmax
    missing = (target >= 0) & ~jnp.any(c_i == target)
    c_i = c_i.at[-1].set(jnp.where(missing, target, c_i[-1]))
    bundle = jnp.stack(
        [
            hz_i.astype(jnp.float32),
            hz_v,
            c_i.astype(jnp.float32),
            k1[c_i],
            k2[c_i],
            cand[c_i].astype(jnp.float32),
        ]
    )
    return most, hazard_mask, victim, svc, target, bundle


def decide_with_forecast(
    state: ClusterState,
    graph: CommGraph,
    policy_id: jax.Array,
    threshold: jax.Array,
    key: jax.Array,
    delta: jax.Array,
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array, jax.Array]:
    """The ``proactive`` decision kernel: :func:`decide` run against the
    PREDICTED next-window state — the observed snapshot with the
    forecaster's per-node load ``delta`` folded into ``node_base_cpu``
    (``policies.proactive.predicted_state``, the one shared definition).

    Hazard detection and ``policy_scores`` therefore see next-window
    loads while the pod/topology arrays stay observed — masked slots
    carry a zero delta by the forecast kernel's contract, so padding
    stays inert. A zero ``delta`` (cold start, skill-gated degrade)
    makes this bit-identical to :func:`decide` on the raw state — the
    reactive-equivalence invariant the cold-start tests pin.
    """
    from kubernetes_rescheduling_tpu.policies.proactive import predicted_state

    return decide(predicted_state(state, delta), graph, policy_id, threshold, key)


def decide_explain_with_forecast(
    state: ClusterState,
    graph: CommGraph,
    policy_id: jax.Array,
    threshold: jax.Array,
    key: jax.Array,
    delta: jax.Array,
    *,
    top_k: int = 3,
) -> tuple[jax.Array, ...]:
    """:func:`decide_explain` against the predicted state — the explain
    twin of :func:`decide_with_forecast`. The recorded bundle carries
    the PREDICTED scores the decision was actually made from, so the
    explain-consistency invariant (chosen == argmax of recorded rows)
    holds for proactive rounds for free."""
    from kubernetes_rescheduling_tpu.policies.proactive import predicted_state

    return decide_explain(
        predicted_state(state, delta), graph, policy_id, threshold, key,
        top_k=top_k,
    )  # decide_explain applies the same finite_guard as decide


def round_step(
    state: ClusterState,
    graph: CommGraph,
    policy_id: jax.Array,
    threshold: jax.Array,
    key: jax.Array,
) -> tuple[ClusterState, RoundTelemetry]:
    """One rescheduling round. Fully traced; all no-op paths are masks."""
    most, hazard_mask, victim, svc, target = decide(
        state, graph, policy_id, threshold, key
    )
    group = deployment_group(state, victim)
    do = (most >= 0) & (victim >= 0) & (target >= 0)
    new_pod_node = jnp.where(do & group, target, state.pod_node)
    new_state = state.replace(pod_node=new_pod_node)

    telemetry = RoundTelemetry(
        moved=do,
        most_hazard=most,
        victim=jnp.where(do, victim, jnp.where(most >= 0, victim, -1)),
        service=jnp.where(victim >= 0, svc, -1),
        target=jnp.where(do, target, -1),
        communication_cost=communication_cost(new_state, graph),
        load_std=load_std(new_state),
    )
    return new_state, telemetry


# instrument_jit instead of bare jax.jit: the whole point of the one-scan
# loop is compiling ONCE per (shape, rounds) signature — the registry's
# jax_traces_total{fn="run_rounds"} makes a silent retrace (the mystery
# slowdown class the module-level-jit comments in bench/trace.py guard
# against by hand) a visible metric and a test assertion
@partial(instrument_jit, name="run_rounds", static_argnames=("rounds",))
def run_rounds(
    state: ClusterState,
    graph: CommGraph,
    policy_id: jax.Array,
    key: jax.Array,
    *,
    rounds: int = 10,
    threshold: float = 30.0,
) -> tuple[ClusterState, RoundTelemetry]:
    """Run ``rounds`` rescheduling rounds (reference MAX_ROUNDS = 10,
    main.py:28) in one compiled scan. Returns the final state and stacked
    per-round telemetry."""
    thr = jnp.asarray(threshold, jnp.float32)

    def step(st, sub):
        new_st, tel = round_step(st, graph, policy_id, thr, sub)
        return new_st, tel

    keys = jax.random.split(key, rounds)
    final, tels = lax.scan(step, state, keys)
    return final, tels
