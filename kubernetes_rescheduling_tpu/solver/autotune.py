"""Latency-budget autotuning: spend a time budget, not a sweep count.

Rounds 2-3 each made the solver faster and then re-spent the savings by
hand-editing the default sweep count. This module turns that manual loop
into a knob: measure the actual per-sweep device cost of THIS config on
THIS hardware at THIS problem size, then pick the sweep count that fills
a ``--latency-budget`` (default 100 ms — the BASELINE.md solve-latency
target). Every future kernel speedup then buys solution quality
automatically.

Measurement discipline (see RESULTS.md): per-sweep cost is a DOUBLE slope
— chained solves inside one jitted scan isolate device time from
dispatch+tunnel RTT, and differencing two sweep counts isolates the
per-sweep cost from the per-round fixed cost (objective epilogue, W build,
pod scatter). Four compilations, one-time; the tuned config itself is
what the controller then reuses every round.
"""

from __future__ import annotations

import time
from functools import partial

import jax
import jax.numpy as jnp

from kubernetes_rescheduling_tpu.solver.global_solver import (
    GlobalSolverConfig,
    global_assign,
)


def _device_ms_per_round(solver, state, graph, config, k1=2, k2=8):
    """Slope-method device latency of one solver round (min-of-2 reps)."""

    @partial(jax.jit, static_argnames=("k", "cfg"))
    def chained(st0, g, key0, k, cfg):
        def body(st_c, i):
            st_n, inf = solver(st_c, g, jax.random.fold_in(key0, i), cfg)
            return st_n, inf["objective_after"]

        return jax.lax.scan(body, st0, jnp.arange(k))

    def timed(k):
        _, objs = chained(state, graph, jax.random.PRNGKey(7), k, config)
        float(objs[-1])  # compile + warm
        best = float("inf")
        for rep in range(2):
            t = time.perf_counter()
            _, objs = chained(state, graph, jax.random.PRNGKey(8 + rep), k, config)
            float(objs[-1])  # completion fence
            best = min(best, time.perf_counter() - t)
        return best

    return (timed(k2) - timed(k1)) / (k2 - k1) * 1e3


def tune_sweeps(
    state,
    graph,
    config: GlobalSolverConfig,
    budget_ms: float,
    *,
    solver=global_assign,
    lo: int = 3,
    hi: int = 9,
    max_sweeps: int = 64,
) -> tuple[GlobalSolverConfig, dict]:
    """Pick the sweep count that fills ``budget_ms`` of device time.

    Returns ``(tuned_config, info)`` where info carries the measured
    per-sweep and fixed costs so the decision is auditable. ``solver`` is
    the round function to measure — ``global_assign`` (default) or a
    sparse/sharded wrapper with the same signature.
    """
    if budget_ms <= 0:
        raise ValueError(f"latency budget must be > 0 ms, got {budget_ms}")
    d_lo = _device_ms_per_round(solver, state, graph, config.replace(sweeps=lo))
    d_hi = _device_ms_per_round(solver, state, graph, config.replace(sweeps=hi))
    per_sweep = max((d_hi - d_lo) / (hi - lo), 1e-3)
    fixed = max(d_lo - lo * per_sweep, 0.0)
    sweeps = int((budget_ms - fixed) // per_sweep)
    sweeps = max(1, min(max_sweeps, sweeps))
    info = {
        "budget_ms": float(budget_ms),
        "per_sweep_ms": round(per_sweep, 3),
        "fixed_ms": round(fixed, 3),
        "measured_lo": (lo, round(d_lo, 3)),
        "measured_hi": (hi, round(d_hi, 3)),
        "sweeps": sweeps,
        "predicted_round_ms": round(fixed + sweeps * per_sweep, 3),
    }
    return config.replace(sweeps=sweeps), info
