"""Pairwise-exchange (swap) phase for the chunked best-response solvers.

Single-service best-response deadlocks when capacity binds: every
improving move is infeasible until another service vacates — exactly the
regime where the measured optimality gap was worst (15-25% above the MILP
optimum on capacity-binding instances, RESULTS.md round 4). This module
adds the second move type: **capacity-feasible pairwise swaps** — two
services exchange nodes when the joint move improves the objective and
both directions fit.

Runs as a per-chunk phase after the single-move admission (on sweeps
selected by ``GlobalSolverConfig.swap_every``). For chunk services with
current nodes ``cur`` and chunk-start neighbor mass ``M[C, N]``, the
exchange gain of services i and j (i → cur_j, j → cur_i, atomically) is

    G[i, j] =  (M[i, cur_j] - M[i, cur_i])          # i's kept-mass delta
             + (M[j, cur_i] - M[j, cur_j])          # j's kept-mass delta
             - 2·W[i, j]                            # mutual-mass correction
             + Δbalance/overload terms + Δmove-cost terms

The ``-2·W[i, j]`` corrects the double-counted mutual mass: ``M[i,
cur_j]`` counts j's mass at cur_j, but after the swap j has left
(symmetrically for ``M[j, cur_i]``; the (i, j) pair's own cut
contribution is unchanged by an exchange). The load terms use the
DEPARTURE-CORRECTED projection ``load[cur_j] - cpu_j + cpu_i`` — the
single-move score's "node load plus me" projection would charge an
arriving service for a resident that is leaving in the same exchange,
vetoing precisely the full-node swaps this phase exists for. Move-cost
pricing charges/credits each side against its round-start anchor exactly
like the single-move score.

Selection is **mutual-best matching**: each service points at its
best-gain feasible partner, and exactly the pairs that point at each
other swap — service-disjoint by construction. Node capacity across
several admitted swaps touching the same node is resolved by the same
sort-free pairwise-priority race as single-move admission, with
higher-priority swaps' node deltas clamped at ≥ 0 (a rejected
higher-priority swap then only makes the estimate conservative, never
unsafe — mirroring the single-move race's departures-ignored rule).

Everything here is replicated [C]- and [C, C]-vector math, shared
verbatim by the single-chip solvers and the shard_map bodies of the
node-sharded solvers — the swap decisions cannot fork between them. The
node-column-dependent inputs (``M[i, cur_j]``, load/capacity at each
member's current node) are reduced by the callers: direct one-hot
contractions and [C] gathers single-chip, the same contractions psum'd
over ``tp`` when node columns are sharded; both produce the exact f32
value (one nonzero term per reduction), so the replicated core sees
identical inputs.

Reference objective being improved: communicationcost.py:40-45. The
reference has no coordinated-move mechanism at all (one deployment per
15 s round, main.py:27,100) — swaps exist because the solver-quality bar
here is the MILP optimum, not the reference.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

# stand-in for an unbounded memory budget inside feasibility arithmetic:
# inf would be correct in comparisons but can surface NaNs through masked
# sums (inf·0); every caller sanitizes with the SAME constant so the
# single-chip and sharded paths compare identical values
BIG_CAP = 3.4e38


def swap_flags(sweeps: int, swap_every: int) -> np.ndarray:
    """Which sweeps run the swap phase: every ``swap_every``-th sweep,
    counted so the LAST sweep of a default config is always included
    (sweeps 2, 5, 8 for sweeps=9, swap_every=3 — polish sweeps, where
    annealing noise has decayed and capacity deadlocks have formed).
    numpy on purpose: factories close over it (trace-agnostic)."""
    if swap_every <= 0:
        return np.zeros((sweeps,), dtype=bool)
    return (np.arange(sweeps) % swap_every) == (swap_every - 1)


def scan_sweeps(make_body, carry, keys, temps, flags):
    """Scan the sweep loop in contiguous same-flag segments so the swap
    phase is a STATIC branch of each segment's body — never a traced
    ``lax.cond`` inside the chunk scan. A per-chunk cond costs real money
    even on non-swap sweeps: it splits the chunk step into separate
    dispatch regions and materializes the [C, N] mass block through HBM
    (measured +4 ms/solve at 10k×1k — ~30× the swap math itself).

    ``make_body(do_swap: bool)`` returns a scan body over ``(key, temp)``;
    ``flags`` is the static numpy bool array from :func:`swap_flags`.
    Key/temp streams are sliced per segment, so decisions are identical
    to a single scan. Returns ``(carry, stacked_outputs)``."""
    flags = np.asarray(flags)
    bodies = {}
    outs = []
    i = 0
    while i < len(flags):
        j = i
        while j < len(flags) and flags[j] == flags[i]:
            j += 1
        flag = bool(flags[i])
        if flag not in bodies:
            bodies[flag] = make_body(flag)
        carry, out = jax.lax.scan(
            bodies[flag], carry, (keys[i:j], temps[i:j])
        )
        outs.append(out)
        i = j
    if len(outs) == 1:
        return carry, outs[0]
    return carry, jax.tree_util.tree_map(
        lambda *xs: jnp.concatenate(xs), *outs
    )


def swap_desire(m_best, m_cur, pen_home):
    """Optimistic per-service exchange desire: best kept mass anywhere
    (``m_best`` — the row max of M, pmax'd over shards when node columns
    are sharded) minus kept mass at the current node, minus the move-cost
    bill if the service still sits on its anchor. Load terms are
    deliberately OMITTED — a capacity-deadlocked service's best target
    projects over-budget under the single-move projection (that veto is
    exactly why it needs the swap phase); the pair-exact gain matrix
    re-prices candidates with departure-corrected loads."""
    return m_best - m_cur - pen_home


def swap_subset(desire, eligible, M, Wc, k):
    """Top-``k`` candidate selection + exact one-hot row contraction of
    ``M``/``Wc`` — ONE definition for the single-chip and sharded paths
    (only the desire reduction differs between them; a forked copy of
    the selection rule could silently diverge their decisions). Returns
    ``(sel, M_k, Wc_k, sub)`` where ``sub`` gathers any [C] vector to
    the subset."""
    C = desire.shape[0]
    HI = jax.lax.Precision.HIGHEST
    _, sel = jax.lax.top_k(jnp.where(eligible, desire, -jnp.inf), k)
    E = (sel[:, None] == jnp.arange(C, dtype=jnp.int32)[None, :]).astype(
        M.dtype
    )
    # one-hot row selection (HIGHEST → bit-exact), never a [k, N] gather
    M_k = jnp.dot(E, M, preferred_element_type=jnp.float32, precision=HI)
    Wc_k = jnp.dot(
        jnp.dot(E, Wc, preferred_element_type=jnp.float32, precision=HI),
        E.T, precision=HI,
    )
    return sel, M_k, Wc_k, (lambda v: v[sel])


def chunk_swap(
    M, Wc, cur, eligible, c_cpu, c_mem, cpu_load, mem_load, cap, mem_cap_s,
    lam, ow, pen, home, k, *, enforce_capacity,
):
    """The full single-chip swap phase for one chunk: desire-ranked
    top-``k`` candidate subset → exact pair decisions → full-width
    results. Subsetting is what keeps the phase off the flagship round's
    critical path: the [C, C] gain/interaction soup at C=1024 costs
    ~0.45 ms of VPU time per chunk, while the same math at k=256 is
    ~30 µs — and a chunk rarely holds more than a handful of genuinely
    deadlocked services. With ``k >= C`` (every small instance) the
    subset is the identity and behavior is unchanged.

    Returns ``(new_node[C], swapped[C], n_swaps)``; the caller commits
    loads/assignment exactly as for single moves."""
    C = cur.shape[0]
    m_cur = jnp.take_along_axis(M, cur[:, None], axis=1)[:, 0]
    pen_home = (
        pen * (cur == home).astype(jnp.float32) if pen is not None else 0.0
    )
    if k < C:
        desire = swap_desire(jnp.max(M, axis=1), m_cur, pen_home)
        sel, M_k, Wc_k, sub = swap_subset(desire, eligible, M, Wc, k)
    else:
        sel = jnp.arange(C, dtype=jnp.int32)
        M_k, Wc_k = M, Wc
        sub = lambda v: v
    cur_k = sub(cur)
    new_k, swapped_k, n_sw = swap_decisions(
        cols_at(M_k, cur_k),
        sub(m_cur),
        Wc_k, cur_k, sub(eligible), sub(c_cpu), sub(c_mem),
        cpu_load[cur_k], mem_load[cur_k], cap[cur_k], mem_cap_s[cur_k],
        lam, ow,
        pen=sub(pen) if pen is not None else None,
        home=sub(home) if home is not None else None,
        enforce_capacity=enforce_capacity,
    )
    new_node = cur.at[sel].set(new_k)
    swapped = jnp.zeros((C,), bool).at[sel].set(swapped_k)
    return new_node, swapped, n_sw


def cols_at(M, cur, col0=0):
    """``M_cur[i, j] = M[i, cur_j]`` as a one-hot contraction (NOT a
    [C, C] gather — XLA's TPU gather runs element-at-a-time and a 1M-
    element gather would cost more than the whole chunk step). HIGHEST
    precision keeps the one-hot product bit-exact in f32, so sharded
    callers psum'ing per-shard partials (zero off-shard) reproduce the
    single-chip values exactly."""
    C, N = M.shape
    gcol = col0 + jnp.arange(N, dtype=jnp.int32)
    E = (gcol[:, None] == cur[None, :]).astype(M.dtype)  # [N, C]
    return jnp.dot(
        M, E,
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )


def swap_decisions(
    M_cur,        # f32[C, C]: M[i, cur_j] (psum'd when node-sharded)
    m_own,        # f32[C]: M[i, cur_i]
    Wc,           # f32[C, C]: pair weight between chunk members i and j
    cur,          # i32[C] current node per service (post single-move phase)
    eligible,     # bool[C]: valid AND not moved by this chunk's single phase
    c_cpu,        # f32[C]
    c_mem,        # f32[C]
    load_cpu_at,  # f32[C]: node CPU load at cur_i (current, incl. i)
    load_mem_at,  # f32[C]
    cap_at,       # f32[C]: budget-scaled CPU capacity at cur_i
    mem_cap_at,   # f32[C] (inf sanitized to BIG_CAP by the caller)
    lam,          # balance weight
    ow,           # overload (over-budget) weight
    pen=None,     # f32[C] move-cost bill per service (None = pricing off)
    home=None,    # i32[C] round-start anchor node (with pen)
    *,
    enforce_capacity: bool,
):
    """The replicated swap core: exchange-gain matrix → mutual-best
    matching → pairwise-priority capacity race. Returns ``(new_node,
    swapped, n_swaps)`` where ``swapped[k]`` marks both members of every
    admitted pair and ``new_node[k] = cur[partner_k]`` there."""
    C = m_own.shape[0]
    idx = jnp.arange(C)

    # kept-mass side of the gain
    G = M_cur + M_cur.T - m_own[:, None] - m_own[None, :] - 2.0 * Wc

    # balance/overload side, with the departure-corrected projection:
    # i lands on cur_j whose load loses j and gains i
    pct_new = (
        (load_cpu_at[None, :] - c_cpu[None, :] + c_cpu[:, None])
        / cap_at[None, :]
        * 100.0
    )                                                   # [i, j]: i at cur_j
    pct_old = load_cpu_at / cap_at * 100.0              # [C]: i resident now
    term_new = -lam * pct_new - ow * jnp.maximum(pct_new - 100.0, 0.0)
    term_old = -lam * pct_old - ow * jnp.maximum(pct_old - 100.0, 0.0)
    G = G + (term_new - term_old[:, None]) + (term_new.T - term_old[None, :])

    # move-cost side: each member re-anchors against ITS round-start node
    if pen is not None:
        off_new = (cur[None, :] != home[:, None]).astype(jnp.float32)
        off_old = (cur != home).astype(jnp.float32)
        P = pen[:, None] * (off_new - off_old[:, None])  # i's bill delta
        G = G - P - P.T

    pair_ok = (
        eligible[:, None] & eligible[None, :] & (cur[:, None] != cur[None, :])
    )
    # net load delta at cur_i if (i, j) swap: j arrives, i departs
    d_cpu_a = c_cpu[None, :] - c_cpu[:, None]
    d_mem_a = c_mem[None, :] - c_mem[:, None]
    free_cpu_at = cap_at - load_cpu_at
    free_mem_at = mem_cap_at - load_mem_at
    if enforce_capacity:
        # the swap is atomic, so its own feasibility uses NET deltas on
        # both end nodes (fits at cur_j is the transpose of fits at cur_i)
        fits_a = (d_cpu_a <= free_cpu_at[:, None]) & (
            d_mem_a <= free_mem_at[:, None]
        )
        fits = fits_a & fits_a.T
    else:
        fits = jnp.broadcast_to(jnp.bool_(True), (C, C))
    Gm = jnp.where(pair_ok & fits & (G > 0), G, -jnp.inf)

    # mutual-best matching: first-max partner per row; pairs that pick
    # each other swap. Service-disjoint by construction (a service is in
    # at most one mutual pair), so commits never collide.
    p = jnp.argmax(Gm, axis=1).astype(jnp.int32)
    gbest = jnp.take_along_axis(Gm, p[:, None], axis=1)[:, 0]
    has = gbest > 0
    mutual = has & (p[p] == idx)
    cand = mutual & (idx < p)  # one representative per pair: the lower id
    gain_c = jnp.where(cand, gbest, -jnp.inf)
    before = (gain_c[None, :] > gain_c[:, None]) | (
        (gain_c[None, :] == gain_c[:, None]) & (idx[None, :] < idx[:, None])
    )
    pri = (before & cand[None, :]).astype(jnp.float32)  # [s, t]

    # cross-swap mass coupling: each pair's gain assumed everyone else
    # stays put, so two swaps whose members communicate can jointly undo
    # what each promised alone (two tied symmetric pairs would otherwise
    # rotate forever). The joint gain of swaps s=(i,j), t=(k,l) is
    # G(s) + G(t) + I(s,t) with I the Σ W·D over their 4 cross edges,
    # D(x,y) = [n'x==n'y] - [n'x==ny] - [nx==n'y] + [nx==ny]. A swap must
    # keep a positive margin after the CLAMPED-NEGATIVE interactions of
    # all higher-priority swaps (a rejected higher-priority swap then only
    # wastes margin, never admits a losing exchange).
    nprime = cur[p]
    D = (
        (nprime[:, None] == nprime[None, :]).astype(jnp.float32)
        - (nprime[:, None] == cur[None, :]).astype(jnp.float32)
        - (cur[:, None] == nprime[None, :]).astype(jnp.float32)
        + (cur[:, None] == cur[None, :]).astype(jnp.float32)
    )
    A = Wc * D
    # I[s, t] = A[i,k] + A[i,l] + A[j,k] + A[j,l] = ((E+Pm) A (E+Pm)ᵀ)[s,t]
    # with Pm the partner permutation — one-hot matmuls, not [C,C] gathers
    Pm = (p[:, None] == idx[None, :]).astype(jnp.float32)
    B = jnp.eye(C, dtype=jnp.float32) + Pm
    I_mat = jnp.dot(
        jnp.dot(B, A, precision=jax.lax.Precision.HIGHEST),
        B.T,
        precision=jax.lax.Precision.HIGHEST,
    )
    neg_i = jnp.sum(pri * jnp.minimum(I_mat, 0.0), axis=1)
    cand = cand & (gain_c + neg_i > 0)
    gain_c = jnp.where(cand, gbest, -jnp.inf)

    if enforce_capacity:
        # cross-swap capacity race: swap s must fit with every strictly-
        # higher-priority (greater gain, ties → lower index) swap's node
        # deltas counted, clamped at ≥ 0 (an uncommitted higher-priority
        # swap then leaves the estimate conservative, never unsafe).
        # Priority is re-derived over the interaction-surviving candidates.
        before = (gain_c[None, :] > gain_c[:, None]) | (
            (gain_c[None, :] == gain_c[:, None]) & (idx[None, :] < idx[:, None])
        )
        pri = (before & cand[None, :]).astype(jnp.float32)  # [s, t]
        in_a_cpu = c_cpu[p] - c_cpu       # net at own node a_t = cur_t
        in_b_cpu = -in_a_cpu              # net at partner node b_t = cur_{p_t}
        in_a_mem = c_mem[p] - c_mem
        in_b_mem = -in_a_mem
        a_of = cur
        b_of = cur[p]
        pos = lambda x: jnp.maximum(x, 0.0)

        def others(node_of):
            # Σ over higher-priority swaps t of their clamped delta at
            # this swap's node (a_t and b_t are distinct, so at most one
            # term is live per t)
            hit_a = (a_of[None, :] == node_of[:, None]).astype(jnp.float32)
            hit_b = (b_of[None, :] == node_of[:, None]).astype(jnp.float32)
            oc = jnp.sum(
                pri * (hit_a * pos(in_a_cpu)[None, :]
                       + hit_b * pos(in_b_cpu)[None, :]),
                axis=1,
            )
            om = jnp.sum(
                pri * (hit_a * pos(in_a_mem)[None, :]
                       + hit_b * pos(in_b_mem)[None, :]),
                axis=1,
            )
            return oc, om

        oa_cpu, oa_mem = others(a_of)
        ob_cpu, ob_mem = others(b_of)
        adm = (
            cand
            & (in_a_cpu + oa_cpu <= free_cpu_at)
            & (in_a_mem + oa_mem <= free_mem_at)
            & (in_b_cpu + ob_cpu <= free_cpu_at[p])
            & (in_b_mem + ob_mem <= free_mem_at[p])
        )
    else:
        adm = cand

    # both members of an admitted pair move to each other's node; the
    # higher-index member reads its representative's verdict through p
    # (mutuality guarantees p[p[k]] == k exactly for pair members)
    swapped = adm | (mutual & adm[p])
    new_node = jnp.where(swapped, cur[p], cur)
    return new_node, swapped, jnp.sum(adm)
