"""Global solver over the sparse (block-local) pair-weight form.

Same optimization as ``solver.global_solver.global_assign`` — chunked
synchronous best-response over service placements, minimizing exact
cut cost + load-balance terms, never worse than the input — but the pair
weights live in ``core.sparsegraph.SparseCommGraph``'s degree-sorted
block-local storage instead of a dense SP×SP matrix:

- memory is O(S·Ū) (Ū = per-block distinct-neighbor width, ~1k for the
  power-law meshes) instead of O(S²) — the ~46k-service sizing wall of the
  dense form becomes headroom (50k services ≈ 0.4 GB vs ≈ 14 GB dense);
- the per-sweep matmul work drops by the same sparsity factor, because the
  MXU contraction runs over each block's neighbor set, not over all SP
  services (ops/sparse_mass.py).

Search structure differences vs the dense solver, both deliberate:

1. **Hub pass.** The degree-sorted *hub blocks* (neighbor sets wider than
   the regular block width) are re-placed once per sweep as their own
   chunk, before the randomized chunks — their tile list is static, so the
   ragged widths cost zero wasted grid steps. Hubs are the highest-impact
   movers, so they also benefit from seeing the freshest loads.
2. **Composition granularity.** Chunks are random sets of 256-service
   blocks (exactly the dense inline-mass path's B=256 composition), and
   the blocks group services of similar degree rather than arbitrary ids.
   With ``degree_sort=False`` (identity relabeling) and no hub blocks the
   decisions are BIT-EQUAL to the dense solver's inline path — the parity
   test pins this.

The per-sweep best-seen objective here is the *exact* f32 cut-sum over the
COO edge list (O(E) — cheap enough that the dense path's bf16 kept-mass
approximation is unnecessary), plus the shared balance terms.

Reference objective being optimized: communicationcost.py:40-45 (the
relation dict there IS a sparse adjacency — this module just stores it the
way the TPU wants to eat it).
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from kubernetes_rescheduling_tpu.core.sparsegraph import (
    BLOCK_R,
    SparseCommGraph,
    edge_cut_sum,
    rv_weighted_edge_w,
    sparse_pair_comm_cost,
)
from kubernetes_rescheduling_tpu.core.state import ClusterState
from kubernetes_rescheduling_tpu.objectives.metrics import load_std
from kubernetes_rescheduling_tpu.telemetry.accounting import instrument_jit
from kubernetes_rescheduling_tpu.ops.fused_admission import (
    admission_stage,
    fused_score_admission,
    reference_score_admission,
)
from kubernetes_rescheduling_tpu.ops.sparse_mass import (
    chunk_local_slabs,
    hub_neighbor_mass,
    hub_tile_arrays,
    reference_hub_mass,
    reference_sparse_mass,
    sparse_mass_score,
    sparse_neighbor_mass,
)

# The noise seed-offset law: the fused mass+score kernel
# (sparse_mass_score) offsets its per-block PRNG seed by the BLOCK_R-row
# block index, while the standalone score kernel inside
# fused_score_admission offsets by program_id over block_c-row tiles. The
# two streams — and therefore noise-on decisions across the two lowerings
# of the same sweep — coincide only when the score kernel tiles at
# exactly BLOCK_R rows, so the solver pins its block_c here instead of
# trusting the kernel's default to stay aligned.
_SCORE_BLOCK_C = 256
assert _SCORE_BLOCK_C == BLOCK_R, (
    "noise seed-offset law broken: fused_score_admission must tile C at "
    "BLOCK_R rows (see ops/sparse_mass._chunk_mass_score_kernel)"
)
from kubernetes_rescheduling_tpu.solver.global_solver import (
    GlobalSolverConfig,
    _pad_to,
    _service_aggregates,
    auto_chunk,
    collapsed_placement,
    pct_balance_terms,
    pod_restart_bill,
)
from kubernetes_rescheduling_tpu.solver.swap import (
    BIG_CAP,
    chunk_swap,
    scan_sweeps,
    swap_flags,
)


def sparse_pod_comm_cost(
    state: ClusterState, sgraph: SparseCommGraph, *, edge_chunk: int = 16384
) -> jax.Array:
    """Pod-level communication cost of the ACTUAL placement (replicas may
    be split across nodes — not representable as a service assignment).

    Per sorted-space edge (s, t, w): cross-node pod pairs =
    ``rv_s·rv_t − Σ_n cnt[s,n]·cnt[t,n]``, subtracted PER EDGE (values are
    small, so f32 error stays per-edge-tiny — never the global ΣW
    subtraction whose ulp error could flip the adopt gate). Halved because
    the COO list carries each undirected edge twice. Scans the edge list
    in chunks to bound the gather footprint at scale.

    The general scan is only NEEDED when some service's replicas are
    split across nodes: its per-edge-chunk row gathers of the count
    matrix cost ~37 ms at 50k×2k (hundreds of thousands of 8 KB row
    DMAs), while every solver OUTPUT colocates each service's replicas —
    so chained production solves always present a collapsed placement.
    Three pod scatters detect that case and a ``lax.cond`` routes it to
    the O(E) COO cut (exactly the same quantity there, ~2.6 ms at 50k);
    genuinely split inputs still pay for the exact general accounting.

    Unlike the dense twin (``global_solver.comm_cost_collapse``), the
    collapse predicate here needs no per-pod service-validity term: a
    sparse graph's invalid services are its sorted-space PADDING slots,
    which ``inv`` never maps a pod onto — the dense failure mode (a split
    invalid-service defeating the fast path) is unrepresentable."""
    SP = sgraph.sp
    N = state.num_nodes
    pod_slot = sgraph.inv[
        jnp.clip(state.pod_service, 0, sgraph.num_services - 1)
    ]
    slot = jnp.where(state.pod_valid, pod_slot, SP)
    node = jnp.clip(jnp.where(state.pod_valid, state.pod_node, N), -1, N)
    # pods counted by the general form: valid AND placed on a real node
    # (node −1 / N fall into sliced-off scatter columns below); the
    # detection itself is the shared `collapsed_placement` — the dense
    # twin's predicate cannot drift from this one
    placed = state.pod_valid & (node >= 0) & (node < N)
    nmin, rv_eff, collapsed = collapsed_placement(slot, node, placed, SP, N)

    def fast(_):
        # every counted service sits on one node: the pod cost IS the
        # service-level cut of (first-node, effective replicas)
        return sparse_pair_comm_cost(sgraph, nmin, rv_eff)

    def slow(_):
        cnt = (
            jnp.zeros((SP + 1, N + 1), jnp.float32)
            .at[slot, node]
            .add(1.0)[:SP, :N]
        )
        rv = jnp.sum(cnt, axis=1)

        E2 = sgraph.edges_src.shape[0]
        EC = min(edge_chunk, max(E2, 1))
        n_ec = -(-E2 // EC)
        src = _pad_to(sgraph.edges_src, n_ec * EC, 0).reshape(n_ec, EC)
        dst = _pad_to(sgraph.edges_dst, n_ec * EC, 0).reshape(n_ec, EC)
        w = _pad_to(sgraph.edges_w, n_ec * EC, 0.0).reshape(n_ec, EC)

        def step(acc, xs):
            s, t, we = xs
            kept = jnp.sum(cnt[s] * cnt[t], axis=1)
            cross = jnp.maximum(rv[s] * rv[t] - kept, 0.0)
            return acc + jnp.sum(we * cross), None

        total, _ = lax.scan(step, jnp.float32(0.0), (src, dst, w))
        return 0.5 * total

    return lax.cond(collapsed, fast, slow, None)


def global_assign_sparse(
    state: ClusterState,
    sgraph: SparseCommGraph,
    key: jax.Array,
    config: GlobalSolverConfig = GlobalSolverConfig(),
) -> tuple[ClusterState, dict[str, jax.Array]]:
    """Sparse twin of ``global_assign`` — same contract: returns the new
    state and solve info; the result never degrades the true objective of
    the input placement.

    Single-block graphs (≤ 256 services) delegate to the dense solver:
    with one 256-row block there is only one chunk per sweep, so the
    search degenerates to fully-synchronous best-response (no inter-chunk
    Gauss-Seidel sequencing) and measurably loses quality (µBench: sparse
    landed at comm 6.0 where dense reaches 0.0) — and at that size the
    dense form costs nothing anyway. The builder carries the dense
    adjacency for exactly this case, so the delegation works inside jit."""
    if sgraph.num_blocks <= 1 and sgraph.dense_adj is not None:
        from kubernetes_rescheduling_tpu.core.state import CommGraph
        from kubernetes_rescheduling_tpu.solver.global_solver import (
            global_assign,
        )

        S = sgraph.num_services
        dense = CommGraph(
            adj=sgraph.dense_adj,
            service_valid=jnp.ones((S,), bool),
            names=sgraph.names,
        )
        new_state, info = global_assign(state, dense, key, config)
        info = dict(info, hub_pass=jnp.asarray(False))
        return new_state, info
    return _global_assign_sparse(state, sgraph, key, config)


def sorted_problem_arrays(state: ClusterState, sgraph: SparseCommGraph, SPX: int):
    """Sorted-space per-service arrays + neighbor replica columns — ONE
    definition shared by the single-chip and node-sharded sparse solvers.
    The tp bit-parity contract depends on these staying identical; edit
    here, never in one solver alone. Returns ``(svc_valid, svc_cpu_s,
    svc_mem_s, cur_s, rv_s, rvu)``, all padded to ``SPX`` (the service
    count incl. dummy chunk-padding blocks)."""
    S = sgraph.num_services
    replicas, svc_cpu, svc_mem, cur_node, has_pods = _service_aggregates(
        state, S
    )
    pclip = jnp.clip(sgraph.perm, 0, S - 1)
    ok = sgraph.perm < S

    def sort_pad(x, fill=0.0):
        return _pad_to(jnp.where(ok, x[pclip], fill), SPX, fill)

    svc_valid = _pad_to(ok & has_pods[pclip] & sgraph.service_valid, SPX, False)
    svc_cpu_s = sort_pad(svc_cpu) * svc_valid
    svc_mem_s = sort_pad(svc_mem) * svc_valid
    cur_s = jnp.where(svc_valid, sort_pad(cur_node, -1), -1)
    rv_s = sort_pad(replicas) * svc_valid
    # neighbor-column replica factor (0 on padding columns — the mass
    # kernels rely on this as the padding mask)
    rvu = jnp.where(
        sgraph.u_ids < sgraph.sp,
        rv_s[jnp.clip(sgraph.u_ids, 0, SPX - 1)],
        0.0,
    )
    return svc_valid, svc_cpu_s, svc_mem_s, cur_s, rv_s, rvu


def hub_slab(sgraph: SparseCommGraph, blocks, rv_s, SPX: int):
    """Concatenated group-local neighbor columns (ids + replica factors)
    for the given hub ``blocks`` — static slices of ``u_ids``, shared by
    both sparse solvers."""
    u_g = jnp.concatenate(
        [
            sgraph.u_ids[
                sgraph.block_toff[b] * sgraph.bu :
                (sgraph.block_toff[b] + sgraph.block_ntiles[b]) * sgraph.bu
            ]
            for b in blocks
        ]
    )
    rvu_g = jnp.where(
        u_g < sgraph.sp, rv_s[jnp.clip(u_g, 0, SPX - 1)], 0.0
    )
    return u_g, rvu_g


# instrumented like the dense twin: per-round dispatches must show one
# trace, and the compiled sparse program's cost/HBM snapshot is captured
# at first compile under fn="global_assign_sparse"
@partial(instrument_jit, name="global_assign_sparse", static_argnames=("config",))
def _global_assign_sparse(
    state: ClusterState,
    sgraph: SparseCommGraph,
    key: jax.Array,
    config: GlobalSolverConfig = GlobalSolverConfig(),
) -> tuple[ClusterState, dict[str, jax.Array]]:
    if not config.capacity_frac > 0:
        raise ValueError(
            f"capacity_frac must be > 0, got {config.capacity_frac}"
        )
    ow = config.overload_weight if config.enforce_capacity else 0.0
    S = sgraph.num_services
    N = state.num_nodes
    SP = sgraph.sp
    NB = sgraph.num_blocks
    hub_blocks = sgraph.hub_blocks
    regular = sgraph.regular_blocks
    NHB = len(hub_blocks)
    NBR = len(regular)
    if sgraph.weight_bytes() > config.max_weight_bytes:
        raise ValueError(
            f"sparse pair weights need {sgraph.weight_bytes() / 2**30:.2f} "
            f"GiB — over max_weight_bytes; the graph is too dense for the "
            "sparse form (use the dense solver)."
        )

    # chunk = KB 256-service blocks of the NBR regular blocks; dummy
    # (all-zero, all-invalid) blocks pad the last chunk
    C = min(auto_chunk(S, config.chunk_size), S)
    KB = max(1, C // BLOCK_R)
    n_chunks = max(1, -(-NBR // KB)) if NBR else 0
    ndummy = n_chunks * KB - NBR
    SPX = SP + ndummy * BLOCK_R  # service-array size incl. dummy blocks

    # ---- sorted-space per-service arrays (SHARED with the node-sharded
    # sparse solver — the tp bit-parity contract) ----
    svc_valid, svc_cpu_s, svc_mem_s, cur_s, rv_s, rvu = sorted_problem_arrays(
        state, sgraph, SPX
    )

    mm_dtype = jnp.dtype(config.matmul_dtype)
    w_mm = sgraph.w_local.astype(mm_dtype)

    cpu_cap = jnp.where(state.node_valid, state.node_cpu_cap, 0.0)
    mem_cap_raw = jnp.where(state.node_valid, state.node_mem_cap, 0.0)
    mem_cap = (
        jnp.where(mem_cap_raw > 0, mem_cap_raw, jnp.inf) * config.capacity_frac
    )
    cap = jnp.where(cpu_cap > 0, cpu_cap, 1.0) * config.capacity_frac

    assign0 = jnp.where(svc_valid, jnp.clip(cur_s, 0, N - 1), 0)
    # disruption pricing (config.move_cost): restart bill per service,
    # anchored at the round-start placement (see GlobalSolverConfig)
    mc_on = config.move_cost > 0
    pen_vec = config.move_cost * rv_s if mc_on else None

    def move_penalty(assign):
        """Service-level restart bill vs the assign0 collapse — the cheap
        per-sweep RANKING form; the adopt gate uses the exact pod-level
        bill (split replicas consolidating to assign0 restart pods this
        form cannot see)."""
        return config.move_cost * jnp.sum(
            jnp.where(svc_valid & (assign != assign0), rv_s, 0.0)
        )

    def _pod_bill(assign):
        slot = jnp.clip(
            sgraph.inv[jnp.clip(state.pod_service, 0, S - 1)], 0, SPX - 1
        )
        return pod_restart_bill(state, assign[slot], config.move_cost)

    def loads(assign):
        a = jnp.where(svc_valid, assign, N)
        cpu = (
            jnp.zeros((N + 1,), jnp.float32).at[a].add(svc_cpu_s)[:N]
        )
        mem = (
            jnp.zeros((N + 1,), jnp.float32).at[a].add(svc_mem_s)[:N]
        )
        return state.node_base_cpu + cpu, state.node_base_mem + mem

    def _balance_terms(cpu_load):
        return pct_balance_terms(
            cpu_load, cap, state.node_valid, config.balance_weight, ow
        )

    # per-edge rv-weighted weight, PRECOMPUTED once per solve: rv is fixed
    # across sweeps, so the per-sweep cut-sum gathers only the two assign
    # columns instead of four (~2.4 of the 2.6 ms/sweep objective cost at
    # 50k). The canonical grouping lives in core.sparsegraph — the value
    # is BIT-IDENTICAL to sparse_pair_comm_cost and to the node-sharded
    # twin's (the tp bit-parity contract) by shared definition.
    e_rvw = rv_weighted_edge_w(sgraph, rv_s)

    def objective_terms(assign, cpu_load):
        """(exact comm, ranking objective) — the sparse cut-sum is O(E),
        cheap enough to be both the per-sweep best-seen ranking AND the
        adopt gate (no bf16 fast-form needed, unlike the dense path). The
        comm term rides the sweep carry so the epilogue's reported cost
        reuses it via the collapse identity (every adopted placement
        colocates each service's replicas) instead of paying a second
        pod-level accounting pass."""
        comm = edge_cut_sum(sgraph, e_rvw, assign)
        obj = comm + _balance_terms(cpu_load)
        # penalized ranking under disruption pricing: a sweep that wins on
        # comm but spends more restarts than the win is worth loses
        return comm, (obj + move_penalty(assign) if mc_on else obj)

    # ---- lowering selection (mirrors the dense solver) ----
    fused_interpret = config.fused_epilogue == "interpret"
    on_tpu = jax.default_backend() == "tpu"
    use_kernels = on_tpu or fused_interpret
    use_fused = config.fused_epilogue in ("on", "interpret") or (
        config.fused_epilogue == "auto" and on_tpu and C >= 128 and N >= 128
    )

    toff_ext = jnp.asarray(
        np.asarray(
            list(sgraph.block_toff) + [sgraph.zero_toff] * ndummy,
            dtype=np.int32,
        )
    )
    reg_ext = jnp.asarray(
        np.asarray(
            list(regular) + [NB + d for d in range(ndummy)], dtype=np.int32
        )
    )
    # hub blocks are processed in chunk-sized groups (≤ KB blocks each):
    # the [BC, C]-tile admission race is quadratic in the chunk width and
    # a single all-hubs chunk blows the VMEM scoped limit past ~8 blocks.
    # Each group's neighbor-id columns are STATIC slices of u_ids, so only
    # the group-local slab (not the full table) hits the gather path.
    hub_groups = []
    for g in range(0, NHB, KB):
        blocks_g = hub_blocks[g : g + KB]
        ids_g = jnp.asarray(
            np.concatenate(
                [
                    np.arange(BLOCK_R, dtype=np.int32) + b * BLOCK_R
                    for b in blocks_g
                ]
            )
        )
        u_g, rvu_g = hub_slab(sgraph, blocks_g, rv_s, SPX)
        hub_groups.append(
            (blocks_g, ids_g, u_g, rvu_g, hub_tile_arrays(sgraph, blocks_g))
        )

    def chunk_slabs(blocks):
        # gather only the chunk's columns: KB contiguous id slices, then a
        # few-thousand-entry gather (full-table gathers cost more than all
        # the matmuls combined — see ops/sparse_mass.py docstring)
        starts = toff_ext[blocks] * sgraph.bu
        return chunk_local_slabs(sgraph.u_ids, rvu, starts, sgraph.u_reg)

    def chunk_mass(tgt_c, rvu_c, blocks, ids, nn):
        """Mass of the chunk's rows against arbitrary targets ``tgt_c``
        over ``nn`` columns — node occupancy for M (nn=N), chunk position
        for the swap phase's pair-weight block Wc (nn=C_eff)."""
        if use_kernels:
            raw = sparse_neighbor_mass(
                w_mm, tgt_c, rvu_c, blocks, toff_ext,
                num_nodes=nn, bu=sgraph.bu, reg_tiles=sgraph.reg_tiles,
                interpret=fused_interpret or not on_tpu,
            )
        else:
            raw = reference_sparse_mass(
                w_mm, tgt_c, rvu_c, blocks, toff_ext,
                num_nodes=nn, bu=sgraph.bu, reg_tiles=sgraph.reg_tiles,
            )
        return raw * rv_s[ids][:, None]

    def hub_mass(assign, group):
        blocks_g, ids_g, u_g, rvu_g, (h_col, h_lcol, h_out, h_first) = group
        tgt_l = assign[jnp.clip(u_g, 0, SPX - 1)]
        if use_kernels:
            raw = hub_neighbor_mass(
                w_mm, tgt_l, rvu_g, h_col, h_lcol, h_out, h_first,
                num_nodes=N, num_hub_blocks=len(blocks_g), bu=sgraph.bu,
                interpret=fused_interpret or not on_tpu,
            )
        else:
            raw = reference_hub_mass(
                sgraph, w_mm, tgt_l, rvu_g, num_nodes=N, blocks=blocks_g
            )
        return raw * rv_s[ids_g][:, None]

    def place(inner, ids, M, chunk_key, temp, seed):
        """Score → argmax → admission → commit for one id set (shared by
        the hub pass and the randomized chunks). ``seed`` feeds the fused
        kernel's core PRNG — drawn once per sweep for ALL chunks (one
        threefry instead of ~50: the per-chunk ``randint`` chatter
        measured 0.34 ms/sweep at 50k×2k); ``chunk_key`` still drives the
        XLA path's gumbel (annealing noise carries no cross-lowering
        parity requirement — ops/fused_admission.py docstring)."""
        assign, cpu_load, mem_load = inner
        valid_c = svc_valid[ids]
        c_cpu = svc_cpu_s[ids]
        c_mem = svc_mem_s[ids]
        cur = assign[ids]
        home = assign0[ids] if mc_on else None
        pen = pen_vec[ids] if mc_on else None
        if use_fused:
            new_node, admitted, d_cpu, d_mem = fused_score_admission(
                M, cur, c_cpu, c_mem, valid_c,
                cpu_load, mem_load, cap, mem_cap, state.node_valid,
                config.balance_weight, temp, seed,
                overload_weight=ow,
                home=home,
                move_pen=pen,
                enforce_capacity=config.enforce_capacity,
                use_noise=config.noise_temp > 0 and not fused_interpret,
                interpret=fused_interpret,
                # pinned, not defaulted: the noise seed-offset law needs
                # the score kernel tiled at exactly BLOCK_R rows (see the
                # module-level assert)
                block_c=_SCORE_BLOCK_C,
                emit_x_rows=False,
            )
            return (
                (
                    assign.at[ids].set(new_node),
                    cpu_load + d_cpu,
                    mem_load + d_mem,
                ),
                admitted,
            )
        noise = (
            temp * jax.random.gumbel(chunk_key, M.shape)
            if config.noise_temp > 0
            else None
        )
        new_node, admitted = reference_score_admission(
            M, cur, c_cpu, c_mem, valid_c,
            cpu_load, mem_load, cap, mem_cap, state.node_valid,
            config.balance_weight, noise,
            overload_weight=ow,
            home=home,
            move_pen=pen,
            enforce_capacity=config.enforce_capacity,
        )
        d_cpu = jnp.where(admitted, c_cpu, 0.0)
        d_mem = jnp.where(admitted, c_mem, 0.0)
        cpu_load = cpu_load.at[new_node].add(d_cpu).at[cur].add(-d_cpu)
        mem_load = mem_load.at[new_node].add(d_mem).at[cur].add(-d_mem)
        return (
            (assign.at[ids].set(new_node), cpu_load, mem_load),
            admitted,
        )

    # pairwise-exchange phase (solver/swap.py): per regular chunk, after
    # single-move admission, on sweeps flagged by config.swap_every. Hub
    # groups sit the swap phase out: hubs are the highest-degree movers
    # (rarely capacity-deadlocked — any node wants them) and their ragged
    # Wc would need its own kernel plumbing for little gain.
    C_eff = KB * BLOCK_R
    use_swaps = config.swap_every > 0
    sw_flags = swap_flags(config.sweeps, config.swap_every)  # static numpy
    mem_cap_sw = jnp.where(jnp.isinf(mem_cap), BIG_CAP, mem_cap)

    def _swap_phase(ids, M, Wc, assign, cpu_load, mem_load, admitted):
        """Identical structure to the dense solver's swap phase, over the
        sorted-space arrays (see global_solver._swap_phase)."""
        cur = assign[ids]
        valid_c = svc_valid[ids]
        eligible = valid_c & ~admitted & state.node_valid[cur]
        c_cpu = svc_cpu_s[ids]
        c_mem = svc_mem_s[ids]
        new_node, swapped, n_sw = chunk_swap(
            M, Wc, cur, eligible, c_cpu, c_mem,
            cpu_load, mem_load, cap, mem_cap_sw,
            config.balance_weight, ow,
            pen_vec[ids] if mc_on else None,
            assign0[ids] if mc_on else None,
            min(config.swap_k, C_eff),
            enforce_capacity=config.enforce_capacity,
        )
        d_c = jnp.where(swapped, c_cpu, 0.0)
        d_m = jnp.where(swapped, c_mem, 0.0)
        cpu_load = cpu_load.at[new_node].add(d_c).at[cur].add(-d_c)
        mem_load = mem_load.at[new_node].add(d_m).at[cur].add(-d_m)
        return assign.at[ids].set(new_node), cpu_load, mem_load, n_sw

    def make_sweep(do_swap: bool):
        return partial(sweep, do_swap=do_swap)

    def sweep(carry, xs, do_swap: bool = False):
        sweep_key, temp = xs
        assign, cpu_load, mem_load, best_assign, best_obj, best_comm = carry
        perm_key, noise_key = jax.random.split(sweep_key)
        # one threefry draw covers every chunk's and hub group's fused-
        # kernel seed (DCE'd entirely on the XLA lowering)
        seeds = jax.random.randint(
            jax.random.fold_in(noise_key, 7),
            (n_chunks + len(hub_groups),), 0, 2**31 - 1,
        )
        # key-split structure matches the dense inline path when NHB == 0
        # (the parity test relies on identical chunk_keys)
        hub_moves = jnp.int32(0)
        if hub_groups:
            keys = jax.random.split(noise_key, n_chunks + len(hub_groups))
            chunk_keys = keys[:n_chunks]
            inner = (assign, cpu_load, mem_load)
            # hubs first, freshest loads; each group re-reads the assign
            # vector, so later groups see earlier groups' moves
            for g, group in enumerate(hub_groups):
                assign = inner[0]
                M = hub_mass(assign, group)
                inner, g_adm = place(
                    inner, group[1], M, keys[n_chunks + g], temp,
                    seeds[n_chunks + g],
                )
                hub_moves = hub_moves + jnp.sum(g_adm)
            assign, cpu_load, mem_load = inner
        else:
            chunk_keys = jax.random.split(noise_key, n_chunks)
        bp = jax.random.permutation(perm_key, n_chunks * KB)
        chunk_blocks = reg_ext[bp].reshape(n_chunks, KB)
        chunk_ids = (
            chunk_blocks[:, :, None] * BLOCK_R
            + jnp.arange(BLOCK_R, dtype=jnp.int32)[None, None, :]
        ).reshape(n_chunks, KB * BLOCK_R)

        def chunk_step(inner, xs_c):
            blocks, ids, chunk_key, seed = xs_c
            assign = inner[0]
            u_c, rvu_c = chunk_slabs(blocks)
            tgt_c = assign[jnp.clip(u_c, 0, SPX - 1)]
            if use_fused and use_kernels and not (use_swaps and do_swap):
                # fused mass+score (round 5): one kernel launch per chunk
                # and the [C, N] mass block never round-trips HBM — shared
                # score_core keeps decisions bit-identical to the
                # two-kernel path (which swap sweeps still use: the swap
                # phase consumes M). ~0.35 → ~0.25 ms/chunk at 50k×2k.
                assign, cpu_load, mem_load = inner
                valid_c = svc_valid[ids]
                c_cpu = svc_cpu_s[ids]
                c_mem = svc_mem_s[ids]
                cur = assign[ids]
                prop, gain, wants, s_cpu, s_mem = sparse_mass_score(
                    w_mm, tgt_c, rvu_c, blocks, toff_ext, rv_s[ids],
                    cur,
                    assign0[ids] if mc_on else cur,
                    pen_vec[ids] if mc_on else None,
                    c_cpu, c_mem, valid_c,
                    cpu_load, mem_load, cap, mem_cap, state.node_valid,
                    config.balance_weight, temp, seed, ow,
                    num_nodes=N, bu=sgraph.bu, reg_tiles=sgraph.reg_tiles,
                    enforce_capacity=config.enforce_capacity,
                    use_noise=config.noise_temp > 0 and not fused_interpret,
                    interpret=fused_interpret or not on_tpu,
                )
                new_node, admitted, d_cpu, d_mem = admission_stage(
                    prop, gain, wants, s_cpu, s_mem,
                    cur, valid_c, c_cpu, c_mem,
                    num_nodes=N,
                    enforce_capacity=config.enforce_capacity,
                    interpret=fused_interpret or not on_tpu,
                    emit_x_rows=False,  # inline-mass path: 4-tuple return
                )
                inner = (
                    assign.at[ids].set(new_node),
                    cpu_load + d_cpu,
                    mem_load + d_mem,
                )
                return inner, (jnp.sum(admitted), jnp.int32(0))
            M = chunk_mass(tgt_c, rvu_c, blocks, ids, N)
            inner, admitted = place(inner, ids, M, chunk_key, temp, seed)
            n_moves = jnp.sum(admitted)
            if not (use_swaps and do_swap):  # STATIC branch (scan_sweeps)
                return inner, (n_moves, jnp.int32(0))

            assign2, cpu2, mem2 = inner
            # chunk-local pair weights via the SAME mass contraction
            # with "node" = chunk position: Wc[i, j] = W[i, ids_j] —
            # reads only the chunk's own strips (cheap, unlike the dense
            # form's full row blocks)
            pos = (
                jnp.full((SPX,), C_eff, jnp.int32)
                .at[ids]
                .set(jnp.arange(C_eff, dtype=jnp.int32))
            )
            Wc = chunk_mass(
                pos[jnp.clip(u_c, 0, SPX - 1)], rvu_c, blocks, ids, C_eff
            )
            assign2, cpu2, mem2, n_sw = _swap_phase(
                ids, M, Wc, assign2, cpu2, mem2, admitted
            )
            return (assign2, cpu2, mem2), (n_moves, n_sw)

        (assign, _, _), (moves, sws) = lax.scan(
            chunk_step, (assign, cpu_load, mem_load),
            (chunk_blocks, chunk_ids, chunk_keys, seeds[:n_chunks]),
            unroll=2,
        )
        # refresh carried loads each sweep boundary — bounds incremental
        # f32 drift to one sweep, matching the dense paths
        cpu_fresh, mem_fresh = loads(assign)
        comm, obj = objective_terms(assign, cpu_fresh)
        better = obj < best_obj
        best_assign = jnp.where(better, assign, best_assign)
        best_obj = jnp.where(better, obj, best_obj)
        best_comm = jnp.where(better, comm, best_comm)
        return (
            (assign, cpu_fresh, mem_fresh, best_assign, best_obj, best_comm),
            (jnp.sum(moves) + hub_moves, jnp.sum(sws)),
        )

    # true objective of the INPUT placement (replicas may be split across
    # nodes); the adopt gate compares against this, so "never worse than
    # the input" holds even when the first-pod collapse of assign0 is worse
    pct_true0 = jnp.where(
        state.node_valid, state.node_cpu_used() / cap * 100.0, 0.0
    )
    comm_true0 = sparse_pod_comm_cost(state, sgraph)
    obj_true0 = (
        comm_true0
        + config.balance_weight * (load_std(state) / config.capacity_frac)
        + ow * jnp.sum(jnp.maximum(pct_true0 - 100.0, 0.0))
    )
    cpu0, mem0 = loads(assign0)
    comm0_c, obj0 = objective_terms(assign0, cpu0)
    keys = jax.random.split(key, config.sweeps)
    temps = config.noise_temp * (
        1.0
        - jnp.arange(config.sweeps, dtype=jnp.float32)
        / max(config.sweeps - 1, 1)
    )
    (
        (_, _, _, best_assign, best_obj, best_comm),
        (moves_per_sweep, swaps_per_sweep),
    ) = scan_sweeps(
        make_sweep, (assign0, cpu0, mem0, assign0, obj0, comm0_c),
        keys, temps, sw_flags,
    )

    # under disruption pricing the adopt gate re-prices with the EXACT
    # pod-level restart bill (the scan ranked with the cheap service-level
    # form); the reported objective stays raw
    raw_after = (
        best_comm + _balance_terms(loads(best_assign)[0])
        if mc_on
        else best_obj
    )
    best_pen = _pod_bill(best_assign) if mc_on else jnp.float32(0.0)
    improved = raw_after + best_pen < obj_true0
    pod_slot = jnp.clip(
        sgraph.inv[jnp.clip(state.pod_service, 0, S - 1)], 0, SPX - 1
    )
    new_pod_node = jnp.where(
        improved & state.pod_valid, best_assign[pod_slot], state.pod_node
    )
    new_state = state.replace(pod_node=new_pod_node)
    info = {
        "objective_before": obj_true0,
        "objective_after": jnp.where(improved, raw_after, obj_true0),
        "improved": improved,
        "moves_per_sweep": moves_per_sweep,
        "swaps_per_sweep": swaps_per_sweep,
        "move_penalty": jnp.where(improved, best_pen, 0.0),
        # collapse identity: an adopted placement colocates every
        # service's replicas, so its pod-level cost IS the tracked
        # service-level cut of best_assign; unadopted keeps the input's
        # (already computed) true cost — no second pod-level pass
        "communication_cost": jnp.where(improved, best_comm, comm_true0),
        "load_std": load_std(new_state),
        "hub_pass": jnp.asarray(NHB > 0),
    }
    return new_state, info
