"""Backend protocol: the host-side boundary of the framework.

Everything above this line is pure JAX; everything below talks to a cluster
(real or simulated). The protocol mirrors the reference's control-loop
surface: snapshot (podmonitor.py:7-125), deployment teardown
(delete_replaced_pod.py:144-185), and pinned re-creation
(rescheduling.py:57-73).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Protocol

from kubernetes_rescheduling_tpu.core.state import ClusterState, CommGraph


@dataclass(frozen=True)
class MoveRequest:
    """Move one service's Deployment — or, with ``pod`` set, a single
    replica — to a target node. Per-pod moves are the mechanism behind
    ``placement_unit='pod'``; a backend that can only re-create whole
    Deployments (the k8s Deployment mechanism, reference
    delete_replaced_pod.py:173) must reject them with a clear error
    rather than silently moving every replica."""

    service: str
    target_node: str
    hazard_nodes: tuple[str, ...] = ()
    mechanism: str = "nodeName"  # nodeName | nodeSelector | affinityOnly
    pod: str | None = None  # move only this named replica


class Backend(Protocol):
    """What a cluster must provide to the controller.

    Backends that cannot express per-pod moves advertise it with a
    ``supports_pod_moves = False`` class attribute (absent means True);
    the reconcile plane then scopes corrective moves to the whole
    Deployment instead of tripping the per-pod rejection above.
    """

    def monitor(self) -> ClusterState:
        """Fresh padded snapshot of the cluster."""
        ...

    def comm_graph(self) -> CommGraph:
        """The service communication graph."""
        ...

    def apply_move(self, move: MoveRequest) -> str | None:
        """Tear down the service's Deployment and re-create it pinned/steered
        to the target node. Returns the node the Deployment actually landed
        on — which may differ from ``move.target_node`` when the mechanism
        leaves the choice to the scheduler (``affinityOnly``; a live cluster
        can only report the advisory target there) — or None if the move
        failed (the round is then a skip, reference main.py:103-107)."""
        ...

    def advance(self, seconds: float) -> None:
        """Let time pass (pacing between rounds, reference main.py:27,100)."""
        ...


def device_kind(n_devices: int | None = None) -> str:
    """The accelerator identity a measured multichip record is keyed
    by: ``"<platform>x<count>"`` (``cpu x8`` forced-host runs vs a real
    ``tpu x8`` slice get DIFFERENT perf-ledger series keys, so their
    baselines can never be compared). Reads the already-initialised jax
    backend; ``"unknown"`` kind when jax is absent so host-only tools
    can still stamp records."""
    try:
        import jax

        devices = jax.devices()
        kind = devices[0].platform
        n = int(n_devices) if n_devices is not None else len(devices)
    except Exception:  # jax missing/uninitialisable: stamp, don't crash
        kind = "unknown"
        n = int(n_devices) if n_devices is not None else 0
    return f"{kind}x{n}"
